"""Direct call plane: ownership-based metadata + caller->worker RPC that
keeps the head out of the hot path.

This is the TPU-native equivalent of the reference's ownership model:

- Small objects live in the OWNER process (the process that created them),
  not in a central store; the owner serves gets, counts borrows, and frees
  on last release (reference: src/ray/core_worker/reference_counter.h:44
  per-owner refcounts; src/ray/object_manager/ownership_object_directory.cc
  owner-directed lookup; the reference keeps returns < 100KB "in the
  owner's in-process store").
- Actor calls go straight from the caller to the actor's worker process on
  a persistent authenticated TCP connection; the head only answers the
  one-time endpoint lookup and handles failure cleanup (reference:
  direct actor call path of core_worker's ActorTaskSubmitter).
- Stateless tasks use worker LEASES: the caller asks the head for a leased
  worker once, then streams task executions to it directly (reference:
  src/ray/raylet/scheduling/cluster_lease_manager.h:41 lease-based
  scheduling; normal_task_submitter.h pipelining onto a leased worker).

The head path remains for everything constrained (placement groups,
runtime_env, streaming generators, labels, TPU resources) and is the
fallback on ANY direct-path failure, so semantics degrade to round-3
behavior rather than erroring.

Wire protocol: length-prefixed pickled dicts over TCP with the cluster's
HMAC challenge/response auth (same scheme as core/transport.py).
"""

from __future__ import annotations

import os
import pickle
import socket
import struct
import threading
import time

from ray_tpu.core.ids import ObjectID, TaskID
from ray_tpu.core.object_ref import ObjectRef as _ObjRef
from ray_tpu.core.task_spec import ArgSpec, Payload
from ray_tpu.exceptions import (
    ActorDiedError,
    GetTimeoutError,
    ObjectLostError,
    TaskError,
    WorkerCrashedError,
)

_MAX_FRAME = 256 << 20


# ---------------------------------------------------------------------------
# framing
# ---------------------------------------------------------------------------
def _send_frame(sock: socket.socket, data: bytes, lock: threading.Lock):
    with lock:
        sock.sendall(struct.pack("<I", len(data)) + data)


def _recv_exact(rf, n: int) -> bytes:
    data = rf.read(n)
    if data is None or len(data) < n:
        raise ConnectionError("direct peer closed")
    return data


def _recv_frame(rf) -> dict:
    (n,) = struct.unpack("<I", _recv_exact(rf, 4))
    if n > _MAX_FRAME:
        raise ConnectionError("oversized direct frame")
    return pickle.loads(_recv_exact(rf, n))


def _dumps(msg: dict) -> bytes:
    return pickle.dumps(msg, protocol=5)


def _auth_server(sock: socket.socket, authkey: bytes):
    import hmac

    lock = threading.Lock()
    challenge = os.urandom(20)
    _send_frame(sock, challenge, lock)
    rf = sock.makefile("rb")
    (n,) = struct.unpack("<I", _recv_exact(rf, 4))
    resp = _recv_exact(rf, n)
    if not hmac.compare_digest(resp, hmac.new(authkey, challenge, "sha256").digest()):
        raise ConnectionError("direct auth failed")
    _send_frame(sock, b"OK", lock)
    return rf


def _auth_client(sock: socket.socket, authkey: bytes):
    import hmac

    lock = threading.Lock()
    rf = sock.makefile("rb")
    (n,) = struct.unpack("<I", _recv_exact(rf, 4))
    challenge = _recv_exact(rf, n)
    _send_frame(sock, hmac.new(authkey, challenge, "sha256").digest(), lock)
    (n,) = struct.unpack("<I", _recv_exact(rf, 4))
    if _recv_exact(rf, n) != b"OK":
        raise ConnectionError("direct auth rejected")
    return rf


# ---------------------------------------------------------------------------
# owned object store (per process)
# ---------------------------------------------------------------------------
PENDING, READY, VALUE, ERROR, REDIRECT = range(5)


class _Entry:
    __slots__ = (
        "state",
        "payload",
        "value",
        "error",
        "event",
        "borrows",
        "zero_since",
        "callbacks",
        "contained",
        "pending_serialized",
    )

    def __init__(self, state: int):
        self.state = state
        self.payload = None
        self.value = None
        self.error = None
        self.event = threading.Event() if state == PENDING else None
        self.borrows = 0
        self.zero_since = None  # monotonic ts when local count hit 0
        self.callbacks = None
        # live ObjectRefs pickled inside this value: the entry pins them
        # while it lives, releasing on free (cascading GC — the owned-store
        # analogue of the head store's contained_refs wrapping)
        self.contained = None
        # serialized-out copies of the ref not yet matched by a
        # registered borrow: while > 0 a borrower may still be about to
        # register, so the owner must wait for the explicit registration
        # (then release) — the timer degrades to a LEAK BACKSTOP. A
        # counter, not a flag: every new serialization re-opens the
        # registration race, however many borrows came and went before.
        self.pending_serialized = 0


class OwnedStore:
    """The owner half of the per-owner metadata protocol: values (or their
    shm descriptors) created by this process, served to borrowers, freed on
    last release plus a short grace window (the grace absorbs the in-flight
    register race inherent to async borrow registration).

    Refs known to have been SERIALIZED OUT of this process wait for the
    explicit borrow-release instead (reference: reference_counter.h
    WaitForRefRemoved); until a borrow registers, the timer is only the
    ``backstop_s`` leak backstop for borrowers that died before
    registering — a ref-pump stall longer than ``grace_s`` no longer
    premature-frees a live borrowed ref. Both windows are RT_* flags
    (_config.py: owned_object_grace_s / owned_object_leak_backstop_s)."""

    def __init__(self, grace_s: float = 1.0, backstop_s: float = 30.0):
        self._lock = threading.Lock()
        self._objects: dict[bytes, _Entry] = {}
        self.grace_s = grace_s
        self.backstop_s = max(backstop_s, grace_s)

    def __contains__(self, k: bytes) -> bool:
        with self._lock:
            return k in self._objects

    def owns(self, k: bytes) -> bool:
        """True when this process is the live owner (REDIRECT entries are
        head-owned leftovers kept only for promote idempotency)."""
        with self._lock:
            e = self._objects.get(k)
            return e is not None and e.state != REDIRECT

    def drop_redirect(self, k: bytes):
        with self._lock:
            e = self._objects.get(k)
            if e is not None and e.state == REDIRECT:
                del self._objects[k]

    def size(self) -> int:
        with self._lock:
            return len(self._objects)

    def put_ready(self, k: bytes, payload: Payload, contained=None):
        with self._lock:
            e = self._objects.get(k)
            if e is None:
                e = self._objects[k] = _Entry(READY)
            e.state = READY
            e.payload = payload
            e.contained = contained or None

    def create_pending(self, k: bytes):
        with self._lock:
            if k not in self._objects:
                self._objects[k] = _Entry(PENDING)

    def reset_pending(self, k: bytes):
        """Force an entry back to PENDING (lineage replay of a lost
        result): getters block until the replay completes it again."""
        with self._lock:
            e = _Entry(PENDING)
            old = self._objects.get(k)
            if old is not None:
                e.borrows = old.borrows
                e.pending_serialized = old.pending_serialized
            self._objects[k] = e

    def complete(self, k: bytes, payload: Payload | None = None, value=None, error=None, redirect=False):
        with self._lock:
            e = self._objects.get(k)
            if e is None:
                e = self._objects[k] = _Entry(PENDING)
                e.event = threading.Event()
            if error is not None:
                e.state, e.error = ERROR, error
            elif redirect:
                e.state = REDIRECT
            elif payload is not None:
                e.state, e.payload = READY, payload
                if payload.contained:
                    # pin objects pickled inside the result while the entry
                    # lives; our ref pump registers the borrow with their
                    # owner/head
                    from ray_tpu.core.object_ref import ObjectRef

                    e.contained = [ObjectRef(c) for c in payload.contained]
            else:
                e.state, e.value = VALUE, value
            ev, cbs = e.event, e.callbacks
            e.callbacks = None
        if ev is not None:
            ev.set()
        if cbs:
            for cb in cbs:
                try:
                    cb()
                except Exception:
                    pass

    def entry(self, k: bytes) -> _Entry | None:
        with self._lock:
            return self._objects.get(k)

    def wait_entry(self, k: bytes, timeout: float | None) -> _Entry | None:
        """Block until the entry leaves PENDING (or timeout). None =
        unknown id (never owned here / already freed)."""
        with self._lock:
            e = self._objects.get(k)
        if e is None:
            return None
        if e.state != PENDING:
            return e
        if not e.event.wait(timeout=timeout):
            return e  # still pending; caller decides on timeout semantics
        return e

    def add_callback(self, k: bytes, cb) -> bool:
        """Run cb() once the entry completes (immediately if done).
        Returns False for unknown ids."""
        with self._lock:
            e = self._objects.get(k)
            if e is None:
                return False
            if e.state == PENDING:
                if e.callbacks is None:
                    e.callbacks = []
                e.callbacks.append(cb)
                return True
        try:
            cb()
        except Exception:
            pass
        return True

    def is_ready(self, k: bytes) -> bool:
        with self._lock:
            e = self._objects.get(k)
            return e is not None and e.state != PENDING

    # -- borrow protocol (owner side) --
    def mark_serialized(self, k: bytes):
        """The ref just left this process inside a pickle (ObjectRef.
        __reduce__): hold the entry for the explicit borrow-release; the
        timer becomes the leak backstop until a borrow registers."""
        with self._lock:
            e = self._objects.get(k)
            if e is not None:
                e.pending_serialized += 1

    def on_borrow(self, k: bytes, registered: bool):
        with self._lock:
            e = self._objects.get(k)
            if e is None:
                return
            e.borrows += 1 if registered else -1
            if registered and e.pending_serialized > 0:
                e.pending_serialized -= 1
            if e.borrows > 0:
                e.zero_since = None
            elif e.zero_since is None and registered is False:
                # explicit release brought borrows back to zero: (re)start
                # the grace clock if the local count is already zero too
                from ray_tpu.core.object_ref import local_ref_count

                if local_ref_count(ObjectID(k)) == 0:
                    e.zero_since = time.monotonic()

    def on_local_zero(self, k: bytes):
        from ray_tpu.core.object_ref import local_ref_count

        with self._lock:
            e = self._objects.get(k)
            if e is None:
                return
            if local_ref_count(ObjectID(k)) == 0 and e.borrows <= 0:
                e.zero_since = time.monotonic()

    def on_local_reregister(self, k: bytes):
        with self._lock:
            e = self._objects.get(k)
            if e is not None:
                e.zero_since = None

    def free(self, k: bytes):
        self._drop(k)

    def _drop(self, k: bytes):
        with self._lock:
            e = self._objects.pop(k, None)
        if e is not None and e.payload is not None and e.payload.shm is not None:
            from ray_tpu.core.object_store import local_shm_name, unlink_shm

            try:
                unlink_shm(e.payload.shm.shm_name)
                unlink_shm(local_shm_name(e.payload.shm))
            except Exception:
                pass
        if e is not None and e.event is not None and not e.event.is_set():
            e.error = ObjectLostError("object was freed by its owner")
            e.state = ERROR
            e.event.set()
        if e is not None:
            e.contained = None  # release contained pins (cascade)

    def gc_pass(self):
        """Free entries whose local count has been zero (and borrow count
        <= 0) for longer than the applicable window: the short grace for
        entries that never left this process (or whose every serialized
        copy registered its borrow, so release is the causal signal), the
        leak backstop while any serialized-out copy's registration may
        still be in flight."""
        from ray_tpu.core.object_ref import local_ref_count

        now = time.monotonic()
        doomed = []
        with self._lock:
            for k, e in self._objects.items():
                window = self.backstop_s if e.pending_serialized > 0 else self.grace_s
                if (
                    e.zero_since is not None
                    and now - e.zero_since > window
                    and e.borrows <= 0
                    and e.state != PENDING
                ):
                    doomed.append(k)
        for k in doomed:
            if local_ref_count(ObjectID(k)) == 0:
                self._drop(k)

    def shutdown(self):
        with self._lock:
            ks = list(self._objects)
        for k in ks:
            self._drop(k)


# ---------------------------------------------------------------------------
# remote-owner hints: obj_id bytes -> "host:port#node_hex" of the owner.
# Module-level (not per-client): populated by ObjectRef materialization in
# ANY process so borrowed refs always know their owner.
# ---------------------------------------------------------------------------
_hints: dict[bytes, str] = {}
_hints_lock = threading.Lock()


def note_hint(k: bytes, owner: str):
    st = _state
    if st is not None:
        if owner is st.self_owner:
            # our own fresh ref: getters consult the owned store first and
            # __reduce__ carries the instance hint — skip both lock takes
            # (this is every direct-call return ref, the hot path)
            return
        if st.owned.owns(k):
            return  # we ARE the owner; no hint needed
    with _hints_lock:
        _hints[k] = owner


def get_hint(k: bytes) -> str | None:
    with _hints_lock:
        return _hints.get(k)


def mark_serialized_out(k: bytes):
    """ObjectRef.__reduce__ hook: if WE own this id, record that the ref
    left the process so the owned store waits for the borrow-release
    instead of the grace timer (see OwnedStore docstring).

    __reduce__ also fires for pickles that never leave the process
    (deepcopy; a value containing the ref entering the local store or
    spill). Common local flows drain the counter anyway — a stored/
    spilled container's contained-ref pin registers a borrow with the
    owner — and the residual cost for a purely local pickle is bounded:
    the entry frees after the backstop window (default 30s) instead of
    the grace window, never leaks. Hooking the real egress path instead
    would save that delay but needs boundary plumbing at every send
    site; deliberately not done at this altitude."""
    st = _state
    if st is not None and st.owned.owns(k):
        st.owned.mark_serialized(k)


def drop_hint(k: bytes):
    with _hints_lock:
        _hints.pop(k, None)


def hint_addr(owner: str) -> tuple[str, int]:
    hp = owner.split("#", 1)[0]
    host, port = hp.rsplit(":", 1)
    return (host, int(port))


def hint_node_hex(owner: str) -> str | None:
    parts = owner.split("#", 1)
    return parts[1] if len(parts) > 1 else None


# ---------------------------------------------------------------------------
# client side: one persistent connection to a peer
# ---------------------------------------------------------------------------
class _CallRec:
    __slots__ = ("kind", "actor_hex", "task_id", "oids", "method", "func_id", "args", "kwargs", "num_returns", "retries_left", "trace", "done_counted", "pins", "raw", "cancelled", "registered")

    def __init__(self, kind, actor_hex, task_id, oids, method, func_id, args, kwargs, num_returns, retries_left, trace, pins=None, raw=None):
        self.done_counted = False
        self.cancelled = False
        # True once the rec is in a PeerConn's _calls: from then on,
        # conn-death failover owns it. False on a ConnectionError means
        # NOBODY will complete the oids unless the submitter fails over.
        self.registered = False
        # live ObjectRefs pinning this call's arguments until completion
        # (the head pins spec args on its path; here the caller does)
        self.pins = pins
        # fast-path args: one pickle blob of (args, kwargs) riding the
        # frame; None when ArgSpec encoding was used
        self.raw = raw
        self.kind = kind  # "actor" | "task"
        self.actor_hex = actor_hex
        self.task_id = task_id
        self.oids = oids
        self.method = method
        self.func_id = func_id
        self.args = args
        self.kwargs = kwargs
        self.num_returns = num_returns
        self.retries_left = retries_left
        self.trace = trace


class PeerConn:
    """Client half of one direct connection: pipelined requests, a reader
    thread completing owned-store entries and blocking slots."""

    def __init__(self, state: "DirectState", addr: tuple[str, int]):
        self.state = state
        self.addr = addr
        self.sock = socket.create_connection(addr, timeout=10.0)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.sock.settimeout(None)
        self._rf = _auth_client(self.sock, state.authkey)
        self._wlock = threading.Lock()
        self._lock = threading.Lock()
        self._cid = 0
        self._calls: dict[int, _CallRec] = {}  # in-flight direct calls
        self._slots: dict[int, list] = {}  # cid -> [Event, ok, payload] blocking requests
        self.dead = False
        self.last_used = time.monotonic()
        self._sent_funcs: set[str] = set()
        self.inflight = 0
        self._reader = threading.Thread(target=self._read_loop, daemon=True, name="rt-direct-peer")
        self._reader.start()

    def _next_cid(self) -> int:
        with self._lock:
            self._cid += 1
            return self._cid

    def send(self, msg: dict):
        data = _dumps(msg)
        try:
            _send_frame(self.sock, data, self._wlock)
        except (OSError, ValueError) as e:
            self._on_death()
            raise ConnectionError(f"direct peer send failed: {e}") from None

    def send_call(self, rec: _CallRec, frame: dict, data: bytes | None = None):
        """Register the in-flight call and send its frame. ``data`` is the
        pre-pickled frame (raw fast path; the cid placeholder inside was
        already filled by the caller via reserve_cid)."""
        cid = frame["cid"] if data is not None else self._next_cid()
        if data is None:
            frame["cid"] = cid
        with self._lock:
            if self.dead:
                raise ConnectionError("direct peer is down")
            self._calls[cid] = rec
            rec.registered = True
            self.inflight += 1
        self.last_used = time.monotonic()
        try:
            if data is not None:
                _send_frame(self.sock, data, self._wlock)
            else:
                self.send(frame)
        except (OSError, ValueError) as e:
            self._on_death()
            raise ConnectionError(f"direct peer send failed: {e}") from None

    def reserve_cid(self) -> int:
        return self._next_cid()

    def ensure_func(self, func_id: str, blob):
        if func_id in self._sent_funcs:
            return
        self.send({"op": "reg_func", "func_id": func_id, "blob": blob})
        self._sent_funcs.add(func_id)

    def request(self, op: str, timeout: float | None = None, _fields: dict | None = None, **fields) -> dict:
        """Blocking request/response (GET etc.). ``timeout`` bounds the
        local wait; ``fields`` ride the frame. A frame field whose name
        collides with a parameter here (the server-side "timeout" a
        bounded GET carries) goes through ``_fields`` instead."""
        if _fields:
            fields.update(_fields)
        cid = self._next_cid()
        slot = [threading.Event(), None]
        with self._lock:
            if self.dead:
                raise ConnectionError("direct peer is down")
            self._slots[cid] = slot
        self.last_used = time.monotonic()
        self.send({"op": op, "cid": cid, **fields})
        if not slot[0].wait(timeout=timeout):
            with self._lock:
                self._slots.pop(cid, None)
            raise GetTimeoutError(f"direct {op} to {self.addr} timed out")
        if isinstance(slot[1], ConnectionError):
            raise slot[1]
        return slot[1]

    def request_get(self, k: bytes, timeout: float | None) -> dict:
        """GET an owned object: the owner waits out PENDING entries with
        OUR timeout (None = indefinitely, like a local get)."""
        return self.request(
            "get",
            timeout=None if timeout is None else timeout + 5.0,
            id=k,
            _fields=None if timeout is None else {"timeout": timeout},
        )

    def _read_loop(self):
        try:
            while True:
                msg = _recv_frame(self._rf)
                op = msg.get("op")
                if op == "result":
                    self._on_result(msg)
                elif op == "value":
                    with self._lock:
                        slot = self._slots.pop(msg["cid"], None)
                    if slot is not None:
                        slot[1] = msg
                        slot[0].set()
                # unknown ops ignored (forward compat)
        except (ConnectionError, OSError, EOFError, pickle.UnpicklingError):  # tpulint: disable=TPL007
            pass  # death observed below: _on_death fails over every in-flight rec
        finally:
            self._on_death()

    def _on_result(self, msg: dict):
        from ray_tpu.core import rpc_chaos

        if not rpc_chaos.apply("direct_result"):
            # chaos: a lost reply is indistinguishable from a dead peer —
            # fail the connection so in-flight calls take the failover path
            self._on_death()
            return
        with self._lock:
            rec = self._calls.pop(msg["cid"], None)
            if rec is not None:
                self.inflight -= 1
        if rec is None:
            return
        self.last_used = time.monotonic()
        owned = self.state.owned
        err = msg.get("error")
        if err is not None:
            for oid in rec.oids:
                owned.complete(oid.binary(), error=err)
        elif "vals" in msg:
            # raw fast path: results came as plain values in the frame
            for oid, v in zip(rec.oids, msg["vals"]):
                owned.complete(oid.binary(), value=v)
        else:
            for (kb, payload, head_owned) in msg["returns"]:
                if head_owned:
                    drop_hint(kb)
                    owned.complete(kb, redirect=True)
                    # the owner (this process) keeps the producing spec:
                    # if the head store loses the bytes, we replay the
                    # call (owner-based lineage; reference:
                    # task_manager.cc lineage reconstruction lives with
                    # the owner, not the GCS)
                    self.state.remember_lineage(kb, rec)
                else:
                    owned.complete(kb, payload=payload)
        self.state.on_call_done(rec)

    def _on_death(self):
        with self._lock:
            if self.dead:
                return
            self.dead = True
            calls, self._calls = self._calls, {}
            slots, self._slots = self._slots, {}
        try:
            self.sock.close()
        except OSError:
            pass
        for slot in slots.values():
            slot[1] = ConnectionError("direct peer died")
            slot[0].set()
        self.state.on_conn_death(self, list(calls.values()))

    def close(self):
        try:
            self.sock.close()
        except OSError:
            pass


# ---------------------------------------------------------------------------
# server side
# ---------------------------------------------------------------------------
class DirectServer:
    """Listener serving the direct protocol for this process: owned-object
    GETs, borrow events, frees — and, when an exec handler is installed
    (worker processes), direct CALL execution."""

    def __init__(self, state: "DirectState", host: str = "0.0.0.0", advertise_host: str | None = None):
        self.state = state
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, 0))
        self._sock.listen(128)
        adv = advertise_host or os.environ.get("RT_DIRECT_HOST") or "127.0.0.1"
        self.address = (adv, self._sock.getsockname()[1])
        self._stopped = threading.Event()
        from concurrent.futures import ThreadPoolExecutor

        self._pool = ThreadPoolExecutor(max_workers=16, thread_name_prefix="rt-direct-srv")
        self._thread = threading.Thread(target=self._accept_loop, daemon=True, name="rt-direct-listen")
        self._thread.start()

    def _accept_loop(self):
        try:
            self._sock.settimeout(0.5)
        except OSError:
            return
        while not self._stopped.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(target=self._serve_conn, args=(conn,), daemon=True, name="rt-direct-conn").start()

    def _serve_conn(self, conn: socket.socket):
        try:
            conn.settimeout(30.0)
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            rf = _auth_server(conn, self.state.authkey)
            conn.settimeout(None)
        except Exception:
            try:
                conn.close()
            except OSError:
                pass
            return
        wlock = threading.Lock()

        def reply(msg):
            # dict or pre-pickled bytes (the worker's raw fast path)
            try:
                _send_frame(conn, msg if isinstance(msg, bytes) else _dumps(msg), wlock)
            except (OSError, ValueError):
                pass

        funcs: dict[str, object] = {}
        try:
            while not self._stopped.is_set():
                msg = _recv_frame(rf)
                op = msg.get("op")
                if op == "call":
                    handler = self.state.exec_handler
                    if handler is None:
                        reply({"op": "result", "cid": msg["cid"], "returns": [], "error": TaskError(tb_str="this process does not execute direct calls", task_desc=msg.get("method", ""))})
                    else:
                        handler(msg, reply, funcs)
                elif op == "get":
                    self._pool.submit(self._serve_get, msg, reply)
                elif op == "poll":
                    e = self.state.owned.entry(msg["id"])
                    ready = e is None or e.state != PENDING
                    reply({"op": "value", "cid": msg["cid"], "payload": None, "ready": ready})
                elif op == "ref":
                    self._on_ref_events(msg["events"])
                elif op == "free":
                    for kb in msg["ids"]:
                        self.state.owned.free(kb)
                elif op == "reg_func":
                    funcs[msg["func_id"]] = msg["blob"]
                elif op == "cancel":
                    cd = self.state.cancelled_direct
                    if len(cd) > 1024:
                        cd.clear()  # best-effort cooperative marks, bounded
                    cd.add(msg["task"])
                elif op == "ping":
                    reply({"op": "value", "cid": msg["cid"], "payload": None})
        except (ConnectionError, OSError, EOFError, pickle.UnpicklingError):  # tpulint: disable=TPL007
            pass  # server side: a vanished client owes us nothing (it fails over)
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _serve_get(self, msg: dict, reply):
        k = msg["id"]
        e = self.state.owned.entry(k)
        if e is not None and e.state == PENDING:
            # long waits get their own thread so pending GETs can never
            # starve the fixed server pool
            threading.Thread(target=self._serve_get_blocking, args=(msg, reply), daemon=True, name="rt-direct-getwait").start()
            return
        self._serve_get_blocking(msg, reply)

    def _serve_get_blocking(self, msg: dict, reply):
        k = msg["id"]
        timeout = msg.get("timeout")  # None = wait as long as the caller does
        e = self.state.owned.wait_entry(k, timeout)
        if e is None or e.state == REDIRECT:
            reply({"op": "value", "cid": msg["cid"], "payload": None, "not_owned": True})
            return
        if e.state == PENDING:
            reply({"op": "value", "cid": msg["cid"], "payload": None, "error": GetTimeoutError("owner-side wait timed out")})
        elif e.state == ERROR:
            reply({"op": "value", "cid": msg["cid"], "payload": None, "error": e.error})
        elif e.state == VALUE:
            from ray_tpu.core.payloads import encode_value

            reply({"op": "value", "cid": msg["cid"], "payload": encode_value(e.value)})
        else:
            reply({"op": "value", "cid": msg["cid"], "payload": e.payload})

    def _on_ref_events(self, events):
        """Borrow register/release for objects we own; stale-hint events
        (ids promoted to the head meanwhile) are forwarded into this
        process's head-bound ref queue so the head's holder table stays
        balanced (see module docstring for the bounded-leak caveat)."""
        owned = self.state.owned
        stale = []
        for kb, reg in events:
            if kb in owned:
                owned.on_borrow(kb, reg)
            else:
                stale.append((kb, reg))
        if stale:
            from ray_tpu.core import object_ref as _oref

            with _oref._rc_lock:
                _oref._rc_events.extend(stale)

    def shutdown(self):
        self._stopped.set()
        try:
            self._sock.close()
        except OSError:
            pass
        self._pool.shutdown(wait=False)


# ---------------------------------------------------------------------------
# actor routes + leases
# ---------------------------------------------------------------------------
class ActorRoute:
    __slots__ = ("addr", "epoch", "max_task_retries", "head_dirty", "inflight_recs", "lock", "drained")

    def __init__(self):
        self.addr = None
        self.epoch = -1
        self.max_task_retries = 0
        self.head_dirty = False  # head-lane submissions since last fence
        self.inflight_recs = 0
        self.lock = threading.Lock()
        self.drained = threading.Event()
        self.drained.set()


class Lease:
    __slots__ = ("wid", "addr", "conn")

    def __init__(self, wid: str, addr, conn: PeerConn):
        self.wid = wid
        self.addr = addr
        self.conn = conn


# ---------------------------------------------------------------------------
# per-process direct state
# ---------------------------------------------------------------------------
class DirectState:
    MAX_CONNS = 256

    def __init__(self, client, authkey: bytes, node_hex: str = "", serve: bool = True, exec_handler=None):
        from ray_tpu._config import get_config

        self.client = client
        self.authkey = authkey
        self.node_hex = node_hex
        self.owned = OwnedStore(
            grace_s=get_config().owned_object_grace_s,
            backstop_s=get_config().owned_object_leak_backstop_s,
        )
        self.exec_handler = exec_handler
        self.cancelled_direct: set = set()
        self.server = DirectServer(self) if serve else None
        self.self_owner = (
            f"{self.server.address[0]}:{self.server.address[1]}#{node_hex}" if self.server else None
        )
        self._conns: dict[tuple, PeerConn] = {}
        self._conns_lock = threading.Lock()
        self.routes: dict[str, ActorRoute] = {}
        self._routes_lock = threading.Lock()
        self.leases: list[Lease] = []
        self._leases_lock = threading.Lock()
        self._lease_last_used = 0.0
        self._owner_ref_queues: dict[str, list] = {}  # owner -> pending ref events
        self._orq_lock = threading.Lock()
        # function blobs this client taught (or may teach) leased workers
        self.func_blobs: dict[str, object] = {}
        # owner-side lineage: return-oid -> producing _CallRec for
        # head-sealed (large) direct results; bounded FIFO
        self.lineage: dict[bytes, _CallRec] = {}
        self._lineage_order: list = []
        self._lineage_lock = threading.Lock()
        self._reconstructing: set = set()
        self._reconstruct_cv = threading.Condition(self._lineage_lock)
        self._stopped = False
        # hot-path cached config values (get_config() per call adds up)
        self.default_max_retries = get_config().default_max_retries
        self.inline_threshold = get_config().max_direct_call_object_size
        self._hk = threading.Thread(target=self._housekeeping, daemon=True, name="rt-direct-hk")
        self._hk.start()

    # -- connections --
    def get_conn(self, addr: tuple[str, int]) -> PeerConn:
        addr = tuple(addr)
        with self._conns_lock:
            c = self._conns.get(addr)
            if c is not None and not c.dead:
                return c
        c = PeerConn(self, addr)
        with self._conns_lock:
            old = self._conns.get(addr)
            if old is not None and not old.dead:
                c.close()
                return old
            self._conns[addr] = c
            if len(self._conns) > self.MAX_CONNS:
                idle = sorted(
                    (x for x in self._conns.values() if x.inflight == 0 and x is not c),
                    key=lambda x: x.last_used,
                )
                for x in idle[: len(self._conns) - self.MAX_CONNS]:
                    self._conns.pop(x.addr, None)
                    x.close()
        return c

    def on_conn_death(self, conn: PeerConn, lost_calls: list[_CallRec]):
        with self._conns_lock:
            if self._conns.get(conn.addr) is conn:
                self._conns.pop(conn.addr, None)
        with self._leases_lock:
            self.leases = [l for l in self.leases if l.conn is not conn]
        for rec in lost_calls:
            threading.Thread(target=self._failover, args=(rec,), daemon=True).start()

    def on_call_done(self, rec: _CallRec):
        if rec.kind == "actor" and not rec.done_counted:
            rec.done_counted = True
            route = self.route(rec.actor_hex)
            with route.lock:
                route.inflight_recs -= 1
                if route.inflight_recs <= 0:
                    route.drained.set()

    # -- failover: direct call lost to a dead peer --
    def _failover(self, rec: _CallRec):
        try:
            if rec.cancelled:
                from ray_tpu.exceptions import RayTpuError

                err = RayTpuError(f"task {rec.task_id.hex()[:8]} was cancelled")
                for oid in rec.oids:
                    self.owned.complete(oid.binary(), error=err)
                return
            if self._stopped:
                err = WorkerCrashedError("runtime shut down with direct calls in flight")
                for oid in rec.oids:
                    self.owned.complete(oid.binary(), error=err)
                return
            client = self.client
            if rec.kind == "actor":
                self._failover_actor(client, rec)
            else:
                self._failover_task(client, rec)
        except BaseException as e:  # noqa: BLE001
            for oid in rec.oids:
                self.owned.complete(oid.binary(), error=e if isinstance(e, Exception) else WorkerCrashedError(str(e)))
        finally:
            self.on_call_done(rec)

    @staticmethod
    def _rec_argspecs(rec: _CallRec):
        """ArgSpecs for a head-path resubmit of this rec. Raw fast-path
        recs keep the SUBMISSION-TIME frame pickle; unpickling it
        reproduces the argument snapshot (a caller mutating its objects
        after .remote() must not change what a retry executes)."""
        if rec.raw is None:
            return rec.args, rec.kwargs
        frame = pickle.loads(rec.raw)
        args, kwargs = frame["argv"], frame.get("kwargv") or {}
        from ray_tpu.api import _encode_args

        specs, kw, _pins = _encode_args(args, kwargs)
        return specs, kw

    def _failover_actor(self, client, rec: _CallRec):
        from ray_tpu.core.ids import ActorID

        route = self.route(rec.actor_hex)
        with route.lock:
            route.addr = None  # force endpoint re-resolution
            route.head_dirty = True
        if rec.retries_left <= 0:
            err = ActorDiedError(rec.actor_hex, "actor worker died during a direct call")
            for oid in rec.oids:
                self.owned.complete(oid.binary(), error=err)
            return
        # resubmit through the head (it owns the restart state machine);
        # bridge the head-path results into our owned pending entries
        args, kwargs = self._rec_argspecs(rec)
        ids = client.submit_actor_task(
            actor_id=ActorID.from_hex(rec.actor_hex),
            method_name=rec.method,
            args=args,
            kwargs=kwargs,
            num_returns=rec.num_returns,
            streaming=False,
            options={"_trace_ctx": rec.trace},
        )
        self._bridge(client, ids, rec.oids)

    def _failover_task(self, client, rec: _CallRec):
        if rec.retries_left <= 0:
            err = WorkerCrashedError(f"leased worker died executing {rec.method}")
            for oid in rec.oids:
                self.owned.complete(oid.binary(), error=err)
            return
        args, kwargs = self._rec_argspecs(rec)
        ids = client.submit_task(
            name=rec.method,
            func_id=rec.func_id,
            args=args,
            kwargs=kwargs,
            num_returns=rec.num_returns,
            streaming=False,
            func_blob=self.func_blobs.get(rec.func_id),
            options={"max_retries": rec.retries_left - 1},
        )
        self._bridge(client, ids, rec.oids)

    def _bridge(self, client, head_ids, owned_oids):
        def _pump():
            for hid, oid in zip(head_ids, owned_oids):
                try:
                    v = client.get_object(hid)
                    self.owned.complete(oid.binary(), value=v)
                except BaseException as e:  # noqa: BLE001
                    self.owned.complete(oid.binary(), error=e)

        threading.Thread(target=_pump, daemon=True).start()

    # -- owner-side lineage --
    MAX_LINEAGE = 4096

    def remember_lineage(self, k: bytes, rec: _CallRec):
        with self._lineage_lock:
            if k not in self.lineage:
                self._lineage_order.append(k)
            self.lineage[k] = rec
            while len(self._lineage_order) > self.MAX_LINEAGE:
                old = self._lineage_order.pop(0)
                self.lineage.pop(old, None)

    def forget_lineage(self, k: bytes):
        with self._lineage_lock:
            self.lineage.pop(k, None)

    def reconstruct(self, client, obj_id: ObjectID) -> bool:
        """Replay the direct call that produced a lost head-sealed result.
        Blocks until the replay completes (entries leave PENDING). Returns
        False when this process holds no lineage for the id."""
        k = obj_id.binary()
        with self._lineage_lock:
            rec = self.lineage.get(k)
            if rec is None:
                return False
            tid_b = rec.task_id.binary()
            if tid_b in self._reconstructing:
                # another getter is already replaying this task: wait it out
                while tid_b in self._reconstructing:
                    self._reconstruct_cv.wait(timeout=120.0)
                return True
            self._reconstructing.add(tid_b)
            for oid in rec.oids:
                self.owned.reset_pending(oid.binary())
        try:
            self._replay(client, rec)
            for oid in rec.oids:
                self.owned.wait_entry(oid.binary(), 120.0)
        finally:
            with self._lineage_lock:
                self._reconstructing.discard(tid_b)
                self._reconstruct_cv.notify_all()
        return True

    def _replay(self, client, rec: _CallRec):
        """Resubmit a completed call (head path; bridged into the owned
        entries). The head path re-pins args and re-seals large results."""
        from ray_tpu.core.ids import ActorID

        try:
            args, kwargs = self._rec_argspecs(rec)
            promote_argspecs(client, args, kwargs)
            if rec.kind == "actor":
                ids = client.submit_actor_task(
                    actor_id=ActorID.from_hex(rec.actor_hex),
                    method_name=rec.method,
                    args=args,
                    kwargs=kwargs,
                    num_returns=rec.num_returns,
                    streaming=False,
                    options={},
                )
            else:
                ids = client.submit_task(
                    name=rec.method,
                    func_id=rec.func_id,
                    args=args,
                    kwargs=kwargs,
                    num_returns=rec.num_returns,
                    streaming=False,
                    func_blob=self.func_blobs.get(rec.func_id),
                    options={},
                )
        except BaseException as e:  # noqa: BLE001
            for oid in rec.oids:
                self.owned.complete(oid.binary(), error=e if isinstance(e, Exception) else ObjectLostError(str(e)))
            return
        self._bridge(client, ids, rec.oids)

    # -- actor routing --
    def route(self, actor_hex: str) -> ActorRoute:
        with self._routes_lock:
            r = self.routes.get(actor_hex)
            if r is None:
                r = self.routes[actor_hex] = ActorRoute()
            return r

    # -- ref-event routing (the owner half of the borrow protocol) --
    def route_ref_events(self, events: list[tuple[bytes, bool]]) -> list[tuple[bytes, bool]]:
        """Split this process's local-count transitions: events for objects
        WE own are applied locally; events for remote-owned objects queue
        to their owner; the rest go to the head (returned)."""
        head_events = []
        to_owner: dict[str, list] = {}
        for k, reg in events:
            if self.owned.owns(k):
                if reg:
                    self.owned.on_local_reregister(k)
                else:
                    self.owned.on_local_zero(k)
                continue
            if k in self.owned:  # REDIRECT: head-owned now
                if not reg:
                    from ray_tpu.core.object_ref import local_ref_count

                    if local_ref_count(ObjectID(k)) == 0:
                        self.owned.drop_redirect(k)
                        self.forget_lineage(k)
                head_events.append((k, reg))
                continue
            owner = get_hint(k)
            if owner is not None:
                to_owner.setdefault(owner, []).append((k, reg))
                if not reg:
                    from ray_tpu.core.object_ref import local_ref_count

                    if local_ref_count(ObjectID(k)) == 0:
                        drop_hint(k)
                continue
            head_events.append((k, reg))
        if to_owner:
            with self._orq_lock:
                for owner, evs in to_owner.items():
                    self._owner_ref_queues.setdefault(owner, []).extend(evs)
        return head_events

    def _flush_owner_refs(self):
        with self._orq_lock:
            queues, self._owner_ref_queues = self._owner_ref_queues, {}
        for owner, evs in queues.items():
            try:
                self.get_conn(hint_addr(owner)).send({"op": "ref", "events": evs})
            except Exception:
                pass  # owner gone: its objects died with it

    # -- leases --
    def acquire_lease(self) -> Lease | None:
        client = self.client
        try:
            info = client.lease_worker()
        except Exception:
            return None
        if not info:
            return None
        try:
            conn = self.get_conn(tuple(info["addr"]))
        except Exception:
            try:
                client.release_lease(info["wid"])
            except Exception:
                pass
            return None
        lease = Lease(info["wid"], tuple(info["addr"]), conn)
        with self._leases_lock:
            self.leases.append(lease)
        return lease

    def pick_lease(self) -> Lease | None:
        self._lease_last_used = time.monotonic()
        with self._leases_lock:
            live = [l for l in self.leases if not l.conn.dead]
            self.leases = live
            if live:
                best = min(live, key=lambda l: l.conn.inflight)
                if best.conn.inflight < 64 or len(live) >= 8:
                    return best
        return self.acquire_lease() or (live[0] if live else None)

    def _release_idle_leases(self):
        now = time.monotonic()
        if now - self._lease_last_used < 2.0:
            return
        with self._leases_lock:
            leases, self.leases = self.leases, []
        for l in leases:
            if l.conn.inflight > 0:
                with self._leases_lock:
                    self.leases.append(l)
                continue
            try:
                self.client.release_lease(l.wid)
            except Exception:
                pass

    # -- housekeeping --
    def _housekeeping(self):
        while not self._stopped:
            time.sleep(0.2)
            try:
                self._flush_owner_refs()
                self.owned.gc_pass()
                self._release_idle_leases()
            except Exception:
                pass

    def shutdown(self):
        self._stopped = True
        with self._leases_lock:
            leases, self.leases = self.leases, []
        for l in leases:
            try:
                self.client.release_lease(l.wid)
            except Exception:
                pass
        self._flush_owner_refs()
        if self.server is not None:
            self.server.shutdown()
        with self._conns_lock:
            conns, self._conns = list(self._conns.values()), {}
        for c in conns:
            c.close()
        self.owned.shutdown()
        with _hints_lock:
            _hints.clear()


# ---------------------------------------------------------------------------
# module-level state management
# ---------------------------------------------------------------------------
_state: DirectState | None = None


def state() -> DirectState | None:
    return _state


def attach(client, authkey: bytes | None, node_hex: str = "", serve: bool = True, exec_handler=None) -> DirectState | None:
    """Install the process-wide direct state for this client. No authkey =
    direct plane disabled (everything stays on the head path)."""
    global _state
    if _state is not None:
        _state.shutdown()
        _state = None
    from ray_tpu._config import get_config

    cfg = get_config()
    # the ownership model rides the borrow protocol: without reference
    # counting there is no owner-side GC, so fall back to the head path
    if authkey is None or not cfg.direct_calls or not cfg.object_ref_counting:
        return None
    try:
        _state = DirectState(client, authkey, node_hex=node_hex, serve=serve, exec_handler=exec_handler)
    except Exception:
        _state = None
    return _state


def detach(client):
    global _state
    if _state is not None and _state.client is client:
        _state.shutdown()
        _state = None


# ---------------------------------------------------------------------------
# submit paths (called from api.py; None return = use the head path)
# ---------------------------------------------------------------------------
def raw_eligible(args, kwargs) -> bool:
    """Fast-path eligibility: args ride the call frame as plain values (a
    single pickle for the whole frame — no per-arg Serialized/ArgSpec
    machinery, no separate blob). Top-level ObjectRefs are excluded (they
    need resolve-before-call semantics); nested ObjectRefs are fine —
    __reduce__ reports them to the active sink for pinning and carries
    their owner hints. Cloudpickle-only/oversized values are caught at
    frame-serialize time (the submit falls back to the ArgSpec path)."""
    for a in args:
        if isinstance(a, _ObjRef):
            return False
    if kwargs:
        for v in kwargs.values():
            if isinstance(v, _ObjRef):
                return False
    return True


def _dump_raw_frame(st, frame) -> tuple[bytes, list | None] | None:
    """Serialize a raw-args call frame in ONE pass, collecting nested-ref
    pins via the serialization sink. None = ineligible (unserializable
    content or too large for inline transport).

    cloudpickle, NOT plain pickle: plain pickle serializes __main__
    functions/classes BY REFERENCE, which dumps fine on the driver and
    then fails to load in the worker (whose __main__ is empty for
    stdin/REPL drivers) — cloudpickle ships them by value like the
    encoded ArgSpec path does, at C-pickler speed for plain data."""
    import cloudpickle as _cp

    from ray_tpu.core import object_ref as _oref

    sink: list = []
    token = _oref.push_ref_sink(sink)
    try:
        data = _cp.dumps(frame, protocol=5)
    except Exception:
        return None  # genuinely unserializable: ArgSpec path decides
    finally:
        _oref.pop_ref_sink(token)
    if len(data) > st.inline_threshold + 4096:
        return None
    pins = [_ObjRef(i) for i in sink] if sink else None
    return data, pins


def _direct_ok(options: dict | None) -> bool:
    o = options or {}
    if o.get("num_returns") in ("streaming", "dynamic"):
        return False
    if o.get("num_cpus") not in (None, 1, 1.0):
        return False  # a lease is exactly one CPU
    for k in ("placement_group", "scheduling_strategy", "runtime_env", "label_selector", "_node_id", "resources", "num_tpus", "memory"):
        if o.get(k):
            return False
    return True


def try_actor_call(client, actor_id, method_name: str, arg_specs, kw_specs, options: dict | None, pins=None, raw=None):
    """Direct actor call (pre-encoded ArgSpecs, or a raw pack_raw blob).
    Returns list[ObjectRef] or None (= head path). The caller OWNS the
    returns (inline results live in this process)."""
    st = _state
    if st is None or st.server is None or not _direct_ok(options):
        return None
    from ray_tpu.core import rpc_chaos

    if not rpc_chaos.apply("direct_call"):
        return None  # chaos: degrade to the head path
    actor_hex = actor_id.hex()
    route = st.route(actor_hex)
    with route.lock:
        addr = route.addr
    if addr is None:
        try:
            ep = client.actor_endpoint(actor_hex)
        except Exception:
            return None
        if not ep or not ep.get("addr"):
            return None  # api fallback marks the route head-dirty
        with route.lock:
            route.addr = tuple(ep["addr"])
            route.epoch = ep.get("epoch", 0)
            route.max_task_retries = ep.get("max_task_retries", 0)
            addr = route.addr
    # lane fence: if we sent head-lane calls to this actor since the last
    # direct call, wait for them to finish so per-caller ordering holds
    if route.head_dirty:
        try:
            rids = client.submit_actor_task(actor_id=actor_id, method_name="__ray_ready__", args=[], kwargs={}, num_returns=1, streaming=False, options={})
            client.get_object(rids[0], timeout=60.0)
        except Exception:
            pass  # actor death surfaces on the direct call below
        route.head_dirty = False
    try:
        conn = st.get_conn(addr)
    except Exception:
        with route.lock:
            route.addr = None
        return None
    nr = int((options or {}).get("num_returns", 1) or 1)
    tid = TaskID.from_random()
    oids = [ObjectID.for_task_return(tid, i) for i in range(nr)]
    frame = {
        "op": "call",
        "actor": actor_id.binary(),
        "method": method_name,
        "task": tid.binary(),
        "num_returns": nr,
        "trace": (options or {}).get("_trace_ctx"),
    }
    data = None
    if raw is not None:
        frame["cid"] = conn.reserve_cid()
        frame["argv"], frame["kwargv"] = raw
        packed = _dump_raw_frame(st, frame)
        if packed is None:
            return None  # unpicklable/oversized: ArgSpec path next
        data, pins = packed
        raw = data  # failover resubmits from this snapshot
    else:
        frame["args"] = arg_specs
        frame["kwargs"] = kw_specs
    for oid in oids:
        st.owned.create_pending(oid.binary())
    rec = _CallRec(
        "actor", actor_hex, tid, oids, method_name, None, arg_specs, kw_specs, nr,
        route.max_task_retries, (options or {}).get("_trace_ctx"), pins=pins, raw=raw,
    )
    with route.lock:
        route.inflight_recs += 1
        route.drained.clear()
    try:
        conn.send_call(rec, frame, data)
    except ConnectionError:
        # conn-death failover only covers recs that made it into _calls;
        # a conn that died BEFORE registration would leave the oids
        # PENDING forever (ray.get hangs) — fail over here instead
        if not rec.registered:
            threading.Thread(target=st._failover, args=(rec,), daemon=True).start()
        # else: failover path completes the pending entries
    return _owned_refs(st, oids)


def try_task_call(client, name: str, func_id: str, blob, arg_specs, kw_specs, options: dict | None, pins=None, raw=None):
    """Direct stateless-task submission onto a leased worker (pre-encoded
    ArgSpecs, or a raw pack_raw blob)."""
    st = _state
    if st is None or st.server is None or not _direct_ok(options):
        return None
    o = options or {}
    if o.get("retry_exceptions"):
        return None  # app-level retry policies stay on the head path
    if o.get("max_retries") == 0:
        # non-retriable tasks run head-supervised: the head pins them and
        # the OOM killer's victim policy spares them (a leased worker is
        # always a retriable victim)
        return None
    from ray_tpu.core import rpc_chaos

    if not rpc_chaos.apply("direct_call"):
        return None
    if blob is not None:
        st.func_blobs[func_id] = blob
    elif func_id not in st.func_blobs:
        return None  # no blob available to teach a leased worker
    lease = st.pick_lease()
    if lease is None:
        return None
    nr = int(o.get("num_returns", 1) or 1)
    tid = TaskID.from_random()
    oids = [ObjectID.for_task_return(tid, i) for i in range(nr)]
    frame = {
        "op": "call",
        "actor": None,
        "method": name,
        "func_id": func_id,
        "task": tid.binary(),
        "num_returns": nr,
        "trace": o.get("_trace_ctx"),
    }
    data = None
    if raw is not None:
        frame["cid"] = lease.conn.reserve_cid()
        frame["argv"], frame["kwargv"] = raw
        packed = _dump_raw_frame(st, frame)
        if packed is None:
            return None  # unpicklable/oversized: ArgSpec path next
        data, pins = packed
        raw = data  # failover resubmits from this snapshot
    else:
        frame["args"] = arg_specs
        frame["kwargs"] = kw_specs
    for oid in oids:
        st.owned.create_pending(oid.binary())
    retries = o.get("max_retries")
    if retries is None:
        retries = st.default_max_retries
    rec = _CallRec("task", None, tid, oids, name, func_id, arg_specs, kw_specs, nr, retries, o.get("_trace_ctx"), pins=pins, raw=raw)
    try:
        lease.conn.ensure_func(func_id, st.func_blobs[func_id])
        lease.conn.send_call(rec, frame, data)
    except ConnectionError:
        # ensure_func can raise before the rec is registered (and
        # send_call before registration on an already-dead conn): those
        # recs are invisible to conn-death failover — resubmit here
        if not rec.registered:
            threading.Thread(target=st._failover, args=(rec,), daemon=True).start()
        # else: failover resubmits via the head
    return _owned_refs(st, oids)


def _owned_refs(st: DirectState, oids):
    from ray_tpu.core.object_ref import ObjectRef

    return [ObjectRef(oid, owner_hint=st.self_owner) for oid in oids]


def head_lane_submit(actor_id):
    """Mark an actor's route head-dirty (a head-path call was submitted);
    drain in-flight direct calls first so ordering holds."""
    st = _state
    if st is None:
        return
    route = st.route(actor_id.hex())
    route.head_dirty = True
    if not route.drained.wait(timeout=60.0):
        pass  # best effort: a stuck direct call will also stall the actor


# ---------------------------------------------------------------------------
# owned puts
# ---------------------------------------------------------------------------
def try_put(value):
    """Owner-local put for small values. Returns (ObjectRef, None) or
    (None, Serialized) — the Serialized is handed back so the head-path
    fallback doesn't re-serialize (and its contained owned refs have been
    promoted already)."""
    st = _state
    if st is None or st.server is None:
        from ray_tpu.core.serialization import serialize

        return None, serialize(value)
    from ray_tpu._config import get_config
    from ray_tpu.core.payloads import encode_serialized
    from ray_tpu.core.serialization import serialize

    s = serialize(value)
    if s.total_size() > get_config().max_direct_call_object_size:
        promote_contained(st.client, s)
        return None, s
    payload = encode_serialized(s)
    if payload.shm is not None:
        promote_contained(st.client, s)
        return None, s
    oid = ObjectID.from_put()
    st.owned.put_ready(oid.binary(), payload, contained=list(s.contained_refs))
    from ray_tpu.core.object_ref import ObjectRef

    return ObjectRef(oid, owner_hint=st.self_owner), None


def put_owned(value) -> "ObjectRef":
    """Owner-local put with NO size cap: the large-buffer publish path.

    Regular ``put()`` keeps anything above the inline threshold
    head-owned (try_put rejects shm payloads) so bulk data survives its
    producer. This is the deliberate opposite for transient multi-MB
    state whose lifetime IS its producer's — the disaggregated KV handoff
    (llm/disagg/handoff.py): the bytes land in a shared-memory segment,
    the descriptor-bearing payload stays in THIS process's OwnedStore,
    and borrowers on the same host attach the segment without the bytes
    ever crossing a socket. Freed on last borrow-release (leak backstop:
    RT_OWNED_OBJECT_LEAK_BACKSTOP_S for borrowers that died before
    registering). The object dies with its owner — callers must treat
    ObjectLostError as \"re-produce or fail\", which is exactly the
    disagg router's retry contract."""
    st = _state
    if st is None or st.server is None:
        raise RuntimeError("put_owned needs the direct plane (call ray_tpu.init first)")
    from ray_tpu import chaos

    # chaos site (ray_tpu/chaos.py): object-plane publish faults — inert
    # single-flag check when no rule is armed
    if not chaos.apply("direct.put_owned"):
        raise RuntimeError("chaos: put_owned dropped")
    from ray_tpu.core.payloads import encode_serialized
    from ray_tpu.core.serialization import serialize

    s = serialize(value)
    payload = encode_serialized(s)
    oid = ObjectID.from_put()
    st.owned.put_ready(oid.binary(), payload, contained=list(s.contained_refs))
    from ray_tpu.core.object_ref import ObjectRef

    return ObjectRef(oid, owner_hint=st.self_owner)


# ---------------------------------------------------------------------------
# get/wait/free interception
# ---------------------------------------------------------------------------
def maybe_get_owned(obj_id: ObjectID, timeout: float | None = None, zero_copy: bool = False):
    """(handled, value) for owned / remote-owned objects; handled=False
    falls through to the caller's head path. ``zero_copy`` decodes
    shm-backed payloads as read-only views into the mapped segment (see
    get_owned_view)."""
    st = _state
    k = obj_id.binary()
    if st is not None:
        e = st.owned.entry(k)
        if e is not None:
            if e.state == PENDING:
                e = st.owned.wait_entry(k, timeout)
                if e is None:
                    # freed concurrently (internal_free / shutdown)
                    raise ObjectLostError(f"object {obj_id.hex()[:16]} was freed by its owner")
                if e.state == PENDING:
                    raise GetTimeoutError(f"get() timed out waiting for {obj_id.hex()[:16]}")
            if e.state == ERROR:
                raise e.error
            if e.state == VALUE:
                return True, e.value
            if e.state == READY:
                return True, _decode(e.payload, zero_copy=zero_copy)
            return False, None  # REDIRECT: head owns it now
    owner = get_hint(k)
    if owner is not None and st is not None:
        try:
            conn = st.get_conn(hint_addr(owner))
            # slot timeout slightly above the wire timeout so the owner's
            # own timeout reply (not ours) names the failure
            resp = conn.request_get(k, timeout)
        except (ConnectionError, OSError):
            drop_hint(k)
            raise ObjectLostError(
                f"object {obj_id.hex()[:16]}: owner process at {owner} is gone "
                "(owned objects die with their owner)"
            ) from None
        if resp.get("not_owned"):
            drop_hint(k)
            return False, None  # promoted to head meanwhile
        if resp.get("error") is not None:
            raise resp["error"]
        return True, _decode(resp["payload"], zero_copy=zero_copy)
    return False, None


def _decode(payload: Payload, zero_copy: bool = False):
    from ray_tpu.core.payloads import decode_payload

    v, _seg = decode_payload(payload, zero_copy=zero_copy)
    if isinstance(v, BaseException):
        raise v
    return v


def get_owned_view(obj_id: ObjectID, timeout: float | None = None):
    """Zero-copy get of an owned/borrowed object: shm-backed payloads
    decode as READ-ONLY views into the GC-managed segment mapping — the
    borrow path never copies the bytes (the frame carries only the shm
    descriptor; same-host borrowers attach the producer's segment). The
    mapping outlives a later owner-side unlink (POSIX shm semantics), so
    a view held past the borrow-release stays valid.

    The large-buffer read half of put_owned (disagg KV handoff fetch).
    Raises ObjectLostError for ids whose owner is gone, GetTimeoutError
    on a bounded wait; falls back to the ordinary (copying) get for ids
    this plane does not own or hint."""
    from ray_tpu import chaos

    # chaos site: owned-object loss at the borrow-get — a drop rule IS
    # the loss signal bounded-retry consumers must absorb
    if not chaos.apply("direct.get_owned_view"):
        raise ObjectLostError(f"chaos: owned object {obj_id.hex()[:16]} lost")
    handled, value = maybe_get_owned(obj_id, timeout=timeout, zero_copy=True)
    if handled:
        return value
    from ray_tpu.core import context as _context

    return _context.get_client().get_object(obj_id, timeout=timeout)


def is_owned_or_hinted(k: bytes) -> bool:
    st = _state
    if st is not None and st.owned.owns(k):
        return True
    return get_hint(k) is not None


def _owned_ready_local(k: bytes) -> bool | None:
    """Readiness from the LOCAL owned table only (dict lookup, no
    network); None = this process can't answer locally (hinted-remote or
    unknown id)."""
    st = _state
    if st is not None:
        e = st.owned.entry(k)
        if e is not None and e.state != REDIRECT:
            return e.state != PENDING
    return None


def owned_ready(k: bytes, poll_timeout: float | None = None) -> bool | None:
    """True/False readiness for an owned/hinted id; None = not ours.
    Remote-owned ids poll the owner (a borrowed ref to an in-flight
    direct result must not report ready early).

    ``poll_timeout`` set means the CALLER is deadline-bounded
    (wait_mixed passes its remaining budget): a poll timeout reports
    not-ready so a small-timeout ray.wait never blocks ~10s on one slow
    owner. Unbounded callers (executor's entry_size probe) keep the
    legacy behavior — a timed-out poll reports ready so the downstream
    get() surfaces the owner's true state instead of stalling forever on
    a blackholed host."""
    st = _state
    local = _owned_ready_local(k)
    if local is not None:
        return local
    owner = get_hint(k)
    if owner is not None:
        if st is None:
            return True
        try:
            resp = st.get_conn(hint_addr(owner)).request(
                "poll", timeout=10.0 if poll_timeout is None else poll_timeout, id=k
            )
            return bool(resp.get("ready", True))
        except GetTimeoutError:
            if poll_timeout is not None:
                return False  # slow owner: not-ready, never block past the deadline
            return True  # unbounded caller: let get() surface the owner state
        except Exception:
            return True  # owner gone: get() surfaces the real error
    return None


def wait_mixed(client, obj_ids, num_returns: int, timeout: float | None, fallback):
    """ray.wait over a mix of owned and head-tracked ids. `fallback` is the
    client's head-path wait_ready."""
    ids = list(obj_ids)
    deadline = None if timeout is None else time.monotonic() + timeout

    def _poll_t() -> float | None:
        # owner polls must respect the caller's remaining budget (a
        # ray.wait(timeout=0.1) blocking 10s per slow owner violates
        # wait semantics); floor keeps a near-expired wait from turning
        # the poll into a busy no-op. An UNBOUNDED wait passes None so
        # owned_ready keeps its legacy ready-on-poll-timeout escape — a
        # blackholed owner must not spin this loop forever, and the
        # follow-up get() surfaces the owner's true state.
        if deadline is None:
            return None
        return max(0.05, min(10.0, deadline - time.monotonic()))

    # classification is local (owned table + hint map, no network): the
    # per-id readiness POLLS belong to the loop below, where they are
    # deadline-bounded — polling here would let a slow owner eat the whole
    # budget before the wait even starts
    split = [is_owned_or_hinted(o.binary() if hasattr(o, "binary") else o) or None for o in ids]
    if all(s is None for s in split):
        return fallback(ids, num_returns, timeout)
    head_ids = [o for o, s in zip(ids, split) if s is None]
    known_ready: set = set()  # readiness is sticky: poll each id once
    delay = 0.002
    while True:
        ready, not_ready = [], []
        for o in ids:
            if o in known_ready:
                ready.append(o)
                continue
            k = o.binary() if hasattr(o, "binary") else o
            # the local owned-table check is a dict lookup and ALWAYS
            # runs — even at timeout=0, ray.wait must see an
            # already-completed local result; only the networked owner
            # poll is gated on remaining budget (one slow owner must not
            # make the round overshoot by a floor-poll per remaining id)
            s = _owned_ready_local(k)
            if s is None:
                if deadline is not None and time.monotonic() >= deadline:
                    not_ready.append(o)
                    continue
                s = owned_ready(k, poll_timeout=_poll_t())
            if s is True:
                known_ready.add(o)
                ready.append(o)
            elif s is False:
                not_ready.append(o)
        head_ready = []
        if head_ids:
            t = 0.05 if deadline is None else max(0.0, min(0.05, deadline - time.monotonic()))
            hr, _ = fallback(head_ids, len(head_ids), t)
            head_ready = hr
        ready.extend(head_ready)
        # preserve input order; cap at num_returns (ray.wait semantics:
        # extra ready refs stay in the not-ready list for the next call)
        want = min(num_returns, len(ids))
        ordered_ready = [o for o in ids if o in ready][:want]
        ordered_not = [o for o in ids if o not in ordered_ready]
        if len(ordered_ready) >= want:
            return ordered_ready, ordered_not
        if deadline is not None and time.monotonic() >= deadline:
            return ordered_ready, ordered_not
        time.sleep(delay)
        delay = min(delay * 1.5, 0.05)  # back off: long waits stop spinning


def free_owned(obj_ids) -> list:
    """Free owned ids locally / at their owner; return the rest for the
    head path."""
    st = _state
    rest = []
    owner_frees: dict[str, list] = {}
    for o in obj_ids:
        k = o.binary() if hasattr(o, "binary") else o
        if st is not None and st.owned.owns(k):
            st.owned.free(k)
            continue
        owner = get_hint(k)
        if owner is not None and st is not None:
            owner_frees.setdefault(owner, []).append(k)
            drop_hint(k)
            continue
        rest.append(o)
    for owner, ks in owner_frees.items():
        try:
            st.get_conn(hint_addr(owner)).send({"op": "free", "ids": ks})
        except Exception:
            pass
    return rest


def add_done_callback_owned(obj_id: ObjectID, cb) -> bool:
    """Wire a done callback for an owned id; returns False if not owned."""
    st = _state
    k = obj_id.binary()
    if st is None:
        return False
    e = st.owned.entry(k)
    if e is None or e.state == REDIRECT:
        if get_hint(k) is not None:
            def _fetch():
                try:
                    handled, v = maybe_get_owned(obj_id)
                    cb(v, None) if handled else cb(None, ObjectLostError("owner lost"))
                except BaseException as err:  # noqa: BLE001
                    cb(None, err)

            threading.Thread(target=_fetch, daemon=True).start()
            return True
        return False

    def _deliver():
        try:
            handled, v = maybe_get_owned(obj_id)
            if handled:
                cb(v, None)
            else:
                try:
                    cb(st.client.get_object(obj_id), None)
                except BaseException as err:  # noqa: BLE001
                    cb(None, err)
        except BaseException as err:  # noqa: BLE001
            cb(None, err)

    if not st.owned.add_callback(k, lambda: threading.Thread(target=_deliver, daemon=True).start()):
        return False
    return True


def owned_location(k: bytes) -> str | None:
    """Node hex for owned/hinted ids (locations API)."""
    st = _state
    if st is not None and st.owned.owns(k):
        return st.node_hex or None
    owner = get_hint(k)
    if owner is not None:
        return hint_node_hex(owner)
    return None


# ---------------------------------------------------------------------------
# promotion: hand an owned object to the head before a head-path submit
# ---------------------------------------------------------------------------
def promote(client, k: bytes) -> bool:
    """Move an owned object into the head store so head-side pinning,
    lineage and locations all see it. Idempotent."""
    st = _state
    if st is None:
        return False
    oid = ObjectID(k)
    if st is not None:
        e = st.owned.entry(k)
        if e is not None:
            if e.state == REDIRECT:
                return True
            if e.state == PENDING:
                e = st.owned.wait_entry(k, 120.0)
                if e is None:
                    raise ObjectLostError(f"object {oid.hex()[:16]} was freed by its owner")
            if e.state == ERROR:
                payload = _encode_err(e.error)
            elif e.state == VALUE:
                from ray_tpu.core.payloads import encode_value

                payload = encode_value(e.value, obj_id=oid)
            elif e.state == READY:
                payload = e.payload
            else:
                return False
            _put_payload(client, oid, payload)
            st.owned.complete(k, redirect=True)
            drop_hint(k)
            return True
    owner = get_hint(k)
    if owner is None:
        return False
    try:
        resp = st.get_conn(hint_addr(owner)).request("get", timeout=120.0, id=k)
    except (ConnectionError, OSError):
        drop_hint(k)
        raise ObjectLostError(f"object {oid.hex()[:16]}: owner at {owner} is gone") from None
    if resp.get("not_owned"):
        drop_hint(k)
        return True  # already at the head
    if resp.get("error") is not None:
        payload = _encode_err(resp["error"])
    else:
        payload = resp["payload"]
    _put_payload(client, oid, payload)
    drop_hint(k)
    return True


def _encode_err(err):
    from ray_tpu.core.payloads import encode_value

    return encode_value(err)


def _put_payload(client, oid: ObjectID, payload: Payload):
    if hasattr(client, "put_payload"):
        client.put_payload(oid, payload)
    else:
        client.call("put_object", obj_id=oid, payload=payload)


def promote_argspecs(client, arg_specs, kw_specs):
    """Before a head-path submit: promote every owned ref appearing as a
    top-level arg or contained inside an inline payload."""
    st = _state
    if st is None:
        return
    for a in list(arg_specs or []) + list((kw_specs or {}).values()):
        if a.ref is not None and is_owned_or_hinted(a.ref.binary()):
            promote(client, a.ref.binary())
            a.owner = None  # now head-owned; resolve via the store
        if a.payload is not None:
            for c in a.payload.contained or []:
                if is_owned_or_hinted(c.binary()):
                    promote(client, c.binary())


def promote_contained(client, serialized):
    """Promote owned refs contained in a value headed for the head store."""
    st = _state
    if st is None:
        return
    for r in serialized.contained_refs:
        if is_owned_or_hinted(r.id.binary()):
            promote(client, r.id.binary())


def try_reconstruct(client, obj_id: ObjectID) -> bool:
    """Owner-side lineage replay hook for client get paths: called when
    the head reports a head-sealed direct result lost."""
    st = _state
    if st is None:
        return False
    try:
        return st.reconstruct(client, obj_id)
    except Exception:
        return False


def cancel_owned(client, obj_id: ObjectID, force: bool = False) -> bool:
    """Cancel an in-flight direct call producing obj_id. Cooperative: the
    executing worker checks a cancelled set before starting. force=True
    additionally asks the head to terminate a LEASED worker (the direct
    analogue of cancel_task(force=True)); the conn death then fails the
    call over, where the cancelled mark turns it into a cancel error
    instead of a retry. Returns True when handled; False = not a live
    direct call of ours (caller falls through to the head path)."""
    st = _state
    if st is None:
        return False
    k = obj_id.binary()
    e = st.owned.entry(k)
    if e is None or e.state != PENDING:
        return False
    tid = obj_id.task_id().binary()
    with st._conns_lock:
        conns = list(st._conns.values())
    for c in conns:
        with c._lock:
            recs = list(c._calls.values())
        for rec in recs:
            if rec.task_id.binary() == tid:
                rec.cancelled = True
                try:
                    c.send({"op": "cancel", "task": tid})
                except Exception:
                    pass
                if force and rec.kind == "task":
                    with st._leases_lock:
                        wid = next((l.wid for l in st.leases if l.conn is c), None)
                    if wid is not None:
                        try:
                            client.terminate_leased_worker(wid)
                        except Exception:
                            pass
                return True
    return False
