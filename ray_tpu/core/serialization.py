"""Serialization: cloudpickle for closures + pickle5 out-of-band buffers.

TPU-native equivalent of the reference's serialization stack (reference:
python/ray/_private/serialization.py — cloudpickle for code, Pickle5
out-of-band buffers for zero-copy numpy, ObjectRef-in-object tracking).

Large contiguous buffers (numpy arrays, arrow buffers) are extracted
out-of-band so they can live in shared memory and be mapped zero-copy by
workers. Host-side jax.Arrays are converted to numpy on serialize.
"""

from __future__ import annotations

import pickle
from dataclasses import dataclass, field

import cloudpickle


@dataclass
class Serialized:
    header: bytes
    buffers: list = field(default_factory=list)  # list of bytes/memoryview
    # ObjectRefs found inside the object (for borrowed-ref tracking;
    # reference: reference_counter.h borrow protocol).
    contained_refs: list = field(default_factory=list)

    def total_size(self) -> int:
        return len(self.header) + sum(len(b.raw() if hasattr(b, "raw") else b) for b in self.buffers)


# exact types that cannot contain ObjectRefs or closures: the C pickler
# handles them directly and the cloudpickle sink machinery is pure
# overhead (it dominated put_small in bench_core)
_FAST_TYPES = frozenset({bytes, bytearray, str, int, float, bool, type(None)})


def serialize(obj) -> Serialized:
    t = type(obj)
    if t in _FAST_TYPES:
        return Serialized(header=pickle.dumps(obj, protocol=5))
    if t.__name__ == "ndarray" and t.__module__ == "numpy" and not obj.dtype.hasobject:
        fast_buffers: list[pickle.PickleBuffer] = []
        header = pickle.dumps(obj, protocol=5, buffer_callback=lambda b: fast_buffers.append(b) or False)
        return Serialized(header=header, buffers=[b.raw() for b in fast_buffers])
    return _serialize_general(obj)


def _serialize_general(obj) -> Serialized:
    from ray_tpu.core import object_ref as _oref

    buffers: list[pickle.PickleBuffer] = []
    contained: list = []
    _track_contained_refs(obj, contained)

    def cb(buf: pickle.PickleBuffer):
        buffers.append(buf)
        return False  # out-of-band

    # pickle-time sink: ObjectRef.__reduce__ reports every ref actually
    # serialized (incl. ones nested in arbitrary objects the pre-scan
    # cannot see) — the union drives borrow/pin bookkeeping
    sink: list = []
    token = _oref.push_ref_sink(sink)
    try:
        header = cloudpickle.dumps(obj, protocol=5, buffer_callback=cb)
    finally:
        _oref.pop_ref_sink(token)
    seen = {r.id.binary() for r in contained}
    for oid in sink:
        if oid.binary() not in seen:
            seen.add(oid.binary())
            contained.append(_oref.ObjectRef(oid))
    return Serialized(header=header, buffers=[b.raw() for b in buffers], contained_refs=contained)


def deserialize(header: bytes, buffers) -> object:
    return pickle.loads(header, buffers=buffers)


def deserialize_s(s: Serialized) -> object:
    return deserialize(s.header, s.buffers)


def _track_contained_refs(obj, out: list, depth: int = 0):
    """Complete tracking happens at pickle time: ObjectRef.__reduce__
    reports into the active serialization sink (see object_ref._REF_SINK),
    catching refs nested inside arbitrary objects. This pre-scan remains
    for the cheap shallow cases so contained_refs is populated even for
    values that skip the sink path."""
    if depth > 3:
        return
    from ray_tpu.core.object_ref import ObjectRef

    if isinstance(obj, ObjectRef):
        out.append(obj)
    elif isinstance(obj, (list, tuple, set)):
        for x in obj:
            _track_contained_refs(x, out, depth + 1)
    elif isinstance(obj, dict):
        for v in obj.values():
            _track_contained_refs(v, out, depth + 1)
