"""Serialization: cloudpickle for closures + pickle5 out-of-band buffers.

TPU-native equivalent of the reference's serialization stack (reference:
python/ray/_private/serialization.py — cloudpickle for code, Pickle5
out-of-band buffers for zero-copy numpy, ObjectRef-in-object tracking).

Large contiguous buffers (numpy arrays, arrow buffers) are extracted
out-of-band so they can live in shared memory and be mapped zero-copy by
workers. Host-side jax.Arrays are converted to numpy on serialize.
"""

from __future__ import annotations

import pickle
from dataclasses import dataclass, field

import cloudpickle


@dataclass
class Serialized:
    header: bytes
    buffers: list = field(default_factory=list)  # list of bytes/memoryview
    # ObjectRefs found inside the object (for borrowed-ref tracking;
    # reference: reference_counter.h borrow protocol).
    contained_refs: list = field(default_factory=list)

    def total_size(self) -> int:
        return len(self.header) + sum(len(b.raw() if hasattr(b, "raw") else b) for b in self.buffers)


def serialize(obj) -> Serialized:
    buffers: list[pickle.PickleBuffer] = []
    contained: list = []
    _track_contained_refs(obj, contained)

    def cb(buf: pickle.PickleBuffer):
        buffers.append(buf)
        return False  # out-of-band

    header = cloudpickle.dumps(obj, protocol=5, buffer_callback=cb)
    return Serialized(header=header, buffers=[b.raw() for b in buffers], contained_refs=contained)


def deserialize(header: bytes, buffers) -> object:
    return pickle.loads(header, buffers=buffers)


def deserialize_s(s: Serialized) -> object:
    return deserialize(s.header, s.buffers)


def _track_contained_refs(obj, out: list, depth: int = 0):
    """Best-effort scan of containers for ObjectRefs (no recursion into
    arbitrary objects — full tracking happens at pickle time via
    ObjectRef.__reduce__ hooks registered by the runtime)."""
    if depth > 3:
        return
    from ray_tpu.core.object_ref import ObjectRef

    if isinstance(obj, ObjectRef):
        out.append(obj)
    elif isinstance(obj, (list, tuple, set)):
        for x in obj:
            _track_contained_refs(x, out, depth + 1)
    elif isinstance(obj, dict):
        for v in obj.values():
            _track_contained_refs(v, out, depth + 1)
