"""RPC chaos injection for the head <-> node-agent transport.

Reference parity: src/ray/rpc/rpc_chaos.h:24 (RpcFailureManager — inject
delays/failures per RPC method via testing config). Here faults apply at
the head's transport boundary with node agents: outbound messages
(dispatch, worker control) and inbound messages (task done, worker death,
pongs) can be delayed or dropped by message type.

Since the serving-plane chaos harness landed, this module is a thin
ADAPTER over the general plane (``ray_tpu/chaos.py``): each message type
becomes a rule at site ``rpc.<msg_type>`` in the shared registry, so one
``chaos.clear()``/``chaos.seed()`` governs transport and serving faults
together (the autouse conftest fixture relies on exactly that). The
public API and the ``_rules`` view (tests assert on ``Rule.hits``) are
unchanged.

Test usage:
    from ray_tpu.core import rpc_chaos
    rpc_chaos.inject("pong", drop_prob=1.0)        # starve health checks
    rpc_chaos.inject("to_worker", delay_s=0.2)     # slow dispatch
    rpc_chaos.clear()

Determinism: drop decisions use the chaos plane's dedicated seeded RNG
(`rpc_chaos.seed(n)` == `chaos.seed(n)`).
"""

from __future__ import annotations

from ray_tpu import chaos
from ray_tpu.chaos import Rule  # noqa: F401 (compat re-export)

class _RulesView:
    """msg_type -> the live chaos Rule, derived ON EVERY ACCESS from the
    shared registry (no second copy of state, so it cannot desync: a
    rule cleared there — e.g. by a direct ``chaos.clear()``, which this
    module's docstring promises governs both planes — is instantly
    absent here too). Rule objects are the live ones, so tests' ``.hits``
    assertions keep working."""

    @staticmethod
    def _live() -> dict:
        return {k[4:]: r for k, r in chaos.rules().items() if k.startswith("rpc.")}

    def __getitem__(self, msg_type):
        return self._live()[msg_type]

    def __contains__(self, msg_type):
        return msg_type in self._live()

    def get(self, msg_type, default=None):
        return self._live().get(msg_type, default)

    def __iter__(self):
        return iter(self._live())

    def __len__(self):
        return len(self._live())

    def __bool__(self):
        return bool(self._live())

    def keys(self):
        return self._live().keys()

    def values(self):
        return self._live().values()

    def items(self):
        return self._live().items()

    def __repr__(self):
        return repr(self._live())


_rules = _RulesView()


def inject(msg_type: str, *, delay_s: float = 0.0, drop_prob: float = 0.0, max_hits: int | None = None):
    chaos.inject("rpc." + msg_type, delay_s=delay_s, drop_prob=drop_prob, max_hits=max_hits)


def clear():
    chaos.clear(prefix="rpc.")


def seed(n: int):
    chaos.seed(n)


def apply(msg_type: str) -> bool:
    """Apply chaos for one message. Returns False if the message must be
    DROPPED; sleeps inline for delay rules."""
    return chaos.apply("rpc." + msg_type)
