"""RPC chaos injection for the head <-> node-agent transport.

Reference parity: src/ray/rpc/rpc_chaos.h:24 (RpcFailureManager — inject
delays/failures per RPC method via testing config). Here faults apply at
the head's transport boundary with node agents: outbound messages
(dispatch, worker control) and inbound messages (task done, worker death,
pongs) can be delayed or dropped by message type.

Test usage:
    from ray_tpu.core import rpc_chaos
    rpc_chaos.inject("pong", drop_prob=1.0)        # starve health checks
    rpc_chaos.inject("to_worker", delay_s=0.2)     # slow dispatch
    rpc_chaos.clear()

Determinism: drop decisions use a dedicated seeded RNG so chaos tests can
be reproduced (`rpc_chaos.seed(n)`).
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass


@dataclass
class Rule:
    delay_s: float = 0.0
    drop_prob: float = 0.0
    max_hits: int | None = None  # stop applying after this many matches
    hits: int = 0


_rules: dict[str, Rule] = {}
_lock = threading.Lock()
_rng = random.Random(0)


def inject(msg_type: str, *, delay_s: float = 0.0, drop_prob: float = 0.0, max_hits: int | None = None):
    with _lock:
        _rules[msg_type] = Rule(delay_s=delay_s, drop_prob=drop_prob, max_hits=max_hits)


def clear():
    with _lock:
        _rules.clear()


def seed(n: int):
    global _rng
    with _lock:
        _rng = random.Random(n)


def apply(msg_type: str) -> bool:
    """Apply chaos for one message. Returns False if the message must be
    DROPPED; sleeps inline for delay rules."""
    with _lock:
        rule = _rules.get(msg_type)
        if rule is None:
            return True
        if rule.max_hits is not None and rule.hits >= rule.max_hits:
            return True
        rule.hits += 1
        delay = rule.delay_s
        drop = rule.drop_prob > 0 and _rng.random() < rule.drop_prob
    if delay > 0:
        time.sleep(delay)
    return not drop
