"""Worker process: task execution loop + client RPC back to the node.

TPU-native equivalent of the reference's worker stack: the execution side of
core_worker (task_execution/task_receiver.h:44, actor scheduling queues incl.
async-actor fibers in task_execution/fiber.h) plus the Cython
``execute_task`` path (python/ray/_raylet.pyx:1557,2131).

One duplex pipe connects the worker to its node manager. Inbound messages are
either task executions or responses to this worker's own client calls
(get/put/submit/...). Execution runs on a thread pool sized by the actor's
``max_concurrency`` (default 1 => strictly ordered, matching the reference's
sequential actor submit queue); ``async`` actors run coroutines on a
dedicated event loop thread.
"""

from __future__ import annotations

import asyncio
import inspect
import os
import threading
import time
import traceback
from concurrent.futures import ThreadPoolExecutor

from ray_tpu.core import context
from ray_tpu.core import direct as _direct
from ray_tpu.core.ids import ObjectID, TaskID
from ray_tpu.core.object_ref import ObjectRef, ObjectRefGenerator
from ray_tpu.core.payloads import decode_payload, encode_value
from ray_tpu.core.serialization import deserialize_s
from ray_tpu.exceptions import ActorDiedError, TaskError


class WorkerClient:
    """CoreClient implementation for worker processes: every control-plane
    operation is an RPC over the pipe to the node manager."""

    def __init__(self, conn, worker_id: str, node_id: str):
        from ray_tpu.core.ids import NodeID, WorkerID

        self.conn = conn
        self.worker_id = WorkerID.from_hex(worker_id)
        self.node_id = NodeID.from_hex(node_id)
        self.job_id = None
        self._send_lock = threading.Lock()
        self._req_lock = threading.Lock()
        self._req_seq = 0
        self._pending: dict[int, list] = {}  # req_id -> [event, ok, payload]
        self.current_task_id = None
        self.current_actor_id = None
        self.assigned_resources = {}
        self._shutdown = False
        # execution machinery (created lazily / per actor)
        self._exec_pool = ThreadPoolExecutor(max_workers=1, thread_name_prefix="rt-exec")
        self._actor_instance = None
        self._actor_loop = None  # asyncio loop thread for async actors
        self._actor_loop_lock = threading.Lock()
        self._func_cache: dict[str, object] = {}
        self._sent_funcs: set[str] = set()
        # shm mappings whose close was deferred because user code still
        # holds zero-copy views into them
        self._deferred_segs: list = []
        # streaming tasks asked to stop early (cooperative cancel: the
        # generator loop checks between items)
        self._cancelled_streams: set = set()

    # ---------------- transport ----------------
    def _send_done(self, msg: dict):
        """Task-completion send: piggybacks this process's pending ref-count
        transitions so the head registers borrows (refs deserialized during
        the task) BEFORE it releases any argument pins — closing the race
        between the async ref pump and pin release."""
        from ray_tpu.core.object_ref import drain_ref_events

        try:
            events = drain_ref_events()
            st = _direct.state()
            if st is not None:
                events = st.route_ref_events(events)  # owned events go to owners
            if events:
                msg["ref_events"] = [(k.hex(), reg) for k, reg in events]
        except Exception:
            pass
        self._send(msg)

    def _send(self, msg: dict):
        with self._send_lock:
            self.conn.send(msg)

    def _check_alive_locked(self):
        """Called under _req_lock before registering a request slot;
        subclasses whose response pump can die (DriverClient) raise here
        so no slot is ever registered with nobody left to complete it."""

    def call(self, method: str, timeout: float | None = None, _kind: str = "req", **params):
        with self._req_lock:
            self._check_alive_locked()
            self._req_seq += 1
            req_id = self._req_seq
            slot = [threading.Event(), False, None]
            self._pending[req_id] = slot
        try:
            self._send({"type": _kind, "req_id": req_id, "method": method, "params": params})
        except Exception:
            with self._req_lock:
                self._pending.pop(req_id, None)
            raise
        if not slot[0].wait(timeout=timeout):
            with self._req_lock:
                self._pending.pop(req_id, None)
            raise TimeoutError(f"worker RPC {method} timed out")
        if not slot[1]:
            raise slot[2]
        return slot[2]

    def call_agent(self, method: str, timeout: float | None = None, **params):
        """RPC answered by this node's agent (data-plane ops like pulling a
        foreign shm segment) instead of the head. Same response framing."""
        return self.call(method, timeout=timeout, _kind="agent_req", **params)

    def _fetch_remote_segment(self, desc) -> str:
        """object_store fetch hook: the node agent pulls the bytes from the
        owning node's transfer server into this node's namespace."""
        return self.call_agent("fetch_object", desc=desc, timeout=120.0)

    def _handle_resp(self, msg):
        with self._req_lock:
            slot = self._pending.pop(msg["req_id"], None)
        if slot is None:
            return
        slot[1] = msg["ok"]
        slot[2] = msg["payload"] if msg["ok"] else msg["error"]
        slot[0].set()

    # ---------------- CoreClient API ----------------
    def get_object(self, obj_id: ObjectID, timeout: float | None = None):
        from ray_tpu.exceptions import ObjectLostError

        handled, v = _direct.maybe_get_owned(obj_id, timeout)
        if handled:
            return v
        for attempt in range(3):
            try:
                payload = self.call("get_object", obj_id=obj_id, timeout_s=timeout, timeout=None)
            except ObjectLostError:
                # owner-side lineage replay for head-sealed direct results
                if _direct.try_reconstruct(self, obj_id):
                    handled, v = _direct.maybe_get_owned(obj_id, timeout)
                    if handled:
                        return v
                    continue
                raise
            try:
                value, seg = decode_payload(payload, zero_copy=True)
            except FileNotFoundError:
                # shm backing raced an eviction; tell the owner and retry
                # (lineage reconstruction will re-produce it)
                self.call("mark_object_lost", obj_id=obj_id)
                continue
            if isinstance(value, BaseException):
                raise value
            return value
        raise FileNotFoundError(f"object {obj_id.hex()[:16]} backing store repeatedly lost")

    def put_object(self, value) -> ObjectRef:
        ref, s = _direct.try_put(value)
        if ref is not None:
            return ref
        from ray_tpu.core.payloads import encode_serialized

        obj_id = ObjectID.from_put()
        payload = encode_serialized(s, obj_id=obj_id)
        self.call("put_object", obj_id=obj_id, payload=payload)
        return ObjectRef(obj_id)

    def put_payload(self, obj_id: ObjectID, payload):
        self.call("put_object", obj_id=obj_id, payload=payload)

    def wait_ready(self, obj_ids, num_returns=1, timeout=None, fetch_local=True):
        return _direct.wait_mixed(
            self, list(obj_ids), num_returns, timeout,
            lambda ids, nr, t: self.call("wait_ready", obj_ids=list(ids), num_returns=nr, timeout_s=t, timeout=None),
        )

    def add_done_callback(self, obj_id, cb):
        if _direct.add_done_callback_owned(obj_id, cb):
            return

        # Poll-free callback support for workers: run a waiter thread.
        def _wait():
            try:
                v = self.get_object(obj_id)
                cb(v, None)
            except BaseException as e:  # noqa: BLE001
                cb(None, e)

        threading.Thread(target=_wait, daemon=True).start()

    def submit_task(self, **payload):
        return self.call("submit_task", **payload)

    def create_actor(self, **payload):
        return self.call("create_actor", **payload)

    def submit_actor_task(self, **payload):
        return self.call("submit_actor_task", **payload)

    def kill_actor(self, actor_id, no_restart=True):
        return self.call("kill_actor", actor_id=actor_id, no_restart=no_restart)

    def cancel_task(self, obj_id, force=False):
        return self.call("cancel_task", obj_id=obj_id, force=force)

    def get_actor_handle_info(self, name, namespace="default"):
        return self.call("get_actor_handle_info", name=name, namespace=namespace)

    def next_generator_item(self, gen_id, index, timeout=None):
        oid = self.call("next_generator_item", gen_id=gen_id, index=index, timeout_s=timeout, timeout=None)
        return ObjectRef(oid) if oid is not None else None

    def free_objects(self, obj_ids):
        rest = _direct.free_owned(list(obj_ids))
        if not rest:
            return
        try:
            self.call("free_objects", obj_ids=rest)
        except Exception:
            pass

    # ---------------- direct-plane head RPCs ----------------
    def actor_endpoint(self, actor_hex: str):
        return self.call("actor_endpoint", actor_id=actor_hex)

    def lease_worker(self):
        return self.call("lease_worker")

    def release_lease(self, wid: str):
        return self.call("release_lease", wid=wid)

    def terminate_leased_worker(self, wid: str):
        return self.call("terminate_leased_worker", wid=wid)

    def object_locations(self, obj_ids) -> dict:
        ids = list(obj_ids)
        out = {}
        rest = []
        for o in ids:
            loc = _direct.owned_location(o.binary())
            if loc is not None or _direct.is_owned_or_hinted(o.binary()):
                out[o.hex()] = loc
            else:
                rest.append(o)
        if rest:
            out.update(self.call("object_locations", obj_ids=rest))
        return out

    def cluster_info(self, kind: str):
        return self.call("cluster_info", kind=kind)

    def kv(self, op: str, **kw):
        return self.call("kv", op=op, **kw)

    def pg(self, op: str, **kw):
        return self.call("pg", op=op, **kw)

    def has_function(self, func_id: str) -> bool:
        return func_id in self._sent_funcs

    def mark_function_sent(self, func_id: str):
        self._sent_funcs.add(func_id)

    def get_function(self, func_id: str):
        if func_id not in self._func_cache:
            blob = self.call("get_function", func_id=func_id)
            self._func_cache[func_id] = deserialize_s(blob)
        return self._func_cache[func_id]

    # ---------------- execution ----------------
    def _apply_env(self, env: dict | None):
        if env:
            os.environ.update({k: str(v) for k, v in env.items()})

    def _decode_args(self, arg_specs, kwarg_specs):
        args, kwargs, segs = [], {}, []

        def one(a):
            if a.ref is not None:
                if getattr(a, "owner", None):
                    # direct-plane owned argument: fetch from its owner
                    _direct.note_hint(a.ref.binary(), a.owner)
                return self.get_object(a.ref)
            try:
                v, seg = decode_payload(a.payload, zero_copy=True)
            except FileNotFoundError:
                shm = getattr(a.payload, "shm", None)
                if shm is None:
                    raise
                # the head resolved a ref into this descriptor but the
                # bytes became unpullable (transfer failures past the
                # retry budget, eviction race): recover the object id
                # from the segment name and go through the owner-mediated
                # get path, which re-pulls or reconstructs via lineage
                from ray_tpu.core.ids import ObjectID as _OID

                return self.get_object(_OID.from_hex(shm.shm_name.rsplit("_", 1)[-1]))
            if seg is not None:
                segs.append(seg)
            return v

        for a in arg_specs:
            args.append(one(a))
        for k, a in (kwarg_specs or {}).items():
            kwargs[k] = one(a)
        return args, kwargs, segs

    def _encode_returns(self, spec, value):
        """Return list of (obj_id, payload)."""
        out = []
        ids = spec_return_ids(spec)
        if spec.num_returns == 1:
            values = [value]
        else:
            values = list(value)
            if len(values) != spec.num_returns:
                raise ValueError(f"task {spec.name} returned {len(values)} values, expected {spec.num_returns}")
        for oid, v in zip(ids, values):
            out.append((oid, encode_value(v, obj_id=oid)))
        return out

    def _execute(self, msg):
        spec = msg["spec"]
        if getattr(spec, "trace_ctx", None) is not None:
            from ray_tpu.util import tracing

            # server span under the caller's submit span; nested .remote
            # calls inside the task inherit this context (one trace id
            # stitches the whole cross-process call tree)
            with tracing.span(
                f"task::{spec.name}", kind="server", parent_ctx=tuple(spec.trace_ctx),
                task_id=spec.task_id.hex(), actor=spec.actor_id.hex() if spec.actor_id else None,
            ):
                return self._execute_inner(msg)
        return self._execute_inner(msg)

    def _execute_inner(self, msg):
        spec = msg["spec"]
        self.current_task_id = spec.task_id
        self.assigned_resources = msg.get("resources", {})
        self._apply_env(msg.get("env"))
        try:
            renv = getattr(spec, "runtime_env", None)
            if renv and ("_packed_working_dir" in renv or "_packed_py_modules" in renv):
                # inside the try: a setup failure (bad archive, fetch
                # timeout, chdir error) must surface as a task error, not
                # hang the caller
                from ray_tpu.core.ids import ObjectID as _OID
                from ray_tpu.runtime_env import apply_runtime_env_in_worker

                apply_runtime_env_in_worker(renv, lambda h: self.get_object(_OID.from_hex(h)))
            if spec.is_actor_creation:
                self._create_actor_instance(spec, msg)
                self._send_done({"type": "done", "task_id": spec.task_id, "returns": [], "error": None})
                return
            if spec.actor_id is not None:
                fn = self._actor_method(spec.method_name)
            else:
                fn = self.get_function(spec.func_id)
            args, kwargs, segs = self._decode_args(msg["args"], msg.get("kwargs"))
            try:
                result = fn(*args, **kwargs)
                if inspect.iscoroutine(result):
                    if spec.streaming:
                        result = self._run_on_actor_loop(result)
                    else:
                        # async actor: complete without blocking the exec slot
                        self._complete_async(spec, result)
                        return
                if spec.streaming:
                    self._stream_generator(spec, result)
                    return
                if inspect.isgenerator(result):
                    result = list(result)
                returns = self._encode_returns(spec, result)
            finally:
                self._release_segments(segs)
                del args, kwargs
            self._send_done({"type": "done", "task_id": spec.task_id, "returns": returns, "error": None})
        except BaseException as e:  # noqa: BLE001
            err = e if isinstance(e, TaskError) else TaskError.from_exception(e, task_desc=spec.desc())
            try:
                self._send_done({"type": "done", "task_id": spec.task_id, "returns": [], "error": err})
            except Exception:
                traceback.print_exc()
                try:
                    fallback = TaskError(cause=None, tb_str=err.tb_str, task_desc=spec.desc())
                    self._send_done({"type": "done", "task_id": spec.task_id, "returns": [], "error": fallback})
                except Exception:
                    pass
        finally:
            self.current_task_id = None

    def _release_segments(self, segs):
        """Close shm mappings; views still referenced by user code defer the
        close (retried after later tasks)."""
        pending = self._deferred_segs + list(segs or [])
        self._deferred_segs = []
        import gc

        for seg in pending:
            try:
                seg.close()
            except BufferError:
                self._deferred_segs.append(seg)
        if len(self._deferred_segs) > 64:
            gc.collect()
            still = []
            for seg in self._deferred_segs:
                try:
                    seg.close()
                except BufferError:
                    still.append(seg)
            self._deferred_segs = still

    def _complete_async(self, spec, coro):
        """Run an async actor method on the actor event loop; send the done
        message from the loop's completion callback (reference: async-actor
        fibers, task_execution/fiber.h). The dispatcher's server span
        closes at handoff (its duration covers dispatch only), but its
        trace CONTEXT rides into the coroutine so nested .remote calls
        stay on the caller's trace."""
        if getattr(spec, "trace_ctx", None) is not None:
            from ray_tpu.util import tracing

            ctx = tracing._ctx()
            if ctx is not None:
                async def _with_ctx(c=coro, ctx=ctx):
                    tracing.set_context(ctx)
                    return await c

                coro = _with_ctx()
        fut = asyncio.run_coroutine_threadsafe(coro, self._get_actor_loop())

        def _cb(f):
            try:
                returns = self._encode_returns(spec, f.result())
                self._send_done({"type": "done", "task_id": spec.task_id, "returns": returns, "error": None})
            except BaseException as e:  # noqa: BLE001
                err = TaskError.from_exception(e, task_desc=spec.desc())
                try:
                    self._send_done({"type": "done", "task_id": spec.task_id, "returns": [], "error": err})
                except Exception:
                    pass

        fut.add_done_callback(_cb)

    def _stream_generator(self, spec, gen):
        index = 0
        try:
            if inspect.isasyncgen(gen):
                gen = _drain_async_gen(self._get_actor_loop(), gen)
            for item in gen:
                if spec.task_id in self._cancelled_streams:
                    # cooperative cancel (reference: streaming generator
                    # cancellation): stop producing, close the generator
                    # so its finally blocks run, end the stream cleanly
                    try:
                        gen.close()
                    except Exception:
                        pass
                    break
                oid = ObjectID.for_task_return(spec.task_id, index + 1)
                payload = encode_value(item, obj_id=oid)
                self._send({"type": "stream_item", "task_id": spec.task_id, "index": index, "obj_id": oid, "payload": payload})
                index += 1
            self._send_done({"type": "done", "task_id": spec.task_id, "returns": [], "error": None, "stream_count": index})
        except BaseException as e:  # noqa: BLE001
            err = TaskError.from_exception(e, task_desc=spec.desc())
            self._send_done({"type": "done", "task_id": spec.task_id, "returns": [], "error": err, "stream_count": index})
        finally:
            self._cancelled_streams.discard(spec.task_id)

    # ---------------- direct-plane execution ----------------
    def _direct_exec_handler(self, msg, reply, conn_funcs):
        """Server hook (core/direct.py): a peer submitted a call straight
        to this worker. Runs on the same exec lane as head-dispatched work
        so per-actor ordering and max_concurrency hold."""
        self._exec_pool.submit(self._execute_direct, msg, reply, conn_funcs)

    def _reply_direct_raw(self, msg, values, reply):
        """Fast-path reply: plain values ride the result frame as one
        pickle. Falls back (False) for cloudpickle-only or store-sized
        results."""
        import cloudpickle as _cp

        from ray_tpu._config import get_config
        from ray_tpu.core import object_ref as _oref

        sink: list = []
        token = _oref.push_ref_sink(sink)
        try:
            # cloudpickle: results may reference classes the driver only
            # knows by value (see direct._dump_raw_frame)
            data = _cp.dumps(
                {"op": "result", "cid": msg["cid"], "vals": values, "error": None},
                protocol=5,
            )
        except Exception:
            return False
        finally:
            _oref.pop_ref_sink(token)
        if len(data) > get_config().max_direct_call_object_size:
            return False
        if sink:
            self._keepalive_refs(sink)
        reply(data)
        return True

    def _buffer_task_event(self, msg, started: float, ok: bool):
        """Buffer one direct-execution span; the ref pump flushes batches
        to the head (observability parity: task_event_buffer.h)."""
        buf = getattr(self, "_task_event_buf", None)
        if buf is None:
            buf = self._task_event_buf = []
        actor = msg.get("actor")
        buf.append({
            "task": msg["task"],
            "name": msg["method"],
            "actor": actor.hex() if actor else None,
            "start": started,
            "end": time.time(),
            "ok": ok,
        })

    def _flush_task_events(self):
        buf = getattr(self, "_task_event_buf", None)
        if buf:
            events, self._task_event_buf = buf, []
            try:
                self._send({"type": "task_events", "events": events})
            except Exception:
                pass

    def _keepalive_refs(self, contained_ids, hold_s: float = 3.0):
        import collections

        ka = getattr(self, "_direct_keepalive", None)
        if ka is None:
            ka = self._direct_keepalive = collections.deque()
        now = time.monotonic()
        ka.append((now + hold_s, [ObjectRef(c) for c in contained_ids]))
        while ka and ka[0][0] < now:
            ka.popleft()

    def _prune_keepalive(self):
        """Timer-driven keepalive expiry (the append-time prune alone
        would hold the LAST call's pins for the worker's lifetime)."""
        ka = getattr(self, "_direct_keepalive", None)
        if ka:
            now = time.monotonic()
            while ka and ka[0][0] < now:
                ka.popleft()

    def _direct_fn(self, func_id: str, conn_funcs: dict):
        fn = self._func_cache.get(func_id)
        if fn is None:
            blob = conn_funcs.get(func_id)
            if blob is None:
                raise RuntimeError(f"direct call for unregistered function {func_id[:12]}")
            fn = deserialize_s(blob)
            self._func_cache[func_id] = fn
        return fn

    def _execute_direct(self, msg, reply, conn_funcs):
        trace = msg.get("trace")
        if trace is not None:
            from ray_tpu.util import tracing

            with tracing.span(
                f"task::{msg['method']}", kind="server", parent_ctx=tuple(trace),
                task_id=msg["task"].hex(),
            ):
                return self._execute_direct_inner(msg, reply, conn_funcs)
        return self._execute_direct_inner(msg, reply, conn_funcs)

    def _execute_direct_inner(self, msg, reply, conn_funcs):
        tid = TaskID(msg["task"])
        st = _direct.state()
        if st is not None and msg["task"] in st.cancelled_direct:
            st.cancelled_direct.discard(msg["task"])
            from ray_tpu.exceptions import RayTpuError

            reply({"op": "result", "cid": msg["cid"], "returns": [],
                   "error": RayTpuError(f"task {tid.hex()[:8]} was cancelled")})
            return
        self.current_task_id = tid
        started = time.time()
        ok = True
        segs = []
        try:
            if msg.get("actor") is not None:
                fn = self._actor_method(msg["method"])
            else:
                fn = self._direct_fn(msg["func_id"], conn_funcs)
            if "argv" in msg:
                # fast path: args arrived as plain values with the frame.
                # POP them out of msg: the server conn loop keeps msg
                # alive until the NEXT frame arrives, and a materialized
                # ObjectRef arg retained there would hold its borrow open
                # indefinitely on an idle connection — the owner could
                # never free (the handoff-block leak the disagg tests
                # guard against)
                args = msg.pop("argv")
                kwargs = msg.pop("kwargv", None) or {}
            else:
                args, kwargs, segs = self._decode_args(msg["args"], msg.get("kwargs"))
            try:
                result = fn(*args, **kwargs)
            finally:
                del args, kwargs
            if inspect.iscoroutine(result):
                self._complete_async_direct(msg, result, reply)
                return  # the loop callback buffers the span
            if inspect.isgenerator(result):
                result = list(result)
            self._reply_direct(msg, result, reply)
            self._buffer_task_event(msg, started, True)
        except BaseException as e:  # noqa: BLE001
            err = e if isinstance(e, TaskError) else TaskError.from_exception(
                e, task_desc=f"{msg['method']}[{tid.hex()[:8]}]"
            )
            try:
                reply({"op": "result", "cid": msg["cid"], "returns": [], "error": err})
            except Exception:
                pass
            self._buffer_task_event(msg, started, False)
        finally:
            self._release_segments(segs)
            self.current_task_id = None

    def _reply_direct(self, msg, result, reply):
        tid = TaskID(msg["task"])
        nr = msg.get("num_returns", 1)
        values = [result] if nr == 1 else list(result)
        if len(values) != nr:
            raise ValueError(f"direct call {msg['method']} returned {len(values)} values, expected {nr}")
        if self._reply_direct_raw(msg, values, reply):
            return
        returns, seals = [], []
        for i, v in enumerate(values):
            oid = ObjectID.for_task_return(tid, i)
            payload = encode_value(v, obj_id=oid)
            head_owned = payload.shm is not None
            if head_owned:
                seals.append((oid, payload))
            if payload.contained:
                # refs pickled inside the result: hold them past the reply
                # so the caller's borrow registration beats our release
                # (the direct-plane analogue of the done-piggyback ordering)
                self._keepalive_refs(payload.contained)
            returns.append((oid.binary(), payload, head_owned))
        if seals:
            # large results go to the shared store under head ownership;
            # the seal must reach the head BEFORE the caller can act on
            # the reply (pipe FIFO gives that ordering on this side; the
            # head blocks unknown-id gets until the seal arrives)
            self._send_done({"type": "seal", "items": seals})
        reply({"op": "result", "cid": msg["cid"], "returns": returns, "error": None})

    def _complete_async_direct(self, msg, coro, reply):
        started = time.time()
        fut = asyncio.run_coroutine_threadsafe(coro, self._get_actor_loop())

        def _cb(f):
            ok = True
            try:
                self._reply_direct(msg, f.result(), reply)
            except BaseException as e:  # noqa: BLE001
                ok = False
                err = e if isinstance(e, TaskError) else TaskError.from_exception(e, task_desc=msg["method"])
                try:
                    reply({"op": "result", "cid": msg["cid"], "returns": [], "error": err})
                except Exception:
                    pass
            self._buffer_task_event(msg, started, ok)

        fut.add_done_callback(_cb)

    # -- actors --
    def _create_actor_instance(self, spec, msg):
        cls = self.get_function(spec.func_id)
        args, kwargs, _ = self._decode_args(msg["args"], msg.get("kwargs"))
        self.current_actor_id = spec.actor_id
        if spec.max_concurrency > 1:
            self._exec_pool = ThreadPoolExecutor(max_workers=spec.max_concurrency, thread_name_prefix="rt-actor")
        self._actor_instance = cls(*args, **kwargs)

    def _actor_method(self, name):
        if self._actor_instance is None:
            raise ActorDiedError(reason="actor instance not created")
        if name == "__ray_terminate__":
            return self._terminate_actor
        if name == "__ray_ready__":
            return lambda: True
        if name == "__rt_device_get__":
            # device-object store export hook: any actor can serve its own
            # registered jax.Arrays to a remote consumer (experimental/
            # device_objects.py)
            from ray_tpu.experimental.device_objects import export_for_transfer

            return export_for_transfer
        if name == "__rt_chan_setup__":
            # channel-compiled DAG: bring up this actor's ring endpoints
            # and start its execution-loop thread (experimental/channels.py)
            def _chan_setup(plan):
                from ray_tpu.experimental.channels import ChannelLoopRunner

                old = getattr(self, "_chan_runner", None)
                if old is not None:
                    old.teardown()
                runner = ChannelLoopRunner(self._actor_instance, plan)
                runner.setup()
                self._chan_runner = runner
                return True

            return _chan_setup
        if name == "__rt_chan_teardown__":
            def _chan_teardown():
                runner = getattr(self, "_chan_runner", None)
                if runner is not None:
                    runner.teardown()
                    self._chan_runner = None
                return True

            return _chan_teardown
        fn = getattr(self._actor_instance, name, None)
        if fn is None:
            raise AttributeError(f"actor has no method {name!r}")
        return fn

    def _terminate_actor(self):
        self._shutdown = True
        return True

    def _get_actor_loop(self):
        # exec-pool threads (max_concurrency of them) race here; one loop only
        with self._actor_loop_lock:
            if self._actor_loop is None:
                loop = asyncio.new_event_loop()
                t = threading.Thread(target=loop.run_forever, daemon=True, name="rt-actor-loop")
                t.start()
                self._actor_loop = loop
            return self._actor_loop

    def _run_on_actor_loop(self, coro):
        fut = asyncio.run_coroutine_threadsafe(coro, self._get_actor_loop())
        return fut.result()

    # ---------------- main loop ----------------
    def _ref_pump_loop(self):
        """Flush this process's ref-count transitions to the head (the
        borrow protocol's worker half; reference_counter.h). Events for
        direct-plane owned objects are routed to their owners instead."""
        from ray_tpu._config import get_config
        from ray_tpu.core.object_ref import drain_ref_events

        interval = max(0.05, get_config().ref_counting_interval_s)
        while not self._shutdown:
            time.sleep(interval)
            self._flush_task_events()
            self._prune_keepalive()
            try:
                events = drain_ref_events()
                st = _direct.state()
                if st is not None:
                    events = st.route_ref_events(events)
                if events:
                    # one-way message on the worker pipe: FIFO with done
                    # messages, so batches can never be applied out of
                    # order relative to done-piggybacked borrows; a broken
                    # pipe means worker death, where the head drops every
                    # holder entry anyway
                    self._send({"type": "ref_events", "events": [(k.hex(), reg) for k, reg in events]})
            except Exception:
                pass

    def run(self):
        from ray_tpu._config import get_config
        from ray_tpu.core.object_ref import set_ref_counting

        if get_config().object_ref_counting:
            threading.Thread(target=self._ref_pump_loop, daemon=True, name="rt-ref-pump").start()
        else:
            set_ref_counting(False)
        ready = {"type": "ready", "worker_id": self.worker_id, "pid": os.getpid()}
        st = _direct.state()
        if st is not None and st.server is not None:
            ready["direct_addr"] = st.server.address
        self._send(ready)
        while not self._shutdown:
            try:
                msg = self.conn.recv()
            except (EOFError, OSError):
                break
            t = msg["type"]
            if t == "resp":
                self._handle_resp(msg)
            elif t == "exec":
                self._exec_pool.submit(self._execute, msg)
            elif t == "exec_inline":
                # ordered lane used for actor creation (must precede methods)
                self._execute(msg)
            elif t == "cancel_stream":
                self._cancelled_streams.add(msg["task_id"])
            elif t == "shutdown":
                break
            elif t == "ping":
                self._send({"type": "pong"})
            elif t == "stack_dump":
                # on-demand profiling attach (reference capability:
                # dashboard/modules/reporter/profile_manager.py py-spy
                # attach — here dependency-free): the recv loop is free
                # even while exec threads run user code, so live stacks
                # of a busy/stuck worker always come back
                self._send(
                    {
                        "type": "stack_dump_result",
                        "req_id": msg.get("req_id"),
                        "stacks": _format_all_stacks(),
                        "pid": os.getpid(),
                        "current_task": self.current_task_id.hex() if self.current_task_id else None,
                    }
                )
        try:
            self._exec_pool.shutdown(wait=False, cancel_futures=True)
        except Exception:
            pass
        os._exit(0)


def _format_all_stacks() -> dict:
    """{thread name: formatted stack} for every live thread."""
    import sys
    import traceback

    names = {t.ident: t.name for t in threading.enumerate()}
    out = {}
    for ident, frame in sys._current_frames().items():
        key = f"{names.get(ident, 'unknown')} ({ident})"
        out[key] = "".join(traceback.format_stack(frame))
    return out


def _drain_async_gen(loop, agen):
    """Convert an async generator to a sync iterator via the actor loop."""

    while True:
        fut = asyncio.run_coroutine_threadsafe(agen.__anext__(), loop)
        try:
            yield fut.result()
        except StopAsyncIteration:
            return


def spec_return_ids(spec):
    return [ObjectID.for_task_return(spec.task_id, i) for i in range(spec.num_returns)]


def _redirect_worker_logs(worker_id: str):
    """Tee this worker's stdout/stderr into a per-worker session log file
    (reference: worker out/err files + log_monitor.py streaming them to
    the driver). fd-level dup2 so subprocess/extension prints land too;
    the head's log monitor tails these files back to the driver tty."""
    try:
        from ray_tpu.util.state import session_dir

        d = os.path.join(session_dir(), "logs")
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, f"worker-{worker_id[:12]}.log")
        f = open(path, "ab", buffering=0)
        os.dup2(f.fileno(), 1)
        os.dup2(f.fileno(), 2)
        import sys

        sys.stdout = os.fdopen(1, "w", buffering=1)
        sys.stderr = os.fdopen(2, "w", buffering=1)
    except Exception:
        pass  # logging must never block a worker from starting


def worker_entry(conn, worker_id: str, node_id: str, env: dict | None = None):
    """Process entry point (multiprocessing target)."""
    if env:
        os.environ.update(env)
    # Honor JAX_PLATFORMS in workers even when the forkserver's interpreter
    # already imported jax with an explicit jax_platforms config (the axon
    # sitecustomize does this): forked children inherit that config, and
    # config beats the env var — so re-assert the env contract here.
    import sys as _sys

    _jp = os.environ.get("JAX_PLATFORMS")
    if _jp and "jax" in _sys.modules:
        try:
            _sys.modules["jax"].config.update("jax_platforms", _jp)
        except Exception:
            pass
    os.environ["RT_WORKER_ID"] = worker_id  # metrics flusher / log capture key
    _redirect_worker_logs(worker_id)
    # Workers must not inherit a driver-side TPU lock; JAX is imported lazily
    # by user code (reference warns likewise: train/v2/jax/jax_trainer.py:88).
    client = WorkerClient(conn, worker_id, node_id)
    from ray_tpu.core.object_store import set_fetch_hook

    set_fetch_hook(client._fetch_remote_segment)
    context.set_client(client)
    # direct call plane: serve owned objects + direct executions on this
    # worker's own socket (core/direct.py); disabled when the head did not
    # hand out a direct authkey (RT_DIRECT_CALLS=0)
    dk = os.environ.get("RT_DIRECT_AUTHKEY")
    _direct.attach(
        client,
        bytes.fromhex(dk) if dk else None,
        node_hex=node_id,
        serve=True,
        exec_handler=client._direct_exec_handler,
    )
    try:
        client.run()
    finally:
        # final observability flush: the worker's last spans (e.g. a
        # decode replica's finish span) and its last second of metric
        # increments must not die with the process
        try:
            from ray_tpu.util import tracing as _tracing

            _tracing.shutdown()
        except Exception:
            pass
        try:
            from ray_tpu.util.metrics import _registry as _metrics_registry

            _metrics_registry.flush_once()
        except Exception:
            pass
