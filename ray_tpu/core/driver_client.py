"""Driver attach: connect an external process to a running cluster.

Reference parity: ``ray.init(address="auto" | "host:port")`` — the
driver registers with the control plane and submits work against the
SHARED cluster (python/ray/_private/worker.py init address handling,
gcs_client driver registration). The reference's separate "Ray Client"
(grpc proxy, ray.init("ray://...")) is deprecated there in favor of this
direct-driver path plus job submission; this module is both in one.

TPU-native/runtime shape: the driver dials the head's AgentListener (the
same authkey-gated TCP rendezvous ``rt agent`` uses), sends a
``driver_ready`` hello, and from then on speaks the exact worker RPC
protocol (core/worker_main.WorkerClient) — put/get/submit/actors/PGs all
reuse the worker client implementation verbatim; only the recv pump
differs (drivers execute no tasks). Same-host drivers attach shm
segments zero-copy from the head namespace; object fetches ride the
head-as-agent path (_handle_agent_req_local).

Job entrypoints get this automatically: JobManager exports
``RT_HEAD_ADDRESS``/``RT_HEAD_AUTHKEY`` into the job env, so a plain
``ray_tpu.init()`` inside a submitted job attaches to the running
cluster instead of booting a private one.
"""

from __future__ import annotations

import os
import threading

from ray_tpu.core.worker_main import WorkerClient


class DriverClient(WorkerClient):
    """WorkerClient over an attached TCP channel + response pump."""

    is_driver_attach = True

    def __init__(self, conn, welcome: dict):
        super().__init__(conn, welcome["worker_id"], welcome["node_id"])
        # session addressing (shm namespaces, session dirs) keys off the
        # head's pid in this runtime
        os.environ["RT_SESSION_PID"] = str(welcome["session_pid"])
        self.namespace = welcome.get("namespace", "default")
        self._head_down = threading.Event()
        self._pump = threading.Thread(target=self._recv_loop, daemon=True, name="rt-driver-pump")
        self._pump.start()
        from ray_tpu._config import get_config
        from ray_tpu.core.object_ref import set_ref_counting

        if get_config().object_ref_counting:
            threading.Thread(target=self._ref_pump_loop, daemon=True, name="rt-ref-pump").start()
        else:
            set_ref_counting(False)
        # direct call plane: an attached driver owns its small objects and
        # calls actors/leased workers without the head in the loop
        from ray_tpu.core import direct as _direct

        dk = welcome.get("direct_authkey")
        _direct.attach(
            self,
            bytes.fromhex(dk) if dk else None,
            node_hex=welcome["node_id"],
            serve=True,
        )

    def _check_alive_locked(self):
        # Runs under the SAME lock the pump's fail-fast flush takes: a
        # slot can only be registered while the pump is still alive to
        # complete (or fail) it, closing the race where a call lands
        # between the pump's exit and its pending-flush and then waits
        # forever on a slot nobody owns.
        if self._shutdown or self._head_down.is_set():
            raise ConnectionError("driver connection to head lost")

    def _recv_loop(self):
        while not self._shutdown:
            try:
                msg = self.conn.recv()
            except (EOFError, OSError):
                break
            t = msg.get("type")
            if t == "resp":
                self._handle_resp(msg)
            elif t == "ping":
                try:
                    self._send({"type": "pong", "seq": msg.get("seq")})
                except Exception:
                    pass
            elif t == "head_shutdown":
                break
        # mark down UNDER the request lock, then flush: call() checks
        # _head_down under the same lock before registering, so no slot
        # can slip in after this flush and wait unowned forever
        with self._req_lock:
            self._head_down.set()
            self._shutdown = True
            pending = list(self._pending.values())
            self._pending.clear()
        for slot in pending:
            slot[1] = False
            slot[2] = ConnectionError("driver connection to head lost")
            slot[0].set()

    def shutdown(self):
        if self._shutdown:
            return
        self._shutdown = True
        from ray_tpu.core import direct as _direct

        _direct.detach(self)
        try:
            self._send({"type": "driver_bye"})
        except Exception:
            pass
        try:
            self.conn.close()
        except Exception:
            pass


def resolve_address(address: str) -> tuple[tuple[str, int], bytes]:
    """Resolve an init(address=...) string to ((host, port), authkey).

    - "auto": newest live session's cluster_info.json on this machine
      (reference: ray.init("auto") via the address file).
    - "host:port": authkey from RT_HEAD_AUTHKEY (hex) or, same-host, from
      cluster_info.json when the address matches.
    """
    from ray_tpu.util.state import load_latest_cluster_info

    env_key = os.environ.get("RT_HEAD_AUTHKEY", "")
    if address == "auto":
        info = load_latest_cluster_info()
        if info is None:
            raise ConnectionError("init(address='auto'): no running session found on this machine")
        return tuple(info["agent_address"]), bytes.fromhex(info["authkey"])
    host, _, port = address.rpartition(":")
    if not host or not port.isdigit():
        raise ValueError(f"init address must be 'auto' or 'host:port', got {address!r}")
    addr = (host, int(port))
    if env_key:
        return addr, bytes.fromhex(env_key)
    info = load_latest_cluster_info()
    if info is not None and tuple(info["agent_address"]) == addr:
        return addr, bytes.fromhex(info["authkey"])
    raise ConnectionError(
        f"init(address={address!r}): no authkey — set RT_HEAD_AUTHKEY (hex from the "
        "head's cluster_info.json) or run on the head's machine"
    )


def connect_driver(address: str, timeout: float = 30.0) -> DriverClient:
    from multiprocessing import connection as mp_connection

    addr, authkey = resolve_address(address)
    conn = mp_connection.Client(tuple(addr), "AF_INET", authkey=authkey)
    conn.send({"type": "driver_ready", "pid": os.getpid()})
    if not conn.poll(timeout):
        conn.close()
        raise ConnectionError(f"driver attach to {addr} timed out waiting for welcome")
    welcome = conn.recv()
    if welcome.get("type") != "driver_welcome":
        conn.close()
        raise ConnectionError(f"driver attach to {addr}: unexpected reply {welcome.get('type')!r}")
    import socket as _socket

    head_host = welcome.get("hostname")
    if head_host and head_host != _socket.gethostname():
        # the object plane of an attached driver rides the HEAD host's
        # /dev/shm namespace; from another machine every non-inline
        # put/get would fail (and could mark healthy objects lost).
        # Cross-host work goes through jobs (which run on the head host)
        # or `rt agent` nodes — refuse loudly instead of corrupting state.
        conn.close()
        raise ConnectionError(
            f"driver attach from {_socket.gethostname()!r} to head on {head_host!r}: "
            "cross-host driver attach is not supported — submit a job "
            "(JobSubmissionClient; entrypoints run on the head host) or join "
            "the machine as a node with `rt agent --address`"
        )
    return DriverClient(conn, welcome)
