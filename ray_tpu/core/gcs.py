"""Control-plane state: KV store, named actors, pubsub, job registry.

TPU-native equivalent of the reference's GCS (reference:
src/ray/gcs/gcs_server.h:98 — internal KV `gcs_kv_manager.h`, actor registry
`gcs_actor_manager.h:93`, pubsub `src/ray/pubsub/publisher.h:245`). Storage is
the in-memory table store (reference: store_client/in_memory_store_client.h:32);
a Redis-backed table store can be slotted behind the same dict interface for
fault tolerance (reference: redis_store_client.h:126).
"""

from __future__ import annotations

import fnmatch
import threading
import time
from collections import defaultdict


class KVStore:
    """Namespaced binary KV (reference: gcs_kv_manager.h InternalKV)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._data: dict[str, dict[bytes, bytes]] = defaultdict(dict)

    def put(self, key: bytes, value: bytes, overwrite: bool = True, namespace: str = "default") -> bool:
        with self._lock:
            ns = self._data[namespace]
            if not overwrite and key in ns:
                return False
            ns[key] = value
            return True

    def get(self, key: bytes, namespace: str = "default") -> bytes | None:
        with self._lock:
            return self._data[namespace].get(key)

    def delete(self, key: bytes, namespace: str = "default") -> bool:
        with self._lock:
            return self._data[namespace].pop(key, None) is not None

    def exists(self, key: bytes, namespace: str = "default") -> bool:
        with self._lock:
            return key in self._data[namespace]

    def keys(self, prefix: bytes = b"", namespace: str = "default") -> list[bytes]:
        with self._lock:
            return [k for k in self._data[namespace] if k.startswith(prefix)]


class Publisher:
    """In-process pubsub (reference: pubsub/publisher.h:245 long-poll based;
    here subscribers get direct callback fan-out)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._subs: dict[str, list] = defaultdict(list)

    def subscribe(self, channel: str, callback) -> callable:
        with self._lock:
            self._subs[channel].append(callback)

        def unsubscribe():
            with self._lock:
                try:
                    self._subs[channel].remove(callback)
                except ValueError:
                    pass

        return unsubscribe

    def publish(self, channel: str, message: dict):
        with self._lock:
            subs = list(self._subs.get(channel, ()))
        for cb in subs:
            try:
                cb(message)
            except Exception:
                pass


class EventBuffer:
    """Ring buffer of structured task/actor/node lifecycle events
    (reference: core_worker/task_event_buffer.h -> gcs/gcs_task_manager.h)."""

    def __init__(self, capacity: int = 100_000):
        self.capacity = capacity
        self._lock = threading.Lock()
        self._events: list[dict] = []

    def record(self, kind: str, **fields):
        ev = {"kind": kind, "ts": time.time(), **fields}
        with self._lock:
            self._events.append(ev)
            if len(self._events) > self.capacity:
                del self._events[: self.capacity // 10]

    def query(self, kind: str | None = None, pattern: str | None = None, limit: int = 1000) -> list[dict]:
        with self._lock:
            evs = list(self._events)
        if kind:
            evs = [e for e in evs if e["kind"] == kind]
        if pattern:
            evs = [e for e in evs if fnmatch.fnmatch(e.get("name", ""), pattern)]
        return evs[-limit:]


class Gcs:
    def __init__(self):
        self.kv = KVStore()
        self.pubsub = Publisher()
        self.events = EventBuffer()
        self._lock = threading.Lock()
        # named actor registry: (namespace, name) -> ActorID
        self.named_actors: dict[tuple, object] = {}
        self.job_counter = 0

    def register_named_actor(self, name: str, namespace: str, actor_id) -> bool:
        with self._lock:
            key = (namespace, name)
            if key in self.named_actors:
                return False
            self.named_actors[key] = actor_id
            return True

    def lookup_named_actor(self, name: str, namespace: str):
        with self._lock:
            return self.named_actors.get((namespace, name))

    def unregister_named_actor(self, name: str, namespace: str):
        with self._lock:
            self.named_actors.pop((namespace, name), None)

    def list_named_actors(self, namespace: str | None = None) -> list:
        with self._lock:
            return [
                {"name": n, "namespace": ns}
                for (ns, n) in self.named_actors
                if namespace is None or ns == namespace
            ]
