"""Control-plane state: KV store, named actors, pubsub, job registry.

TPU-native equivalent of the reference's GCS (reference:
src/ray/gcs/gcs_server.h:98 — internal KV `gcs_kv_manager.h`, actor registry
`gcs_actor_manager.h:93`, pubsub `src/ray/pubsub/publisher.h:245`). Storage is
the in-memory table store (reference: store_client/in_memory_store_client.h:32);
a Redis-backed table store can be slotted behind the same dict interface for
fault tolerance (reference: redis_store_client.h:126).
"""

from __future__ import annotations

import fnmatch
import threading
import time
from collections import defaultdict


class KVStore:
    """Namespaced binary KV (reference: gcs_kv_manager.h InternalKV),
    write-through to the pluggable table store so a persistent backend
    makes it survive head restarts (reference: redis_store_client.h:126)."""

    def __init__(self, store=None):
        import base64
        import pickle

        from ray_tpu.core.table_store import InMemoryTableStore

        self._lock = threading.Lock()
        self._persist_lock = threading.Lock()  # see put(): ordered log appends
        self._data: dict[str, dict[bytes, bytes]] = defaultdict(dict)
        self._store = store or InMemoryTableStore()
        # re-hydrate from a persistent backend. Keys/values are arbitrary
        # picklable objects (callers pass str, bytes, dicts), so the table
        # rows are pickled on both sides.
        for skey, value in self._store.all("kv").items():
            ns, _, key_b64 = skey.partition("::")
            try:
                self._data[ns][pickle.loads(base64.b64decode(key_b64))] = pickle.loads(value)
            except Exception:
                continue

    @staticmethod
    def _skey(namespace: str, key) -> str:
        import base64
        import pickle

        return f"{namespace}::{base64.b64encode(pickle.dumps(key)).decode()}"

    def put(self, key: bytes, value: bytes, overwrite: bool = True, namespace: str = "default") -> bool:
        import pickle

        # persist OUTSIDE the KV lock: with gcs_persist_path set, the
        # table-store append fsyncs per record, and holding _lock across
        # that would serialize every head KV read behind disk latency.
        # _persist_lock is chained (acquired under _lock, released after
        # the append) so log order always matches memory order; with >1
        # concurrent WRITER this degenerates to the old serialization
        # (the second writer waits inside _lock), but the common
        # single-writer case frees readers entirely.
        with self._lock:
            ns = self._data[namespace]
            if not overwrite and key in ns:
                return False
            ns[key] = value
            self._persist_lock.acquire()
        try:
            self._store.put("kv", self._skey(namespace, key), pickle.dumps(value))
        except Exception:
            pass  # unpicklable value: kept in memory only
        finally:
            self._persist_lock.release()
        return True

    def get(self, key: bytes, namespace: str = "default") -> bytes | None:
        with self._lock:
            return self._data[namespace].get(key)

    def delete(self, key: bytes, namespace: str = "default") -> bool:
        # same chained ordering as put(): a racing put's append must not
        # land AFTER this tombstone and resurrect the key on restart
        with self._lock:
            existed = self._data[namespace].pop(key, None) is not None
            if not existed:
                return False
            self._persist_lock.acquire()
        try:
            self._store.delete("kv", self._skey(namespace, key))
        finally:
            self._persist_lock.release()
        return True

    def exists(self, key: bytes, namespace: str = "default") -> bool:
        with self._lock:
            return key in self._data[namespace]

    def keys(self, prefix: bytes = b"", namespace: str = "default") -> list[bytes]:
        with self._lock:
            if not prefix:
                return list(self._data[namespace])
            # keys may be str or bytes depending on the caller; only
            # same-typed keys can match a prefix
            return [k for k in self._data[namespace] if isinstance(k, type(prefix)) and k.startswith(prefix)]


class Publisher:
    """In-process pubsub (reference: pubsub/publisher.h:245 long-poll based;
    here subscribers get direct callback fan-out)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._subs: dict[str, list] = defaultdict(list)

    def subscribe(self, channel: str, callback) -> callable:
        with self._lock:
            self._subs[channel].append(callback)

        def unsubscribe():
            with self._lock:
                try:
                    self._subs[channel].remove(callback)
                except ValueError:
                    pass

        return unsubscribe

    def publish(self, channel: str, message: dict):
        with self._lock:
            subs = list(self._subs.get(channel, ()))
        for cb in subs:
            try:
                cb(message)
            except Exception:
                pass


class EventBuffer:
    """Ring buffer of structured task/actor/node lifecycle events
    (reference: core_worker/task_event_buffer.h -> gcs/gcs_task_manager.h)."""

    def __init__(self, capacity: int = 100_000):
        self.capacity = capacity
        self._lock = threading.Lock()
        self._events: list[dict] = []

    def record(self, kind: str, **fields):
        ev = {"kind": kind, "ts": time.time(), **fields}
        with self._lock:
            self._events.append(ev)
            if len(self._events) > self.capacity:
                del self._events[: self.capacity // 10]

    def query(self, kind: str | None = None, pattern: str | None = None, limit: int = 1000) -> list[dict]:
        with self._lock:
            evs = list(self._events)
        if kind:
            evs = [e for e in evs if e["kind"] == kind]
        if pattern:
            evs = [e for e in evs if fnmatch.fnmatch(e.get("name", ""), pattern)]
        return evs[-limit:]


class Gcs:
    def __init__(self, store=None):
        from ray_tpu.core.table_store import InMemoryTableStore

        self.store = store or InMemoryTableStore()
        self.kv = KVStore(self.store)
        self.pubsub = Publisher()
        self.events = EventBuffer()
        self._lock = threading.Lock()
        # named actor registry: (namespace, name) -> ActorID
        self.named_actors: dict[tuple, object] = {}
        self.job_counter = 0

    # -- detached actor persistence (reference: gcs_actor_manager.h
    # RegisterActor persisted to the store; on GCS restart detached actors
    # are reloaded and restarted) --
    def persist_detached_actor(self, actor_id, blob: bytes):
        self.store.put("detached_actors", actor_id.hex(), blob)

    def drop_detached_actor(self, actor_id):
        self.store.delete("detached_actors", actor_id.hex())

    def load_detached_actors(self) -> dict[str, bytes]:
        return self.store.all("detached_actors")

    def register_named_actor(self, name: str, namespace: str, actor_id) -> bool:
        with self._lock:
            key = (namespace, name)
            if key in self.named_actors:
                return False
            self.named_actors[key] = actor_id
            return True

    def lookup_named_actor(self, name: str, namespace: str):
        with self._lock:
            return self.named_actors.get((namespace, name))

    def unregister_named_actor(self, name: str, namespace: str):
        with self._lock:
            self.named_actors.pop((namespace, name), None)

    def list_named_actors(self, namespace: str | None = None) -> list:
        with self._lock:
            return [
                {"name": n, "namespace": ns}
                for (ns, n) in self.named_actors
                if namespace is None or ns == namespace
            ]
