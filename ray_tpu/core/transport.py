"""Cross-node object transfer: chunked pulls of shm segments over TCP.

This is the DCN half of the object plane. Every node process (the head and
each node agent) runs an ``ObjectTransferServer`` that serves byte ranges of
the shared-memory segments living in ITS shm namespace; any other node pulls
a segment it needs in chunks and installs it in its own namespace as a local
cache. Reference semantics: the object manager's admission-controlled pulls
and chunked pushes between nodes (reference:
src/ray/object_manager/pull_manager.h:50 chunked pull orchestration,
src/ray/object_manager/push_manager.h:28 chunk windowing,
src/ray/object_manager/ownership_object_directory.h owner-directed location
lookup — here the head IS the owner directory, resolving an shm namespace to
the transfer address of the node that holds the bytes).

Design notes (TPU-first framing): the data plane stays host-to-host TCP
(DCN); device arrays never travel through here during a jitted step — GSPMD
collectives over ICI own that path. This service moves task arguments,
returns and dataset blocks between hosts.
"""

from __future__ import annotations

import os
import select
import socket
import struct
import threading
import time

_STATS_LOCK = threading.Lock()
STATS = {"pulls": 0, "pull_bytes": 0, "serves": 0, "serve_bytes": 0, "pull_errors": 0, "pull_retries": 0}


def _bump(key: str, n: int = 1):
    with _STATS_LOCK:
        STATS[key] += n


def reset_stats():
    with _STATS_LOCK:
        for k in STATS:
            STATS[k] = 0


# ---------------------------------------------------------------------------
# wire protocol: length-prefixed frames over a raw TCP socket.
#   client -> server:  HMAC-free hello: 16-byte authkey digest handshake via
#                      challenge/response (same scheme as multiprocessing's
#                      connection auth, reimplemented minimally), then one
#                      request frame: b"PULL" + u32 name_len + name bytes.
#   server -> client:  u64 total_size (or 0xFFFF..FF on error + error frame),
#                      then raw chunks until total_size bytes are sent.
# ---------------------------------------------------------------------------
_ERR = 0xFFFFFFFFFFFFFFFF


def _send_frame(sock: socket.socket, data: bytes):
    sock.sendall(struct.pack("<I", len(data)) + data)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        part = sock.recv(n - len(buf))
        if not part:
            raise ConnectionError("transfer peer closed")
        buf.extend(part)
    return bytes(buf)


def _recv_frame(sock: socket.socket) -> bytes:
    (n,) = struct.unpack("<I", _recv_exact(sock, 4))
    if n > 1 << 20:
        raise ConnectionError("oversized transfer frame")
    return _recv_exact(sock, n)


def _auth_server(sock: socket.socket, authkey: bytes):
    import hmac

    challenge = os.urandom(20)
    _send_frame(sock, challenge)
    resp = _recv_frame(sock)
    if not hmac.compare_digest(resp, hmac.new(authkey, challenge, "sha256").digest()):
        raise ConnectionError("transfer auth failed")
    _send_frame(sock, b"OK")


def _auth_client(sock: socket.socket, authkey: bytes):
    import hmac

    challenge = _recv_frame(sock)
    _send_frame(sock, hmac.new(authkey, challenge, "sha256").digest())
    if _recv_frame(sock) != b"OK":
        raise ConnectionError("transfer auth rejected")


class ObjectTransferServer:
    """Serves chunked reads of /dev/shm segments in this process's namespace.

    ``advertise_host`` is the address peers dial — it must be routable FROM
    other nodes, so a cross-host agent advertises the interface it reaches
    the head on, not the bind wildcard."""

    def __init__(self, authkey: bytes, host: str = "0.0.0.0", advertise_host: str = "127.0.0.1", chunk_bytes: int = 4 << 20, allowed_prefixes: tuple | None = None):
        self.authkey = authkey
        self.chunk_bytes = chunk_bytes
        # only serve THIS node's namespaces: an authenticated peer must not
        # be able to read /dev/shm segments of other sessions/clusters on
        # the same host (default: the process's own session tag)
        if allowed_prefixes is None:
            from ray_tpu.core.object_store import _session_tag

            allowed_prefixes = (f"rt{_session_tag()}_",)
        self.allowed_prefixes = tuple(allowed_prefixes)
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, 0))
        self._sock.listen(64)
        self.address = (advertise_host, self._sock.getsockname()[1])
        self._stopped = threading.Event()
        self._thread = threading.Thread(target=self._accept_loop, daemon=True, name="rt-transfer-srv")
        self._thread.start()

    def _accept_loop(self):
        # timeout-polling accept: close() from another thread does NOT
        # reliably wake a blocked accept() on Linux, which leaked this
        # thread on every runtime shutdown
        try:
            self._sock.settimeout(0.5)
        except OSError:
            return  # raced an immediate shutdown(): socket already closed
        while not self._stopped.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(target=self._serve_one, args=(conn,), daemon=True).start()

    def _serve_one(self, conn: socket.socket):
        """Serve PULL requests on one authenticated connection until the
        peer closes it (persistent connections: the pull-side pool reuses
        sockets across pulls, reference push/pull-manager style —
        pull_manager.h:50). Ops:
          b"PULL" + name                      -> whole segment
          b"PULLR" + u64 off + u64 len + name -> byte range (parallel pulls)
        """
        try:
            conn.settimeout(30.0)
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            try:
                conn.setsockopt(socket.SOL_SOCKET, socket.SO_SNDBUF, 8 << 20)
            except OSError:
                pass
            _auth_server(conn, self.authkey)
            while True:
                conn.settimeout(300.0)  # idle pooled conns park here
                try:
                    req = _recv_frame(conn)
                except ConnectionError:
                    return  # peer closed / retired the pooled socket
                conn.settimeout(30.0)
                head_want = None
                stat_only = False
                if req.startswith(b"PULLR"):
                    off, length = struct.unpack("<QQ", req[5:21])
                    name = req[21:].decode()
                elif req.startswith(b"PULLH"):
                    # head pull: announce the TOTAL size, then stream at
                    # most `want` bytes — small segments finish in ONE
                    # round trip, large ones learn the size for ranged
                    # sibling pulls without a wasted full-stream push
                    (head_want,) = struct.unpack("<Q", req[5:13])
                    name = req[13:].decode()
                    off, length = 0, None
                elif req.startswith(b"PULL"):
                    off, length = 0, None
                    name = req[4:].decode()
                elif req.startswith(b"STAT"):
                    name = req[4:].decode()
                    off, length, stat_only = 0, 0, True
                else:
                    raise ConnectionError(f"bad transfer op {req[:8]!r}")
                if "/" in name or not name.startswith(self.allowed_prefixes):
                    raise ConnectionError("illegal segment name")
                path = "/dev/shm/" + name
                if stat_only:
                    try:
                        conn.sendall(struct.pack("<Q", os.path.getsize(path)))
                    except OSError:
                        conn.sendall(struct.pack("<Q", _ERR))
                        _send_frame(conn, b"not found")
                    continue
                try:
                    f = open(path, "rb")
                except OSError:
                    conn.sendall(struct.pack("<Q", _ERR))
                    _send_frame(conn, b"not found")
                    continue
                with f:
                    from ray_tpu.core import rpc_chaos

                    size = os.fstat(f.fileno()).st_size
                    if head_want is not None:
                        send_size = min(head_want, size)
                        conn.sendall(struct.pack("<QQ", size, send_size))
                    elif length is None:
                        send_size = max(0, size - off)
                        conn.sendall(struct.pack("<Q", send_size))
                    else:
                        send_size = max(0, min(length, size - off))
                        conn.sendall(struct.pack("<Q", send_size))
                    f.seek(off)
                    sent = 0
                    use_sendfile = True
                    while sent < send_size:
                        if not rpc_chaos.apply("transfer_chunk"):
                            raise ConnectionError("chaos: transfer aborted mid-stream")
                        want = min(self.chunk_bytes, send_size - sent)
                        if use_sendfile:
                            # kernel path: page cache -> socket with the
                            # GIL released. socket.sendfile (not raw
                            # os.sendfile) handles the timeout socket's
                            # EAGAIN internally by waiting for
                            # writability instead of failing the window.
                            try:
                                m = conn.sendfile(f, offset=off + sent, count=want)
                                if m == 0:
                                    break
                                sent += m
                                continue
                            except OSError:
                                use_sendfile = False
                                # sendfile(offset=...) never moved f's
                                # position; resume the read fallback at
                                # the bytes actually sent
                                f.seek(off + sent)
                        chunk = f.read(want)
                        if not chunk:
                            break
                        conn.sendall(chunk)
                        sent += len(chunk)
                _bump("serves")
                _bump("serve_bytes", sent)
        except Exception:
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def shutdown(self):
        self._stopped.set()
        try:
            self._sock.close()
        except OSError:
            pass


# ---------------------------------------------------------------------------
# client side: persistent authenticated connection pool + parallel range
# pulls (reference: pull_manager.h:50 admission-controlled chunked pulls,
# push_manager.h:28 chunk windowing). The round-4 measurement showed 47ms
# per 1MB pull — fresh TCP + auth per segment, small frames without
# TCP_NODELAY (Nagle + delayed ACK). Pooled NODELAY sockets + ranged
# parallel streams fix both axes.
# ---------------------------------------------------------------------------
_PARALLEL_THRESHOLD = 16 << 20  # range-split pulls above this size
_PARALLEL_STREAMS = 4
_POOL_MAX_PER_ADDR = 6
_pool_lock = threading.Lock()
_conn_pool: dict[tuple, list] = {}  # addr -> [socket, ...]
# admission control: global cap on concurrent pull streams so a burst of
# large pulls cannot swamp the NIC/loopback (pull_manager admission)
_admission = threading.BoundedSemaphore(8)


def _pool_get(addr, authkey: bytes, timeout: float) -> socket.socket:
    addr = tuple(addr)
    with _pool_lock:
        conns = _conn_pool.get(addr)
        while conns:
            sock = conns.pop()
            return sock
    sock = socket.create_connection(addr, timeout=timeout)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    try:
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 8 << 20)
    except OSError:
        pass
    sock.settimeout(timeout)
    _auth_client(sock, authkey)
    return sock


def _pool_put(addr, sock: socket.socket):
    addr = tuple(addr)
    with _pool_lock:
        conns = _conn_pool.setdefault(addr, [])
        if len(conns) < _POOL_MAX_PER_ADDR:
            conns.append(sock)
            return
    try:
        sock.close()
    except OSError:
        pass


def _drop_pool():
    with _pool_lock:
        pools = list(_conn_pool.values())
        _conn_pool.clear()
    for conns in pools:
        for s in conns:
            try:
                s.close()
            except OSError:
                pass


def pull_segment(addr, authkey: bytes, src_name: str, dst_name: str, timeout: float = 60.0, retries: int = 2) -> int:
    """Pull segment ``src_name`` from the transfer server at ``addr`` and
    install it atomically as /dev/shm/``dst_name``. Returns byte count.

    Transient transport failures (reset, truncation, timeout) RETRY with
    backoff before surfacing: a network blip on a large expensive block
    must not force a full lineage recompute. Only an authoritative
    peer-side not-found — or exhausted retries — raises FileNotFoundError
    (which callers treat as object-lost -> reconstruction)."""
    if os.path.exists("/dev/shm/" + dst_name):
        return os.path.getsize("/dev/shm/" + dst_name)
    last: Exception | None = None
    for attempt in range(retries + 1):
        try:
            return _pull_once(addr, authkey, src_name, dst_name, timeout)
        except FileNotFoundError:
            raise  # peer says gone: retrying cannot help
        except (ConnectionError, socket.timeout, OSError) as e:
            _bump("pull_errors")
            _drop_addr(addr)  # siblings of a broken conn are suspect too
            last = e
            if attempt < retries:
                _bump("pull_retries")
                time.sleep(0.1 * (attempt + 1))
    raise FileNotFoundError(
        f"pull of {src_name} from {addr} failed after {retries + 1} attempts: {last}"
    ) from None


def _recv_to_file(sock: socket.socket, fd: int, file_off: int, length: int) -> int:
    """Stream exactly ``length`` socket bytes into ``fd`` at ``file_off``.
    Kernel path (socket -> pipe -> file via splice: zero userspace copies,
    GIL released per ~1MB window) with a recv_into/pwrite fallback."""
    got = 0
    if hasattr(os, "splice"):
        pr = pw = -1
        consumed_any = False  # bytes left the SOCKET (possibly into the pipe)
        try:
            pr, pw = os.pipe()
            try:
                import fcntl

                fcntl.fcntl(pw, 1031, 1 << 20)  # F_SETPIPE_SZ
            except OSError:
                pass
            while got < length:
                try:
                    n = os.splice(sock.fileno(), pw, min(1 << 20, length - got))
                except BlockingIOError:
                    # the Python-level socket timeout puts the fd in
                    # non-blocking mode, so a momentarily-empty receive
                    # buffer surfaces as EAGAIN — routine mid-stream on
                    # real networks, NOT a transport failure. The stream
                    # offset is well-defined here (nothing left the
                    # socket): wait for readability and resume. poll(),
                    # not select(): a busy head can sit above FD_SETSIZE
                    # and select() would raise ValueError there.
                    waiter = select.poll()
                    waiter.register(sock, select.POLLIN)
                    t = sock.gettimeout()
                    if not waiter.poll(None if t is None else max(0, int(t * 1000))):
                        raise socket.timeout(
                            "splice read stalled past the socket timeout"
                        ) from None
                    continue
                except OSError:
                    if consumed_any:
                        raise ConnectionError("splice transfer failed mid-stream") from None
                    break  # first socket splice unsupported: clean fallback
                if n == 0:
                    raise ConnectionError("transfer truncated")
                consumed_any = True
                moved = 0
                while moved < n:
                    # any failure past this point strands bytes in the
                    # pipe — the stream offset is unknowable, so the pull
                    # (and its pooled socket) must fail, never fall back
                    try:
                        moved += os.splice(pr, fd, n - moved, offset_dst=file_off + got + moved)
                    except OSError:
                        raise ConnectionError("splice pipe drain failed mid-stream") from None
                got += n
            else:
                return got
        finally:
            for p in (pr, pw):
                if p >= 0:
                    try:
                        os.close(p)
                    except OSError:
                        pass
    buf = bytearray(min(max(length - got, 1), 4 << 20))
    mv = memoryview(buf)
    while got < length:
        n = sock.recv_into(mv[: min(len(mv), length - got)])
        if not n:
            raise ConnectionError("transfer truncated")
        os.pwrite(fd, mv[:n], file_off + got)
        got += n
    return got


def _drop_addr(addr):
    """Discard pooled sockets to a peer after a transport error: siblings
    of a broken connection are usually broken too (server restart)."""
    with _pool_lock:
        conns = _conn_pool.pop(tuple(addr), [])
    for s in conns:
        try:
            s.close()
        except OSError:
            pass


def _pull_once(addr, authkey: bytes, src_name: str, dst_name: str, timeout: float) -> int:
    tmp = f"/dev/shm/{dst_name}.t{os.getpid()}.{threading.get_ident()}"
    sock = _pool_get(addr, authkey, timeout)
    pooled = False
    try:
        sock.settimeout(timeout)
        # ONE round trip: PULLH streams up to the parallel threshold and
        # announces the total, so small segments finish immediately and
        # large ones learn the size for ranged sibling pulls with no
        # wasted full-stream push
        _send_frame(sock, b"PULLH" + struct.pack("<Q", _PARALLEL_THRESHOLD) + src_name.encode())
        (total,) = struct.unpack("<Q", _recv_exact(sock, 8))
        if total == _ERR:
            err = _recv_frame(sock)
            _bump("pull_errors")
            _pool_put(addr, sock)
            pooled = True
            raise FileNotFoundError(f"remote segment {src_name}: {err.decode()}")
        (sending,) = struct.unpack("<Q", _recv_exact(sock, 8))
        with _admission:
            fd = os.open(tmp, os.O_WRONLY | os.O_CREAT, 0o600)
            try:
                os.ftruncate(fd, total)
                got = _recv_to_file(sock, fd, 0, sending)
            finally:
                os.close(fd)
        _pool_put(addr, sock)
        pooled = True
        if total > sending:
            got += _pull_parallel(addr, authkey, src_name, tmp, sending, total, timeout)
        os.rename(tmp, "/dev/shm/" + dst_name)
        _bump("pulls")
        _bump("pull_bytes", got)
        return got
    finally:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        if not pooled:
            try:
                sock.close()
            except OSError:
                pass


def _pull_parallel(addr, authkey: bytes, src_name: str, tmp: str, start: int, size: int, timeout: float) -> int:
    """Pull [start, size) of a segment as parallel ranged streams over
    pooled connections (admission-controlled; reference pull_manager
    windowing). The file already holds [0, start)."""
    nstreams = _PARALLEL_STREAMS
    todo = size - start
    part = (todo + nstreams - 1) // nstreams
    ranges = [
        (start + i * part, min(part, todo - i * part)) for i in range(nstreams) if i * part < todo
    ]
    fd = os.open(tmp, os.O_WRONLY)
    errors: list = []
    try:
        def fetch_range(off, length, sock=None):
            own = sock is None
            with _admission:
                try:
                    if own:
                        sock = _pool_get(addr, authkey, timeout)
                        _send_frame(sock, b"PULLR" + struct.pack("<QQ", off, length) + src_name.encode())
                        (announced,) = struct.unpack("<Q", _recv_exact(sock, 8))
                        if announced == _ERR:
                            _recv_frame(sock)
                            raise FileNotFoundError(f"remote segment {src_name} vanished mid-pull")
                        if announced != length:
                            raise ConnectionError("range size mismatch")
                    _recv_to_file(sock, fd, off, length)
                    if own:
                        _pool_put(addr, sock)
                        sock = None
                finally:
                    if own and sock is not None:
                        try:
                            sock.close()
                        except OSError:
                            pass

        threads = []
        try:
            for off, length in ranges[1:]:
                t = threading.Thread(target=lambda o=off, l=length: _capture(errors, fetch_range, o, l), daemon=True)
                t.start()
                threads.append(t)
            fetch_range(ranges[0][0], ranges[0][1])
        finally:
            # join BEFORE the fd closes below: a failed range must not
            # leave siblings writing into a recycled fd number. The join
            # is transitively bounded: every sibling socket op carries
            # the pull timeout, so an unbounded join here cannot outlive
            # the siblings' own deadlines.
            for t in threads:
                t.join()  # tpulint: disable=TPL006
    finally:
        os.close(fd)
    if errors:
        raise errors[0]
    return todo  # bytes THIS call transferred (the caller holds [0, start))


def _capture(errors: list, fn, *a):
    try:
        fn(*a)
    except BaseException as e:  # noqa: BLE001
        errors.append(e)
