"""Cross-node object transfer: chunked pulls of shm segments over TCP.

This is the DCN half of the object plane. Every node process (the head and
each node agent) runs an ``ObjectTransferServer`` that serves byte ranges of
the shared-memory segments living in ITS shm namespace; any other node pulls
a segment it needs in chunks and installs it in its own namespace as a local
cache. Reference semantics: the object manager's admission-controlled pulls
and chunked pushes between nodes (reference:
src/ray/object_manager/pull_manager.h:50 chunked pull orchestration,
src/ray/object_manager/push_manager.h:28 chunk windowing,
src/ray/object_manager/ownership_object_directory.h owner-directed location
lookup — here the head IS the owner directory, resolving an shm namespace to
the transfer address of the node that holds the bytes).

Design notes (TPU-first framing): the data plane stays host-to-host TCP
(DCN); device arrays never travel through here during a jitted step — GSPMD
collectives over ICI own that path. This service moves task arguments,
returns and dataset blocks between hosts.
"""

from __future__ import annotations

import os
import socket
import struct
import threading
import time

_STATS_LOCK = threading.Lock()
STATS = {"pulls": 0, "pull_bytes": 0, "serves": 0, "serve_bytes": 0, "pull_errors": 0, "pull_retries": 0}


def _bump(key: str, n: int = 1):
    with _STATS_LOCK:
        STATS[key] += n


def reset_stats():
    with _STATS_LOCK:
        for k in STATS:
            STATS[k] = 0


# ---------------------------------------------------------------------------
# wire protocol: length-prefixed frames over a raw TCP socket.
#   client -> server:  HMAC-free hello: 16-byte authkey digest handshake via
#                      challenge/response (same scheme as multiprocessing's
#                      connection auth, reimplemented minimally), then one
#                      request frame: b"PULL" + u32 name_len + name bytes.
#   server -> client:  u64 total_size (or 0xFFFF..FF on error + error frame),
#                      then raw chunks until total_size bytes are sent.
# ---------------------------------------------------------------------------
_ERR = 0xFFFFFFFFFFFFFFFF


def _send_frame(sock: socket.socket, data: bytes):
    sock.sendall(struct.pack("<I", len(data)) + data)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        part = sock.recv(n - len(buf))
        if not part:
            raise ConnectionError("transfer peer closed")
        buf.extend(part)
    return bytes(buf)


def _recv_frame(sock: socket.socket) -> bytes:
    (n,) = struct.unpack("<I", _recv_exact(sock, 4))
    if n > 1 << 20:
        raise ConnectionError("oversized transfer frame")
    return _recv_exact(sock, n)


def _auth_server(sock: socket.socket, authkey: bytes):
    import hmac

    challenge = os.urandom(20)
    _send_frame(sock, challenge)
    resp = _recv_frame(sock)
    if not hmac.compare_digest(resp, hmac.new(authkey, challenge, "sha256").digest()):
        raise ConnectionError("transfer auth failed")
    _send_frame(sock, b"OK")


def _auth_client(sock: socket.socket, authkey: bytes):
    import hmac

    challenge = _recv_frame(sock)
    _send_frame(sock, hmac.new(authkey, challenge, "sha256").digest())
    if _recv_frame(sock) != b"OK":
        raise ConnectionError("transfer auth rejected")


class ObjectTransferServer:
    """Serves chunked reads of /dev/shm segments in this process's namespace.

    ``advertise_host`` is the address peers dial — it must be routable FROM
    other nodes, so a cross-host agent advertises the interface it reaches
    the head on, not the bind wildcard."""

    def __init__(self, authkey: bytes, host: str = "0.0.0.0", advertise_host: str = "127.0.0.1", chunk_bytes: int = 1 << 20, allowed_prefixes: tuple | None = None):
        self.authkey = authkey
        self.chunk_bytes = chunk_bytes
        # only serve THIS node's namespaces: an authenticated peer must not
        # be able to read /dev/shm segments of other sessions/clusters on
        # the same host (default: the process's own session tag)
        if allowed_prefixes is None:
            from ray_tpu.core.object_store import _session_tag

            allowed_prefixes = (f"rt{_session_tag()}_",)
        self.allowed_prefixes = tuple(allowed_prefixes)
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, 0))
        self._sock.listen(64)
        self.address = (advertise_host, self._sock.getsockname()[1])
        self._stopped = threading.Event()
        self._thread = threading.Thread(target=self._accept_loop, daemon=True, name="rt-transfer-srv")
        self._thread.start()

    def _accept_loop(self):
        # timeout-polling accept: close() from another thread does NOT
        # reliably wake a blocked accept() on Linux, which leaked this
        # thread on every runtime shutdown
        try:
            self._sock.settimeout(0.5)
        except OSError:
            return  # raced an immediate shutdown(): socket already closed
        while not self._stopped.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(target=self._serve_one, args=(conn,), daemon=True).start()

    def _serve_one(self, conn: socket.socket):
        try:
            conn.settimeout(30.0)
            _auth_server(conn, self.authkey)
            req = _recv_frame(conn)
            if not req.startswith(b"PULL"):
                raise ConnectionError(f"bad transfer op {req[:8]!r}")
            name = req[4:].decode()
            if "/" in name or not name.startswith(self.allowed_prefixes):
                raise ConnectionError("illegal segment name")
            path = "/dev/shm/" + name
            try:
                f = open(path, "rb")
            except OSError:
                conn.sendall(struct.pack("<Q", _ERR))
                _send_frame(conn, b"not found")
                return
            with f:
                from ray_tpu.core import rpc_chaos

                size = os.fstat(f.fileno()).st_size
                conn.sendall(struct.pack("<Q", size))
                sent = 0
                while sent < size:
                    if not rpc_chaos.apply("transfer_chunk"):
                        raise ConnectionError("chaos: transfer aborted mid-stream")
                    chunk = f.read(min(self.chunk_bytes, size - sent))
                    if not chunk:
                        break
                    conn.sendall(chunk)
                    sent += len(chunk)
            _bump("serves")
            _bump("serve_bytes", sent)
        except Exception:
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def shutdown(self):
        self._stopped.set()
        try:
            self._sock.close()
        except OSError:
            pass


def pull_segment(addr, authkey: bytes, src_name: str, dst_name: str, timeout: float = 60.0, retries: int = 2) -> int:
    """Pull segment ``src_name`` from the transfer server at ``addr`` and
    install it atomically as /dev/shm/``dst_name``. Returns byte count.

    Transient transport failures (reset, truncation, timeout) RETRY with
    backoff before surfacing: a network blip on a large expensive block
    must not force a full lineage recompute. Only an authoritative
    peer-side not-found — or exhausted retries — raises FileNotFoundError
    (which callers treat as object-lost -> reconstruction)."""
    if os.path.exists("/dev/shm/" + dst_name):
        return os.path.getsize("/dev/shm/" + dst_name)
    last: Exception | None = None
    for attempt in range(retries + 1):
        try:
            return _pull_once(addr, authkey, src_name, dst_name, timeout)
        except FileNotFoundError:
            raise  # peer says gone: retrying cannot help
        except (ConnectionError, socket.timeout, OSError) as e:
            _bump("pull_errors")
            last = e
            if attempt < retries:
                _bump("pull_retries")
                time.sleep(0.1 * (attempt + 1))
    raise FileNotFoundError(
        f"pull of {src_name} from {addr} failed after {retries + 1} attempts: {last}"
    ) from None


def _pull_once(addr, authkey: bytes, src_name: str, dst_name: str, timeout: float) -> int:
    sock = socket.create_connection(tuple(addr), timeout=timeout)
    tmp = f"/dev/shm/{dst_name}.t{os.getpid()}.{threading.get_ident()}"
    try:
        sock.settimeout(timeout)
        _auth_client(sock, authkey)
        _send_frame(sock, b"PULL" + src_name.encode())
        (size,) = struct.unpack("<Q", _recv_exact(sock, 8))
        if size == _ERR:
            err = _recv_frame(sock)
            _bump("pull_errors")
            raise FileNotFoundError(f"remote segment {src_name}: {err.decode()}")
        got = 0
        with open(tmp, "wb") as f:
            while got < size:
                part = sock.recv(min(1 << 20, size - got))
                if not part:
                    raise ConnectionError("transfer truncated")
                f.write(part)
                got += len(part)
        os.rename(tmp, "/dev/shm/" + dst_name)
        _bump("pulls")
        _bump("pull_bytes", got)
        return got
    finally:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        try:
            sock.close()
        except OSError:
            pass
