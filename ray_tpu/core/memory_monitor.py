"""Memory monitor + worker killing policy.

Reference parity: src/ray/common/memory_monitor.h (periodic usage vs
threshold from /proc) + src/ray/raylet/worker_killing_policy.h (pick a
victim; prefer retriable, then newest). The round-1 review flagged the
absence: a fat map_batches could OOM the whole single-process control
plane. Here a head-side thread samples system memory; above the
threshold it SIGKILLs the worker with the largest RSS whose tasks are
retriable, so the job degrades to retries instead of the OS OOM-killer
shooting the head.
"""

from __future__ import annotations

import logging
import os
import threading
import time

logger = logging.getLogger(__name__)


def system_memory() -> tuple[int, int]:
    """(available_bytes, total_bytes) from /proc/meminfo; cgroup v2 limits
    win when tighter (containers). (0, 0) where /proc is unavailable —
    the monitor disables itself."""
    total = avail = 0
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    total = int(line.split()[1]) * 1024
                elif line.startswith("MemAvailable:"):
                    avail = int(line.split()[1]) * 1024
    except OSError:
        return 0, 0
    try:
        with open("/sys/fs/cgroup/memory.max") as f:
            raw = f.read().strip()
        if raw != "max":
            limit = int(raw)
            if 0 < limit < total:
                with open("/sys/fs/cgroup/memory.current") as f:
                    used = int(f.read())
                return max(0, limit - used), limit
    except OSError:
        pass
    return avail, total


def proc_rss(pid: int) -> int:
    try:
        with open(f"/proc/{pid}/statm") as f:
            return int(f.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")
    except (OSError, IndexError, ValueError):
        return 0


class MemoryMonitor:
    #: minimum seconds between kills — lets the previous victim actually
    #: die and memory recover before re-evaluating (the reference policy
    #: likewise serializes kills)
    KILL_COOLDOWN_S = 2.0

    def __init__(self, runtime):
        self.rt = runtime
        self.cfg = runtime.cfg
        self._stopped = threading.Event()
        self.kills = 0
        self._last_victim = None
        self._last_kill_ts = 0.0

    def start(self):
        if self.cfg.memory_monitor_refresh_ms <= 0:
            return self
        if system_memory() == (0, 0):
            logger.info("memory monitor disabled: /proc/meminfo unavailable")
            return self
        threading.Thread(target=self._loop, daemon=True, name="rt-memory-monitor").start()
        return self

    def stop(self):
        self._stopped.set()

    def _loop(self):
        period = self.cfg.memory_monitor_refresh_ms / 1000.0
        while not self._stopped.wait(period):
            try:
                self.check_once()
            except Exception:
                logger.exception("memory monitor error")

    def usage_fraction(self) -> float:
        avail, total = system_memory()
        if total <= 0:
            return 0.0
        return 1.0 - avail / total

    def check_once(self):
        frac = self.usage_fraction()
        if frac < self.cfg.memory_usage_threshold:
            return
        # serialize kills: wait out the cooldown AND the previous victim's
        # actual death before choosing again (otherwise sustained pressure
        # burns a retry every refresh tick, or re-picks the dying worker)
        if self._last_victim is not None:
            if time.monotonic() - self._last_kill_ts < self.KILL_COOLDOWN_S:
                return
            if self._last_victim.state not in ("dead",) and self._last_victim.alive():
                return
            self._last_victim = None
        victim = self._pick_victim()
        if victim is None:
            return
        node, w, rss = victim
        self.kills += 1
        self._last_victim = w
        self._last_kill_ts = time.monotonic()
        logger.warning(
            "memory usage %.1f%% >= %.0f%%: killing worker %s (rss=%dMB) to free memory",
            frac * 100,
            self.cfg.memory_usage_threshold * 100,
            w.worker_id.hex()[:8],
            rss >> 20,
        )
        self.rt.gcs.events.record(
            "worker_oom_killed", worker_id=w.worker_id.hex(), rss=rss, usage=frac
        )
        try:
            w.proc.terminate()
        except Exception:
            pass

    def _pick_victim(self):
        """Largest-RSS busy/leased worker whose running tasks are all
        retriable (worker_killing_policy: prefer retriable, spare actors).
        Leased workers (direct call plane) are always retriable victims:
        non-retriable tasks never take the lease path (api.py routes
        max_retries=0 through the head), and killing a leased worker makes
        the callers' failover resubmit its in-flight calls."""
        best = None
        for node in self.rt.node_list():
            for w in list(node.workers.values()):
                if w.state not in ("busy", "leased"):
                    continue
                if w.state == "busy":
                    specs = [s for s, _ in w.running_tasks.values()]
                    if not specs or not all(s.max_retries > 0 for s in specs):
                        continue
                pid = getattr(w.proc, "pid", None)
                if not pid:
                    continue
                rss = proc_rss(pid)
                if best is None or rss > best[2]:
                    best = (node, w, rss)
        return best
