"""Log monitor: stream worker log files back to the driver tty.

Reference parity: python/ray/_private/log_monitor.py (tail worker
out/err files, publish lines to the driver which prints them with
``(pid=...)`` prefixes). Collapsed: one thread in the head tails every
file under <session>/logs/ and writes prefixed lines to the driver's
stderr. New files are discovered each sweep; rotated/truncated files
restart from zero.
"""

from __future__ import annotations

import os
import sys
import threading


class LogMonitor:
    def __init__(self, session_logs_dir: str, out=None, interval_s: float = 0.25):
        self.dir = session_logs_dir
        self.out = out or sys.stderr
        self.interval_s = interval_s
        self._offsets: dict[str, int] = {}
        self._stopped = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self, clean: bool = True):
        if clean:
            # session dirs are keyed by pid: a second init() in the same
            # process (or pid reuse) must not replay the old session's logs
            try:
                for name in os.listdir(self.dir):
                    if name.endswith(".log"):
                        os.unlink(os.path.join(self.dir, name))
            except OSError:
                pass
        self._thread = threading.Thread(target=self._loop, daemon=True, name="rt-log-monitor")
        self._thread.start()
        return self

    def stop(self):
        """Stop and join the poll thread (callers may then poll_once() for
        a final race-free flush)."""
        self._stopped.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)

    def _loop(self):
        while not self._stopped.wait(self.interval_s):
            try:
                self.poll_once()
            except Exception:
                pass

    def poll_once(self):
        try:
            names = sorted(os.listdir(self.dir))
        except FileNotFoundError:
            return
        for name in names:
            if not name.endswith(".log"):
                continue
            path = os.path.join(self.dir, name)
            tag = name[len("worker-"):-len(".log")] if name.startswith("worker-") else name
            try:
                size = os.path.getsize(path)
            except OSError:
                continue
            pos = self._offsets.get(name, 0)
            if size < pos:
                pos = 0  # truncated/rotated
            if size == pos:
                continue
            try:
                with open(path, "rb") as f:
                    f.seek(pos)
                    chunk = f.read(1 << 20)
                    self._offsets[name] = f.tell()
            except OSError:
                continue
            text = chunk.decode(errors="replace")
            for line in text.splitlines():
                print(f"(worker={tag}) {line}", file=self.out)
