"""The head runtime: object ownership, scheduling, actor management, worker IO.

This process plays the roles the reference splits across GCS + raylet +
driver core_worker (reference: src/ray/gcs/gcs_server.h:98,
src/ray/raylet/node_manager.h:133, src/ray/core_worker/core_worker.h:167):
it owns all objects, runs the cluster scheduler over the (possibly many)
node managers, maintains the actor registry with restart state machines, and
serves client RPCs from worker processes over their pipes.
"""

from __future__ import annotations

import collections
import logging
import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from multiprocessing import connection as mp_connection

from ray_tpu._config import get_config, reset_config
from ray_tpu.core import context
from ray_tpu.core.gcs import Gcs
from ray_tpu.core.ids import ActorID, JobID, NodeID, ObjectID, PlacementGroupID, TaskID
from ray_tpu.core.node import Node, WorkerHandle
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.object_store import ObjectStore, StoredObject, read_from_shm
from ray_tpu.core.payloads import decode_payload, encode_serialized, encode_value
from ray_tpu.core.scheduler import Scheduler
from ray_tpu.core.serialization import Serialized, deserialize_s
from ray_tpu.core.task_manager import TaskManager
from ray_tpu.core.task_spec import ActorInfo, ArgSpec, Payload, SchedulingOptions, TaskSpec
from ray_tpu.exceptions import (
    ActorDiedError,
    GetTimeoutError,
    PlacementGroupUnschedulableError,
    TaskError,
)

logger = logging.getLogger(__name__)

_exit_hook_registered = False


#: placeholder for a stream index whose item has not arrived (out-of-order
#: replay gap). Distinct from None, which means end-of-stream to consumers.
_STREAM_HOLE = object()


class GenState:
    """Streaming-generator bookkeeping (reference: streaming returns in
    task_manager.h + _raylet.pyx:1067)."""

    __slots__ = ("items", "finished", "error", "error_ref_made", "total_items")

    def __init__(self):
        self.items: list[ObjectID] = []
        self.finished = False
        self.error: BaseException | None = None
        self.error_ref_made = False
        self.total_items = -1  # set when the items list is cleared on exhaustion


class ActorState:
    def __init__(self, info: ActorInfo):
        self.info = info
        self.lock = threading.RLock()
        self.seq = 0
        self.pending: list[tuple] = []  # (spec, msg) queued while not ALIVE
        self.allocation = None  # (node, resources, chips)
        self.expected_exit = False
        self.waiters = threading.Condition(self.lock)


class PlacementGroupState:
    def __init__(self, pg_id: PlacementGroupID, bundles: list[dict], strategy: str, name: str = ""):
        self.pg_id = pg_id
        self.bundles = bundles
        self.strategy = strategy
        self.name = name
        self.state = "PENDING"  # PENDING | CREATED | REMOVED
        self.placements: list = []  # bundle_idx -> NodeID
        self.cond = threading.Condition()


class Runtime:
    """Driver-side CoreClient + cluster control plane."""

    def __init__(
        self,
        resources: dict | None = None,
        num_nodes: int = 1,
        local_mode: bool = False,
        namespace: str = "default",
        system_config: dict | None = None,
        labels: dict | None = None,
    ):
        reset_config()
        self.cfg = get_config()
        self.cfg.update(system_config)
        os.environ["RT_SESSION_PID"] = str(os.getpid())
        # One-time exit hook: stop the forkserver before interpreter
        # teardown so the resource tracker's finalizer can't deadlock on
        # it (see node.stop_forkserver). NOT done per-shutdown — a live
        # forkserver is reused by the next init() and saves its ~5s boot.
        global _exit_hook_registered
        if not _exit_hook_registered:
            import atexit

            from ray_tpu.core.node import stop_forkserver

            atexit.register(stop_forkserver)
            _exit_hook_registered = True
        from ray_tpu.core.object_store import cleanup_orphan_segments

        cleanup_orphan_segments()
        self.local_mode = local_mode
        self.namespace = namespace
        self.job_id = JobID.from_random()
        self.node_id = None
        self.worker_id = None
        self.current_task_id = None
        self.current_actor_id = None
        self.assigned_resources = {}

        self.store = ObjectStore()
        # GCS tables: persistent append-only log when configured, so KV /
        # jobs / named+detached actors survive a head kill -9 (reference:
        # redis_store_client.h:126, test_gcs_fault_tolerance.py)
        if self.cfg.gcs_persist_path and not local_mode:
            from ray_tpu.core.table_store import FileTableStore

            self.gcs = Gcs(FileTableStore(self.cfg.gcs_persist_path))
        else:
            self.gcs = Gcs()
        self.task_manager = TaskManager(self)
        self.scheduler = Scheduler(self)
        # ---- cross-node object plane (core/transport.py) ----
        # The head is the owner directory: shm namespace -> transfer
        # address of the node holding the bytes (reference:
        # object_manager/ownership_object_directory.h).
        from ray_tpu.core import object_store as _os_mod
        from ray_tpu.core import transport as _transport

        # Cluster credentials: stable across head restarts when the GCS is
        # persistent — reconnecting agents still hold the old keys.
        self._transfer_authkey = self._persistent_secret("transfer_authkey")
        self._listener_authkey = self._persistent_secret("listener_authkey")
        self._direct_authkey = self._persistent_secret("direct_authkey")
        # worker leases for the direct call plane (core/direct.py):
        # wid -> (node, resources, owner_hex)
        self._leases: dict = {}
        self._leases_lock = threading.Lock()
        if not local_mode:
            adv = self.cfg.node_manager_host
            if adv in ("", "0.0.0.0"):
                import socket as _socket

                try:
                    adv = _socket.gethostbyname(_socket.gethostname())
                except OSError:
                    adv = "127.0.0.1"
            self._transfer_server = _transport.ObjectTransferServer(self._transfer_authkey, advertise_host=adv)
        else:
            self._transfer_server = None
        self._head_ns = _os_mod._session_tag()
        self._ns_addrs: dict[str, tuple] = {}
        self._ns_nodes: dict[str, NodeID] = {}
        self._shm_ns_counter = 0
        if self._transfer_server is not None:
            self._ns_addrs[self._head_ns] = self._transfer_server.address
        _os_mod.set_fetch_hook(self._fetch_foreign_segment)
        self.store.remote_free = self._free_foreign_segment
        # TCP rendezvous all node agents dial into (spawned locally or
        # joined from another host via `rt agent --address`).
        if not local_mode:
            from ray_tpu.core.node import AgentListener

            self._agent_listener = AgentListener(
                host=self.cfg.node_manager_host,
                port=self.cfg.node_manager_port,
                authkey=self._listener_authkey,
                on_join=self._on_agent_join,
                on_driver=self._on_driver_join,
            )
            try:
                from ray_tpu.util.state import dump_cluster_info

                dump_cluster_info(self)
            except Exception:
                pass
        else:
            self._agent_listener = None
        from ray_tpu.core.lock_sanitizer import make_lock

        self._nodes_lock = make_lock("runtime.nodes")
        self._drivers: dict = {}  # attached external drivers (worker_id hex -> handle)
        self._drivers_lock = threading.Lock()
        # dead-worker pipes waiting for the io loop to close them (see
        # _retire_conn: fd-reuse vs mp_connection.wait)
        self._conn_graveyard: list = []
        self._conn_graveyard_lock = threading.Lock()
        self.nodes: dict[NodeID, Node] = {}
        self.actors: dict[ActorID, ActorState] = {}
        self.placement_groups: dict[PlacementGroupID, PlacementGroupState] = {}
        self._pending_pgs: set = set()  # PENDING pg ids (re-place kicks scan only these)
        self.generators: dict[ObjectID, GenState] = {}
        self._gen_tombstones: collections.deque[ObjectID] = collections.deque()
        self._gen_cond = threading.Condition()
        self._functions: dict[str, Serialized] = {}
        self._local_fn_cache: dict[str, object] = {}
        self._done_callbacks: dict[ObjectID, list] = {}
        self._dc_lock = threading.Lock()
        self._stack_pending: dict[str, tuple] = {}  # req_id -> (Event, results)
        # reference counting (reference: reference_counter.h): remote
        # holders per object + pins from live task specs' args. The head
        # process's own refs are covered by object_ref's local registry.
        self._ref_holders: dict[bytes, set[str]] = {}
        self._arg_pins: dict[bytes, int] = {}
        self._freed_ids: collections.deque = collections.deque(maxlen=65536)
        self._freed_set: set = set()
        self._rc_head_lock = threading.Lock()
        from ray_tpu.core import object_ref as _oref_mod

        _oref_mod.set_ref_counting(self.cfg.object_ref_counting)
        self._stopped = False
        self._worker_count_limit_extra = 4
        # Large pool: client RPCs like get_object block until the object is
        # produced, so the pool must exceed the worst-case number of
        # simultaneously blocked workers to avoid starving put/submit RPCs.
        self._req_pool = ThreadPoolExecutor(max_workers=256, thread_name_prefix="rt-req")

        from ray_tpu.accelerators.tpu import TPUAcceleratorManager

        base_res = dict(resources or {})
        base_res.setdefault("CPU", float(os.cpu_count() or 4))
        base_res.setdefault("memory", float(2**33))
        base_res.setdefault("TPU", float(TPUAcceleratorManager.get_current_node_num_accelerators()))
        if base_res.get("TPU", 0) <= 0:
            base_res.pop("TPU", None)
        # slice gang-scheduling resources + labels when running on a TPU VM
        # (reference: tpu.py:576-672)
        for k, v in TPUAcceleratorManager.get_current_node_additional_resources().items():
            base_res.setdefault(k, v)
        node_labels = {"ray_tpu.io/node-type": "head", **TPUAcceleratorManager.get_current_node_labels(), **(labels or {})}
        head = Node(None, base_res, labels=node_labels, env=self._base_worker_env())
        self.head_node = head
        self.node_id = head.node_id
        self.nodes[head.node_id] = head
        self.gcs.events.record("node_added", node_id=head.node_id.hex(), resources=base_res)
        for _ in range(max(0, num_nodes - 1)):
            self.add_node(dict(base_res))

        self.store.listeners.append(self._on_sealed)
        # direct call plane for the in-process driver: own a small-object
        # store + serve it to workers (core/direct.py ownership model)
        from ray_tpu.core import direct as _direct_mod

        self._direct = _direct_mod.attach(
            self,
            self._direct_authkey if (self.cfg.direct_calls and not local_mode) else None,
            node_hex=self.node_id.hex(),
            serve=True,
        )
        if not local_mode:
            self._io_thread = threading.Thread(target=self._io_loop, daemon=True, name="rt-io")
            self._io_thread.start()
            self._sched_thread = threading.Thread(target=self.scheduler.run_loop, daemon=True, name="rt-sched")
            self._sched_thread.start()
            self._health_thread = threading.Thread(target=self._health_loop, daemon=True, name="rt-health")
            self._health_thread.start()
            if self.cfg.object_ref_counting:
                threading.Thread(target=self._ref_gc_loop, daemon=True, name="rt-ref-gc").start()
            if self.cfg.state_dump_interval_s > 0:
                threading.Thread(target=self._state_dump_loop, daemon=True, name="rt-state-dump").start()
            if self.cfg.log_to_driver:
                from ray_tpu.core.log_monitor import LogMonitor
                from ray_tpu.util.state import session_dir

                self._log_monitor = LogMonitor(os.path.join(session_dir(), "logs")).start()
            from ray_tpu.core.memory_monitor import MemoryMonitor

            self._memory_monitor = MemoryMonitor(self).start()
            if self.cfg.prestart_workers:
                # Warm the pool in the background (reference: worker_pool.h
                # prestart) — overlaps the one-time forkserver boot with user
                # setup code.
                n = min(int(head.total_resources.get("CPU", 1)), 4)

                def _prestart():
                    for _ in range(n):
                        if self._stopped:  # re-check: shutdown can race the warmup
                            return
                        try:
                            head.start_worker()
                        except RuntimeError:
                            return  # node shut down mid-spawn

                self._prestart_thread = threading.Thread(target=_prestart, daemon=True)
                self._prestart_thread.start()

        if self.cfg.gcs_persist_path and not local_mode:
            self._rehydrate_detached_actors()

    # ------------------------------------------------------------------
    # cluster membership
    # ------------------------------------------------------------------
    def add_node(
        self,
        resources: dict,
        labels: dict | None = None,
        env: dict | None = None,
        remote: bool = True,
        shm_isolation: bool | None = None,
    ) -> Node:
        """Add a node. remote=True (default) runs the node manager as a
        separate agent process over the TCP agent channel + health checks —
        real process separation like the reference's raylet; remote=False
        keeps the legacy in-process simulation. shm_isolation=True gives
        the node its own shm namespace so every object crossing the node
        boundary moves through the transfer service — exactly what a
        separate host would do (no same-host fast path)."""
        if shm_isolation is None:
            shm_isolation = self.cfg.shm_isolation
        if remote and not self.local_mode:
            from ray_tpu.core.node import RemoteNode

            env = {**self._base_worker_env(), **(env or {})}
            if shm_isolation:
                self._shm_ns_counter += 1
                env["RT_SHM_NS"] = f"{self._head_ns.split('n')[0]}n{self._shm_ns_counter}"
            node = RemoteNode(
                None,
                resources,
                labels=labels,
                env=env,
                listener=self._agent_listener,
                transfer_authkey=self._transfer_authkey,
            )
            self._register_node_transfer(node)
        else:
            node = Node(None, resources, labels=labels, env=env)
        with self._nodes_lock:
            self.nodes[node.node_id] = node
        self.gcs.events.record("node_added", node_id=node.node_id.hex(), resources=resources)
        self.gcs.pubsub.publish("node", {"event": "added", "node_id": node.node_id.hex()})
        self.scheduler.bump_capacity()
        return node

    def _persistent_secret(self, name: str) -> bytes:
        key = self.gcs.store.get("cluster_secrets", name)
        if key is None:
            key = os.urandom(16)
            self.gcs.store.put("cluster_secrets", name, key)
        return key

    def _base_worker_env(self) -> dict:
        """Env every worker must see explicitly: the forkserver freezes
        os.environ at ITS boot, so driver-side settings made later (e.g.
        enabling tracing) only reach workers through the per-worker env."""
        env = {}
        from ray_tpu.util import tracing

        if tracing.enabled():
            env["RT_TRACING"] = "1"
        if self.cfg.direct_calls and not self.local_mode:
            env["RT_DIRECT_AUTHKEY"] = self._direct_authkey.hex()
        return env

    def _register_node_transfer(self, node):
        ns = getattr(node, "shm_ns", "")
        if ns and getattr(node, "transfer_addr", None):
            self._ns_addrs.setdefault(ns, node.transfer_addr)
            self._ns_nodes[ns] = node.node_id

    def _on_driver_join(self, conn, hello: dict):
        """An external driver process attached over the agent listener
        (reference: ray.init(address=...) joining through the GCS — here
        the driver speaks the same RPC protocol a worker does, minus task
        execution). Each driver gets its own recv pump; its ref-count
        holder entry is dropped on disconnect exactly like a dead
        worker's."""
        from ray_tpu.core.ids import WorkerID

        import socket as _socket

        wid = WorkerID.from_random()
        handle = _DriverHandle(conn, wid)
        handle.send(
            {
                "type": "driver_welcome",
                "worker_id": wid.hex(),
                "node_id": self.node_id.hex(),
                "session_pid": os.getpid(),
                "namespace": self.namespace,
                "hostname": _socket.gethostname(),
                "direct_authkey": self._direct_authkey.hex() if self.cfg.direct_calls else None,
            }
        )
        # register only after the welcome went through: a dialer that died
        # mid-handshake must not leave a stale handle behind (the pump's
        # finally is the sole removal path)
        with self._drivers_lock:
            self._drivers[wid.hex()] = handle
        threading.Thread(
            target=self._driver_pump, args=(handle,), daemon=True, name=f"rt-driver-{wid.hex()[:8]}"
        ).start()
        self.gcs.events.record("driver_attached", worker_id=wid.hex(), pid=hello.get("pid"))

    def _driver_pump(self, handle: "_DriverHandle"):
        wid_hex = handle.worker_id.hex()
        try:
            while not self._stopped:
                try:
                    msg = handle.conn.recv()
                except (EOFError, OSError):
                    break
                if msg.get("type") == "driver_bye":
                    break
                self._dispatch_client_msg(handle, msg)
        finally:
            with self._drivers_lock:
                self._drivers.pop(wid_hex, None)
            self._drop_holder(wid_hex)
            self._release_leases_of_owner(wid_hex)
            try:
                handle.conn.close()
            except Exception:
                pass
            self.gcs.events.record("driver_detached", worker_id=wid_hex)

    def _on_agent_join(self, conn, hello: dict):
        """A standalone agent (``rt agent --address head:port``, typically
        another host) connected to the agent listener: adopt it as a node."""
        from ray_tpu.core.ids import NodeID as _NodeID
        from ray_tpu.core.node import JoinedNode

        node_id = _NodeID.from_hex(hello["node_id"])
        with self._nodes_lock:
            stale = self.nodes.get(node_id)
        if stale is not None:
            # re-join after a transient drop: the old record's socket is
            # dead — retire it before adopting the fresh connection
            self.remove_node(node_id, graceful=False)
        node = JoinedNode(node_id, conn, hello)
        self._register_node_transfer(node)
        with self._nodes_lock:
            self.nodes[node.node_id] = node
        self.gcs.events.record("node_added", node_id=node.node_id.hex(), resources=node.total_resources, joined=True)
        self.gcs.pubsub.publish("node", {"event": "added", "node_id": node.node_id.hex()})
        logger.info("node %s joined via agent listener (ns=%s)", node.node_id.hex()[:8], node.shm_ns)
        self.scheduler.bump_capacity()  # parked infeasible shapes re-evaluate

    # ---- cross-node segment fetch/free (head side) ----
    def _fetch_foreign_segment(self, desc) -> str:
        """object_store fetch hook: pull a foreign-namespace segment into
        the head's namespace; returns the local segment name."""
        from ray_tpu.core import transport
        from ray_tpu.core.object_store import local_shm_name

        addr = self._ns_addrs.get(desc.ns)
        if addr is None:
            raise FileNotFoundError(f"no transfer address for shm namespace {desc.ns!r} (node dead?)")
        local = local_shm_name(desc)
        transport.pull_segment(addr, self._transfer_authkey, desc.shm_name, local)
        return local

    def _free_foreign_segment(self, desc):
        """object_store remote_free hook: ask the owning node's agent to
        unlink a segment living in its namespace."""
        node_id = self._ns_nodes.get(desc.ns)
        if node_id is None:
            return
        with self._nodes_lock:
            node = self.nodes.get(node_id)
        if node is not None and getattr(node, "remote", False) and node.alive:
            node.agent_send({"type": "free_shm", "name": desc.shm_name})

    def remove_node(self, node_id: NodeID, graceful: bool = False):
        """Simulate node death (reference: GcsHealthCheckManager failure path —
        gcs_health_check_manager.h:45: leases killed, objects failed)."""
        with self._nodes_lock:
            node = self.nodes.get(node_id)
        if node is None:
            return
        # tasks with resources reserved but no worker yet go back to the
        # scheduler (with slow worker spawn — e.g. agent forkserver boot —
        # a node can die while its dispatch queue is non-empty). alive flips
        # and the queue drains under node._lock so the scheduler thread's
        # _dispatch_node can't pop a spec this drain also resubmits.
        with node._lock:
            node.alive = False
            queued = list(node.dispatch_queue)
            node.dispatch_queue.clear()
        workers = list(node.workers.values())
        for w in workers:
            self._on_worker_death(node, w, "node removed")
            try:
                w.proc.terminate()
            except Exception:
                pass
        for spec, _alloc, _chips in queued:
            if spec.is_actor_creation or spec.actor_id is None:
                self.scheduler.submit(spec)
        node.shutdown()
        with self._nodes_lock:
            self.nodes.pop(node_id, None)
        ns = getattr(node, "shm_ns", "")
        if ns and ns != self._head_ns:
            # the node's namespace dies with it: lookups fail fast and
            # objects there fall back to lineage reconstruction
            self._ns_addrs.pop(ns, None)
            self._ns_nodes.pop(ns, None)
        self.gcs.events.record("node_removed", node_id=node_id.hex())
        self.gcs.pubsub.publish("node", {"event": "removed", "node_id": node_id.hex()})
        # membership changed: parked shapes re-evaluate against the
        # post-removal cluster view
        self.scheduler.bump_capacity()

    def node_list(self) -> list[Node]:
        with self._nodes_lock:
            return [n for n in self.nodes.values() if n.alive]

    # ------------------------------------------------------------------
    # object plane (CoreClient impl)
    # ------------------------------------------------------------------
    def put_object(self, value) -> ObjectRef:
        from ray_tpu.core import direct as _direct

        ref, s = _direct.try_put(value)
        if ref is not None:
            return ref
        obj_id = ObjectID.from_put()
        self.store.put_serialized(obj_id, s if s is not None else _to_serialized(value))
        return ObjectRef(obj_id)

    def put_payload(self, obj_id: ObjectID, payload: Payload):
        # wrap contained ids as live refs on the entry: the head's local
        # ref count then pins inner objects while the container lives
        contained = [ObjectRef(c) for c in (payload.contained or [])]
        if payload.shm is not None:
            self.store.seal(obj_id, StoredObject(shm=payload.shm, contained_refs=contained))
        else:
            self.store.seal(obj_id, StoredObject(value=payload.inline, contained_refs=contained))

    def get_object(self, obj_id: ObjectID, timeout: float | None = None, _depth: int = 0):
        from ray_tpu.core import direct as _direct
        from ray_tpu.exceptions import ObjectLostError

        for _attempt in range(3):
            handled, v = _direct.maybe_get_owned(obj_id, timeout)
            if handled:
                return v
            try:
                return self._get_object_store(obj_id, timeout)
            except ObjectLostError:
                # owner-side lineage: a head-sealed direct result can be
                # replayed by its owner (this process) even though the
                # head never saw the producing task
                if not _direct.try_reconstruct(self, obj_id):
                    raise
        raise ObjectLostError(f"object {obj_id.hex()[:16]} lost repeatedly despite lineage replay")

    def _get_object_store(self, obj_id: ObjectID, timeout: float | None = None):
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            entry = self._get_entry_reconstructing(obj_id, deadline)
            if entry is None:
                raise GetTimeoutError(f"get() timed out waiting for {obj_id.hex()[:16]}")
            if entry.error is not None:
                raise entry.error
            if entry.shm is not None:
                try:
                    # zero-copy: buffers are read-only views of a GC-managed
                    # mapping (plasma get semantics — arrays come back
                    # immutable; copy() to mutate)
                    s, _ = read_from_shm(entry.shm, zero_copy=True)
                except FileNotFoundError:
                    # raced an eviction or the bytes were spilled to disk
                    self.store.restore_or_mark_lost(obj_id)
                    continue
                return deserialize_s(s)
            return deserialize_s(entry.value)

    def _get_entry_reconstructing(self, obj_id, deadline):
        while True:
            if obj_id in getattr(self, "_freed_set", ()):
                from ray_tpu.exceptions import ObjectLostError

                raise ObjectLostError(
                    f"object {obj_id.hex()[:16]} was freed: every reference "
                    "went out of scope (reference counting)"
                )
            timeout = None if deadline is None else max(0.0, deadline - time.monotonic())
            if self.store.is_evicted(obj_id):
                self.task_manager.reconstruct(obj_id)
            entry = self.store.get_entry(obj_id, timeout=0.2 if timeout is None else min(timeout, 0.2))
            if entry is not None:
                if not self.store.shm_backing_exists(entry):
                    self.store.restore_or_mark_lost(obj_id)
                    continue
                return entry
            if deadline is not None and time.monotonic() >= deadline:
                return None

    def entry_to_payload(self, entry: StoredObject) -> Payload:
        if entry.error is not None:
            return encode_value(entry.error)
        if entry.shm is not None:
            return Payload(shm=entry.shm)
        s = entry.value
        return Payload(inline=Serialized(header=s.header, buffers=[bytes(b) for b in s.buffers]))

    def wait_ready(self, obj_ids, num_returns=1, timeout=None, fetch_local=True):
        from ray_tpu.core import direct as _direct

        return _direct.wait_mixed(
            self, list(obj_ids), num_returns, timeout,
            lambda ids, nr, t: self.store.wait_ready(ids, nr, t),
        )

    def add_done_callback(self, obj_id: ObjectID, cb):
        from ray_tpu.core import direct as _direct

        if _direct.add_done_callback_owned(obj_id, cb):
            return
        with self._dc_lock:
            if not self.store.contains(obj_id):
                self._done_callbacks.setdefault(obj_id, []).append(cb)
                return
        self._req_pool.submit(self._fire_callback, obj_id, cb)

    def _fire_callback(self, obj_id, cb):
        try:
            v = self.get_object(obj_id)
            cb(v, None)
        except BaseException as e:  # noqa: BLE001
            cb(None, e)

    def free_objects(self, obj_ids):
        from ray_tpu.core import direct as _direct

        for oid in _direct.free_owned(list(obj_ids)):
            self.store.delete(oid)

    def dump_worker_stacks(self, worker_prefix: str = "", timeout: float = 10.0) -> dict:
        """Live Python stacks of every (matching) worker — the on-demand
        profiling attach (reference capability: dashboard/modules/
        reporter/profile_manager.py:82 py-spy dump on live workers;
        dependency-free here: workers self-report via sys._current_frames
        on their always-free recv loop). Returns {worker_id_hex: {pid,
        current_task, stacks: {thread: stack}}}; unresponsive workers are
        reported with an 'unresponsive' marker instead of hanging the
        call."""
        import uuid

        req_id = uuid.uuid4().hex[:12]
        ev = threading.Event()
        results: dict = {}
        targets = []
        for node in self.node_list():
            for w in node.workers.values():
                whex = w.worker_id.hex()
                if worker_prefix and not whex.startswith(worker_prefix):
                    continue
                if w.state in ("starting", "dead", "retiring"):
                    continue
                targets.append((w, whex))
        if not targets:
            return {}
        with self._dc_lock:
            self._stack_pending[req_id] = (ev, results)
        try:
            for w, _ in targets:
                try:
                    w.send({"type": "stack_dump", "req_id": req_id})
                except Exception:
                    pass
            deadline = time.monotonic() + timeout
            while len(results) < len(targets) and time.monotonic() < deadline:
                ev.wait(timeout=0.2)
                ev.clear()
        finally:
            with self._dc_lock:
                self._stack_pending.pop(req_id, None)
        for _, whex in targets:
            if whex not in results:
                results[whex] = {"unresponsive": True, "stacks": {}}
        return results

    def object_locations(self, obj_ids) -> dict:
        """Primary-copy node per object (reference:
        ownership_object_directory.h lookups / ray.experimental.
        get_object_locations). The shm namespace tag IS the location
        record: a descriptor's ns maps to the node holding the bytes;
        inline/spilled values live with the head. None = unknown/unsealed."""
        from ray_tpu.core import direct as _direct

        out = {}
        head_hex = self.node_id.hex()
        for oid in obj_ids:
            entry = self.store.try_get_entry(oid)
            if entry is None:
                out[oid.hex()] = _direct.owned_location(oid.binary())
            elif entry.shm is None or not entry.shm.ns or entry.shm.ns == self._head_ns:
                out[oid.hex()] = head_hex
            else:
                nid = self._ns_nodes.get(entry.shm.ns)
                out[oid.hex()] = nid.hex() if nid is not None else None
        return out

    def _on_sealed(self, obj_id: ObjectID):
        self.scheduler.on_object_sealed(obj_id)
        with self._dc_lock:
            cbs = self._done_callbacks.pop(obj_id, None)
        if cbs:
            for cb in cbs:
                self._req_pool.submit(self._fire_callback, obj_id, cb)

    # ------------------------------------------------------------------
    # function registry
    # ------------------------------------------------------------------
    def register_function(self, func_id: str, blob: Serialized | None):
        if blob is not None and func_id not in self._functions:
            self._functions[func_id] = Serialized(header=blob.header, buffers=[bytes(b) for b in blob.buffers])

    def has_function(self, func_id: str) -> bool:
        return func_id in self._functions

    def get_function_blob(self, func_id: str) -> Serialized:
        return self._functions[func_id]

    def get_function(self, func_id: str):
        if func_id not in self._local_fn_cache:
            self._local_fn_cache[func_id] = deserialize_s(self._functions[func_id])
        return self._local_fn_cache[func_id]

    # ------------------------------------------------------------------
    # task submission (CoreClient impl)
    # ------------------------------------------------------------------
    def submit_task(
        self,
        name: str,
        func_id: str,
        args: list[ArgSpec],
        kwargs: dict[str, ArgSpec] | None = None,
        num_returns: int = 1,
        streaming: bool = False,
        func_blob: Serialized | None = None,
        options: dict | None = None,
    ):
        self.register_function(func_id, func_blob)
        opts = options or {}
        spec = TaskSpec(
            task_id=TaskID.from_random(),
            name=name,
            func_id=func_id,
            args=args,
            num_returns=num_returns,
            streaming=streaming,
            scheduling=_sched_options(opts),
            max_retries=opts.get("max_retries", self.cfg.default_max_retries),
            retry_exceptions=opts.get("retry_exceptions", False),
            runtime_env=self._prepare_runtime_env(opts.get("runtime_env")),
            trace_ctx=opts.get("_trace_ctx"),
        )
        spec._kwargs = kwargs or {}
        self.task_manager.register(spec)
        if self.local_mode:
            self._local_execute(spec)
        elif not self._fast_submit(spec):
            self.scheduler.submit(spec)
        if streaming:
            return [spec.generator_id()]
        return spec.return_ids()

    def _fast_submit(self, spec: TaskSpec) -> bool:
        """Submit-side fast path: an unconstrained task whose deps are all
        local reserves + dispatches inline on the calling thread, skipping
        the scheduler-thread hop (reference: direct task submission to a
        leased worker, core_worker task submitter). Falls back to the
        policy queue when placement is constrained or capacity is tight."""
        s = spec.scheduling
        if (
            s.placement_group is not None
            or s.node_id is not None
            or s.soft_node_id is not None
            or s.label_selector
            or s.scheduling_strategy != "DEFAULT"
        ):
            return False
        if self.scheduler.has_pending():
            return False  # don't jump ahead of queued work
        for a in spec.args:
            if a.ref is not None and not self.store.contains(a.ref):
                return False
        for node in self.node_list():
            if node.alive and self.reserve_and_queue(node, spec):
                self._dispatch_node(node)
                return True
        return False

    def _prepare_runtime_env(self, renv: dict | None) -> dict | None:
        """Package working_dir/py_modules once (cached by paths) into the
        object store; archives are pinned so LRU eviction cannot lose them
        (runtime_env/packaging.py)."""
        if not renv:
            return renv
        if not any(k in renv for k in ("working_dir", "py_modules", "pip", "conda", "uv", "container")):
            return renv
        from ray_tpu.runtime_env.packaging import dir_fingerprint, validate_runtime_env

        validate_runtime_env(renv)  # gated kinds error on EVERY submit
        # cache by content fingerprint, not path alone: edits re-package
        key = tuple(
            (p, dir_fingerprint(p))
            for p in [renv.get("working_dir"), *(renv.get("py_modules") or ())]
            if p
        )
        if not hasattr(self, "_renv_cache"):
            self._renv_cache = {}
        cached = self._renv_cache.get(key)
        if cached is None:
            from ray_tpu.core import direct as _direct
            from ray_tpu.runtime_env import prepare_runtime_env

            prepared = prepare_runtime_env(renv)
            for packed in [prepared.get("_packed_working_dir")] + list(prepared.get("_packed_py_modules") or []):
                if packed:
                    ref = packed.pop("_ref", None)
                    if ref is not None:
                        # archive ids travel as HEX STRINGS inside the
                        # runtime_env dict — no owner hint rides along, so
                        # an owner-local put would be unreachable from
                        # workers; move it into the head store, pin
                        # against eviction AND hold a live ref so the
                        # reference counter can never free it (the hex
                        # string in the env dict is invisible to it)
                        _direct.promote(self, ref.id.binary())
                        self.store.pin(ref.id)
                        if not hasattr(self, "_renv_pins"):
                            self._renv_pins = []
                        self._renv_pins.append(ref)
            cached = {k: v for k, v in prepared.items() if k != "env_vars"}
            self._renv_cache[key] = cached
        out = dict(cached)
        if renv.get("env_vars"):
            out["env_vars"] = renv["env_vars"]
        return out

    def resubmit(self, spec: TaskSpec):
        """Re-run a task (retry or lineage reconstruction)."""
        spec.attempt += 1
        if spec.actor_id is not None and not spec.is_actor_creation:
            self._submit_actor_spec(spec)
        elif self.local_mode:
            self._local_execute(spec)
        else:
            self.scheduler.submit(spec)

    # ------------------------------------------------------------------
    # actors (CoreClient impl)
    # ------------------------------------------------------------------
    def create_actor(
        self,
        name_desc: str,
        func_id: str,
        args: list[ArgSpec],
        kwargs: dict | None = None,
        func_blob: Serialized | None = None,
        options: dict | None = None,
    ) -> dict:
        self.register_function(func_id, func_blob)
        opts = options or {}
        actor_id = ActorID.from_random()
        actor_name = opts.get("name")
        namespace = opts.get("namespace", self.namespace)
        if actor_name:
            if not self.gcs.register_named_actor(actor_name, namespace, actor_id):
                raise ValueError(f"actor name {actor_name!r} already taken in namespace {namespace!r}")
        spec = TaskSpec(
            task_id=TaskID.from_random(),
            name=f"{name_desc}.__init__",
            func_id=func_id,
            args=args,
            num_returns=0,
            scheduling=_sched_options(opts, is_actor=True),
            actor_id=actor_id,
            is_actor_creation=True,
            max_restarts=opts.get("max_restarts", 0),
            max_task_retries=opts.get("max_task_retries", 0),
            max_concurrency=opts.get("max_concurrency", 1),
            runtime_env=self._prepare_runtime_env(opts.get("runtime_env")),
        )
        spec._kwargs = kwargs or {}
        info = ActorInfo(
            actor_id=actor_id,
            name=actor_name,
            namespace=namespace,
            class_id=func_id,
            state="PENDING",
            max_restarts=spec.max_restarts,
            max_task_retries=spec.max_task_retries,
            max_concurrency=spec.max_concurrency,
            creation_spec=spec,
            resources=dict(spec.scheduling.resources),
            placement_group=spec.scheduling.placement_group,
            bundle_index=spec.scheduling.bundle_index,
            detached=opts.get("lifetime") == "detached",
        )
        self.actors[actor_id] = ActorState(info)
        self.task_manager.register(spec)
        if info.detached and self.cfg.gcs_persist_path:
            self._persist_detached_actor(info, func_blob)
        self.gcs.events.record("actor_created", actor_id=actor_id.hex(), name=name_desc)
        if self.local_mode:
            self._local_create_actor(spec)
        else:
            self.scheduler.submit(spec)
        return {"actor_id": actor_id, "method_meta": {}}

    # ---- detached-actor persistence (GCS fault tolerance) ----
    def _persist_detached_actor(self, info: ActorInfo, func_blob):
        """Record everything needed to recreate the actor after a head
        restart: creation spec + class blob. Inline ctor args only — args
        referencing shm objects would dangle across a restart (reference:
        gcs_actor_manager.h persists registered actors to the store)."""
        import pickle

        spec = info.creation_spec
        if any(a.ref is not None or (a.payload and a.payload.shm is not None) for a in spec.args):
            return  # not restorable: ctor args live in the object plane
        try:
            blob = pickle.dumps(
                {
                    "spec": spec,
                    "kwargs": getattr(spec, "_kwargs", {}),
                    "func_blob": func_blob if func_blob is not None else self._functions.get(spec.func_id),
                    "name": info.name,
                    "namespace": info.namespace,
                    "detached": True,
                }
            )
        except Exception:
            return  # unpicklable spec: skip persistence, actor still works
        self.gcs.persist_detached_actor(info.actor_id, blob)

    def _rehydrate_detached_actors(self):
        """On head start with a persistent GCS: recreate detached actors
        recorded by the previous head, keeping their actor ids and names
        (the reference restarts detached actors on GCS recovery)."""
        import pickle

        for actor_hex, blob in self.gcs.load_detached_actors().items():
            try:
                rec = pickle.loads(blob)
            except Exception:
                continue
            spec = rec["spec"]
            if spec.actor_id in self.actors:
                continue
            self.register_function(spec.func_id, rec.get("func_blob"))
            spec._kwargs = rec.get("kwargs", {})
            spec.attempt = 0
            info = ActorInfo(
                actor_id=spec.actor_id,
                name=rec.get("name"),
                namespace=rec.get("namespace", "default"),
                class_id=spec.func_id,
                state="PENDING",
                max_restarts=spec.max_restarts,
                max_task_retries=spec.max_task_retries,
                max_concurrency=spec.max_concurrency,
                creation_spec=spec,
                resources=dict(spec.scheduling.resources),
                placement_group=None,
                bundle_index=-1,
                detached=True,
            )
            if info.name:
                self.gcs.register_named_actor(info.name, info.namespace, spec.actor_id)
            self.actors[spec.actor_id] = ActorState(info)
            self.task_manager.register(spec)
            self.gcs.events.record("actor_rehydrated", actor_id=actor_hex, name=info.name or "")
            self.scheduler.submit(spec)

    def submit_actor_task(
        self,
        actor_id: ActorID,
        method_name: str,
        args: list[ArgSpec],
        kwargs: dict | None = None,
        num_returns: int = 1,
        streaming: bool = False,
        options: dict | None = None,
    ):
        astate = self.actors.get(actor_id)
        if astate is None:
            raise ActorDiedError(actor_id, "unknown actor")
        with astate.lock:
            if astate.info.state == "DEAD":
                err_ids = self._make_actor_error_returns(actor_id, method_name, num_returns, streaming, astate.info.death_cause)
                return err_ids
            astate.seq += 1
            spec = TaskSpec(
                task_id=TaskID.for_actor(actor_id, astate.seq),
                name=f"{method_name}",
                func_id="",
                args=args,
                num_returns=num_returns,
                streaming=streaming,
                actor_id=actor_id,
                method_name=method_name,
                seq_no=astate.seq,
                max_retries=astate.info.max_task_retries,
                trace_ctx=(options or {}).get("_trace_ctx"),
            )
            spec._kwargs = kwargs or {}
            self.task_manager.register(spec)
            if self.local_mode:
                self._local_actor_call(spec)
            else:
                self._submit_actor_spec(spec)
        if streaming:
            return [spec.generator_id()]
        return spec.return_ids()

    def _make_actor_error_returns(self, actor_id, method_name, num_returns, streaming, cause):
        tid = TaskID.from_random()
        err = ActorDiedError(actor_id, cause or "actor is dead")
        ids = []
        if streaming:
            ids = [ObjectID.for_task_return(tid, 0)]
        else:
            ids = [ObjectID.for_task_return(tid, i) for i in range(num_returns)]
        for oid in ids:
            self.store.put_error(oid, err)
        return ids

    def _submit_actor_spec(self, spec: TaskSpec):
        astate = self.actors[spec.actor_id]
        with astate.lock:
            if astate.info.state == "ALIVE":
                self._dispatch_actor_task(astate, spec)
            elif astate.info.state in ("PENDING", "RESTARTING"):
                astate.pending.append(spec)
            else:
                err = ActorDiedError(spec.actor_id, astate.info.death_cause)
                for oid in self._spec_return_ids(spec):
                    self.store.put_error(oid, err)

    def _dispatch_actor_task(self, astate: ActorState, spec: TaskSpec):
        node = self.nodes.get(astate.info.node_id)
        worker = node.workers.get(astate.info.worker_id) if node else None
        if worker is None or not worker.alive():
            astate.pending.append(spec)
            return
        msg = self._build_exec_msg(spec, node, resources=astate.info.resources, env=None)
        if msg is None:
            return  # dependency error already sealed
        worker.running_tasks[spec.task_id] = (spec, None)
        self.task_manager.mark_running(spec.task_id, node.node_id, worker.worker_id)
        try:
            worker.send(msg)
        except (OSError, ValueError):
            # pipe closed between alive() check and send: route through the
            # normal worker-death path (restart machinery + retry policy)
            # instead of raising to the submit_actor_task caller
            self._on_worker_death(node, worker, "send failed")

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True):
        astate = self.actors.get(actor_id)
        if astate is None:
            return
        with astate.lock:
            astate.expected_exit = no_restart
            if no_restart:
                astate.info.max_restarts = 0
            node = self.nodes.get(astate.info.node_id)
            worker = node.workers.get(astate.info.worker_id) if node else None
            if worker is None and astate.info.creation_spec is not None:
                # still PENDING: pull the creation task out of the scheduler
                # so the actor can't resurrect after the kill
                self.scheduler.remove_task(astate.info.creation_spec.task_id)
        if worker is not None:
            try:
                worker.proc.terminate()
            except Exception:
                pass
        else:
            self._finalize_actor_death(astate, "killed via ray_tpu.kill")

    def get_actor_handle_info(self, name: str, namespace: str = "default") -> dict | None:
        actor_id = self.gcs.lookup_named_actor(name, namespace)
        if actor_id is None:
            return None
        astate = self.actors.get(actor_id)
        if astate is None or astate.info.state == "DEAD":
            return None
        return {"actor_id": actor_id, "class_id": astate.info.class_id}

    # ------------------------------------------------------------------
    # placement groups
    # ------------------------------------------------------------------
    def create_placement_group(self, bundles: list[dict], strategy: str = "PACK", name: str = "") -> PlacementGroupID:
        """Atomic all-or-nothing bundle reservation (reference: 2-phase
        commit in gcs/gcs_placement_group_scheduler.h; atomicity is trivial
        here because the control plane is single-process)."""
        pg_id = PlacementGroupID.from_random()
        pgs = PlacementGroupState(pg_id, bundles, strategy, name)
        self.placement_groups[pg_id] = pgs
        self._pending_pgs.add(pg_id)
        self._try_place_pg(pgs)
        return pg_id

    def _try_place_pg(self, pgs: PlacementGroupState) -> bool:
        with self._nodes_lock:
            with pgs.cond:
                if pgs.state != "PENDING":
                    return pgs.state == "CREATED"
            nodes = self.node_list()
            plan = _plan_pg(pgs.bundles, pgs.strategy, nodes)
            if plan is None:
                return False
            reserved = []
            ok = True
            for idx, node in enumerate(plan):
                if node.reserve_bundle(pgs.pg_id, idx, pgs.bundles[idx]):
                    reserved.append((node, idx))
                else:
                    ok = False
                    break
            if not ok:
                for node, idx in reserved:
                    node.return_bundle(pgs.pg_id, idx)
                return False
            with pgs.cond:
                if pgs.state != "PENDING":
                    # removed while we were reserving: roll back, don't
                    # let a dead group consume capacity
                    for node, idx in reserved:
                        node.return_bundle(pgs.pg_id, idx)
                    return False
                pgs.placements = [n.node_id for n in plan]
                pgs.state = "CREATED"
                pgs.cond.notify_all()
        self._pending_pgs.discard(pgs.pg_id)
        from ray_tpu.util.placement_group import _pg_ready_oid

        self.store.put_serialized(_pg_ready_oid(pgs.pg_id), _to_serialized(True))
        self.gcs.events.record("pg_created", pg_id=pgs.pg_id.hex(), strategy=pgs.strategy)
        self.scheduler.bump_capacity()
        return True

    def pending_pg_demand(self) -> list[dict]:
        """Resource requests of PENDING placement groups, for the
        autoscaler (reference: autoscaler v2 folds GCS placement-group
        demand into cluster resource demand). STRICT_PACK bundles merge
        into one per-node request — the whole group must fit one node —
        while PACK/SPREAD bundles are independent per-node requests."""
        out = []
        for pg_id in list(self._pending_pgs):
            pgs = self.placement_groups.get(pg_id)
            if pgs is None:
                continue
            with pgs.cond:
                if pgs.state != "PENDING":
                    continue
                bundles = [dict(b) for b in pgs.bundles]
                strategy = pgs.strategy
            if strategy == "STRICT_PACK" and len(bundles) > 1:
                merged: dict = {}
                for b in bundles:
                    for k, v in b.items():
                        merged[k] = merged.get(k, 0.0) + v
                out.append(merged)
            else:
                out.extend(bundles)
        return out

    def wait_placement_group(self, pg_id: PlacementGroupID, timeout: float | None = None) -> bool:
        pgs = self.placement_groups.get(pg_id)
        if pgs is None:
            raise PlacementGroupUnschedulableError(f"unknown placement group {pg_id}")
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            with pgs.cond:
                if pgs.state == "CREATED":
                    return True
                if pgs.state == "REMOVED":
                    raise PlacementGroupUnschedulableError("placement group removed")
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return False
                pgs.cond.wait(timeout=0.1 if remaining is None else min(0.1, remaining))
            if pgs.state == "PENDING":
                self._try_place_pg(pgs)

    def remove_placement_group(self, pg_id: PlacementGroupID):
        pgs = self.placement_groups.get(pg_id)
        if pgs is None:
            return
        # flip REMOVED first (under the cond): a concurrent _try_place_pg
        # commit sees it and rolls its reservation back
        with pgs.cond:
            pgs.state = "REMOVED"
            pgs.cond.notify_all()
        self._pending_pgs.discard(pg_id)
        # reference semantics: actors scheduled into the group die with it
        # (their bundles are about to be reclaimed — letting them run
        # would oversubscribe the freed capacity)
        for astate in list(self.actors.values()):
            if astate.info.placement_group == pg_id and astate.info.state != "DEAD":
                try:
                    self.kill_actor(astate.info.actor_id, no_restart=True)
                except Exception:
                    pass
        with self._nodes_lock:
            for node in self.node_list():
                for idx in list(node.pg_bundles.get(pg_id, {})):
                    node.return_bundle(pg_id, idx)
        self.gcs.events.record("pg_removed", pg_id=pg_id.hex())
        self.scheduler.bump_capacity()
        # freed capacity may satisfy queued gang reservations (reference:
        # pending PG queue re-scheduled on resource release)
        for other_id in list(self._pending_pgs):
            other = self.placement_groups.get(other_id)
            if other is not None:
                self._try_place_pg(other)

    def placement_group_table(self) -> list[dict]:
        return [
            {
                "pg_id": p.pg_id.hex(),
                "name": p.name,
                "state": p.state,
                "strategy": p.strategy,
                "bundles": p.bundles,
                "nodes": [n.hex() for n in p.placements],
            }
            for p in self.placement_groups.values()
        ]

    # ------------------------------------------------------------------
    # generators
    # ------------------------------------------------------------------
    def next_generator_item(self, gen_id: ObjectID, index: int, timeout: float | None = None):
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._gen_cond:
            while True:
                gen = self.generators.get(gen_id)
                # error sealed directly on the generator id (worker crash,
                # actor death, dependency failure) terminates the stream
                entry = self.store.try_get_entry(gen_id)
                if entry is not None and entry.error is not None:
                    if gen is None:
                        gen = self.generators.setdefault(gen_id, GenState())
                    gen.finished = True
                    gen.error = entry.error
                if gen is not None:
                    if index < len(gen.items) and gen.items[index] is not _STREAM_HOLE:
                        return gen.items[index]
                    if gen.finished and (index >= len(gen.items) or gen.total_items >= 0):
                        if gen.error is not None and not gen.error_ref_made:
                            gen.error_ref_made = True
                            err_id = ObjectID.for_task_return(gen_id.task_id(), len(gen.items) + 1)
                            self.store.put_error(err_id, gen.error)
                            gen.items.append(err_id)
                            return err_id
                        # exhausted: drop the item list (the obj ids live in the
                        # store; consumers past this point only need StopIteration)
                        # but keep the GenState as a bounded tombstone so a late
                        # or repeat consumer terminates instead of blocking forever
                        if gen.total_items < 0:
                            gen.total_items = len(gen.items)
                            gen.items = []
                            self._gen_tombstones.append(gen_id)
                            while len(self._gen_tombstones) > 4096:
                                old = self._gen_tombstones.popleft()
                                self.generators.pop(old, None)
                        return None
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    raise GetTimeoutError("generator next timed out")
                self._gen_cond.wait(timeout=0.2 if remaining is None else min(remaining, 0.2))

    # ------------------------------------------------------------------
    # scheduling integration
    # ------------------------------------------------------------------
    def reserve_and_queue(self, node: Node, spec: TaskSpec) -> bool:
        sched = spec.scheduling
        res = dict(sched.resources)
        if sched.placement_group is not None:
            idx = sched.bundle_index
            if idx < 0:
                bundles = node.pg_bundles.get(sched.placement_group, {})
                idx = next(
                    (
                        i
                        for i, avail in bundles.items()
                        if all(avail.get(k, 0) >= v - 1e-9 for k, v in res.items() if v > 0)
                    ),
                    -1,
                )
                if idx < 0:
                    return False
            if not node.allocate_from_bundle(sched.placement_group, idx, res):
                return False
            alloc = ("pg", sched.placement_group, idx, res)
        else:
            if not node.allocate(res):
                return False
            alloc = ("node", None, -1, res)
        chips = []
        n_tpu = int(res.get("TPU", 0))
        if n_tpu > 0:
            chips = node.take_tpu_chips(n_tpu)
        with node._lock:
            if not node.alive:
                # raced node removal: don't strand the spec on a dead queue
                self._release_alloc(node, alloc, chips)
                return False
            node.dispatch_queue.append((spec, alloc, chips))
        return True

    def dispatch_all(self):
        for node in self.node_list():
            self._dispatch_node(node)

    @staticmethod
    def _renv_key(spec: TaskSpec) -> str | None:
        renv = spec.runtime_env or {}
        wd = renv.get("_packed_working_dir")
        mods = renv.get("_packed_py_modules") or []
        if not wd and not mods:
            return None
        return (wd or {}).get("hash", "") + ":" + ",".join(m["hash"] for m in mods)

    def _dispatch_node(self, node: Node):
        while True:
            with node._lock:
                if not node.alive or not node.dispatch_queue:
                    return
                spec, alloc, chips = node.dispatch_queue[0]
            renv_key = self._renv_key(spec)
            # a worker is reusable iff its sticky env is compatible: no TPU
            # chip binding, and either the same runtime_env materialization
            # or none yet (it gets bound on dispatch). Workers bound to a
            # DIFFERENT runtime_env (or any env, for a plain task) are
            # excluded — their cwd/sys.path are polluted.
            idle = []
            for w in node.idle_workers():
                if "TPU_VISIBLE_CHIPS" in w.env_binding:
                    continue
                wkey = w.env_binding.get("runtime_env")
                if wkey == renv_key or wkey is None:
                    idle.append(w)
            idle.sort(key=lambda w: w.env_binding.get("runtime_env") != renv_key)
            _reuse_dbg = os.environ.get("RT_DEBUG_REUSE_ACTOR_WORKERS") == "1"
            if chips or (spec.is_actor_creation and not _reuse_dbg):
                # never-used workers only: chip-isolation env must precede
                # any jax import, and actors get a dedicated fresh process
                # (reference parity: the raylet does not recycle task
                # workers into actors). The actor rule is load-bearing
                # here too: an actor placed on a worker that previously
                # executed Data block tasks intermittently segfaulted in
                # pyarrow reading its dataset shard (the second-fit crash;
                # tests/test_train.py::test_second_dataset_fit_same_session).
                idle = [w for w in idle if w.fresh]
            if not idle:
                starting = sum(1 for w in node.workers.values() if w.state == "starting")
                nonactor = sum(1 for w in node.workers.values() if w.state in ("starting", "idle", "busy"))
                limit = int(node.total_resources.get("CPU", 1)) + self._worker_count_limit_extra
                # actor creations (like chip-bound tasks) need a FRESH
                # worker and may find the pool full of used idle ones —
                # they must be allowed to spawn past the soft limit
                if (nonactor < limit or chips or spec.is_actor_creation) and starting < len(node.dispatch_queue):
                    try:
                        node.start_worker()
                    except RuntimeError:
                        pass  # node shut down mid-spawn; queue drains via remove_node
                elif nonactor >= limit and starting == 0:
                    # pool full of env-incompatible idle workers (different
                    # runtime_env or chip binding): retire one so a
                    # compatible worker can spawn — otherwise dispatch
                    # deadlocks with resources reserved forever
                    stale = [w for w in node.idle_workers() if w.env_binding]
                    if stale:
                        victim = min(stale, key=lambda w: w.last_idle)
                        victim.state = "retiring"
                        try:
                            victim.proc.terminate()
                        except Exception:
                            pass
                return
            with node._lock:
                if not node.alive or not node.dispatch_queue or node.dispatch_queue[0][0] is not spec:
                    continue  # raced remove_node's drain
                # _dispatch_node runs concurrently from the scheduler pass,
                # the completion fast path (worker-IO thread) and
                # _fast_submit: the worker must be claimed under the node
                # lock or two dispatchers hand two tasks to the same
                # worker. The claim re-checks env compatibility too — a
                # racing dispatcher may have bound a different runtime_env
                # to this worker since the idle snapshot above.
                w = next(
                    (
                        x
                        for x in idle
                        if x.state == "idle"
                        and (not (chips or (spec.is_actor_creation and not _reuse_dbg)) or x.fresh)
                        and "TPU_VISIBLE_CHIPS" not in x.env_binding
                        and x.env_binding.get("runtime_env") in (renv_key, None)
                    ),
                    None,
                )
                if w is None:
                    continue  # idle snapshot went stale; rescan
                node.dispatch_queue.pop(0)
                w.state = "busy"
            self._dispatch_to_worker(node, w, spec, alloc, chips)

    def _dispatch_to_worker(self, node: Node, worker: WorkerHandle, spec: TaskSpec, alloc, chips):
        env = {}
        if chips:
            from ray_tpu.accelerators.tpu import TPUAcceleratorManager

            env.update(TPUAcceleratorManager.worker_env_for_chips(chips))
            worker.env_binding = {"TPU_VISIBLE_CHIPS": env["TPU_VISIBLE_CHIPS"]}
        if spec.runtime_env and spec.runtime_env.get("env_vars"):
            env.update(spec.runtime_env["env_vars"])
        renv_key = self._renv_key(spec)
        if renv_key is not None:
            worker.env_binding["runtime_env"] = renv_key
        resources = dict(alloc[3])
        if chips:
            resources["_tpu_chip_ids"] = chips
        msg = self._build_exec_msg(spec, node, resources=resources, env=env)
        if msg is None:
            self._release_alloc(node, alloc, chips)
            # un-claim: the worker was marked busy under the node lock in
            # _dispatch_node before the exec message was built
            if worker.state == "busy":
                worker.state = "idle"
                worker.last_idle = time.monotonic()
            return
        if spec.is_actor_creation:
            worker.state = "actor"
            worker.actor_id = spec.actor_id
            astate = self.actors[spec.actor_id]
            with astate.lock:
                astate.info.node_id = node.node_id
                astate.info.worker_id = worker.worker_id
                astate.allocation = (node, alloc, chips)
        else:
            worker.state = "busy"
        worker.fresh = False
        worker.running_tasks[spec.task_id] = (spec, (node, alloc, chips))
        self.task_manager.mark_running(spec.task_id, node.node_id, worker.worker_id)
        try:
            worker.send(msg)
        except (OSError, ValueError):
            self._on_worker_death(node, worker, "send failed")

    def _build_exec_msg(self, spec: TaskSpec, node: Node, resources: dict, env: dict | None):
        """Resolve ref args into payloads; returns None if a dependency
        failed (the dependency's error is propagated to the task returns)."""
        args, err = self._resolve_args(spec.args)
        if err is None:
            kw, err = self._resolve_kwargs(getattr(spec, "_kwargs", {}))
        if err is not None:
            retried = self.task_manager.handle_app_error(spec.task_id, err if isinstance(err, TaskError) else TaskError.from_exception(err, spec.desc()))
            if not retried:
                for oid in self._spec_return_ids(spec):
                    self.store.put_error(oid, err)
            return None
        import dataclasses

        wire_spec = dataclasses.replace(spec, args=[])  # args travel separately, resolved
        return {
            "type": "exec",
            "spec": wire_spec,
            "args": args,
            "kwargs": kw,
            "resources": resources,
            "env": env,
        }

    def _resolve_args(self, args: list[ArgSpec]):
        out = []
        for a in args:
            if a.ref is None:
                out.append(a)
                continue
            entry = self.store.try_get_entry(a.ref)
            if entry is None:
                # evicted or not yet local: let the worker fetch via RPC
                out.append(a)
                continue
            if entry.error is not None:
                return None, entry.error
            out.append(ArgSpec(payload=self.entry_to_payload(entry)))
        return out, None

    def _resolve_kwargs(self, kwargs: dict[str, ArgSpec]):
        out = {}
        for k, a in (kwargs or {}).items():
            lst, err = self._resolve_args([a])
            if err is not None:
                return None, err
            out[k] = lst[0]
        return out, None

    def _spec_return_ids(self, spec: TaskSpec):
        if spec.streaming:
            with self._gen_cond:
                self.generators.setdefault(spec.generator_id(), GenState())
            return [spec.generator_id()]
        return spec.return_ids()

    def _release_alloc(self, node: Node, alloc, chips):
        if chips:
            node.return_tpu_chips(chips)
        kind, pg_id, idx, res = alloc
        if kind == "pg":
            node.release_to_bundle(pg_id, idx, res)
        else:
            node.release(res)
        # parked (infeasible/busy) shapes become placeable again
        self.scheduler.bump_capacity()

    # ------------------------------------------------------------------
    # worker IO loop
    # ------------------------------------------------------------------
    def _retire_conn(self, conn):
        """Queue a dead worker's pipe for closing ON the io-loop thread.

        Closing it here (possibly from a kill/submit-failure thread) frees
        the fd while the io loop's current mp_connection.wait() may still
        list this Connection; a NEW worker's pipe can then be allocated
        the SAME fd number, and the stale Connection object steals the new
        worker's bytes — the head misreads the framing and declares a
        perfectly healthy worker dead (observed as a second Trainer.fit
        dying with 'worker process exited' while the process lived on).
        Only the io loop closes pipes it waits on."""
        with self._conn_graveyard_lock:
            self._conn_graveyard.append(conn)

    def _drain_conn_graveyard(self):
        with self._conn_graveyard_lock:
            conns, self._conn_graveyard = self._conn_graveyard, []
        for c in conns:
            try:
                c.close()
            except Exception:
                pass

    def _io_loop(self):
        while not self._stopped:
            # safe point: the previous wait() has returned, so no listed
            # fd is still being polled — dead pipes can close without
            # their fd numbers being reused under the poll
            self._drain_conn_graveyard()
            conn_map = {}
            for node in self.node_list():
                if getattr(node, "remote", False):
                    conn_map[node.agent_conn] = (node, None)
                    continue
                for w in list(node.workers.values()):
                    if w.state != "dead":
                        conn_map[w.conn] = (node, w)
            if not conn_map:
                time.sleep(0.02)
                continue
            try:
                ready = mp_connection.wait(list(conn_map), timeout=0.05)
            except OSError:
                continue
            for c in ready:
                node, w = conn_map[c]
                if w is not None and w.state == "dead":
                    # died (on another thread) after this wait() started:
                    # its conn is graveyarded but still open, so buffered
                    # messages would otherwise be applied for a holder
                    # whose state was already dropped (e.g. ref_events
                    # re-registering borrows after _drop_holder)
                    continue
                if w is None:  # node-agent socket
                    try:
                        msg = c.recv()
                    except (EOFError, OSError):
                        self._on_agent_death(node)
                        continue
                    try:
                        self._handle_agent_msg(node, msg)
                    except Exception:
                        logger.exception("error handling agent message %s", msg.get("type"))
                    continue
                try:
                    msg = c.recv()
                except (EOFError, OSError):
                    # a broken channel from a STILL-LIVE process (observed
                    # after a sibling worker segfaults mid-read) must not
                    # leave a zombie holding an actor: kill it so the
                    # death handling below matches reality
                    if w.proc.is_alive():
                        try:
                            w.proc.terminate()
                        except Exception:
                            pass
                        reason = "worker channel broke (process terminated)"
                    else:
                        reason = "worker process exited"
                    self._on_worker_death(node, w, reason)
                    continue
                except Exception:
                    logger.exception("bad message from worker")
                    continue
                try:
                    self._handle_worker_msg(node, w, msg)
                except Exception:
                    logger.exception("error handling worker message %s", msg.get("type"))

    def _handle_agent_msg(self, node: Node, msg: dict):
        """Demultiplex one envelope from a node-agent socket."""
        from ray_tpu.core import rpc_chaos
        from ray_tpu.core.ids import WorkerID

        t = msg.get("type")
        if not rpc_chaos.apply(t):
            return  # chaos: inbound message dropped
        if t == "from_worker":
            w = node.workers.get(WorkerID.from_hex(msg["wid"]))
            if w is not None and w.state != "dead":
                self._handle_worker_msg(node, w, msg["data"])
        elif t == "worker_death":
            w = node.workers.get(WorkerID.from_hex(msg["wid"]))
            if w is not None:
                w.proc.dead = True
                self._on_worker_death(node, w, msg.get("reason", "worker died"))
        elif t == "worker_started":
            w = node.workers.get(WorkerID.from_hex(msg["wid"]))
            if w is not None:
                w.proc.pid = msg.get("pid")
        elif t == "pong":
            node.last_pong = time.monotonic()
        elif t == "resolve_ns":
            # owner-directory lookup: which node serves this shm namespace
            # (reference: ownership_object_directory.h)
            ns = msg.get("ns", "")
            node.agent_send({"type": "ns_addr", "ns": ns, "addr": self._ns_addrs.get(ns)})

    def _state_dump_loop(self):
        """Periodic session state.json for the out-of-process CLI
        (util/state.py; reference: `ray status` against the state API)."""
        from ray_tpu.util import state as state_mod

        while not self._stopped:
            time.sleep(self.cfg.state_dump_interval_s)
            if self._stopped:
                return
            try:
                state_mod.dump_state(self)
            except Exception:
                pass

    # ------------------------------------------------------------------
    # reference-counted object GC (reference: reference_counter.h)
    # ------------------------------------------------------------------
    def _ref_gc_loop(self):
        """Drain the head process's own 1->0 transitions and re-check any
        object whose last known holder vanished."""
        from ray_tpu.core.object_ref import drain_ref_events

        from ray_tpu.core import direct as _direct

        while not self._stopped:
            time.sleep(self.cfg.ref_counting_interval_s)
            if self._stopped:
                return
            try:
                events = drain_ref_events()
                st = _direct.state()
                if st is not None and st.client is self:
                    # owned-object events apply owner-locally; remote-owned
                    # events flow to their owners (core/direct.py)
                    events = st.route_ref_events(events)
                for k, registered in events:
                    if not registered:
                        self._maybe_free_object(k)
            except Exception:
                logger.exception("ref gc loop error")

    def on_ref_events(self, holder: str, events: list):
        """A worker's batched 0->1 / 1->0 local-count transitions."""
        to_check = []
        with self._rc_head_lock:
            for k, registered in events:
                if registered:
                    self._ref_holders.setdefault(k, set()).add(holder)
                else:
                    s = self._ref_holders.get(k)
                    if s is not None:
                        s.discard(holder)
                        if not s:
                            del self._ref_holders[k]
                            to_check.append(k)
        for k in to_check:
            self._maybe_free_object(k)

    def _drop_holder(self, holder: str):
        """A worker process died: everything it held is released."""
        to_check = []
        with self._rc_head_lock:
            for k, s in list(self._ref_holders.items()):
                s.discard(holder)
                if not s:
                    del self._ref_holders[k]
                    to_check.append(k)
        for k in to_check:
            self._maybe_free_object(k)

    def pin_spec_args(self, spec: TaskSpec):
        """Pin every object a live spec's args reference (top-level refs +
        refs pickled inside payloads) — retries/lineage re-resolve them."""
        if not self.cfg.object_ref_counting:
            return
        if getattr(spec, "_pinned_arg_ids", None) is not None:
            return  # already pinned (actor restarts re-register the spec)
        ids = set()
        for a in list(spec.args) + list(getattr(spec, "_kwargs", {}).values()):
            if a.ref is not None:
                ids.add(a.ref.binary())
            if a.payload is not None:
                for c in a.payload.contained or []:
                    ids.add(c.binary())
        spec._pinned_arg_ids = ids
        with self._rc_head_lock:
            for k in ids:
                self._arg_pins[k] = self._arg_pins.get(k, 0) + 1

    def unpin_spec_args(self, spec: TaskSpec):
        ids = getattr(spec, "_pinned_arg_ids", None)
        if not ids:
            return
        spec._pinned_arg_ids = None
        with self._rc_head_lock:
            for k in ids:
                n = self._arg_pins.get(k, 0) - 1
                if n <= 0:
                    self._arg_pins.pop(k, None)
                else:
                    self._arg_pins[k] = n
        for k in ids:
            self._maybe_free_object(k)

    def _maybe_free_object(self, k: bytes):
        """Free the store entry once NOTHING can reach it: no ref in any
        process (head local count included — store containers hold live
        refs there), no live spec pinning it as an argument."""
        if self._stopped or not self.cfg.object_ref_counting:
            return
        if k.endswith(b"\xfe\xfe\xfe\xfe"):
            return  # actor-ready sentinels are runtime-managed
        from ray_tpu.core.object_ref import local_ref_count

        oid = ObjectID(k)
        if oid in self.generators:
            return  # streaming generator state (incl. tombstones) manages these
        with self._rc_head_lock:
            # holder registrations serialize on this lock, and the local
            # count is re-checked immediately before the delete — the
            # remaining head-local incref window is the unavoidable
            # distributed-GC race, shrunk to the delete call itself
            if self._ref_holders.get(k) or self._arg_pins.get(k, 0) > 0:
                return
            if local_ref_count(oid) > 0:
                return
            entry = self.store.try_get_entry(oid)
            if entry is not None:
                self.store.delete(oid)
                # a late get() of a freed id must error, not block forever
                if len(self._freed_ids) == self._freed_ids.maxlen:
                    self._freed_set.discard(self._freed_ids[0])
                self._freed_ids.append(oid)
                self._freed_set.add(oid)
                # the entry's contained_refs die with it -> cascading
                # releases surface on the next gc tick
        # transitive lineage release: once ALL of a terminal task's outputs
        # are unreachable, reconstruction can never run again, so the
        # spec's argument pins release too (reference: lineage refcounting)
        self._maybe_release_lineage(oid)

    def _maybe_release_lineage(self, oid: ObjectID):
        try:
            tid = oid.task_id()
        except Exception:
            return
        st = self.task_manager.get(tid)
        if st is None or getattr(st.spec, "_pinned_arg_ids", None) is None:
            return
        from ray_tpu.core.task_manager import TERMINAL

        if st.status not in TERMINAL:
            return
        from ray_tpu.core.object_ref import local_ref_count

        for out_id in self._spec_return_ids(st.spec):
            if self.store.contains(out_id) or local_ref_count(out_id) > 0:
                return
            with self._rc_head_lock:
                if self._ref_holders.get(out_id.binary()):
                    return
        self.unpin_spec_args(st.spec)

    def _on_agent_death(self, node: Node):
        """A node agent went away: the whole node is dead (reference:
        gcs_health_check_manager.h:45 failure path)."""
        if not node.alive:
            return
        logger.warning("node agent %s died; removing node", node.node_id.hex()[:8])
        self.remove_node(node.node_id, graceful=False)

    def _health_loop(self):
        """Ping node agents; declare nodes dead after threshold misses
        (reference: gcs_health_check_manager.h — period + failure
        threshold)."""
        from ray_tpu.core import rpc_chaos

        period = self.cfg.health_check_period_s
        threshold = self.cfg.health_check_failure_threshold
        while not self._stopped:
            time.sleep(period)
            for node in self.node_list():
                if not getattr(node, "remote", False) or not node.alive:
                    continue
                if time.monotonic() - node.last_pong > period * threshold:
                    logger.warning(
                        "node %s failed %d health checks; declaring dead",
                        node.node_id.hex()[:8],
                        threshold,
                    )
                    self._on_agent_death(node)
                    continue
                node.ping_seq += 1
                if rpc_chaos.apply("ping"):
                    node.agent_send({"type": "ping", "seq": node.ping_seq})

    def _handle_worker_msg(self, node: Node, w: WorkerHandle, msg: dict):
        from ray_tpu.core import rpc_chaos

        t = msg["type"]
        if not rpc_chaos.apply(t):
            return  # chaos: per-message-type fault injection (done, stream_item, ...)
        if t == "ready":
            if msg.get("direct_addr"):
                w.direct_addr = tuple(msg["direct_addr"])
            if w.state == "starting":
                w.state = "idle"
                w.last_idle = time.monotonic()
            self.scheduler.wake()
        elif t == "done":
            self._on_task_done(node, w, msg)
        elif t == "seal":
            # a worker completed a direct call with large results: they
            # live in shm under head ownership (core/direct.py)
            for oid, payload in msg["items"]:
                self.put_payload(oid, payload)
        elif t == "task_events":
            # batched spans of direct-plane executions (observability)
            self.task_manager.record_external(msg["events"], node_id=node.node_id, worker_id=w.worker_id)
        elif t == "stream_item":
            self._on_stream_item(msg)
        elif self._dispatch_client_msg(w, msg):
            pass  # shared client-protocol subset (req/agent_req/ref_events)
        elif t == "stack_dump_result":
            with self._dc_lock:
                slot = self._stack_pending.get(msg.get("req_id"))
            if slot is not None:
                slot[1][w.worker_id.hex()] = {
                    "pid": msg.get("pid"),
                    "current_task": msg.get("current_task"),
                    "stacks": msg.get("stacks", {}),
                }
                slot[0].set()
        elif t == "pong":
            pass

    def _dispatch_client_msg(self, handle, msg: dict) -> bool:
        """The client-protocol subset shared by worker pipes and attached
        drivers: req (control-plane RPC), agent_req (the head filling the
        agent role for same-namespace peers), ref_events (borrow-protocol
        flushes, ordered with the sender's other messages on one channel).
        Returns True when handled."""
        t = msg.get("type")
        if t == "req":
            self._req_pool.submit(self._handle_client_req, handle, msg)
        elif t == "agent_req":
            self._req_pool.submit(self._handle_agent_req_local, handle, msg)
        elif t == "ref_events":
            self.on_ref_events(handle.worker_id.hex(), [(bytes.fromhex(h), reg) for h, reg in msg["events"]])
        else:
            return False
        return True

    def _handle_agent_req_local(self, w: WorkerHandle, msg: dict):
        resp = {"type": "resp", "req_id": msg["req_id"], "ok": True, "payload": None, "error": None}
        try:
            if msg.get("method") == "fetch_object":
                desc = msg["params"]["desc"]
                from ray_tpu.core.object_store import ensure_local_segment

                resp["payload"] = ensure_local_segment(desc)
            else:
                raise ValueError(f"unknown agent method {msg.get('method')!r}")
        except BaseException as e:  # noqa: BLE001
            resp["ok"] = False
            resp["error"] = e
        try:
            w.send(resp)
        except Exception:
            pass

    def _on_task_done(self, node: Node, w: WorkerHandle, msg: dict):
        if msg.get("ref_events"):
            # borrows registered BEFORE any pin release below
            self.on_ref_events(
                w.worker_id.hex(), [(bytes.fromhex(h), reg) for h, reg in msg["ref_events"]]
            )
        task_id = msg["task_id"]
        entry = w.running_tasks.pop(task_id, None)
        if entry is None:
            return
        spec, allocation = entry
        if allocation is not None and not spec.is_actor_creation:
            anode, alloc, chips = allocation
            if w.state == "busy" and w.env_binding:
                # TPU-bound workers are single-use: the chip binding is baked
                # into the process (jax backend init). Release CPU-side
                # resources now but hold the chips until the process has
                # actually exited — a fresh worker must not bind chips the
                # dying libtpu still holds.
                self._release_alloc(anode, alloc, [])
                w.retired_chips = (anode, chips)
                w.state = "retiring"
                try:
                    w.send({"type": "shutdown"})
                except Exception:
                    self._finish_retirement(node, w)
            else:
                self._release_alloc(anode, alloc, chips)
                if w.state == "busy":
                    w.state = "idle"
                    w.last_idle = time.monotonic()
                    # completion fast path: grab the next ready task for
                    # this node inline (IO thread), skipping the scheduler
                    # thread wake for the common unconstrained case
                    try:
                        self.scheduler.take_ready_for(node, self.reserve_and_queue)
                        self._dispatch_node(node)
                    except Exception:
                        logger.exception("fast dispatch failed")
        err = msg.get("error")
        if spec.is_actor_creation:
            self._on_actor_creation_done(spec, err, w)
            self.scheduler.wake()
            return
        if err is not None:
            retried = self.task_manager.handle_app_error(task_id, err)
            if not retried:
                if spec.streaming:
                    with self._gen_cond:
                        gen = self.generators.setdefault(spec.generator_id(), GenState())
                        gen.finished = True
                        gen.error = err
                        self._gen_cond.notify_all()
                else:
                    for oid in spec.return_ids():
                        self.store.put_error(oid, err)
        else:
            for oid, payload in msg["returns"]:
                self.put_payload(oid, payload)
            if spec.streaming:
                with self._gen_cond:
                    gen = self.generators.setdefault(spec.generator_id(), GenState())
                    gen.finished = True
                    self._gen_cond.notify_all()
            self.task_manager.complete(task_id)
        self.gcs.events.record("task_finished", task_id=task_id.hex(), name=spec.name, ok=err is None)
        self.scheduler.wake()

    def _on_actor_creation_done(self, spec: TaskSpec, err, w: WorkerHandle):
        astate = self.actors.get(spec.actor_id)
        if astate is None:
            return
        with astate.lock:
            if astate.info.state == "DEAD":
                # killed while the creation was in flight: tear down the
                # worker that just constructed it
                if w is not None:
                    try:
                        w.proc.terminate()
                    except Exception:
                        pass
                return
            if err is not None:
                astate.info.state = "DEAD"
                astate.info.death_cause = f"creation failed: {err}"
                self.store.put_error(_actor_ready_oid(spec.actor_id), err)
                pending, astate.pending = astate.pending, []
                for p in pending:
                    for oid in self._spec_return_ids(p):
                        self.store.put_error(oid, ActorDiedError(spec.actor_id, astate.info.death_cause))
                self._release_actor_resources(astate)
                return
            astate.info.state = "ALIVE"
            self.store.put_serialized(_actor_ready_oid(spec.actor_id), _to_serialized(True))
            pending, astate.pending = astate.pending, []
            for p in pending:
                self._dispatch_actor_task(astate, p)
        self.gcs.events.record("actor_alive", actor_id=spec.actor_id.hex())

    def _on_stream_item(self, msg: dict):
        task_id = msg["task_id"]
        obj_id = msg["obj_id"]
        index = msg.get("index", None)
        self.put_payload(obj_id, msg["payload"])
        gen_id = ObjectID.for_task_return(task_id, 0)
        with self._gen_cond:
            gen = self.generators.setdefault(gen_id, GenState())
            # Place idempotently by the worker-assigned index so a retried
            # attempt replaying its prefix never duplicates items consumers
            # already saw (reference keys streamed returns by index).
            if index is None:
                gen.items.append(obj_id)
            elif index < len(gen.items):
                gen.items[index] = obj_id
            else:
                if index > len(gen.items):
                    # protocol violation over in-order pipes; holes make the
                    # reader wait (not truncate) until the item is replayed
                    logger.error("stream gap for %s: got index %d at length %d", gen_id, index, len(gen.items))
                    gen.items.extend([_STREAM_HOLE] * (index - len(gen.items)))
                gen.items.append(obj_id)
            self._gen_cond.notify_all()

    def _finish_retirement(self, node: Node, w: WorkerHandle):
        """The retired TPU worker's process is gone: chips are safe to reuse."""
        retired = getattr(w, "retired_chips", None)
        if retired is not None:
            anode, chips = retired
            w.retired_chips = None
            anode.return_tpu_chips(chips)
        w.state = "dead"
        node.remove_worker(w.worker_id)
        self._retire_conn(w.conn)
        self.scheduler.wake()

    # ---- worker death / actor restart ----
    def _on_worker_death(self, node: Node, w: WorkerHandle, reason: str):
        if w.state == "dead" or self._stopped:
            return
        self._drop_holder(w.worker_id.hex())
        # direct plane: reclaim the lease ON this worker and any leases it
        # held as a client
        with self._leases_lock:
            lease = self._leases.pop(w.worker_id, None)
        if lease is not None:
            lnode, res, _owner = lease
            lnode.release(res)
        self._release_leases_of_owner(w.worker_id.hex())
        if w.state == "retiring":
            self._finish_retirement(node, w)
            return
        was_actor = w.state == "actor"
        w.state = "dead"
        node.remove_worker(w.worker_id)
        self._retire_conn(w.conn)
        running = dict(w.running_tasks)
        w.running_tasks.clear()
        for task_id, (spec, allocation) in running.items():
            if allocation is not None and not spec.is_actor_creation:
                anode, alloc, chips = allocation
                self._release_alloc(anode, alloc, chips)
            if spec.is_actor_creation or spec.actor_id is not None:
                continue  # handled by actor death path
            self.task_manager.handle_worker_crash(task_id, reason)
        if was_actor and w.actor_id is not None:
            self._on_actor_worker_death(w.actor_id, running, reason)
        self.scheduler.wake()

    def _on_actor_worker_death(self, actor_id: ActorID, running: dict, reason: str):
        astate = self.actors.get(actor_id)
        if astate is None:
            return
        with astate.lock:
            info = astate.info
            inflight = [spec for _, (spec, _) in running.items() if not spec.is_actor_creation]
            if astate.expected_exit or info.num_restarts >= info.max_restarts:
                cause = "expected exit" if astate.expected_exit else f"{reason}; max_restarts exhausted"
                self._finalize_actor_death(astate, cause, inflight)
                return
            # restart (reference: gcs_actor_manager.h restart state machine)
            info.num_restarts += 1
            info.state = "RESTARTING"
            logger.info("restarting actor %s (%d/%d): %s", actor_id.hex()[:8], info.num_restarts, info.max_restarts, reason)
            for spec in inflight:
                if info.max_task_retries != 0:
                    astate.pending.append(spec)
                else:
                    for oid in self._spec_return_ids(spec):
                        self.store.put_error(oid, ActorDiedError(actor_id, f"actor died while task inflight: {reason}"))
            self.store.delete(_actor_ready_oid(actor_id))
            if astate.allocation is not None:
                anode, alloc, chips = astate.allocation
                self._release_alloc(anode, alloc, chips)
                astate.allocation = None
            creation = info.creation_spec
        self.task_manager.register(creation)
        self.scheduler.submit(creation)

    def _finalize_actor_death(self, astate: ActorState, cause: str, inflight: list | None = None):
        info = astate.info
        info.state = "DEAD"
        info.death_cause = cause
        if info.creation_spec is not None:
            self.unpin_spec_args(info.creation_spec)  # no more restarts
        # ready-ref waiters must observe the death (even if creation never ran)
        self.store.put_error(_actor_ready_oid(info.actor_id), ActorDiedError(info.actor_id, cause))
        for spec in inflight or []:
            for oid in self._spec_return_ids(spec):
                self.store.put_error(oid, ActorDiedError(info.actor_id, cause))
        pending, astate.pending = astate.pending, []
        for spec in pending:
            for oid in self._spec_return_ids(spec):
                self.store.put_error(oid, ActorDiedError(info.actor_id, cause))
        self._release_actor_resources(astate)
        if info.name:
            self.gcs.unregister_named_actor(info.name, info.namespace)
        if info.detached:
            self.gcs.drop_detached_actor(info.actor_id)  # dead for good
        self.gcs.events.record("actor_dead", actor_id=info.actor_id.hex(), cause=cause)

    def _release_actor_resources(self, astate: ActorState):
        if astate.allocation is not None:
            node, alloc, chips = astate.allocation
            self._release_alloc(node, alloc, chips)
            astate.allocation = None

    # ------------------------------------------------------------------
    # client RPC handling (requests from worker processes)
    # ------------------------------------------------------------------
    def _handle_client_req(self, w: WorkerHandle, msg: dict):
        method = msg["method"]
        params = msg["params"]
        if method == "lease_worker":
            # lease ownership rides the requesting channel's identity so a
            # dead client's leases can be reclaimed
            params = {**params, "_owner": w.worker_id.hex()}
        try:
            handler = getattr(self, f"_rpc_{method}", None)
            if handler is None:
                raise AttributeError(f"unknown client RPC {method}")
            payload = handler(**params)
            w.send({"type": "resp", "req_id": msg["req_id"], "ok": True, "payload": payload})
        except BaseException as e:  # noqa: BLE001
            try:
                w.send({"type": "resp", "req_id": msg["req_id"], "ok": False, "error": _picklable_error(e)})
            except Exception:
                logger.exception("failed to send error response")

    def _rpc_object_locations(self, obj_ids):
        return self.object_locations(obj_ids)

    def _rpc_get_object(self, obj_id, timeout_s=None):
        deadline = None if timeout_s is None else time.monotonic() + timeout_s
        entry = self._get_entry_reconstructing(obj_id, deadline)
        if entry is None:
            raise GetTimeoutError(f"get() timed out waiting for {obj_id.hex()[:16]}")
        return self.entry_to_payload(entry)

    def _rpc_put_object(self, obj_id, payload):
        self.put_payload(obj_id, payload)
        return True

    def _rpc_mark_object_lost(self, obj_id):
        # a worker failed to attach the segment: restore from spill when
        # the bytes are on disk, otherwise mark lost for reconstruction
        self.store.restore_or_mark_lost(obj_id)
        return True

    def _rpc_wait_ready(self, obj_ids, num_returns, timeout_s=None):
        return self.store.wait_ready(obj_ids, num_returns, timeout_s)

    def _rpc_submit_task(self, **kw):
        return self.submit_task(**kw)

    def _rpc_create_actor(self, **kw):
        return self.create_actor(**kw)

    def _rpc_submit_actor_task(self, **kw):
        return self.submit_actor_task(**kw)

    def _rpc_kill_actor(self, actor_id, no_restart=True):
        self.kill_actor(actor_id, no_restart)
        return True

    def _rpc_cancel_task(self, obj_id, force=False):
        return self.cancel_task(obj_id, force)

    def _rpc_get_actor_handle_info(self, name, namespace="default"):
        return self.get_actor_handle_info(name, namespace)

    def _rpc_next_generator_item(self, gen_id, index, timeout_s=None):
        return self.next_generator_item(gen_id, index, timeout=timeout_s)

    def _rpc_free_objects(self, obj_ids):
        self.free_objects(obj_ids)
        return True

    def _rpc_get_function(self, func_id):
        return self.get_function_blob(func_id)

    def _rpc_actor_endpoint(self, actor_id):
        return self.actor_endpoint(actor_id)

    def _rpc_lease_worker(self, _owner=""):
        return self.lease_worker(owner=_owner)

    def _rpc_release_lease(self, wid):
        return self.release_lease(wid)

    def _rpc_cluster_info(self, kind):
        return self.cluster_info(kind)

    def _rpc_kv(self, op, **kw):
        return getattr(self.gcs.kv, op)(**kw)

    def _rpc_pg(self, op, **kw):
        if op == "create":
            return self.create_placement_group(**kw)
        if op == "wait":
            return self.wait_placement_group(**kw)
        if op == "remove":
            return self.remove_placement_group(**kw)
        if op == "table":
            return self.placement_group_table()
        raise ValueError(op)

    def pg(self, op, **kw):
        return self._rpc_pg(op, **kw)

    def kv(self, op, **kw):
        return getattr(self.gcs.kv, op)(**kw)

    # ------------------------------------------------------------------
    # misc API
    # ------------------------------------------------------------------
    def cancel_task(self, obj_id: ObjectID, force: bool = False) -> bool:
        from ray_tpu.exceptions import RayTpuError

        task_id = obj_id.task_id()
        if self.scheduler.remove_task(task_id):
            self.task_manager.mark_cancelled(task_id)
            st = self.task_manager.get(task_id)
            if st:
                for oid in self._spec_return_ids(st.spec):
                    self.store.put_error(oid, RayTpuError(f"task {task_id.hex()[:8]} was cancelled"))
            return True
        # running streaming task: cooperative cancel — the worker's
        # generator loop stops between items and ends the stream cleanly
        # (reference: streaming generator cancellation in core_worker)
        for node in self.node_list():
            for w in list(node.workers.values()):
                entry = w.running_tasks.get(task_id)
                if entry is not None and entry[0].streaming:
                    try:
                        w.send({"type": "cancel_stream", "task_id": task_id})
                    except Exception:
                        pass
                    return True
        if force:
            for node in self.node_list():
                for w in list(node.workers.values()):
                    if task_id in w.running_tasks and w.state == "busy":
                        self.task_manager.mark_cancelled(task_id)
                        try:
                            w.proc.terminate()
                        except Exception:
                            pass
                        return True
        return False

    # ------------------------------------------------------------------
    # direct call plane: endpoints + worker leases (core/direct.py;
    # reference: cluster_lease_manager.h lease-based scheduling)
    # ------------------------------------------------------------------
    def actor_endpoint(self, actor_id) -> dict | None:
        """Direct address of an ALIVE actor's worker, or None (caller then
        stays on the head path, which owns PENDING/RESTARTING queueing)."""
        if isinstance(actor_id, str):
            actor_id = ActorID.from_hex(actor_id)
        astate = self.actors.get(actor_id)
        if astate is None:
            return None
        info = astate.info
        if info.state != "ALIVE":
            return None
        node = self.nodes.get(info.node_id)
        w = node.workers.get(info.worker_id) if node else None
        if w is None or not w.alive() or w.direct_addr is None:
            return None
        return {
            "addr": w.direct_addr,
            "epoch": info.num_restarts,
            "max_task_retries": info.max_task_retries,
        }

    def lease_worker(self, owner: str = "") -> dict | None:
        """Reserve one CPU and a worker for direct task submission. The
        worker leaves the dispatch pool until the lease is released."""
        res = {"CPU": 1.0}
        for node in self.node_list():
            if getattr(node, "remote", False) and not node.workers:
                continue
            if not node.allocate(res):
                continue
            w = self._claim_lease_worker(node)
            if w is None:
                node.release(res)
                continue
            with self._leases_lock:
                self._leases[w.worker_id] = (node, res, owner)
            return {"wid": w.worker_id.hex(), "addr": w.direct_addr}
        return None

    def _claim_lease_worker(self, node: Node, timeout: float = 15.0):
        """An idle unbound worker with a direct address; spawns one if the
        pool is empty (bounded wait for its ready handshake)."""
        deadline = time.monotonic() + timeout
        spawned = False
        while time.monotonic() < deadline and not self._stopped:
            with node._lock:
                for w in node.workers.values():
                    if w.state == "idle" and not w.env_binding and w.direct_addr is not None:
                        w.state = "leased"
                        return w
                starting = any(w.state == "starting" for w in node.workers.values())
            if not starting and not spawned:
                try:
                    node.start_worker()
                    spawned = True
                except RuntimeError:
                    return None
            time.sleep(0.005)
        return None

    def release_lease(self, wid_hex: str) -> bool:
        from ray_tpu.core.ids import WorkerID

        wid = WorkerID.from_hex(wid_hex) if isinstance(wid_hex, str) else wid_hex
        with self._leases_lock:
            lease = self._leases.pop(wid, None)
        if lease is None:
            return False
        node, res, _owner = lease
        node.release(res)
        w = node.workers.get(wid)
        if w is not None and w.state == "leased":
            w.state = "idle"
            w.last_idle = time.monotonic()
        self.scheduler.bump_capacity()
        return True

    def terminate_leased_worker(self, wid_hex: str) -> bool:
        """force-cancel support for direct-plane tasks: kill a LEASED
        worker (only — never an actor/busy worker) so the caller's conn
        death completes the cancelled call."""
        from ray_tpu.core.ids import WorkerID

        wid = WorkerID.from_hex(wid_hex) if isinstance(wid_hex, str) else wid_hex
        for node in self.node_list():
            w = node.workers.get(wid)
            if w is not None and w.state == "leased":
                try:
                    w.proc.terminate()
                except Exception:
                    pass
                return True
        return False

    def _rpc_terminate_leased_worker(self, wid):
        return self.terminate_leased_worker(wid)

    def _release_leases_of_owner(self, owner_hex: str):
        with self._leases_lock:
            doomed = [wid for wid, (_, _, o) in self._leases.items() if o == owner_hex]
        for wid in doomed:
            self.release_lease(wid)

    def cluster_info(self, kind: str):
        if kind == "nodes":
            return [
                {
                    "node_id": n.node_id.hex(),
                    "alive": n.alive,
                    "resources": dict(n.total_resources),
                    "available": dict(n.available),
                    "labels": dict(n.labels),
                    "num_workers": len(n.workers),
                }
                for n in self.node_list()
            ]
        if kind == "cluster_resources":
            out = {}
            for n in self.node_list():
                for k, v in n.total_resources.items():
                    out[k] = out.get(k, 0) + v
            return out
        if kind == "available_resources":
            out = {}
            for n in self.node_list():
                for k, v in n.available.items():
                    out[k] = out.get(k, 0) + v
            return out
        if kind == "actors":
            return [
                {
                    "actor_id": a.info.actor_id.hex(),
                    "name": a.info.name,
                    "state": a.info.state,
                    "class": a.info.class_id[:16],
                    "num_restarts": a.info.num_restarts,
                    "node_id": a.info.node_id.hex() if a.info.node_id else None,
                }
                for a in self.actors.values()
            ]
        if kind == "tasks":
            return self.task_manager.states()
        if kind == "objects":
            return self.store.stats()
        if kind == "placement_groups":
            return self.placement_group_table()
        raise ValueError(kind)

    def actor_ready_ref(self, actor_id: ActorID) -> ObjectRef:
        return ObjectRef(_actor_ready_oid(actor_id))

    # ------------------------------------------------------------------
    # local mode execution
    # ------------------------------------------------------------------
    def _local_decode_args(self, spec):
        args = []
        for a in spec.args:
            if a.ref is not None:
                args.append(self.get_object(a.ref))
            else:
                v, _ = decode_payload(a.payload, zero_copy=False)
                args.append(v)
        kwargs = {}
        for k, a in getattr(spec, "_kwargs", {}).items():
            if a.ref is not None:
                kwargs[k] = self.get_object(a.ref)
            else:
                v, _ = decode_payload(a.payload, zero_copy=False)
                kwargs[k] = v
        return args, kwargs

    def _local_execute(self, spec: TaskSpec):
        import inspect as _inspect

        fn = self.get_function(spec.func_id)
        try:
            args, kwargs = self._local_decode_args(spec)
            result = fn(*args, **kwargs)
            if spec.streaming:
                with self._gen_cond:
                    gen = self.generators.setdefault(spec.generator_id(), GenState())
                for i, item in enumerate(result):
                    oid = ObjectID.for_task_return(spec.task_id, i + 1)
                    self.store.put_serialized(oid, _to_serialized(item))
                    with self._gen_cond:
                        gen.items.append(oid)
                        self._gen_cond.notify_all()
                with self._gen_cond:
                    gen.finished = True
                    self._gen_cond.notify_all()
                return
            if _inspect.isgenerator(result):
                result = list(result)
            values = [result] if spec.num_returns == 1 else list(result)
            for oid, v in zip(spec.return_ids(), values):
                self.store.put_serialized(oid, _to_serialized(v))
            self.task_manager.complete(spec.task_id)
        except BaseException as e:  # noqa: BLE001
            err = TaskError.from_exception(e, spec.desc())
            if not self.task_manager.handle_app_error(spec.task_id, err):
                for oid in self._spec_return_ids(spec):
                    self.store.put_error(oid, err)

    def _local_create_actor(self, spec: TaskSpec):
        cls = self.get_function(spec.func_id)
        astate = self.actors[spec.actor_id]
        try:
            args, kwargs = self._local_decode_args(spec)
            astate.local_instance = cls(*args, **kwargs)
            astate.info.state = "ALIVE"
            self.store.put_serialized(_actor_ready_oid(spec.actor_id), _to_serialized(True))
        except BaseException as e:  # noqa: BLE001
            astate.info.state = "DEAD"
            astate.info.death_cause = str(e)
            self.store.put_error(_actor_ready_oid(spec.actor_id), TaskError.from_exception(e, spec.desc()))

    def _local_actor_call(self, spec: TaskSpec):
        astate = self.actors[spec.actor_id]
        inst = getattr(astate, "local_instance", None)
        try:
            args, kwargs = self._local_decode_args(spec)
            if spec.method_name == "__ray_ready__":
                result = True
            elif spec.method_name == "__ray_terminate__":
                result = True
            else:
                result = getattr(inst, spec.method_name)(*args, **kwargs)
            import inspect as _inspect

            if _inspect.iscoroutine(result):
                import asyncio

                result = asyncio.get_event_loop().run_until_complete(result)
            values = [result] if spec.num_returns == 1 else list(result)
            for oid, v in zip(spec.return_ids(), values):
                self.store.put_serialized(oid, _to_serialized(v))
        except BaseException as e:  # noqa: BLE001
            err = TaskError.from_exception(e, spec.desc())
            for oid in spec.return_ids():
                self.store.put_error(oid, err)

    # ------------------------------------------------------------------
    def shutdown(self):
        if self._stopped:
            return
        self._stopped = True
        from ray_tpu.core import direct as _direct_mod

        _direct_mod.detach(self)
        if getattr(self, "_log_monitor", None) is not None:
            self._log_monitor.stop()  # joins the poll thread
            self._log_monitor.poll_once()  # final race-free flush
        if getattr(self, "_memory_monitor", None) is not None:
            self._memory_monitor.stop()
        self.scheduler.stop()
        # a prestart spawn mid-forkserver-boot must finish (and be reaped
        # by the alive check in start_worker) before teardown, or the
        # orphan worker wedges the resource tracker at interpreter exit
        t = getattr(self, "_prestart_thread", None)
        if t is not None and t.is_alive():
            t.join(timeout=15.0)
        with self._drivers_lock:
            drivers = list(self._drivers.values())
        for d in drivers:
            try:
                d.send({"type": "head_shutdown"})
            except Exception:
                pass
            try:
                d.conn.close()
            except Exception:
                pass
        for node in list(self.nodes.values()):
            node.shutdown()
        self.store.shutdown()
        if getattr(self, "_agent_listener", None) is not None:
            self._agent_listener.shutdown()
        if getattr(self, "_transfer_server", None) is not None:
            self._transfer_server.shutdown()
        t_io = getattr(self, "_io_thread", None)
        if t_io is not None and t_io.is_alive():
            # the loop exits within ~70ms of _stopped; closing graveyarded
            # conns while its current wait() still lists them would recreate
            # the fd-reuse hazard _retire_conn exists to prevent
            t_io.join(timeout=2.0)
        self._drain_conn_graveyard()
        from ray_tpu.core import object_store as _os_mod

        _os_mod.set_fetch_hook(None)
        try:
            self.gcs.store.close()
        except Exception:
            pass
        self._req_pool.shutdown(wait=False, cancel_futures=True)
        context.set_client(None)


def _actor_ready_oid(actor_id: ActorID) -> ObjectID:
    return ObjectID(actor_id.binary() + b"\xfe\xfe\xfe\xfe")


def _to_serialized(value) -> Serialized:
    from ray_tpu.core.serialization import serialize

    # contained_refs MUST survive: the store entry holding them is what
    # keeps objects pickled inside this value alive (borrow protocol).
    # Buffers stay as pickle5 views: put_serialized copies them exactly
    # once — into shm for large values, into bytes for inline entries.
    return serialize(value)


def _sched_options(opts: dict, is_actor: bool = False) -> SchedulingOptions:
    resources = dict(opts.get("resources") or {})
    num_cpus = opts.get("num_cpus")
    if num_cpus is None:
        num_cpus = 0 if is_actor else 1
    if num_cpus:
        resources["CPU"] = float(num_cpus)
    num_tpus = opts.get("num_tpus") or opts.get("num_gpus")
    if num_tpus:
        from ray_tpu.accelerators.tpu import TPUAcceleratorManager

        ok, msg = TPUAcceleratorManager.validate_resource_request_quantity(num_tpus)
        if not ok:
            raise ValueError(msg)
        resources["TPU"] = float(num_tpus)
    if opts.get("memory"):
        resources["memory"] = float(opts["memory"])
    pg = opts.get("placement_group")
    pg_id = None
    bundle_index = -1
    if pg is not None:
        pg_id = pg.id if hasattr(pg, "id") else pg
        bundle_index = opts.get("placement_group_bundle_index", -1)
    strategy = opts.get("scheduling_strategy", "DEFAULT")
    node_id = None
    soft_node_id = None
    if hasattr(strategy, "node_id"):  # NodeAffinitySchedulingStrategy
        if strategy.soft:
            soft_node_id = strategy.node_id
        else:
            node_id = strategy.node_id
        strategy = "DEFAULT"
    elif hasattr(strategy, "placement_group"):  # PlacementGroupSchedulingStrategy
        pg_obj = strategy.placement_group
        pg_id = pg_obj.id if hasattr(pg_obj, "id") else pg_obj
        bundle_index = getattr(strategy, "placement_group_bundle_index", -1)
        strategy = "DEFAULT"
    return SchedulingOptions(
        resources=resources,
        node_id=node_id,
        soft_node_id=soft_node_id,
        placement_group=pg_id,
        bundle_index=bundle_index if bundle_index is not None else -1,
        scheduling_strategy=strategy if isinstance(strategy, str) else "DEFAULT",
        label_selector=opts.get("label_selector") or {},
    )


def _plan_pg(bundles: list[dict], strategy: str, nodes: list[Node]):
    """Choose a node per bundle; None if infeasible. All-or-nothing commit
    happens in the caller under the cluster lock."""
    if not nodes:
        return None
    plan = []
    # track would-be availability to keep the plan feasible
    avail = {n.node_id: dict(n.available) for n in nodes}

    def fits(node, res):
        a = avail[node.node_id]
        return all(a.get(k, 0) >= v - 1e-9 for k, v in res.items() if v > 0)

    def take(node, res):
        a = avail[node.node_id]
        for k, v in res.items():
            if v > 0:
                a[k] = a.get(k, 0) - v

    order = list(nodes)
    for i, b in enumerate(bundles):
        cands = [n for n in order if fits(n, b)]
        if strategy in ("STRICT_SPREAD",):
            cands = [n for n in cands if n not in plan]
        if not cands:
            return None
        if strategy in ("PACK", "STRICT_PACK"):
            # prefer the node already used by previous bundles
            used = [n for n in plan if n in cands]
            node = used[0] if used else cands[0]
            if strategy == "STRICT_PACK" and plan and node is not plan[0]:
                if plan[0] in cands:
                    node = plan[0]
                else:
                    return None
        elif strategy in ("SPREAD", "STRICT_SPREAD"):
            unused = [n for n in cands if n not in plan]
            node = (unused or cands)[0]
        else:
            node = cands[0]
        plan.append(node)
        take(node, b)
    return plan


def _picklable_error(e: BaseException) -> BaseException:
    import pickle

    try:
        pickle.dumps(e)
        return e
    except Exception:
        return TaskError(cause=None, tb_str=str(e), task_desc="rpc")




class _DriverHandle:
    """Head-side record of an attached external driver: just enough of
    WorkerHandle's surface (send + worker_id) for _handle_client_req and
    the ref-event plumbing (reference: the GCS's registered-driver table,
    gcs_job_manager; drivers here are protocol peers, never execution
    targets)."""

    __slots__ = ("conn", "worker_id", "_send_lock")

    def __init__(self, conn, worker_id):
        self.conn = conn
        self.worker_id = worker_id
        self._send_lock = threading.Lock()

    def send(self, msg: dict):
        with self._send_lock:
            self.conn.send(msg)
