"""Flash attention: Pallas TPU kernels (fwd + bwd) with XLA fallback.

The hot op of the framework (the reference delegates attention to
torch/vLLM CUDA kernels; here it is TPU-native). Forward and backward are
Pallas kernels tiled for the MXU: online softmax with f32 accumulation in
VMEM scratch across the kv grid dimension; backward never materializes the
[T, T] probability matrix (dq kernel iterates kv blocks, dk/dv kernel
iterates q blocks). O(T) residuals: output + logsumexp.

Layout: [batch, num_heads, seq, head_dim] (GQA: kv heads broadcast).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


def _default_blocks(head_dim: int) -> tuple[int, int]:
    """Flash tile sizes: 1024x1024 measured fastest on v5e for hd<=128
    (0.595 vs 0.568 MFU at 512x512 on the bench model); larger head dims
    fall back to 512 to stay inside VMEM."""
    return (1024, 1024) if head_dim <= 128 else (512, 512)


# ----------------------------------------------------------------------
# reference / fallback implementation (XLA; used on CPU)
# ----------------------------------------------------------------------
def attention_xla(q, k, v, causal: bool = True, scale: float | None = None, segment_ids=None):
    """Plain XLA attention, f32 softmax. q,k,v: [B, H, T, D]."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
    logits = _apply_masks(logits, causal, segment_ids)
    probs = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


def _apply_masks(logits, causal, segment_ids):
    B, H, Tq, Tk = logits.shape
    if causal:
        qi = jax.lax.broadcasted_iota(jnp.int32, (Tq, Tk), 0)
        ki = jax.lax.broadcasted_iota(jnp.int32, (Tq, Tk), 1)
        logits = jnp.where((ki <= qi)[None, None], logits, _NEG_INF)
    if segment_ids is not None:
        same = segment_ids[:, None, :, None] == segment_ids[:, None, None, :]
        logits = jnp.where(same, logits, _NEG_INF)
    return logits


# ----------------------------------------------------------------------
# pallas forward kernel
# ----------------------------------------------------------------------
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr, *, scale, causal, block_q, block_k):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    ki = pl.program_id(2)
    n_k = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(k_pos <= q_pos, s, _NEG_INF)
        m_prev = m_scr[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_scr[:] = alpha * l_scr[:] + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        m_scr[:] = m_new

    if causal:
        @pl.when(ki * block_k <= qi * block_q + block_q - 1)
        def _():
            _compute()
    else:
        _compute()

    @pl.when(ki == n_k - 1)
    def _finalize():
        l = jnp.maximum(l_scr[:], 1e-30)
        o_ref[0] = (acc_scr[:] / l).astype(o_ref.dtype)
        lse_ref[0, 0] = (m_scr[:] + jnp.log(l))[:, 0]


@functools.partial(jax.jit, static_argnames=("causal", "scale", "block_q", "block_k"))
def _fwd_pallas(q, k, v, causal=True, scale=None, block_q=None, block_k=None):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, T, D = q.shape
    Tk = k.shape[2]
    if scale is None:
        scale = D**-0.5
    dq, dk = _default_blocks(D)
    block_q = min(block_q or dq, T)
    block_k = min(block_k or dk, Tk)
    grid = (B * H, pl.cdiv(T, block_q), pl.cdiv(Tk, block_k))
    qs, ks, vs = (x.reshape(B * H, x.shape[2], D) for x in (q, k, v))

    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal, block_q=block_q, block_k=block_k)
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, 1, T), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, D), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(dimension_semantics=("parallel", "arbitrary", "arbitrary")),
        cost_estimate=pl.CostEstimate(
            flops=4 * B * H * T * Tk * D,
            bytes_accessed=(qs.size + ks.size + vs.size) * 2,
            transcendentals=B * H * T * Tk,
        ),
    )(qs, ks, vs)
    return o.reshape(B, H, T, D), lse.reshape(B, H, T)


# ----------------------------------------------------------------------
# pallas backward kernels
# ----------------------------------------------------------------------
def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_scr, *, scale, causal, block_q, block_k):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    ki = pl.program_id(2)
    n_k = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(k_pos <= q_pos, s, _NEG_INF)
        p = jnp.exp(s - lse_ref[0, 0][:, None])
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0, 0][:, None]) * scale
        dq_scr[:] += jax.lax.dot_general(ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    if causal:
        @pl.when(ki * block_k <= qi * block_q + block_q - 1)
        def _():
            _compute()
    else:
        _compute()

    @pl.when(ki == n_k - 1)
    def _fin():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref, dk_scr, dv_scr, *, scale, causal, block_q, block_k):
    from jax.experimental import pallas as pl

    ki = pl.program_id(1)
    qi = pl.program_id(2)
    n_q = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(k_pos <= q_pos, s, _NEG_INF)
        p = jnp.exp(s - lse_ref[0, 0][:, None])  # [bq, bk]
        dv_scr[:] += jax.lax.dot_general(p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0, 0][:, None]) * scale
        dk_scr[:] += jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    if causal:
        # skip q blocks entirely before this kv block
        @pl.when(qi * block_q + block_q - 1 >= ki * block_k)
        def _():
            _compute()
    else:
        _compute()

    @pl.when(qi == n_q - 1)
    def _fin():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


@functools.partial(jax.jit, static_argnames=("causal", "scale", "block_q", "block_k"))
def _bwd_pallas(q, k, v, o, lse, g, causal=True, scale=None, block_q=None, block_k=None):
    delta = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    return _bwd_pallas_with_delta(q, k, v, g, lse, delta, causal=causal, scale=scale, block_q=block_q, block_k=block_k)


@functools.partial(jax.jit, static_argnames=("causal", "scale", "block_q", "block_k"))
def _bwd_pallas_with_delta(q, k, v, g, lse, delta, causal=True, scale=None, block_q=None, block_k=None):
    """Backward kernels with a caller-supplied delta = sum(dO * O, -1).

    Ring attention computes delta once from the globally-merged output and
    reuses it for every ring step's local backward (delta: [B, H, T] f32).
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, T, D = q.shape
    Tk = k.shape[2]
    if scale is None:
        scale = D**-0.5
    dbq, dbk = _default_blocks(D)
    block_q = min(block_q or dbq, T)
    block_k = min(block_k or dbk, Tk)
    qs, ks, vs, dos = (x.reshape(B * H, x.shape[2], D) for x in (q, k, v, g))
    lse3 = lse.reshape(B * H, 1, T)
    delta = delta.reshape(B * H, 1, T)

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal, block_q=block_q, block_k=block_k),
        grid=(B * H, pl.cdiv(T, block_q), pl.cdiv(Tk, block_k)),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((B * H, T, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        compiler_params=pltpu.CompilerParams(dimension_semantics=("parallel", "arbitrary", "arbitrary")),
    )(qs, ks, vs, dos, lse3, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal, block_q=block_q, block_k=block_k),
        grid=(B * H, pl.cdiv(Tk, block_k), pl.cdiv(T, block_q)),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, j, i: (b, i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_q, D), lambda b, j, i: (b, i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_q), lambda b, j, i: (b, 0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_q), lambda b, j, i: (b, 0, i), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, Tk, D), k.dtype),
            jax.ShapeDtypeStruct((B * H, Tk, D), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(dimension_semantics=("parallel", "arbitrary", "arbitrary")),
    )(qs, ks, vs, dos, lse3, delta)

    return (
        dq.reshape(B, H, T, D),
        dk.reshape(B, H, Tk, D),
        dv.reshape(B, H, Tk, D),
    )


# ----------------------------------------------------------------------
# custom VJP
# ----------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention(q, k, v, causal: bool = True, scale: float | None = None, impl: str = "auto"):
    """Flash attention with GQA support. q: [B,H,T,D]; k,v: [B,Hkv,T,D].

    impl: "auto" (pallas on TPU when head_dim tiles), "pallas", or "xla".
    """
    out, _ = _flash_fwd(q, k, v, causal, scale, impl)
    return out


def _broadcast_kv(q, k, v):
    H, Hkv = q.shape[1], k.shape[1]
    if H != Hkv:
        rep = H // Hkv
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    return k, v


def _flash_fwd(q, k, v, causal, scale, impl="auto"):
    kb, vb = _broadcast_kv(q, k, v)
    if _use_pallas(q, impl):
        o, lse = _fwd_pallas(q, kb, vb, causal=causal, scale=scale)
    else:
        o, lse = _fwd_xla_with_lse(q, kb, vb, causal, scale)
    return o, (q, k, v, o, lse)


def _fwd_xla_with_lse(q, k, v, causal, scale):
    if scale is None:
        scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
    logits = _apply_masks(logits, causal, None)
    lse = jax.nn.logsumexp(logits, axis=-1)
    probs = jnp.exp(logits - lse[..., None]).astype(v.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v), lse


def _flash_bwd(causal, scale, impl, residuals, g):
    q, k, v, o, lse = residuals
    kb, vb = _broadcast_kv(q, k, v)
    if _use_pallas(q, impl):
        dq, dk, dv = _bwd_pallas(q, kb, vb, o, lse, g, causal=causal, scale=scale)
    else:
        dq, dk, dv = _bwd_xla(q, kb, vb, o, lse, g, causal, scale)
    H, Hkv = q.shape[1], k.shape[1]
    if H != Hkv:
        rep = H // Hkv
        dk = dk.reshape(dk.shape[0], Hkv, rep, *dk.shape[2:]).sum(axis=2).astype(k.dtype)
        dv = dv.reshape(dv.shape[0], Hkv, rep, *dv.shape[2:]).sum(axis=2).astype(v.dtype)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


def _bwd_xla(q, k, v, o, lse, g, causal, scale):
    if scale is None:
        scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
    logits = _apply_masks(logits, causal, None)
    p = jnp.exp(logits - lse[..., None])
    g32 = g.astype(jnp.float32)
    dv = jnp.einsum("bhqk,bhqd->bhkd", p, g32)
    delta = jnp.sum(g32 * o.astype(jnp.float32), axis=-1, keepdims=True)
    dp = jnp.einsum("bhqd,bhkd->bhqk", g32, v.astype(jnp.float32))
    ds = p * (dp - delta)
    dq = jnp.einsum("bhqk,bhkd->bhqd", ds, k.astype(jnp.float32)) * scale
    dk = jnp.einsum("bhqk,bhqd->bhkd", ds, q.astype(jnp.float32)) * scale
    return dq, dk, dv


flash_attention.defvjp(_flash_fwd, _flash_bwd)

# kept for callers/tests that used the older name
_flash_fwd_pallas = _fwd_pallas


# ----------------------------------------------------------------------
# chunked (blockwise) XLA attention: O(T * chunk) memory, no pallas.
# The non-pallas path of ring attention (parallel/ring_attention.py) — a
# lax.scan over kv chunks with an online-softmax carry, so the full
# [Tq, Tk] score matrix never exists.
# ----------------------------------------------------------------------
def _pick_chunk(T: int, target: int) -> int:
    if T <= target:
        return T
    for c in range(target, 0, -1):
        if T % c == 0:
            return c
    return T


def chunked_attention_fwd(q, k, v, causal: bool, scale: float, chunk: int = 1024):
    """Returns (o [B,H,Tq,D] f32, lse [B,H,Tq] f32). kv is consumed in
    chunks of `chunk`; the first chunk initializes the online-softmax carry
    (for causal it always contains key 0, so no -inf max to guard)."""
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    q32 = q.astype(jnp.float32)
    C = _pick_chunk(Tk, chunk)
    nk = Tk // C

    def attend_chunk(k_c, v_c, k_off):
        s = jnp.einsum("bhqd,bhkd->bhqk", q32, k_c.astype(jnp.float32), preferred_element_type=jnp.float32) * scale
        if causal:
            qp = jax.lax.broadcasted_iota(jnp.int32, (Tq, C), 0)
            kp = k_off + jax.lax.broadcasted_iota(jnp.int32, (Tq, C), 1)
            s = jnp.where((kp <= qp)[None, None], s, _NEG_INF)
        m = jnp.max(s, axis=-1)
        p = jnp.exp(s - m[..., None])
        return m, jnp.sum(p, axis=-1), jnp.einsum("bhqk,bhkd->bhqd", p, v_c.astype(jnp.float32))

    m0, l0, acc0 = attend_chunk(k[:, :, :C], v[:, :, :C], 0)
    if nk > 1:
        ks = jnp.moveaxis(k[:, :, C:].reshape(B, H, nk - 1, C, D), 2, 0)
        vs = jnp.moveaxis(v[:, :, C:].reshape(B, H, nk - 1, C, D), 2, 0)

        def body(carry, xs):
            m, l, acc = carry
            k_c, v_c, j = xs
            m_b, l_b, acc_b = attend_chunk(k_c, v_c, (j + 1) * C)
            m_new = jnp.maximum(m, m_b)
            alpha = jnp.exp(m - m_new)
            beta = jnp.exp(m_b - m_new)
            return (m_new, alpha * l + beta * l_b, acc * alpha[..., None] + acc_b * beta[..., None]), None

        (m0, l0, acc0), _ = jax.lax.scan(body, (m0, l0, acc0), (ks, vs, jnp.arange(nk - 1)))
    l_safe = jnp.maximum(l0, 1e-30)
    return acc0 / l_safe[..., None], m0 + jnp.log(l_safe)


def chunked_attention_bwd(q, k, v, g, lse, delta, causal: bool, scale: float, chunk: int = 1024):
    """Chunked backward given the (globally merged, in the ring case) lse
    and delta = sum(dO*O, -1). Returns (dq, dk, dv) in f32.

    dq scans kv chunks ([Tq, C] live at a time); dk/dv scan q chunks
    ([Cq, Tk] live at a time) — mirrors the pallas kernel split."""
    B, H, Tq, D = q.shape
    Tk = k.shape[2]
    q32, k32, v32, g32 = (x.astype(jnp.float32) for x in (q, k, v, g))

    Ck = _pick_chunk(Tk, chunk)
    nk = Tk // Ck
    ks = jnp.moveaxis(k32.reshape(B, H, nk, Ck, D), 2, 0)
    vs = jnp.moveaxis(v32.reshape(B, H, nk, Ck, D), 2, 0)

    def dq_body(dq, xs):
        k_c, v_c, j = xs
        s = jnp.einsum("bhqd,bhkd->bhqk", q32, k_c, preferred_element_type=jnp.float32) * scale
        if causal:
            qp = jax.lax.broadcasted_iota(jnp.int32, (Tq, Ck), 0)
            kp = j * Ck + jax.lax.broadcasted_iota(jnp.int32, (Tq, Ck), 1)
            s = jnp.where((kp <= qp)[None, None], s, _NEG_INF)
        p = jnp.exp(s - lse[..., None])
        dp = jnp.einsum("bhqd,bhkd->bhqk", g32, v_c, preferred_element_type=jnp.float32)
        ds = p * (dp - delta[..., None]) * scale
        return dq + jnp.einsum("bhqk,bhkd->bhqd", ds, k_c), None

    # carry zeros derive from q so they inherit any varying manual axes
    # (vma) when this runs inside a shard_map region (e.g. ring attention
    # under the pp x sp pipeline) — fresh jnp.zeros would be unvarying
    # and lax.scan rejects the carry-type mismatch
    dq, _ = jax.lax.scan(dq_body, (q32 * 0).astype(jnp.float32), (ks, vs, jnp.arange(nk)))

    Cq = _pick_chunk(Tq, chunk)
    nq = Tq // Cq
    qs = jnp.moveaxis(q32.reshape(B, H, nq, Cq, D), 2, 0)
    gs = jnp.moveaxis(g32.reshape(B, H, nq, Cq, D), 2, 0)
    lses = jnp.moveaxis(lse.reshape(B, H, nq, Cq), 2, 0)
    deltas = jnp.moveaxis(delta.reshape(B, H, nq, Cq), 2, 0)

    def dkv_body(carry, xs):
        dk, dv = carry
        q_c, g_c, lse_c, delta_c, i = xs
        s = jnp.einsum("bhqd,bhkd->bhqk", q_c, k32, preferred_element_type=jnp.float32) * scale
        if causal:
            qp = i * Cq + jax.lax.broadcasted_iota(jnp.int32, (Cq, Tk), 0)
            kp = jax.lax.broadcasted_iota(jnp.int32, (Cq, Tk), 1)
            s = jnp.where((kp <= qp)[None, None], s, _NEG_INF)
        p = jnp.exp(s - lse_c[..., None])
        dv_c = jnp.einsum("bhqk,bhqd->bhkd", p, g_c)
        dp = jnp.einsum("bhqd,bhkd->bhqk", g_c, v32, preferred_element_type=jnp.float32)
        ds = p * (dp - delta_c[..., None]) * scale
        dk_c = jnp.einsum("bhqk,bhqd->bhkd", ds, q_c)
        return (dk + dk_c, dv + dv_c), None

    (dk, dv), _ = jax.lax.scan(
        dkv_body,
        ((k32 * 0).astype(jnp.float32), (v32 * 0).astype(jnp.float32)),  # vma-inheriting zeros
        (qs, gs, lses, deltas, jnp.arange(nq)),
    )
    return dq, dk, dv


def _use_pallas(q, impl: str = "auto") -> bool:
    import os

    if impl == "auto":
        impl = os.environ.get("RT_ATTENTION_IMPL", "auto")
    if impl == "xla":
        return False
    if impl == "pallas":
        return True
    try:
        # axon is the tunneled TPU PJRT plugin; same hardware
        return q.shape[-1] in (64, 128, 256) and jax.default_backend() in ("tpu", "axon")
    except Exception:
        return False
