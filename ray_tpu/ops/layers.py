"""Elementwise / norm / embedding ops. XLA fuses these into surrounding
matmuls; the Pallas fused rmsnorm is used standalone where no producer
matmul exists to fuse with."""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def rms_norm(x, weight, eps: float = 1e-6):
    """RMSNorm in f32 with cast back (llama convention)."""
    x32 = x.astype(jnp.float32)
    var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    out = x32 * jax.lax.rsqrt(var + eps)
    return (out * weight.astype(jnp.float32)).astype(x.dtype)


@functools.partial(jax.jit, static_argnames=("eps",))
def rms_norm_pallas(x, weight, eps: float = 1e-6):
    """Fused RMSNorm Pallas kernel: one HBM round trip for [rows, d]."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    orig_shape = x.shape
    d = x.shape[-1]
    rows = x.size // d
    x2 = x.reshape(rows, d)
    block_rows = min(256, rows)

    def kernel(x_ref, w_ref, o_ref):
        xf = x_ref[:].astype(jnp.float32)
        var = jnp.mean(xf * xf, axis=-1, keepdims=True)
        o_ref[:] = (xf * jax.lax.rsqrt(var + eps) * w_ref[:].astype(jnp.float32)).astype(o_ref.dtype)

    out = pl.pallas_call(
        kernel,
        grid=(pl.cdiv(rows, block_rows),),
        in_specs=[
            pl.BlockSpec((block_rows, d), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((d,), lambda i: (0,), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((block_rows, d), lambda i: (i, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((rows, d), x.dtype),
    )(x2, weight)
    return out.reshape(orig_shape)


def rotary_embedding(positions, head_dim: int, theta: float = 10000.0, dtype=jnp.float32):
    """RoPE cos/sin tables for integer positions [.., T]."""
    half = head_dim // 2
    freqs = 1.0 / (theta ** (jnp.arange(half, dtype=jnp.float32) / half))
    angles = positions[..., None].astype(jnp.float32) * freqs  # [..., T, half]
    return jnp.cos(angles).astype(dtype), jnp.sin(angles).astype(dtype)


def apply_rope(x, cos, sin):
    """x: [B, H, T, D]; cos/sin: [B, T, D/2] or [T, D/2] (split-half rope)."""
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    if cos.ndim == 2:
        cos = cos[None, None]
        sin = sin[None, None]
    else:
        cos = cos[:, None]
        sin = sin[:, None]
    out1 = x1 * cos - x2 * sin
    out2 = x2 * cos + x1 * sin
    return jnp.concatenate([out1, out2], axis=-1).astype(x.dtype)


def swiglu(x, w_gate, w_up, w_down):
    """SwiGLU MLP: down( silu(x@gate) * (x@up) )."""
    g = jnp.dot(x, w_gate)
    u = jnp.dot(x, w_up)
    return jnp.dot(jax.nn.silu(g) * u, w_down)


def cross_entropy_loss(logits, labels, mask=None, z_loss: float = 0.0):
    """Token cross entropy in f32; labels -100 or mask==0 are ignored."""
    logits = logits.astype(jnp.float32)
    valid = labels >= 0 if mask is None else mask > 0
    safe_labels = jnp.where(valid, labels, 0)
    lse = jax.nn.logsumexp(logits, axis=-1)
    ll = jnp.take_along_axis(logits, safe_labels[..., None], axis=-1)[..., 0]
    loss = (lse - ll) * valid
    if z_loss > 0.0:
        loss = loss + z_loss * (lse * valid) ** 2
    denom = jnp.maximum(valid.sum(), 1)
    return loss.sum() / denom


def embedding_lookup(table, ids):
    return jnp.take(table, ids, axis=0)
