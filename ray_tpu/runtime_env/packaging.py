"""runtime_env: per-task/actor execution environments.

Reference parity: python/ray/_private/runtime_env/working_dir.py (zip the
dir, content-hash URI, cache, unpack + chdir in workers), py_modules.py
(extra import roots), and the env_vars passthrough the runtime already
had. pip/conda/container isolation is intentionally gated: this image has
no package index (zero egress), so `pip` raises a clear error instead of
silently half-working.

Flow:
- driver: prepare_runtime_env() zips working_dir / py_modules (content-
  hashed, size-capped), stores each archive ONCE in the shm object store,
  and rewrites the runtime_env to carry object ids.
- worker: apply_runtime_env_in_worker() fetches archives it has not
  cached, unpacks under /tmp/ray_tpu/runtime_env/<hash>/, inserts
  py_modules on sys.path, and chdirs into the working_dir copy.
"""

from __future__ import annotations

import hashlib
import io
import os
import zipfile

_MAX_ARCHIVE_BYTES = 512 * 1024 * 1024
_EXCLUDE_DIRS = {".git", "__pycache__", ".venv", "node_modules"}
_CACHE_ROOT = "/tmp/ray_tpu/runtime_env"


def _zip_dir(path: str) -> bytes:
    path = os.path.abspath(path)
    if not os.path.isdir(path):
        raise ValueError(f"runtime_env path {path!r} is not a directory")
    buf = io.BytesIO()
    total = 0
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for root, dirs, files in os.walk(path):
            dirs[:] = [d for d in dirs if d not in _EXCLUDE_DIRS]
            for fn in files:
                full = os.path.join(root, fn)
                rel = os.path.relpath(full, path)
                try:
                    total += os.path.getsize(full)
                except OSError:
                    continue
                if total > _MAX_ARCHIVE_BYTES:
                    raise ValueError(
                        f"runtime_env dir {path!r} exceeds {_MAX_ARCHIVE_BYTES >> 20}MB"
                    )
                zf.write(full, rel)
    return buf.getvalue()


def validate_runtime_env(runtime_env: dict | None):
    """Reject env kinds this deployment cannot honor (called on EVERY
    submit, before any cache shortcut)."""
    for gated in ("pip", "conda", "uv", "container"):
        if runtime_env and runtime_env.get(gated):
            raise ValueError(
                f"runtime_env[{gated!r}] is not supported in this deployment: "
                "the environment has no package index (zero egress). Bake "
                "dependencies into the image or ship code via working_dir/"
                "py_modules."
            )


def dir_fingerprint(path: str) -> str:
    """Cheap content fingerprint: (relpath, size, mtime_ns) of every file.
    Lets the driver-side cache detect edits without re-zipping."""
    path = os.path.abspath(path)
    h = hashlib.sha256()
    for root, dirs, files in os.walk(path):
        dirs[:] = sorted(d for d in dirs if d not in _EXCLUDE_DIRS)
        for fn in sorted(files):
            full = os.path.join(root, fn)
            try:
                st = os.stat(full)
            except OSError:
                continue
            h.update(f"{os.path.relpath(full, path)}:{st.st_size}:{st.st_mtime_ns};".encode())
    return h.hexdigest()[:16]


def prepare_runtime_env(runtime_env: dict | None) -> dict | None:
    """Driver-side: package + upload dirs; returns the rewritten env."""
    if not runtime_env:
        return runtime_env
    env = dict(runtime_env)
    validate_runtime_env(env)
    import ray_tpu

    def pack(path: str) -> dict:
        data = _zip_dir(path)
        digest = hashlib.sha256(data).hexdigest()[:16]
        ref = ray_tpu.put(data)
        return {"hash": digest, "ref_hex": ref.id.hex(), "_ref": ref}

    if env.get("working_dir"):
        env["_packed_working_dir"] = pack(env.pop("working_dir"))
    if env.get("py_modules"):
        env["_packed_py_modules"] = [pack(p) for p in env.pop("py_modules")]
    return env


def _materialize(packed: dict, fetch) -> str:
    """Worker-side: ensure the archive is unpacked; returns its dir."""
    dest = os.path.join(_CACHE_ROOT, packed["hash"])
    marker = os.path.join(dest, ".complete")
    if os.path.exists(marker):
        return dest
    data = fetch(packed["ref_hex"])
    tmp = dest + f".tmp{os.getpid()}"
    os.makedirs(tmp, exist_ok=True)
    with zipfile.ZipFile(io.BytesIO(data)) as zf:
        zf.extractall(tmp)
    open(os.path.join(tmp, ".complete"), "w").close()
    try:
        os.rename(tmp, dest)
    except OSError:
        # raced another worker; theirs won
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)
    return dest


def apply_runtime_env_in_worker(runtime_env: dict | None, fetch):
    """Worker-side: fetch(ref_hex) -> bytes loads an archive from the
    object store. Applies py_modules to sys.path and chdirs into the
    working_dir copy (also appended to sys.path, like the reference)."""
    if not runtime_env:
        return
    import sys

    for packed in runtime_env.get("_packed_py_modules") or []:
        d = _materialize(packed, fetch)
        if d not in sys.path:
            sys.path.insert(0, d)
    packed = runtime_env.get("_packed_working_dir")
    if packed:
        d = _materialize(packed, fetch)
        os.chdir(d)
        if d not in sys.path:
            sys.path.insert(0, d)
