from ray_tpu.runtime_env.packaging import (
    apply_runtime_env_in_worker,
    prepare_runtime_env,
)

__all__ = ["apply_runtime_env_in_worker", "prepare_runtime_env"]
