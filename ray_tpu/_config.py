"""Runtime config flag registry.

TPU-native equivalent of the reference's ``RayConfig`` flag system
(reference: src/ray/common/ray_config_def.h — 232 RAY_CONFIG entries, each
overridable via a ``RAY_<name>`` env var, parsed in common/ray_config.h:60).

Every flag declared here is overridable via the ``RT_<NAME>`` environment
variable at import time, and via ``ray_tpu.init(_system_config={...})`` at
runtime.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field, fields
from typing import Any

_ENV_PREFIX = "RT_"


def _env_override(name: str, default: Any) -> Any:
    raw = os.environ.get(_ENV_PREFIX + name.upper())
    if raw is None:
        return default
    if isinstance(default, bool):
        return raw.lower() in ("1", "true", "yes", "on")
    if isinstance(default, int):
        return int(raw)
    if isinstance(default, float):
        return float(raw)
    return raw


@dataclass
class Config:
    """Global runtime configuration (one instance per process)."""

    # --- object store ---
    # Objects smaller than this are stored inline in the owner's in-process
    # memory store and piggybacked on RPC replies (reference:
    # max_direct_call_object_size, common/ray_config_def.h:198).
    max_direct_call_object_size: int = 100 * 1024
    # Per-node shared-memory object store capacity.
    object_store_memory: int = 2 * 1024 * 1024 * 1024
    # Fraction of the store above which LRU-evictable objects are released.
    object_store_eviction_threshold: float = 0.8
    # Use the C++ shared-memory store when the extension is built.
    use_native_object_store: bool = True
    # Spill cold sealed objects to disk under memory pressure instead of
    # evicting them (reference: local_object_manager.h:43); restore on read.
    object_spilling_enabled: bool = True
    # Directory for spill files; empty = <session_dir>/spill.
    object_spill_dir: str = ""
    # Disk budget for spilled bytes; past it, cold objects are evicted
    # (lineage reconstruction) instead of spilled.
    object_spill_max_bytes: int = 50 * 1024 * 1024 * 1024

    # --- transport / cross-node object plane ---
    # Bind host for the head's agent listener (TCP) and transfer servers.
    # 127.0.0.1 for single-host; 0.0.0.0 to accept cross-host `rt agent`
    # joins (reference: gRPC server bind, rpc/grpc_server.h).
    node_manager_host: str = "127.0.0.1"
    # Give every added node its own shm namespace so all object movement
    # crosses the transfer service, as it would between real hosts.
    shm_isolation: bool = False
    # Fixed agent-listener port (0 = ephemeral). A fixed port lets agents
    # reconnect to a RESTARTED head (GCS fault tolerance; reference:
    # gcs_server_port + raylet reconnect backoff).
    node_manager_port: int = 0
    # Seconds an agent keeps redialing the head after connection loss
    # (0 = die with the head; set alongside node_manager_port for head FT).
    agent_reconnect_s: float = 0.0

    # --- GCS persistence (reference: redis_store_client.h:126) ---
    # Path of the append-only GCS table log; empty = in-memory only.
    # With a path set, KV / job table / named+detached actors survive a
    # head kill -9 and are re-hydrated by the next head.
    gcs_persist_path: str = ""

    # --- scheduler ---
    # Pack onto busiest feasible node until its utilization crosses this
    # threshold, then spread (reference: scheduler_spread_threshold=0.5,
    # common/ray_config_def.h:178).
    scheduler_spread_threshold: float = 0.5
    # Max task retries on worker crash when not overridden per task.
    default_max_retries: int = 3
    # Worker lease/dispatch batch size.
    dispatch_batch_size: int = 64

    # --- worker pool ---
    num_workers_soft_limit: int = 0  # 0 => num_cpus
    worker_start_method: str = "forkserver"
    prestart_workers: bool = True
    worker_register_timeout_s: float = 60.0
    idle_worker_killing_time_s: float = 300.0

    # --- health / failure detection ---
    # Reference: gcs_health_check_manager.h — period + failure threshold.
    health_check_period_s: float = 1.0
    health_check_failure_threshold: int = 5
    # Session state.json dump period for the out-of-process CLI
    # (scripts/cli.py); 0 disables.
    state_dump_interval_s: float = 2.0
    # Stream worker log files back to the driver tty (log_monitor.py).
    log_to_driver: bool = True
    # --- reference counting (reference: reference_counter.h) ---
    # Free store entries once no process holds a ref and no live task spec
    # pins them as an argument. RT_OBJECT_REF_COUNTING=0 disables.
    object_ref_counting: bool = True
    ref_counting_interval_s: float = 0.2
    # --- memory protection (reference: memory_monitor.h,
    # worker_killing_policy.h) ---
    memory_monitor_refresh_ms: int = 250  # 0 disables
    memory_usage_threshold: float = 0.95
    # Actor restart backoff.
    actor_restart_backoff_s: float = 0.1

    # --- fault injection (reference: rpc_chaos.h, RAY_testing_rpc_failure) ---
    # Format: "method1=N,method2=M" — fail the first N calls of method1.
    testing_rpc_failure: str = ""

    # --- data ---
    # Blocks observed above this size are split into ~this-sized chunks
    # between pipeline stages (reference: DataContext.target_max_block_size
    # + _internal/execution dynamic block splitting). 0 disables.
    target_max_block_size: int = 128 * 1024 * 1024

    # --- direct call plane (ownership model; core/direct.py) ---
    # Caller->worker direct actor calls, worker leases for stateless tasks
    # and owner-local small objects (reference: reference_counter.h
    # per-owner metadata + cluster_lease_manager.h lease scheduling).
    # RT_DIRECT_CALLS=0 routes everything through the head (round-3 mode).
    direct_calls: bool = True
    # Seconds an owned object lingers after its last reference drops
    # (absorbs the async borrow-registration race).
    owned_object_grace_s: float = 1.0
    # Entries whose ref was SERIALIZED OUT of this process but never saw a
    # registered borrow use this much longer window instead: the owner
    # waits for the explicit borrow-release, and the timer is only the
    # leak backstop for borrowers that died before registering (round-5
    # advisory: time-based grace premature-frees a live borrowed ref when
    # the ref pump stalls past the grace window).
    owned_object_leak_backstop_s: float = 30.0

    # --- llm serving ---
    # Device-resident decode loop: per-step state (tokens, PRNG keys,
    # sampling params, block tables, lengths) lives on device, mutated by
    # one fused jitted step + small scatter deltas; token readback trails
    # the dispatch by one step. RT_LLM_DEVICE_RESIDENT=0 restores the
    # synchronous host-driven loop (also the equivalence-test oracle).
    llm_device_resident: bool = True
    # Batch same-bucket prompt prefills into one forward at admission.
    llm_batch_prefill: bool = True

    # --- collective / mesh ---
    collective_timeout_s: float = 120.0

    # --- lineage ---
    # Bounded lineage window: terminal task specs beyond this count are
    # pruned (their outputs become non-reconstructable, like the
    # reference's lineage eviction under max_lineage_bytes).
    max_lineage_tasks: int = 20_000

    # --- observability ---
    task_events_buffer_size: int = 100_000
    metrics_report_interval_s: float = 5.0
    log_to_driver: bool = True

    # --- misc ---
    session_dir: str = "/tmp/ray_tpu"
    enable_timeline: bool = True

    extra: dict = field(default_factory=dict)

    def __post_init__(self):
        for f in fields(self):
            if f.name == "extra":
                continue
            setattr(self, f.name, _env_override(f.name, getattr(self, f.name)))

    def update(self, overrides: dict | None):
        if not overrides:
            return
        known = {f.name for f in fields(self)}
        for k, v in overrides.items():
            if k in known:
                setattr(self, k, v)
            else:
                self.extra[k] = v


_config: Config | None = None


def get_config() -> Config:
    global _config
    if _config is None:
        _config = Config()
    return _config


def reset_config():
    global _config
    _config = None
