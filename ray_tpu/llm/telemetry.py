"""Serving telemetry plane: flight recorder, live SLO metrics, tracing.

The serving stack (engine.py, llm/disagg/, serve/llm.py) reports through
this module into the runtime's existing observability substrate —
util/metrics (worker→GCS flush, /metrics exposition), util/tracing
(JSONL spans under the session dir), dashboard/grafana.py (a "Serving"
panel row) — instead of ad-hoc ``*_stats()`` dicts only a caller who
knows to poll can see.

Hard rule: ZERO device synchronization. Every sample here is host-side
scheduler state (shadow lengths, queue depths, wall clocks at the
one-step-delayed drain); instrumentation never reads a device array and
never injects a host callback into a fused program (jaxcheck JXC002
keeps that honest). The cost of being observed is a few dict updates per
step, gated in tests/test_perf_smoke.py at ≤1.05x the uninstrumented
step.

Three pieces:

- **Flight recorder** — a fixed-size ring of per-step records (phase,
  host wall ms, occupancy, queue depth, spec round accounting, handoff
  events, recompile sentinel) plus a ring of finished-request lifecycle
  records (submit/admit/first-token/finish stamps, per-token ITL
  samples). ``LLMEngine.telemetry()`` returns the snapshot; on an engine
  error the ring is dumped as JSONL into the session dir for
  postmortems. The recompile sentinel watches each registered
  fixed-shape fused entry's jit cache: the serving hot path compiles
  ONCE per entry, so any growth after the first program is a bug
  (a varying static arg, a dtype drifting per step) and gets its own
  counter instead of a silent 100x step.
- **Live SLO metrics** — the catalog in ``METRICS`` (TTFT/ITL/queue-wait
  histograms, token/preemption/recompile counters, KV-occupancy /
  HBM-bytes / spec-acceptance / collective-wire-bytes gauges), tagged by
  model/replica/stage so a fleet's series stay separable in one scrape.
- **Request-lifecycle tracing** — spans for admission → prefill →
  handoff(put/fetch/scatter-in) → decode → first-token → finish when
  RT_TRACING=1. The trace context rides INSIDE the disagg handoff wire
  dict, so one trace id stitches a request across the prefill and
  decode replicas.
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid
from collections import deque

from ray_tpu.util import tracing

# SLO histogram boundaries (seconds): decode steps are single-digit ms on
# chip, prefill stalls are tens-to-hundreds of ms, a cold compile is
# seconds — the buckets must resolve all three regimes.
_LATENCY_BOUNDARIES = [0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0]

_SERVE_TAGS = ("model", "replica", "stage")

# The serving metric catalog: name -> {kind, desc, tags[, boundaries]}.
# scripts/lint_gate.py's telemetry gate validates every name is legal
# Prometheus, unique across kinds (including histogram-derived
# _bucket/_count/_sum names), and that the Grafana "Serving" panels
# reference only names registered here.
METRICS: dict[str, dict] = {
    "rt_llm_ttft_s": {
        "kind": "histogram", "tags": _SERVE_TAGS, "boundaries": _LATENCY_BOUNDARIES,
        "desc": "time to first token: request submit -> first emitted token",
    },
    "rt_llm_itl_s": {
        "kind": "histogram", "tags": _SERVE_TAGS, "boundaries": _LATENCY_BOUNDARIES,
        "desc": "inter-token latency between consecutive emitted tokens",
    },
    "rt_llm_queue_wait_s": {
        "kind": "histogram", "tags": _SERVE_TAGS, "boundaries": _LATENCY_BOUNDARIES,
        "desc": "admission queue wait: request submit -> prefill-wave start",
    },
    "rt_llm_tokens_total": {
        "kind": "counter", "tags": _SERVE_TAGS,
        "desc": "generated tokens emitted to consumers",
    },
    "rt_llm_prefill_tokens_total": {
        "kind": "counter", "tags": _SERVE_TAGS,
        "desc": "prompt tokens prefilled (transferred-KV admissions count 0)",
    },
    "rt_llm_requests_finished_total": {
        "kind": "counter", "tags": _SERVE_TAGS + ("reason",),
        "desc": "finished requests by finish reason",
    },
    "rt_llm_preemptions_total": {
        "kind": "counter", "tags": _SERVE_TAGS,
        "desc": "recompute preemptions (paged pool pressure)",
    },
    "rt_llm_recompiles_total": {
        "kind": "counter", "tags": _SERVE_TAGS,
        "desc": "fused-entry recompiles after warmup (serving-path bug sentinel)",
    },
    "rt_llm_kv_occupancy": {
        "kind": "gauge", "tags": _SERVE_TAGS,
        "desc": "occupied fraction of KV-cache token capacity",
    },
    "rt_llm_kv_hbm_bytes": {
        "kind": "gauge", "tags": _SERVE_TAGS,
        "desc": "occupied KV bytes (scale-inclusive for int8 caches)",
    },
    "rt_llm_queue_depth": {
        "kind": "gauge", "tags": _SERVE_TAGS,
        "desc": "requests waiting for a slot",
    },
    "rt_llm_slots_in_use": {
        "kind": "gauge", "tags": _SERVE_TAGS,
        "desc": "KV slots bound to live sequences",
    },
    "rt_llm_spec_acceptance": {
        "kind": "gauge", "tags": _SERVE_TAGS,
        "desc": "speculative acceptance rate over drained rounds (lifetime mean)",
    },
    "rt_llm_collective_wire_bytes_total": {
        "kind": "counter", "tags": _SERVE_TAGS,
        "desc": "estimated ICI bytes shipped by the fused step's collectives (jaxpr-accounted per step)",
    },
    "rt_llm_handoff_bytes_total": {
        "kind": "counter", "tags": _SERVE_TAGS,
        "desc": "disagg KV handoff bytes leaving prefill replicas",
    },
    "rt_llm_handoffs_total": {
        "kind": "counter", "tags": _SERVE_TAGS + ("event",),
        "desc": "disagg handoff events (published/scattered/lost/reused)",
    },
    # cluster KV plane (llm/kvplane/): prefix reuse by tier. "local" =
    # this replica's own PrefixCache; "remote" = a block fetched from
    # another replica over the object plane. Cluster hit-rate =
    # sum(rate(hits)) / rate(requests); the Grafana "cluster prefix
    # reuse" panel plots both tiers.
    "rt_llm_prefix_hits_total": {
        "kind": "counter", "tags": _SERVE_TAGS + ("tier",),
        "desc": "prefix-cache hits by tier (local replica cache vs remote cluster KV plane)",
    },
    "rt_llm_prefix_tokens_saved_total": {
        "kind": "counter", "tags": _SERVE_TAGS + ("tier",),
        "desc": "prompt tokens served from cached prefixes instead of prefill compute, by tier",
    },
    "rt_llm_prefix_fetch_bytes_total": {
        "kind": "counter", "tags": _SERVE_TAGS,
        "desc": "bytes fetched from remote replicas' published prefix blocks (cluster KV plane)",
    },
    # overload plane (serve/overload.py): admission control sheds by
    # request class BEFORE queue wait grows, queue wait grows before
    # decode ITL ever does — these series are how a dashboard sees that
    # degradation order actually holding.
    "rt_llm_requests_shed_total": {
        "kind": "counter", "tags": _SERVE_TAGS + ("class",),
        "desc": (
            "admission sheds (OverloadedError) by request class; each replica ingress counts "
            "its own shed and a router counts once per client request, so separate by stage "
            "when summing request-level shed rates"
        ),
    },
    "rt_llm_admission_queue_wait_est_ms": {
        "kind": "gauge", "tags": _SERVE_TAGS,
        "desc": "admission controller's live queue-wait estimate (queue depth x service-time EMA / slots)",
    },
    "rt_llm_retry_budget_exhausted_total": {
        "kind": "counter", "tags": _SERVE_TAGS,
        "desc": "requests whose router failover budget ran out (terminal typed error surfaced)",
    },
    "rt_llm_drain_state": {
        "kind": "gauge", "tags": _SERVE_TAGS,
        "desc": "replica drain lifecycle: 0 serving, 1 draining (shedding new work), 2 drained",
    },
    # live request migration (llm/migrate.py): preemption-tolerant
    # serving's evacuation path. Outcomes: "checkpointed" (source
    # extracted + published), "restored" (peer spliced), "aborted"
    # (could not checkpoint before the deadline — the abort fallback),
    # "resumed"/"lost" (router-stage resume leg succeeded / checkpoint
    # gone before fetch). Source and destination replicas count their
    # own halves, routers count once per client request — separate by
    # stage when summing.
    "rt_llm_migrations_total": {
        "kind": "counter", "tags": _SERVE_TAGS + ("outcome",),
        "desc": "live request migrations by outcome (checkpointed/restored/aborted/resumed/lost)",
    },
    "rt_llm_migration_bytes_total": {
        "kind": "counter", "tags": _SERVE_TAGS,
        "desc": "live_state checkpoint bytes (KV block + scales) moved over the object plane",
    },
    "rt_llm_migration_splice_s": {
        "kind": "histogram", "tags": _SERVE_TAGS, "boundaries": _LATENCY_BOUNDARIES,
        "desc": "splice latency: restore ingress -> first post-splice token on the peer",
    },
    # latency-hiding KV plane v2 (ROADMAP item 3): the async fetch span
    # (runs on the engine's fetch worker, overlapping prefill/decode
    # steps — the histogram is what the A/B bench reads), predictive
    # prefetch attribution (a local-tier hit served by a block pulled in
    # ahead of demand), and the tiered-conversation-KV spill volume.
    "rt_llm_prefix_fetch_overlap_s": {
        "kind": "histogram", "tags": _SERVE_TAGS, "boundaries": _LATENCY_BOUNDARIES,
        "desc": "async remote prefix fetch span (launch -> result landed), overlapped with serving steps",
    },
    "rt_llm_prefix_prefetch_hits_total": {
        "kind": "counter", "tags": _SERVE_TAGS,
        "desc": "local prefix hits served by predictively prefetched blocks (remote->local conversion)",
    },
    "rt_llm_kv_spilled_bytes_total": {
        "kind": "counter", "tags": _SERVE_TAGS,
        "desc": "conversation KV bytes spilled out of HBM by suspend_request (tiered conversation KV)",
    },
}

_instruments: dict = {}
_instr_lock = threading.Lock()


def instruments() -> dict:
    """Instantiate (once per process) and return the catalog's util.metrics
    instruments, name -> Counter/Gauge/Histogram. Registration is shared
    across engines in the process; per-engine separation rides the tags."""
    from ray_tpu.util import metrics as m

    with _instr_lock:
        if _instruments:
            return _instruments
        ctor = {"counter": m.Counter, "gauge": m.Gauge, "histogram": m.Histogram}
        for name, spec in METRICS.items():
            kw = {"description": spec["desc"], "tag_keys": tuple(spec["tags"])}
            if spec["kind"] == "histogram":
                kw["boundaries"] = list(spec["boundaries"])
            _instruments[name] = ctor[spec["kind"]](name, **kw)
        return _instruments


def default_tags(stage: str, model: str | None = None, replica: str | None = None) -> dict:
    """The model/replica/stage tag triple every serving series carries.
    Replica defaults to the worker id (the same key the metrics flusher
    uses) so a fleet's series stay separable after the GCS merge."""
    return {
        "model": model or "default",
        "replica": replica or os.environ.get("RT_WORKER_ID", str(os.getpid())),
        "stage": stage,
    }


# ----------------------------------------------------------------------
# flight recorder
# ----------------------------------------------------------------------
class FlightRecorder:
    """Fixed-size ring of per-step records + finished-request lifecycle
    records, all host-side. Thread-safe against concurrent readers
    (``snapshot`` under the engine lock vs. a stats scrape).

    The step ring stores flat TUPLES (schema ``STEP_FIELDS``) and only
    expands them to dicts in ``snapshot()``: record_step runs on every
    serving step, so it allocates one small tuple instead of a 12-slot
    dict, keeping the hot path inside the zero-overhead gate; snapshot
    and the JSONL dump are cold paths."""

    STEP_FIELDS = (
        "step", "t", "phase", "wall_ms", "admitted", "emitted", "batch", "waiting",
        "occupied_tokens", "capacity_tokens", "pages_free", "pages_total",
        "recompiled", "spec_k", "spec_accepted",
    )

    def __init__(self, max_steps: int = 512, max_requests: int = 256):
        self.steps: deque = deque(maxlen=max_steps)
        self.requests: deque = deque(maxlen=max_requests)
        # async prefix-fetch spans (engine fetch worker): cross-checking
        # a fetch record's [t0, t1] against step records' timestamps is
        # the item-3a overlap evidence the bench and tests read
        self.fetches: deque = deque(maxlen=max_requests)
        self._lock = threading.Lock()
        self._entries: dict[str, tuple] = {}  # name -> (fn, warm_size or None)
        self.recompiles: dict[str, int] = {}
        self.step_count = 0

    # -- recompile sentinel --
    def register_entry(self, name: str, fn) -> None:
        """Register a FIXED-SHAPE fused entry (the decode hot path's jit
        handles: fused step, delta scatters, spec verify). These compile
        exactly once per engine config; cache growth after the first
        observed program is counted as a recompile — the bug class where
        a drifting static arg or dtype silently mints a program per step."""
        if fn is not None and hasattr(fn, "_cache_size"):
            self._entries[name] = (fn, None)

    def check_recompiles(self) -> list[str]:
        """Poll every registered entry's jit cache size (a host attribute
        read — no device work). Returns the entries that recompiled since
        the last check."""
        hits: list[str] = []
        for name, (fn, warm) in list(self._entries.items()):
            try:
                size = fn._cache_size()
            except Exception:
                continue
            if warm is None:
                if size > 0:  # first program = warm baseline
                    self._entries[name] = (fn, size)
                continue
            if size > warm:
                self.recompiles[name] = self.recompiles.get(name, 0) + (size - warm)
                self._entries[name] = (fn, size)
                hits.append(name)
        return hits

    def record_step(self, row: tuple) -> None:
        """``row`` = STEP_FIELDS[1:] values (the step counter is
        prepended here)."""
        with self._lock:
            self.step_count += 1
            self.steps.append((self.step_count,) + row)

    def record_request(self, rec: dict) -> None:
        with self._lock:
            self.requests.append(rec)

    def record_fetch(self, rec: dict) -> None:
        with self._lock:
            self.fetches.append(rec)

    def snapshot(self) -> dict:
        with self._lock:
            rows = list(self.steps)
            reqs = [dict(r) for r in self.requests]
            fetches = [dict(r) for r in self.fetches]
            count = self.step_count
            recs = dict(self.recompiles)
        steps = []
        for row in rows:
            d = dict(zip(self.STEP_FIELDS, row))
            # drop layout-/mode-inapplicable fields (None) for readability
            steps.append({k: v for k, v in d.items() if v is not None})
        return {"step_count": count, "steps": steps, "requests": reqs,
                "fetches": fetches, "recompiles": recs}

    def dump_jsonl(self, path: str, header: dict | None = None) -> str:
        """Write the ring as JSONL (one header line, then one line per
        step record, then one per request record) for postmortems."""
        snap = self.snapshot()
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            f.write(json.dumps({"kind": "flight_header", "ts": time.time(),
                                "recompiles": snap["recompiles"], **(header or {})}) + "\n")
            for rec in snap["steps"]:
                f.write(json.dumps({"kind": "step", **rec}) + "\n")
            for rec in snap["requests"]:
                f.write(json.dumps({"kind": "request", **rec}) + "\n")
        return path


# ----------------------------------------------------------------------
# engine-facing facade
# ----------------------------------------------------------------------
class EngineTelemetry:
    """Everything LLMEngine calls, one object. All entry points are
    host-only and cheap; the engine holds its own lock while calling in,
    so internal state needs no second lock beyond the recorder's."""

    def __init__(self, engine, tags: dict | None = None):
        self.engine = engine
        base = default_tags("engine")
        base.update(tags or {})
        self.tags = {k: str(v) for k, v in base.items() if k in _SERVE_TAGS}
        self.m = instruments()
        self.recorder = FlightRecorder(
            max_steps=int(os.environ.get("RT_LLM_FLIGHT_STEPS", "512")),
            max_requests=int(os.environ.get("RT_LLM_FLIGHT_REQUESTS", "256")),
        )
        # hot-path handles: tags resolved ONCE (util.metrics bind); the
        # per-step/per-token calls below must stay in single-digit
        # microseconds each to hold the 1.05x zero-overhead gate
        self._b_ttft = self.m["rt_llm_ttft_s"].bind(self.tags)
        self._b_itl = self.m["rt_llm_itl_s"].bind(self.tags)
        self._b_qwait = self.m["rt_llm_queue_wait_s"].bind(self.tags)
        self._b_tokens = self.m["rt_llm_tokens_total"].bind(self.tags)
        self._b_pf_tokens = self.m["rt_llm_prefill_tokens_total"].bind(self.tags)
        self._b_preempt = self.m["rt_llm_preemptions_total"].bind(self.tags)
        self._b_recompiles = self.m["rt_llm_recompiles_total"].bind(self.tags)
        self._b_wire = self.m["rt_llm_collective_wire_bytes_total"].bind(self.tags)
        self._b_qdepth = self.m["rt_llm_queue_depth"].bind(self.tags)
        self._b_slots = self.m["rt_llm_slots_in_use"].bind(self.tags)
        self._b_occ = self.m["rt_llm_kv_occupancy"].bind(self.tags)
        self._b_hbm = self.m["rt_llm_kv_hbm_bytes"].bind(self.tags)
        self._b_spec = self.m["rt_llm_spec_acceptance"].bind(self.tags)
        # prefix-reuse tiers (cluster KV plane): per-ADMISSION events, so
        # pre-bound handles keep them off the per-step budget entirely
        self._b_pfx_hits = {
            tier: self.m["rt_llm_prefix_hits_total"].bind({**self.tags, "tier": tier})
            for tier in ("local", "remote")
        }
        self._b_pfx_tokens = {
            tier: self.m["rt_llm_prefix_tokens_saved_total"].bind({**self.tags, "tier": tier})
            for tier in ("local", "remote")
        }
        self._b_pfx_bytes = self.m["rt_llm_prefix_fetch_bytes_total"].bind(self.tags)
        self._b_pfx_prefetch = self.m["rt_llm_prefix_prefetch_hits_total"].bind(self.tags)
        self._b_fetch_overlap = self.m["rt_llm_prefix_fetch_overlap_s"].bind(self.tags)
        self._b_spill = self.m["rt_llm_kv_spilled_bytes_total"].bind(self.tags)
        # materialize the sentinel series at 0 so a dashboard can alert
        # on ANY increase (a series that only appears on the first
        # recompile is invisible to a rate()/increase() alert rule)
        self._b_recompiles.inc(0.0)
        self._b_preempt.inc(0.0)
        # per-step constants, computed once (the on_step path must stay
        # in the tens-of-microseconds)
        from ray_tpu.llm.kv_quant import bytes_per_token

        cfg = engine.config
        self._bytes_per_token = int(bytes_per_token(cfg.num_layers, cfg.num_kv_heads, cfg.hd, engine.kv_dtype))
        if engine.kv_layout == "paged":
            self._capacity_tokens = (engine._pcfg.num_pages - 1) * engine._pcfg.page_size
        else:
            self._capacity_tokens = engine.max_num_seqs * engine.max_seq_len
        # gauges + the recompile poll refresh every SAMPLE_EVERY steps:
        # scrapes run at >= 1s cadence, so per-step gauge precision buys
        # nothing and the saved metric ops keep on_step inside the
        # zero-overhead gate (the flight RECORD still lands every step)
        self.SAMPLE_EVERY = 16
        self._nstep = 0
        self._wire_accum = 0.0
        self._tok_accum = 0.0
        # cumulative spec accounting mirrors (deltas per step go into the
        # flight record; the gauge shows the lifetime mean)
        self._last_preemptions = 0
        self._dumped = False
        # per-step ICI wire bytes of the fused step's collectives: a
        # one-shot jaxpr accounting turned into a LIVE series (counter
        # advanced every dispatched step). 0 on tp=1 engines; computed
        # lazily so engine construction never pays an extra trace.
        self._wire_bytes_per_step: float | None = None
        # live EMAs the admission controller reads (serve/overload.py):
        # inter-token latency and per-request service time (admit ->
        # finish wall). One multiply-add on paths already stamping these
        # clocks — inside the zero-overhead gate's budget.
        self.itl_ema_s = 0.0
        self.service_ema_s = 0.0
        # optional per-sample-tick callback (the admission controller's
        # queue-wait-gauge refresh): called with the current queue depth
        # so the gauge tracks DRAINING pressure too — a gauge only set at
        # admission time would freeze at its peak once arrivals stop
        self.sample_hook = None

    # -- registration -----------------------------------------------------
    def register_fused_entries(self) -> None:
        """Pick up the engine's fixed-shape jit handles for the recompile
        sentinel (called after the engine finished building them)."""
        eng = self.engine
        for name in ("_fused_step", "_fused_attn", "_fused_append",
                     "_set_lane", "_set_table", "_set_table_cell",
                     "_verify_step", "_verify_attn", "_verify_append"):
            self.recorder.register_entry(name.lstrip("_"), getattr(eng, name, None))
        if getattr(eng, "_tp_fused", False):
            # pay the one-shot wire-bytes jaxpr trace HERE, at engine
            # construction (which already compiles these programs), never
            # inside a live serving step under the engine lock
            self._wire_bytes()

    # -- wire-bytes accounting -------------------------------------------
    def _wire_bytes(self) -> float:
        """Per-step collective wire bytes, computed once from the fused
        program's jaxpr (collective/ici.collective_wire_report) for tp>=2
        shard_map engines; 0 elsewhere. Abstract tracing only — no
        compile, no device work — and any failure degrades to 0 rather
        than touching the hot path."""
        if self._wire_bytes_per_step is not None:
            return self._wire_bytes_per_step
        eng = self.engine
        bytes_per_step = 0.0
        if getattr(eng, "_tp_fused", False):
            try:
                import jax

                from ray_tpu.collective.ici import collective_wire_report
                from ray_tpu.parallel.mesh import axis_size

                sds = lambda t: jax.tree.map(  # noqa: E731
                    lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), t
                )
                tp = axis_size(eng.mesh, "tp")
                if eng.kv_layout == "paged":
                    from ray_tpu.llm.model_runner import _sharded_fused_paged

                    fn = _sharded_fused_paged(eng.config, eng.mesh, eng.tp_collective, eng.kv_quant)
                    args = (sds(eng.params), sds(eng.pool), sds(eng._dtables), sds(eng._dlengths),
                            sds(eng._dtokens), sds(eng._dkeys), sds(eng._dtemps), sds(eng._dtopk),
                            sds(eng._dtopp))
                else:
                    from ray_tpu.llm.model_runner import _sharded_fused_slots

                    fn = _sharded_fused_slots(eng.config, eng.mesh, eng.tp_collective, eng.kv_quant)
                    args = (sds(eng.params), sds(eng.cache), sds(eng._dtokens), sds(eng._dkeys),
                            sds(eng._dtemps), sds(eng._dtopk), sds(eng._dtopp))
                rep = collective_wire_report(jax.make_jaxpr(fn)(*args), axis_size=tp)
                bytes_per_step = float(rep["total_bytes"])
            except Exception:
                bytes_per_step = 0.0
        self._wire_bytes_per_step = bytes_per_step
        return bytes_per_step

    # -- request lifecycle ------------------------------------------------
    def on_submit(self, st, submitted_at: float | None = None, parent_trace: tuple | None = None) -> None:
        """Stamp admission-queue entry. ``parent_trace`` (trace_id,
        span_id) joins an existing trace — the disagg decode side passes
        the context the handoff carried so ONE trace id spans replicas."""
        st.t_submit = float(submitted_at) if submitted_at is not None else time.time()
        # latched HERE: the prefill stage consumes st.prefilled (sets it
        # None) before the slot binds, so on_bind can't tell a transferred
        # block from a local prefill anymore
        st.kv_transferred = st.prefilled is not None
        if tracing.enabled():
            if parent_trace is not None:
                trace_id, parent_id = parent_trace[0], parent_trace[1]
            else:
                trace_id, parent_id = tracing.child_context()
            st.trace = (trace_id, uuid.uuid4().hex[:16], parent_id)  # (trace, root span, parent)

    def on_bind(self, st, t_prefill_start: float) -> None:
        """Slot bound + prefill executed: close the admission and prefill
        spans, observe queue wait. FIRST bind only — a recompute-preempted
        request re-binds through here, but its queue wait was already
        observed (re-measuring from t_submit would report the request's
        whole lifetime) and a second admission/prefill span pair would
        show the one request admitted twice; preemptions have their own
        counter and flight-record field."""
        now = time.time()
        if st.t_admit != 0.0:
            return
        st.t_admit = now
        # one queue-wait definition everywhere: submit -> prefill-wave
        # start (the moment the request stops WAITING and starts being
        # worked on); the finish record reuses this exact value so a
        # postmortem dump can never disagree with the live histogram
        st.queue_wait = max(t_prefill_start - st.t_submit, 0.0)
        self._b_qwait.observe(st.queue_wait)
        if not getattr(st, "kv_transferred", False) and not st.token_ids:
            self._b_pf_tokens.inc(float(len(st.prompt_token_ids)))
        if st.trace is not None:
            self._span(st, "llm.admission", st.t_submit, t_prefill_start)
            self._span(st, "llm.prefill", t_prefill_start, now)

    def on_emit(self, st, now: float | None = None) -> None:
        """One token reached the host (the one-step-delayed drain, or the
        sync oracle's readback — either way this is when a consumer could
        see it). First token observes TTFT; later ones observe ITL."""
        now = time.time() if now is None else now
        if st.t_first == 0.0:
            st.t_first = now
            self._b_ttft.observe(max(now - st.t_submit, 0.0))
            if st.t_restore:
                # a restored request's first token IS the splice landing:
                # restore ingress -> first post-splice token on this peer
                self.m["rt_llm_migration_splice_s"].observe(
                    max(now - st.t_restore, 0.0), tags=self.tags
                )
            if st.trace is not None:
                self._span(st, "llm.first_token", st.t_admit or st.t_submit, now)
        else:
            gap = now - st.t_last
            st.itls.append(gap)
            self._b_itl.observe(max(gap, 0.0))
            g = max(gap, 0.0)
            self.itl_ema_s = g if self.itl_ema_s == 0.0 else 0.9 * self.itl_ema_s + 0.1 * g
        st.t_last = now
        self._tok_accum += 1.0  # flushed into the counter on sample ticks

    def on_finish(self, st, reason: str) -> None:
        now = time.time()
        if st.t_admit:
            dur = max(now - st.t_admit, 0.0)
            self.service_ema_s = dur if self.service_ema_s == 0.0 else 0.9 * self.service_ema_s + 0.1 * dur
        self.m["rt_llm_requests_finished_total"].inc(1.0, tags={**self.tags, "reason": reason.split(":")[0]})
        self.recorder.record_request({
            "request_id": st.request_id,
            "reason": reason,
            "submit_t": st.t_submit,
            "admit_t": st.t_admit,
            "first_token_t": st.t_first,
            "finish_t": now,
            "ttft_s": (st.t_first - st.t_submit) if st.t_first else None,
            "queue_wait_s": getattr(st, "queue_wait", None),
            "itl_s": list(st.itls),
            "tokens": len(st.token_ids),
            "prompt_tokens": len(st.prompt_token_ids),
            "preemptions": st.preemptions,
            "trace_id": st.trace[0] if st.trace else None,
        })
        if st.trace is not None:
            if st.t_first:
                self._span(st, "llm.decode", st.t_first, now)
            # the root span: the whole request, recorded last so child
            # spans exist when a viewer walks the tree
            trace_id, span_id, parent_id = st.trace
            tracing.record_span(
                "llm.request", "server", trace_id, span_id, parent_id,
                int(st.t_submit * 1e9), int(now * 1e9),
                {"request_id": st.request_id, "reason": reason,
                 "tokens": len(st.token_ids), "stage": self.tags["stage"]},
            )

    def on_prefix_hit(self, tier: str, tokens: int, nbytes: int = 0) -> None:
        """A prompt admission reused a cached prefix. ``tier``: "local"
        (this replica's PrefixCache) or "remote" (fetched over the
        cluster KV plane — ``nbytes`` then counts the object-plane
        transfer). Admission-path only: never on the per-step budget."""
        self._b_pfx_hits[tier].inc(1.0)
        self._b_pfx_tokens[tier].inc(float(tokens))
        if nbytes:
            self._b_pfx_bytes.inc(float(nbytes))

    def on_prefetch_hit(self) -> None:
        """A local-tier admission hit was served by a block the
        predictive prefetcher pulled in ahead of demand — the
        remote->local conversion the prefetch A/B bench measures.
        Rides alongside the tier="local" on_prefix_hit for the same
        admission."""
        self._b_pfx_prefetch.inc(1.0)

    def on_kv_spill(self, nbytes: int) -> None:
        """suspend_request spilled a conversation's KV out of HBM
        (tiered conversation KV). Once per suspension, never per step."""
        self._b_spill.inc(float(nbytes))

    def on_prefix_fetch(self, t0: float, t1: float, tokens: int, hit: bool) -> None:
        """An async remote prefix fetch span closed. Called from the
        engine's FETCH WORKER thread — the one entry point not under the
        engine lock; the instruments and the recorder ring carry their
        own thread-safety. The recorded [t0, t1] span is the overlap
        evidence: tests/bench cross-check it against concurrent step
        records."""
        self._b_fetch_overlap.observe(max(t1 - t0, 0.0))
        self.recorder.record_fetch(
            {"t0": float(t0), "t1": float(t1), "tokens": int(tokens), "hit": bool(hit)}
        )

    def on_handoff_extract(self, st, payload: dict, t_start: float) -> None:
        """Prefill side: the KV block left the cache into a handoff stash.
        Plants the trace context + original submit stamp in the payload so
        the decode replica's telemetry continues the same request."""
        # same accounting as handoff.meta_of (k + v + logits + scales):
        # the prefill-stage and router-stage series must agree byte for
        # byte so extracted-vs-published comparisons can detect drops
        nbytes = int(payload["k"].nbytes + payload["v"].nbytes + payload["logits"].nbytes)
        if payload.get("k_scale") is not None:
            nbytes += int(payload["k_scale"].nbytes + payload["v_scale"].nbytes)
        self.m["rt_llm_handoff_bytes_total"].inc(float(nbytes), tags=self.tags)
        self.m["rt_llm_handoffs_total"].inc(1.0, tags={**self.tags, "event": "extracted"})
        payload["submitted_at"] = st.t_submit
        if st.trace is not None:
            payload["trace"] = {"trace_id": st.trace[0], "parent_id": st.trace[1]}
            self._span(st, "llm.handoff", t_start, time.time(), nbytes=nbytes)

    def on_scatter_in(self, st, t_start: float) -> None:
        """Decode side: a transferred KV block scattered into the live
        cache/pool."""
        self.m["rt_llm_handoffs_total"].inc(1.0, tags={**self.tags, "event": "scattered"})
        if st.trace is not None:
            self._span(st, "llm.handoff.scatter_in", t_start, time.time())

    def on_migration(self, outcome: str, nbytes: int = 0) -> None:
        """Live-migration event (llm/migrate.py): checkpoint extracted
        here, checkpoint restored here, or the abort fallback. Cold
        path — once per evacuated request, never per step."""
        self.m["rt_llm_migrations_total"].inc(1.0, tags={**self.tags, "outcome": str(outcome)})
        if nbytes:
            self.m["rt_llm_migration_bytes_total"].inc(float(nbytes), tags=self.tags)

    def _span(self, st, name: str, t0: float, t1: float, **attrs) -> None:
        trace_id, root_id, _ = st.trace
        tracing.record_span(
            name, "internal", trace_id, uuid.uuid4().hex[:16], root_id,
            int(t0 * 1e9), int(t1 * 1e9),
            {"request_id": st.request_id, "stage": self.tags["stage"], **attrs},
        )

    # -- per-step ----------------------------------------------------------
    def on_step(self, t0: float, n_admitted: int, n_emitted: int, spec_drained: tuple | None) -> None:
        """Called at the tail of engine.step() under the engine lock.
        Everything read here is host shadow state."""
        eng = self.engine
        now = time.time()
        wall_ms = (time.perf_counter() - t0) * 1e3
        slots_in_use = sum(1 for s in eng._slots if s is not None)
        waiting = len(eng._waiting)
        phase = (
            "idle" if not n_admitted and not slots_in_use and not n_emitted
            else "mixed" if n_admitted and (slots_in_use or n_emitted)
            else "prefill" if n_admitted
            else "decode"
        )
        if eng.kv_layout == "paged":
            occupied = int(eng._lengths.sum())
        else:
            occupied = sum(
                len(s.prompt_token_ids) + len(s.token_ids) for s in eng._slots if s is not None
            )
        capacity = self._capacity_tokens
        per_tok = self._bytes_per_token
        self._nstep += 1
        # first step always samples; a drained engine (no bound slots)
        # samples too, so the token/wire accumulators flush when traffic
        # stops instead of waiting for a tick that never comes
        sample = self._nstep % self.SAMPLE_EVERY == 1 or slots_in_use == 0
        recompiled = self.recorder.check_recompiles() if sample else []
        if recompiled:
            self._b_recompiles.inc(float(len(recompiled)))
        preempt_delta = eng.preemption_count - self._last_preemptions
        if preempt_delta > 0:
            self._b_preempt.inc(float(preempt_delta))
        self._last_preemptions = eng.preemption_count

        paged = eng.kv_layout == "paged"
        sd = spec_drained or (None, None)
        self.recorder.record_step((
            now, phase, round(wall_ms, 4), n_admitted, n_emitted, slots_in_use, waiting,
            occupied, capacity,
            eng._page_alloc.free_pages if paged else None,
            eng._pcfg.num_pages - 1 if paged else None,
            recompiled or None, sd[0], sd[1],
        ))

        if slots_in_use and eng._device_resident and self._wire_bytes_per_step:
            # accumulate locally (one float add), flush on sample ticks
            self._wire_accum += self._wire_bytes_per_step
        if not sample:
            return
        if self._tok_accum:
            self._b_tokens.inc(self._tok_accum)
            self._tok_accum = 0.0
        self._b_qdepth.set(float(waiting))
        self._b_slots.set(float(slots_in_use))
        self._b_occ.set(occupied / max(capacity, 1))
        self._b_hbm.set(float(occupied * per_tok))
        if eng._spec_cfg is not None:
            prop = eng._spec_proposed
            if prop:
                self._b_spec.set(eng._spec_accepted / prop)
        if self._wire_accum:
            self._b_wire.inc(self._wire_accum)
            self._wire_accum = 0.0
        if self.sample_hook is not None:
            try:
                self.sample_hook(waiting)
            except Exception:  # noqa: BLE001 — observers never break the step
                pass

    # -- postmortem --------------------------------------------------------
    def dump_on_error(self, exc: BaseException) -> str | None:
        """Engine died mid-step: persist the flight ring as JSONL under
        the session dir (once — the serve stepper surfaces the SAME
        exception to every waiter). Returns the path, or None if dumping
        itself failed (a dying engine must still raise its real error)."""
        if self._dumped:
            return None
        self._dumped = True
        try:
            from ray_tpu.util.state import session_dir

            d = os.path.join(session_dir(), "llm_flight")
            path = os.path.join(d, f"flight-{os.getpid()}-{int(time.time() * 1e3)}.jsonl")
            eng = self.engine
            return self.recorder.dump_jsonl(path, header={
                "error": f"{type(exc).__name__}: {exc}",
                "tags": self.tags,
                "kv_layout": eng.kv_layout,
                "kv_dtype": str(eng.kv_dtype),
                "max_num_seqs": eng.max_num_seqs,
                "device_resident": eng._device_resident,
            })
        except Exception:
            return None

    def snapshot(self) -> dict:
        snap = self.recorder.snapshot()
        snap["tags"] = dict(self.tags)
        snap["wire_bytes_per_step"] = self._wire_bytes_per_step or 0.0
        return snap


# ----------------------------------------------------------------------
# router-facing metrics (control plane: no engine, no recorder)
# ----------------------------------------------------------------------
class RouterTelemetry:
    """Counters for the disagg router's control-plane events, sharing the
    serving catalog so one scrape covers the whole split."""

    def __init__(self, tags: dict | None = None):
        base = default_tags("router")
        base.update(tags or {})
        self.tags = {k: str(v) for k, v in base.items() if k in _SERVE_TAGS}
        self.m = instruments()

    def on_published(self, nbytes: int) -> None:
        self.m["rt_llm_handoff_bytes_total"].inc(float(nbytes), tags=self.tags)
        self.m["rt_llm_handoffs_total"].inc(1.0, tags={**self.tags, "event": "published"})

    def on_lost(self) -> None:
        self.m["rt_llm_handoffs_total"].inc(1.0, tags={**self.tags, "event": "lost"})

    def on_reused(self) -> None:
        self.m["rt_llm_handoffs_total"].inc(1.0, tags={**self.tags, "event": "reused"})

    def on_failed(self) -> None:
        self.m["rt_llm_requests_finished_total"].inc(1.0, tags={**self.tags, "reason": "error"})

    def on_budget_exhausted(self) -> None:
        """A request's shared failover budget (serve/overload.RetryBudget)
        ran dry — the typed terminal error is about to surface."""
        self.m["rt_llm_retry_budget_exhausted_total"].inc(1.0, tags=self.tags)

    def on_migration(self, outcome: str) -> None:
        """Router-stage migration event: "resumed" (a dying replica's
        checkpoint spliced on a peer, zero recomputed tokens) or "lost"
        (checkpoint gone before the fetch — degraded to re-prefill)."""
        self.m["rt_llm_migrations_total"].inc(1.0, tags={**self.tags, "outcome": str(outcome)})

    def on_shed(self, shed_class: int) -> None:
        """The router itself shed a request (every ranked replica was
        overloaded/draining). Same series as the replica-level sheds but
        under this router's ``stage`` tag: one CLIENT request that shed
        at several replicas during failover counts once per replica plus
        once here — separate by stage when summing request-level rates
        (the Grafana panel does). Label clamped like the replicas'."""
        self.m["rt_llm_requests_shed_total"].inc(
            1.0, tags={**self.tags, "class": str(max(0, min(int(shed_class), 9)))}
        )
