"""Block-table paged KV cache: concurrency bounded by HBM pages, not slots.

The slot cache (llm/kv_cache.py) reserves ``max_seq_len`` tokens of HBM per
concurrent sequence; short sequences strand most of it. This module is the
vLLM-class answer the reference gets from its engine (reference capability:
python/ray/llm/_internal/serve/engines/vllm/vllm_models.py:215-228 —
block_size / gpu_memory_utilization paging), re-designed for XLA:

- One page POOL per layer stack: ``k,v: [L, num_pages, page, kv, hd]``.
  Page 0 is reserved as the trash page: block-table padding points at it,
  so scatters for inactive lanes land somewhere harmless and gathers from
  it are masked by length.
- A host-side ``PageAllocator`` free list; the block table
  ``[slots, max_pages_per_seq] int32`` is host state shipped to the device
  each step (tiny) — allocation decisions stay in Python, the compiled
  program never sees a dynamic shape.
- Attention runs as a ``lax.scan`` over the page axis with an online
  softmax (flash-style m/l/acc carry): each step gathers ONE page per
  sequence, so nothing ever materializes a [slots, max_seq] view. Static
  trip count = max_pages_per_seq -> one compiled program for every
  occupancy mix.

Preemption (pool exhausted) is recompute-style like vLLM's default: the
youngest sequence frees its pages and re-queues with prompt+generated as
its new prompt.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from ray_tpu.llm.kv_quant import dequantize, is_int8, quantize_heads

_NEG = -1e30  # -inf surrogate: keeps exp() NaN-free for fully-masked pages


@dataclass(frozen=True)
class PagedCacheConfig:
    num_layers: int
    num_pages: int  # total pool pages (page 0 reserved as trash)
    page_size: int
    max_pages_per_seq: int
    num_slots: int
    num_kv_heads: int
    head_dim: int
    dtype: str = "bfloat16"  # bf16/f32 variants, or "int8" (kv_quant.py)

    @property
    def max_seq_len(self) -> int:
        return self.max_pages_per_seq * self.page_size


def alloc(cfg: PagedCacheConfig) -> dict:
    shape = (cfg.num_layers, cfg.num_pages, cfg.page_size, cfg.num_kv_heads, cfg.head_dim)
    if is_int8(cfg.dtype):
        # per-head scales, position axis last ([L, P, kv, page]) — the
        # same tile rationale as the slot layout (kv_quant.py)
        sshape = (cfg.num_layers, cfg.num_pages, cfg.num_kv_heads, cfg.page_size)
        return {
            "k": jnp.zeros(shape, dtype=jnp.int8),
            "v": jnp.zeros(shape, dtype=jnp.int8),
            "k_scale": jnp.zeros(sshape, dtype=jnp.float32),
            "v_scale": jnp.zeros(sshape, dtype=jnp.float32),
        }
    dt = jnp.dtype(cfg.dtype)
    return {"k": jnp.zeros(shape, dtype=dt), "v": jnp.zeros(shape, dtype=dt)}


class PageAllocator:
    """Host-side free list over pages 1..num_pages-1 (0 = trash)."""

    def __init__(self, num_pages: int):
        self._free = list(range(num_pages - 1, 0, -1))
        self.num_pages = num_pages

    @property
    def free_pages(self) -> int:
        return len(self._free)

    def pages_for(self, n_tokens: int, page_size: int) -> int:
        return max(1, -(-n_tokens // page_size))

    def alloc(self, n: int) -> list[int] | None:
        if n > len(self._free):
            return None
        out = self._free[-n:]
        del self._free[-n:]
        return out

    def free(self, pages) -> None:
        for p in pages:
            if p:  # never recycle the trash page
                self._free.append(int(p))


# ---------------------------------------------------------------------------
# jitted pool ops
# ---------------------------------------------------------------------------
def insert_pages(pool: dict, page_ids, k_new, v_new, k_scale=None, v_scale=None) -> dict:
    """Write a prefilled sequence's K/V into its pages.

    k_new/v_new: [L, T_pad, kv, hd] with T_pad == len(page_ids)*page_size
    (host pads); page_ids: [n_pg] int32 (padding entries = 0 -> trash).

    Same four-way dtype adaptation as kv_cache.insert_sequence: an fp
    block quantizes into an int8 pool, an int8 block (+ scales in the
    [L, kv, T_pad] wire layout) copies bytes, int8 into fp dequantizes.
    """
    L, T, kvh, hd = k_new.shape
    npg = page_ids.shape[0]
    page = pool["k"].shape[2]
    quant = "k_scale" in pool
    if not quant and k_scale is not None:  # int8 block -> fp pool
        k_new = dequantize(k_new, k_scale.transpose(0, 2, 1))
        v_new = dequantize(v_new, v_scale.transpose(0, 2, 1))
        k_scale = v_scale = None
    if quant and k_scale is None:  # fp block -> quantize on insert
        k_new, sk = quantize_heads(k_new)  # sk: [L, T, kv]
        v_new, sv = quantize_heads(v_new)
        k_scale, v_scale = sk.transpose(0, 2, 1), sv.transpose(0, 2, 1)
    kr = k_new.reshape(L, npg, page, kvh, hd).astype(pool["k"].dtype)
    vr = v_new.reshape(L, npg, page, kvh, hd).astype(pool["v"].dtype)
    out = {
        "k": pool["k"].at[:, page_ids].set(kr),
        "v": pool["v"].at[:, page_ids].set(vr),
    }
    if quant:
        # wire layout [L, kv, T] -> page-major [L, npg, kv, page]
        sr_k = k_scale.reshape(L, kvh, npg, page).transpose(0, 2, 1, 3).astype(jnp.float32)
        sr_v = v_scale.reshape(L, kvh, npg, page).transpose(0, 2, 1, 3).astype(jnp.float32)
        out["k_scale"] = pool["k_scale"].at[:, page_ids].set(sr_k)
        out["v_scale"] = pool["v_scale"].at[:, page_ids].set(sr_v)
    return out


def gather_pages(pool: dict, page_ids):
    """Read a sequence's pages back as one contiguous block.

    Inverse of insert_pages: page_ids [n_pg] int32 (static length; padding
    entries point at the trash page and yield garbage the consumer masks
    by length). Returns (k [L, n_pg*page, kv, hd], v same) — the
    disaggregated-prefill extract primitive for the paged layout
    (llm/disagg/) — plus (k_scale [L, kv, n_pg*page], v_scale same) for
    an int8 pool, the handoff wire layout. Read-only over the pool: safe
    to run in the same program as other gathers, never fused with a pool
    scatter (the documented aliasing hazard)."""
    L, _, page, kvh, hd = pool["k"].shape
    npg = page_ids.shape[0]
    k = pool["k"][:, page_ids].reshape(L, npg * page, kvh, hd)
    v = pool["v"][:, page_ids].reshape(L, npg * page, kvh, hd)
    if "k_scale" in pool:
        # [L, npg, kv, page] -> wire layout [L, kv, npg*page]
        k_sc = pool["k_scale"][:, page_ids].transpose(0, 2, 1, 3).reshape(L, kvh, npg * page)
        v_sc = pool["v_scale"][:, page_ids].transpose(0, 2, 1, 3).reshape(L, kvh, npg * page)
        return k, v, k_sc, v_sc
    return k, v


def _combine(m1, l1, a1, m2, l2, a2):
    """Merge two online-softmax partials (flash-attention combine)."""
    m = jnp.maximum(m1, m2)
    x1 = jnp.exp(m1 - m)
    x2 = jnp.exp(m2 - m)
    return m, l1 * x1 + l2 * x2, a1 * x1[..., None] + a2 * x2[..., None]


def _paged_attn_batch(qg, pool_k_l, pool_v_l, table, lengths, scale, k_self=None, v_self=None,
                      k_scale_l=None, v_scale_l=None, impl="xla"):
    """Online-softmax attention of one query token per slot over paged KV.

    qg: [B, nkv, rep, hd]; pool_*_l: [P, page, kv, hd] (one layer);
    table: [B, max_pg] int32; lengths: [B] int32 — attend to CACHED
    positions 0..lengths[b]-1 (strictly pre-existing data) plus the
    current token's own K/V passed in REGISTERS as k_self/v_self
    [B, kv, hd]. The current position is never read back from the pool:
    a same-program scatter->gather on one buffer is exactly the in-place
    aliasing pattern XLA's CPU thunk executor was observed to mis-order
    (nondeterministic stale reads), and keeping the self term out of
    memory sidesteps it while also saving the round trip. THREE consumers
    rely on this in-registers split: this decode path, the speculative
    wide-block path (`_paged_attn_seq`'s causal chunk), and the Pallas
    kernel (llm/pallas/paged_attn.py), whose page reads are bounded by
    ``lengths`` so the position being written this step can only reach
    attention through the register operands — regression-locked by the
    poisoned-write-target test in tests/test_llm_pallas.py.

    k_scale_l/v_scale_l ([P, kv, page], int8 pools only): gathered pages
    dequantize at the f32 compute dtype this function already uses —
    the convert stays off the flops-dominant dots (JXC003).

    impl="pallas" computes the page-prefix partials with the fused
    HBM-streaming kernel instead of the gather-materializing XLA scan;
    the self fold and normalization below are shared, so the two impls
    differ only in how the (m, l, acc) partials are produced.
    Returns [B, nkv, rep, hd] float32.
    """
    B, nkv, rep, hd = qg.shape
    page = pool_k_l.shape[1]
    max_pg = table.shape[1]
    qf = qg.astype(jnp.float32) * scale
    if impl == "pallas":
        # the kernel path REQUIRES the current token in registers: its
        # pool reads stop strictly below `lengths`, so nothing else could
        # supply the self term (the aliasing contract documented above)
        assert k_self is not None and v_self is not None, (
            "impl='pallas' needs the current token's K/V in registers (k_self/v_self)"
        )
        from ray_tpu.llm.pallas.paged_attn import paged_attn_partials

        m, l, acc = paged_attn_partials(
            qf[:, :, :, None, :], pool_k_l, pool_v_l, table, lengths, k_scale_l, v_scale_l
        )
        m, l, acc = m[..., 0], l[..., 0], acc[..., 0, :]
    else:
        def body(carry, p):
            m, l, acc = carry
            pids = table[:, p]  # [B]
            kp = pool_k_l[pids].astype(jnp.float32)  # [B, page, kv, hd]
            vp = pool_v_l[pids].astype(jnp.float32)
            if k_scale_l is not None:
                kp = kp * k_scale_l[pids].transpose(0, 2, 1)[..., None]  # [B, page, kv, 1]
                vp = vp * v_scale_l[pids].transpose(0, 2, 1)[..., None]
            s = jnp.einsum("bgrh,bpgh->bgrp", qf, kp)  # [B, nkv, rep, page]
            pos = p * page + jnp.arange(page, dtype=jnp.int32)  # [page]
            ok = pos[None, :] < lengths[:, None]  # [B, page] cached only
            s = jnp.where(ok[:, None, None, :], s, _NEG)
            m_new = jnp.maximum(m, s.max(axis=-1))
            alpha = jnp.exp(m - m_new)
            pexp = jnp.exp(s - m_new[..., None])
            l_new = l * alpha + pexp.sum(axis=-1)
            acc_new = acc * alpha[..., None] + jnp.einsum("bgrp,bpgh->bgrh", pexp, vp)
            return (m_new, l_new, acc_new), None

        m0 = jnp.full((B, nkv, rep), _NEG, jnp.float32)
        l0 = jnp.zeros((B, nkv, rep), jnp.float32)
        a0 = jnp.zeros((B, nkv, rep, hd), jnp.float32)
        (m, l, acc), _ = jax.lax.scan(body, (m0, l0, a0), jnp.arange(max_pg, dtype=jnp.int32))
    if k_self is not None:
        # fold the current token as a one-element softmax partial:
        # m2 = s_self, l2 = exp(s_self - m2) = 1, acc2 = 1 * v_self
        s_self = jnp.einsum("bgrh,bgh->bgr", qf, k_self.astype(jnp.float32))  # [B, nkv, rep]
        vs = jnp.broadcast_to(v_self.astype(jnp.float32)[:, :, None, :], acc.shape)
        m, l, acc = _combine(m, l, acc, s_self, jnp.ones_like(s_self), vs)
    return acc / jnp.maximum(l, 1e-20)[..., None]


def _paged_attn_seq(qg, pool_k_l, pool_v_l, table_row, start, k_chunk, v_chunk, scale,
                    k_scale_l=None, v_scale_l=None):
    """Online-softmax attention of T query tokens of ONE sequence: a
    cached PREFIX (positions 0..start-1, read from pages) plus the chunk's
    own K/V attended causally IN REGISTERS (the chunk was produced this
    call and is never read back from the pool — see _paged_attn_batch for
    the aliasing rationale).

    qg: [nkv, rep, T, hd]; table_row: [max_pg] int32; start: [] int32;
    k_chunk/v_chunk: [T, kv, hd]. Query t (absolute position start+t)
    attends prefix fully and chunk positions 0..t. k_scale_l/v_scale_l
    ([P, kv, page], int8 pools only) dequantize the gathered prefix pages
    at the f32 compute dtype; the in-register chunk stays fp. Returns
    [nkv, rep, T, hd] float32.

    CONTRACT: this function is also vmapped over lanes (through
    `_paged_attn_seq_batch`) by the speculative verify step
    (llm/spec/verify.py spec_verify_paged, with T = k+1) — keep it free
    of lane-global logic so per-sequence and batched uses stay the same
    program.
    """
    nkv, rep, T, hd = qg.shape
    page = pool_k_l.shape[1]
    max_pg = table_row.shape[0]
    qf = qg.astype(jnp.float32) * scale

    def body(carry, p):
        m, l, acc = carry  # [nkv, rep, T], ..., [nkv, rep, T, hd]
        pid = table_row[p]
        kp = pool_k_l[pid].astype(jnp.float32)  # [page, kv, hd]
        vp = pool_v_l[pid].astype(jnp.float32)
        if k_scale_l is not None:
            kp = kp * k_scale_l[pid].transpose(1, 0)[..., None]  # [page, kv, 1]
            vp = vp * v_scale_l[pid].transpose(1, 0)[..., None]
        s = jnp.einsum("grth,pgh->grtp", qf, kp)  # [nkv, rep, T, page]
        pos = p * page + jnp.arange(page, dtype=jnp.int32)
        ok = pos < start  # [page] prefix only, same bound for every query
        s = jnp.where(ok[None, None, None], s, _NEG)
        m_new = jnp.maximum(m, s.max(axis=-1))
        alpha = jnp.exp(m - m_new)
        pexp = jnp.exp(s - m_new[..., None])
        l_new = l * alpha + pexp.sum(axis=-1)
        acc_new = acc * alpha[..., None] + jnp.einsum("grtp,pgh->grth", pexp, vp)
        return (m_new, l_new, acc_new), None

    m0 = jnp.full((nkv, rep, T), _NEG, jnp.float32)
    l0 = jnp.zeros((nkv, rep, T), jnp.float32)
    a0 = jnp.zeros((nkv, rep, T, hd), jnp.float32)
    (m, l, acc), _ = jax.lax.scan(body, (m0, l0, a0), jnp.arange(max_pg, dtype=jnp.int32))
    # causal in-chunk part from registers
    s_c = jnp.einsum("grth,ugh->grtu", qf, k_chunk.astype(jnp.float32))  # [nkv, rep, T, T]
    causal = jnp.arange(T, dtype=jnp.int32)[None, :] <= jnp.arange(T, dtype=jnp.int32)[:, None]  # [T(q), T(k)]
    s_c = jnp.where(causal[None, None], s_c, _NEG)
    m2 = s_c.max(axis=-1)
    pe2 = jnp.exp(s_c - m2[..., None])
    l2 = pe2.sum(axis=-1)
    a2 = jnp.einsum("grtu,ugh->grth", pe2, v_chunk.astype(jnp.float32))
    m, l, acc = _combine(m, l, acc, m2, l2, a2)
    return acc / jnp.maximum(l, 1e-20)[..., None]


def _paged_attn_seq_batch(qg, pool_k_l, pool_v_l, tables, starts, k_chunk, v_chunk, scale,
                          k_scale_l=None, v_scale_l=None, impl="xla"):
    """Lane-batched `_paged_attn_seq`: T query tokens PER LANE against
    each lane's own paged prefix + in-register causal chunk.

    qg: [B, nkv, rep, T, hd]; tables: [B, max_pg]; starts: [B] int32;
    k_chunk/v_chunk: [B, T, kv, hd]. impl="xla" IS the vmapped per-lane
    program (byte-for-byte what spec_verify_paged always compiled — the
    oracle); impl="pallas" streams every lane's prefix pages through the
    fused kernel (llm/pallas/paged_attn.py) and folds the causal chunk
    with the identical register math, batched. A pallas_call cannot ride
    `jax.vmap`, which is why the kernel path enters through this batched
    front instead of the per-lane function. Returns
    [B, nkv, rep, T, hd] float32.
    """
    if impl != "pallas":
        return jax.vmap(_paged_attn_seq, in_axes=(0, None, None, 0, 0, 0, 0, None, None, None))(
            qg, pool_k_l, pool_v_l, tables, starts, k_chunk, v_chunk, scale, k_scale_l, v_scale_l
        )
    T = qg.shape[3]
    qf = qg.astype(jnp.float32) * scale
    from ray_tpu.llm.pallas.paged_attn import paged_attn_partials

    m, l, acc = paged_attn_partials(qf, pool_k_l, pool_v_l, tables, starts, k_scale_l, v_scale_l)
    # causal in-chunk part from registers — the batched twin of
    # _paged_attn_seq's tail (the chunk is produced this call and never
    # read back from the pool: the same aliasing contract)
    s_c = jnp.einsum("bgrth,bugh->bgrtu", qf, k_chunk.astype(jnp.float32))
    causal = jnp.arange(T, dtype=jnp.int32)[None, :] <= jnp.arange(T, dtype=jnp.int32)[:, None]
    s_c = jnp.where(causal[None, None, None], s_c, _NEG)
    m2 = s_c.max(axis=-1)
    pe2 = jnp.exp(s_c - m2[..., None])
    l2 = pe2.sum(axis=-1)
    a2 = jnp.einsum("bgrtu,bugh->bgrth", pe2, v_chunk.astype(jnp.float32))
    m, l, acc = _combine(m, l, acc, m2, l2, a2)
    return acc / jnp.maximum(l, 1e-20)[..., None]
