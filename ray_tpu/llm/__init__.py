"""ray_tpu.llm — TPU-native LLM serving engine.

Replaces the reference's vLLM-wrapping `ray.llm` (python/ray/llm/) with a
jit-native continuous-batching engine: slot KV cache, bucketed prefill,
single compiled decode program (see engine.py / model_runner.py /
kv_cache.py). Serve integration (batched LLM deployments with
autoscaling replicas) lives in ray_tpu.serve.llm.
"""

from ray_tpu.util.usage import record_library_usage as _rlu

_rlu("llm")

from ray_tpu.llm.engine import LLMEngine, RequestOutput
from ray_tpu.llm.sampling import SamplingParams
from ray_tpu.llm.spec import SpecConfig

__all__ = ["LLMEngine", "RequestOutput", "SamplingParams", "SpecConfig"]
