"""Replica-side client of the cluster KV plane.

One ``KVPlaneClient`` per engine glues three planes together:

- **data plane**: published prefix blocks are OWNED objects in THIS
  process (core/direct.put_owned — bytes in shm, descriptor in the
  replica's OwnedStore; the replica owns each block for its whole life,
  same lifecycle as a disagg handoff). Remote hits borrow-get them
  zero-copy (``get_owned_view``) with a bounded retry budget.
- **control plane**: the cluster ``PrefixIndex`` (index.py) — in-process
  object in tests/benches, a Serve deployment handle in a fleet (duck-
  typed on ``.remote``: the SAME client code drives both).
- **wire format**: the disagg handoff codec with ``kind=PREFIX_KIND``
  (llm/disagg/handoff.py) — shape/dtype/scale validation, int8 wire for
  int8-cache engines (wire blocks quantized by the fused
  ``kvplane.quant`` program so remote scatter-ins are byte-identical to
  local prefill).

Failure policy: the plane is an ACCELERATOR, never a dependency. Every
index RPC and every fetch is bounded and caught — a dead index, a dead
holder, or an evicted block degrades to "local prefill", counted in
``stats()``, and can never wedge or crash the serving engine. Local
eviction of a published block first unregisters its keys (the route dies
before the bytes) and then frees the owned object; the leak backstop
(RT_OWNED_OBJECT_LEAK_BACKSTOP_S) covers borrowers that died mid-fetch.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from ray_tpu.llm.kvplane.index import boundary_keys, stable_hash  # noqa: F401 (re-export for engines)


def index_call(index, name: str, *args, timeout_s: float = 10.0):
    """Dispatch one index method against either transport: a Serve
    deployment handle (``.remote(...).result(timeout_s)``) or an
    in-process PrefixIndex (direct call). The ONE copy of this duck-type
    — the client and the cache-aware router both route through it, so
    transport semantics can never diverge between them. Raises on
    transport failure; callers own their degrade policy.

    Chaos plane (ray_tpu/chaos.py, site ``kvplane.index``): tests inject
    per-method delays/failures HERE — the one seam every index RPC
    crosses — so the client's circuit breaker and the router's
    index-down degrade are exercised over the real call path instead of
    hand-mocked transports. Inert single-flag check when unarmed."""
    from ray_tpu import chaos

    if not chaos.apply("kvplane.index", method=name):
        raise ConnectionError(f"chaos: dropped index rpc {name}")
    method = getattr(index, name)
    remote = getattr(method, "remote", None)
    if remote is not None:
        return remote(*args).result(timeout_s=timeout_s)
    return method(*args)


class KVPlaneClient:
    """Publish / lookup / fetch / evict against a cluster prefix index.

    ``index``: a PrefixIndex or a Serve deployment handle exposing the
    same methods. ``replica_id`` defaults to the telemetry replica tag
    (worker id / pid) so index entries, metrics and flight records all
    name the replica identically."""

    def __init__(
        self,
        index,
        replica_id: str | None = None,
        *,
        fetch_timeout_s: float = 5.0,
        fetch_retries: int = 1,
        retry_wait_s: float = 0.1,
        heartbeat_every_s: float = 5.0,
        index_timeout_s: float = 10.0,
        index_down_cooldown_s: float = 30.0,
        publish: bool = True,
        publish_min_hits: int = 2,
        prefetch_k: int = 0,
    ):
        """``publish_min_hits``: capacity-aware publication policy — a
        boundary key is offered to ``publish()`` once per local-cache
        event (the store that minted it, then every local hit's re-offer
        self-heal), and only publishes once it has been seen >= this
        many times. The default (2) keeps cold, never-reused prefixes
        from churning the object plane with blocks nobody will fetch: a
        once-seen prefix costs nothing; the second touch — the first
        evidence of reuse — publishes it. 1 restores publish-on-store.
        Skips are counted in ``stats()['published_skipped']`` (surfaced
        through ``prefix_cache_stats()``'s plane tier).

        ``prefetch_k``: predictive prefetch — every heartbeat tick also
        asks the index for the fleet's ``k`` hottest prefix blocks
        (``PrefixIndex.top_hot``, decayed demand) and pulls the ones this
        replica doesn't hold into its local PrefixCache on a worker
        thread, so the next shared-prefix request is a LOCAL-tier hit
        instead of a remote fetch. 0 (default) disables: prefetch spends
        fetch bandwidth ahead of demand, which is a per-deployment choice
        (serve/llm.py's KVPlaneServer exposes it as ``prefetch_k``)."""
        import os

        self._index = index
        self.replica_id = str(replica_id or os.environ.get("RT_WORKER_ID", str(os.getpid())))
        self.fetch_timeout_s = float(fetch_timeout_s)
        self.fetch_retries = int(fetch_retries)
        self.retry_wait_s = float(retry_wait_s)
        self.heartbeat_every_s = float(heartbeat_every_s)
        self.index_timeout_s = float(index_timeout_s)
        self.index_down_cooldown_s = float(index_down_cooldown_s)
        self._publish_enabled = bool(publish)
        self.publish_min_hits = max(1, int(publish_min_hits))
        # boundary key -> publish-offer count (stores + local-hit
        # re-offers); bounded — see _note_seen
        self._seen: dict[bytes, int] = {}  # guarded-by: _lock
        # circuit breaker: repeated index failures open it for a cooldown
        # so a DEAD index costs one timeout, not one per admission under
        # the engine lock (heartbeats keep probing and close it on success)
        self._consec_errors = 0
        self._down_until = 0.0
        self._shutdown = False
        self._lock = threading.Lock()
        self._published: dict[bytes, tuple] = {}  # boundary key -> (n, meta, ref); guarded-by: _lock
        self._ref_keys: dict[bytes, set] = {}  # ref id -> live boundary keys; guarded-by: _lock
        self._evict_q = None  # lazy: SimpleQueue + daemon worker on first evict
        self._last_heartbeat = 0.0
        # predictive prefetch (heartbeat-piggybacked): one round in
        # flight at a time, on its own daemon thread — never the stepper
        self.prefetch_k = max(0, int(prefetch_k))
        self._prefetch_thread = None
        # attach() fills these from the engine's config
        self._engine = None
        self._wire_int8 = False
        self._compute_dtype = "float32"
        self._block = 64
        self._quantize = self._dequantize = None
        self.counts = {
            "published_blocks": 0, "published_bytes": 0, "unpublished_blocks": 0,
            "published_skipped": 0,
            "fetches": 0, "fetched_bytes": 0, "fetch_lost": 0,
            "index_errors": 0, "publish_errors": 0, "free_errors": 0,
            "prefetch_rounds": 0, "prefetch_blocks": 0, "prefetch_bytes": 0,
            "prefetch_skipped": 0, "prefetch_errors": 0,
        }

    # -- engine wiring -----------------------------------------------------
    def attach(self, engine) -> None:
        """Bind the client to its engine's cache format: int8-cache
        engines publish int8 wire blocks (fused quantize, ~half the
        bytes); fp engines publish at the block's own dtype. The engine
        handle also feeds the predictive prefetcher: adopted hot blocks
        store into the engine's PrefixCache via adopt_prefetched()."""
        self._engine = engine
        self._wire_int8 = bool(engine.kv_quant)
        self._compute_dtype = str(engine.config.dtype)
        if engine._prefix_cache is not None:
            self._block = int(engine._prefix_cache.block)
        if self._wire_int8 and self._quantize is None:
            from ray_tpu.llm.kvplane.quant import make_wire_fns

            self._quantize, self._dequantize = make_wire_fns()

    # -- index transport ---------------------------------------------------
    def _safe_call(self, name: str, *args, default=None):
        try:
            out = index_call(self._index, name, *args, timeout_s=self.index_timeout_s)
        except BaseException:  # noqa: BLE001 — a dead index must degrade, never propagate
            self.counts["index_errors"] += 1
            self._consec_errors += 1
            if self._consec_errors >= 2:
                self._down_until = time.time() + self.index_down_cooldown_s
            return default
        self._consec_errors = 0
        self._down_until = 0.0
        return out

    def index_down(self) -> bool:
        """Circuit-breaker state: True while recent consecutive index
        failures have the plane opened (lookups/publishes short-circuit
        instead of paying a timeout per admission)."""
        return time.time() < self._down_until

    def maybe_heartbeat(self) -> None:
        """Refresh this replica's index lease, throttled (host wall clock
        only; called from the engine's step tail and the serve stepper's
        idle wait). The heartbeat reply carries the index's key count for
        this replica: fewer than we hold published means the index pruned
        us (partition outliving the lease) — re-register every live block
        so pruned entries can never stay unroutable forever.

        Each heartbeat tick also piggybacks one PREDICTIVE PREFETCH round
        (prefetch_k > 0): the index's top-k hottest prefix blocks pull
        into the local PrefixCache on a daemon worker, ahead of demand."""
        now = time.time()
        if now - self._last_heartbeat < self.heartbeat_every_s:
            return
        self._last_heartbeat = now
        known = self._safe_call("heartbeat", self.replica_id)
        if known is None:
            return
        with self._lock:
            entries = (
                [(key, n, meta, ref) for key, (n, meta, ref) in self._published.items()]
                if int(known) < len(self._published) else None
            )
        if entries:
            self._safe_call("register", self.replica_id, entries)
        self._maybe_prefetch()

    # -- predictive prefetch -----------------------------------------------
    def _maybe_prefetch(self) -> None:
        """Kick one prefetch round on a daemon worker (at most one in
        flight; a still-running round means the previous tick's transfers
        haven't landed — skip, don't queue). Called from the heartbeat
        path, i.e. the engine's step tail or the serve stepper's idle
        wait, with NO lock held — the round's index RPC, multi-MB fetches
        and dequant/store must never ride the serving thread."""
        if self.prefetch_k <= 0 or self._engine is None or self._shutdown or self.index_down():
            return
        t = self._prefetch_thread
        if t is not None and t.is_alive():
            return
        t = threading.Thread(target=self._prefetch_round, daemon=True, name="kvplane-prefetch")
        self._prefetch_thread = t
        t.start()

    def _prefetch_round(self) -> None:
        """One predictive-prefetch round: ask the index for the fleet's
        hottest live blocks (PrefixIndex.top_hot — decayed demand), pull
        every block this replica doesn't already hold, and adopt it into
        the engine's local PrefixCache (remote tier -> local tier, before
        any request asks). EVERY failure degrades to "no prefetch this
        round" — counted, never raised; the demand path is unaffected.

        Chaos plane (site ``kvplane.prefetch``): tests inject drops,
        delays and faults HERE — prefetch is background opportunism, so
        any injected failure must leave serving token-identical."""
        from ray_tpu import chaos

        try:
            if not chaos.apply("kvplane.prefetch"):
                self.counts["prefetch_skipped"] += 1
                return
            self.counts["prefetch_rounds"] += 1
            hot = self._safe_call("top_hot", self.prefetch_k, self.replica_id, default=None)
            for hit in hot or ():
                if self._shutdown:
                    return
                with self._lock:
                    if bytes(hit["key"]) in self._published:
                        continue  # already hold + registered these bytes
                payload = self.fetch(hit)
                if payload is None:
                    continue  # lost/evicted: fetch() already reported the route
                self._adopt_payload(hit, payload)
        except BaseException:  # noqa: BLE001 — prefetch is opportunistic, never load-bearing
            self.counts["prefetch_errors"] += 1

    def _adopt_payload(self, hit: dict, payload: dict) -> int:
        """Hand one fetched hot block to the engine's PrefixCache (same
        wire-compatibility rule as the demand path's re-store: the cache
        bytes a later local hit serves must equal what a local prefill
        would have produced, so a wire/cache dtype mismatch skips)."""
        import jax.numpy as jnp

        wire_int8 = str(payload["k"].dtype) == "int8"
        if wire_int8 != self._wire_int8:
            return 0  # re-store would drift from the local prefill oracle
        n = int(hit["n"])
        if int(payload["n"]) < n:
            return 0
        if wire_int8:
            k_fp, v_fp = self.dequantize_wire(
                payload["k"], payload["v"], payload["k_scale"], payload["v_scale"]
            )
        else:
            k_fp, v_fp = jnp.asarray(payload["k"]), jnp.asarray(payload["v"])
        nb = int(self._engine.adopt_prefetched(payload["prompt_token_ids"][:n], k_fp, v_fp))
        if nb:
            self.counts["prefetch_blocks"] += 1
            self.counts["prefetch_bytes"] += nb
        return nb

    # -- publish -----------------------------------------------------------
    def publish(self, prefix_ids, k_blk, v_blk, bounds: list | None = None,
                proven_reuse: bool = False) -> int:
        """Publish one prefix block (fp device/host arrays [L, T_pad, kv,
        hd], T_pad >= len(prefix_ids)) as an owned object and register
        its block boundaries against the one ref. ``bounds`` ([(n, key)])
        restricts registration to boundaries the local cache just minted
        (already-published boundaries keep their existing block); default
        is every boundary of ``prefix_ids``. ``proven_reuse`` bypasses
        the publish_min_hits policy outright — set by callers whose offer
        IS reuse evidence (the engine's republish of a block it just
        fetched over the cluster plane: somebody else demonstrably wants
        this prefix, so holding it back only hides a live holder from the
        index). Returns published bytes (0 = skipped/failed; the plane
        degrades, it never raises into the prefill stage)."""
        if not self._publish_enabled or self.index_down():
            return 0
        from ray_tpu.core import direct as _direct
        from ray_tpu.llm.disagg import handoff

        if bounds is None:
            bounds = boundary_keys(prefix_ids, self._block, strict=False)
        with self._lock:
            # publication policy: every offer of a still-unpublished key
            # (store mint, local-hit re-offer) counts as one sighting;
            # the key only ships once seen publish_min_hits times — cold
            # single-use prefixes never serialize, quantize, or register
            fresh = []
            for bn, key in bounds:
                kb = bytes(key)
                if kb in self._published:
                    continue
                if not proven_reuse:
                    seen = self._note_seen(kb)
                    if seen < self.publish_min_hits:
                        self.counts["published_skipped"] += 1
                        continue
                fresh.append((bn, key))
            bounds = fresh
        if not bounds:
            return 0
        n = len(prefix_ids)
        try:
            payload = {"n": n, "prompt_token_ids": [int(t) for t in prefix_ids]}
            if self._wire_int8:
                kq, vq, ks, vs = self._quantize(k_blk, v_blk)
                payload.update(k=np.asarray(kq), v=np.asarray(vq),
                               k_scale=np.asarray(ks), v_scale=np.asarray(vs))
            else:
                payload.update(k=np.asarray(k_blk), v=np.asarray(v_blk))
            wire = handoff.encode(payload, kind=handoff.PREFIX_KIND)
            meta = handoff.meta_of(wire)
        except BaseException:  # noqa: BLE001 — transient (XLA/codec): next store retries
            self.counts["publish_errors"] += 1
            return 0
        try:
            ref = _direct.put_owned(wire)
        except RuntimeError as e:
            self.counts["publish_errors"] += 1
            if "direct plane" in str(e):
                # no direct plane in this process (ray_tpu.init never
                # ran): publishing is PERMANENTLY impossible — stop
                # serializing blocks. Any other RuntimeError is transient
                # and must not disable the tier for the engine's life.
                self._publish_enabled = False
            return 0
        except BaseException:  # noqa: BLE001
            self.counts["publish_errors"] += 1
            return 0
        # every covered boundary aliases the ONE ref with its own valid
        # length — a shorter-prefix lookup slices the same block
        entries = [(key, bn, meta, ref) for bn, key in bounds]
        if self._safe_call("register", self.replica_id, entries) is None:
            # index unreachable: nobody can ever route to this block —
            # free it now instead of stranding owner-side bytes
            try:
                _direct.free_owned([ref.id])
            except BaseException:  # noqa: BLE001
                # best-effort, but the failed free must stay visible:
                # stranded owner bytes show up in stats() as free_errors
                self.counts["free_errors"] += 1
            return 0
        with self._lock:
            for bn, key in bounds:
                kb = bytes(key)
                self._published[kb] = (bn, meta, ref)
                self._seen.pop(kb, None)  # published: the policy no longer needs its count
            self._ref_keys[ref.id.binary()] = {bytes(key) for _, key in bounds}
        self.counts["published_blocks"] += 1
        self.counts["published_bytes"] += int(meta["nbytes"])
        return int(meta["nbytes"])

    def _note_seen(self, key: bytes) -> int:  # holds-lock: _lock
        """Bump and return a boundary key's sighting count (caller holds
        the lock). The map holds only keys the policy still needs —
        publish() drops a key's count the moment it ships — and is
        bounded: past 64k tracked keys the OLDEST-INSERTED half is
        dropped (plain dict insertion order; a true LRU isn't worth the
        bookkeeping here) — losing a count only delays a cold prefix's
        publication by one more sighting, never breaks correctness."""
        if len(self._seen) > 65536:
            for k in list(self._seen)[: len(self._seen) // 2]:
                del self._seen[k]
        n = self._seen.get(key, 0) + 1
        self._seen[key] = n
        return n

    # -- lookup / fetch ----------------------------------------------------
    def lookup(self, keys: list):
        """Longest live remote match for ``[(n, key)]`` boundary keys
        (excluding this replica's own entries — its local cache already
        missed). None on miss, index failure, or while the breaker is
        open (a dead index must not cost a timeout per admission)."""
        if self.index_down():
            return None
        return self._safe_call("lookup", keys, self.replica_id, self.replica_id, default=None)

    def fetch(self, hit: dict):
        """Borrow-get a remote prefix block (bounded retry, zero-copy
        decode + full codec validation). Returns the decoded payload, or
        None when the block is gone/corrupt — the dead route is reported
        back to the index so nobody retries it."""
        from ray_tpu.llm.disagg import handoff

        self.counts["fetches"] += 1
        try:
            payload = handoff.fetch(
                hit["ref"], hit.get("meta"), kind=handoff.PREFIX_KIND,
                timeout_s=self.fetch_timeout_s, retries=self.fetch_retries,
                retry_wait_s=self.retry_wait_s,
            )
        except (handoff.HandoffLostError, handoff.HandoffError):
            self.counts["fetch_lost"] += 1
            self._safe_call("report_lost", hit.get("replica"), hit.get("key"))
            return None
        nbytes = int(hit.get("meta", {}).get("nbytes") or (payload["k"].nbytes + payload["v"].nbytes))
        self.counts["fetched_bytes"] += nbytes
        return payload

    def dequantize_wire(self, k_blk, v_blk, k_scale, v_scale):
        """Int8 wire block -> fp device twins at the engine's compute
        dtype (fused program; see kvplane/quant.py), for the local
        re-store of a fetched remote prefix."""
        if self._dequantize is None:
            from ray_tpu.llm.kvplane.quant import make_wire_fns

            self._quantize, self._dequantize = make_wire_fns()
        import jax.numpy as jnp

        return self._dequantize(
            jnp.asarray(k_blk), jnp.asarray(v_blk),
            jnp.asarray(k_scale), jnp.asarray(v_scale), self._compute_dtype,
        )

    # -- eviction ----------------------------------------------------------
    def on_evict(self, keys: list) -> None:
        """PrefixCache eviction hook: the local block is dying, so (1)
        unregister its boundary keys — the route dies first — and (2)
        free the owned object once no boundary still references it.
        Fetches racing this see ObjectLostError and fall back to local
        prefill (the bounded-retry contract).

        Runs under the ENGINE lock (store -> LRU evict), so the index RPC
        and the free are handed to a worker thread — a slow or dead index
        must never stall the serving loop through the eviction path (the
        same rule the heartbeat already follows). The worker preserves
        unregister-then-free order per eviction."""
        dead_refs = []
        with self._lock:
            for key in keys:
                entry = self._published.pop(bytes(key), None)
                if entry is None:
                    continue
                ref = entry[2]
                rk = ref.id.binary()
                alive = self._ref_keys.get(rk)
                if alive is not None:
                    alive.discard(bytes(key))
                    if not alive:
                        del self._ref_keys[rk]
                        dead_refs.append(ref)
            if not keys:
                return
            if self._evict_q is None:
                import queue as _queue

                self._evict_q = _queue.SimpleQueue()
                t = threading.Thread(target=self._evict_worker, daemon=True, name="kvplane-evict")
                t.start()
        self._evict_q.put(([bytes(k) for k in keys], dead_refs))

    def _evict_worker(self):
        """Drains eviction work off the engine's hot path: unregister the
        route first, then free the bytes."""
        from ray_tpu.core import direct as _direct

        while True:
            keys, dead_refs = self._evict_q.get()
            if not self.index_down():
                # skipped while the breaker is open: fetchers hitting the
                # stale route get ObjectLostError and report_lost cleans it
                self._safe_call("unregister", self.replica_id, keys)
            for ref in dead_refs:
                try:
                    _direct.free_owned([ref.id])
                    self.counts["unpublished_blocks"] += 1
                except BaseException:  # noqa: BLE001
                    pass  # the leak backstop reclaims what an errored free leaves

    # -- drain / teardown --------------------------------------------------
    def shutdown(self) -> int:
        """Replica drain/teardown: drop every route this replica
        registered (one ``drop_replica`` call — the index forgets us
        atomically) and then free the owned blocks, preserving the
        route-dies-before-bytes order the eviction path keeps. Publishing
        disables permanently (the replica is exiting). Returns how many
        published keys were released. A dead index degrades silently —
        the lease expiry prunes our entries anyway, and the owned bytes
        die with this process regardless. IDEMPOTENT: a second call (a
        controller retrying the drain hook races the stepper) is a
        no-op — never a second drop_replica RPC or a double-free."""
        with self._lock:
            if self._shutdown:
                return 0
            self._shutdown = True
            self._publish_enabled = False
            published = dict(self._published)
            self._published.clear()
            self._ref_keys.clear()
            self._seen.clear()
        n = len(published)
        self._safe_call("drop_replica", self.replica_id)
        refs = {}
        for _, _, ref in published.values():
            refs[ref.id.binary()] = ref
        if refs:
            from ray_tpu.core import direct as _direct

            for ref in refs.values():
                try:
                    _direct.free_owned([ref.id])
                    self.counts["unpublished_blocks"] += 1
                except BaseException:  # noqa: BLE001 — backstop reclaims stragglers
                    pass
        return n

    def stats(self) -> dict:
        with self._lock:
            return {
                **self.counts,
                "replica_id": self.replica_id,
                "live_published_keys": len(self._published),
                "wire_dtype": "int8" if self._wire_int8 else self._compute_dtype,
                "index_down": self.index_down(),
            }
