"""Fused wire quantize/dequantize for published prefix blocks.

An int8-cache replica publishes its prefix blocks in the int8 handoff
wire format (values + [L, kv, T] per-head scales — ~half the
object-plane bytes of an fp block), but the local ``PrefixCache`` holds
the fp prefill output. These two programs bridge the formats on the
publish and remote-hit paths:

- ``wire_quantize``: fp block -> (int8 values, wire-layout scales), ONE
  program per bucket width. Uses the exact ``kv_quant.quantize_heads``
  recipe the fused append/insert paths use, so the bytes a remote int8
  consumer scatters in are bit-identical to what its own local prefill
  would have written — the cross-replica token-identity guarantee rests
  on this.
- ``wire_dequantize``: int8 wire block -> fp block at the consumer's
  compute dtype, for re-storing a fetched remote prefix into the LOCAL
  PrefixCache (whose entries are fp). Quantization is idempotent at the
  byte level (kv_quant.py), so a later local hit re-quantizing this
  output reproduces the same cache bytes.

Both are ``@jaxcheck.entry`` so donation and the JXC003 dequant trap
stay audited on the publish path like every other serving program.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ray_tpu.lint import jaxcheck
from ray_tpu.llm.kv_quant import dequantize, quantize_heads
from ray_tpu.llm.model_runner import _sds, _trace_cfg


def _bucket_wire_quantize(T=128):
    cfg = _trace_cfg()
    blk = _sds((cfg.num_layers, T, cfg.num_kv_heads, cfg.hd), jnp.dtype(cfg.dtype))
    return (blk, blk), {}


def _bucket_wire_dequantize(T=128):
    cfg = _trace_cfg()
    blk = _sds((cfg.num_layers, T, cfg.num_kv_heads, cfg.hd), jnp.int8)
    sc = _sds((cfg.num_layers, cfg.num_kv_heads, T), jnp.float32)
    return (blk, blk, sc, sc), {"dtype": "float32"}


@jaxcheck.entry(
    name="llm.kvplane_wire_quantize",
    shapes={"t128": _bucket_wire_quantize},
    donate_bytes=0,  # publish path: dtype changes, nothing aliasable
)
def wire_quantize(k_blk, v_blk):
    """[L, T, kv, hd] fp twins -> (k int8, v int8, k_scale [L, kv, T] f32,
    v_scale) in the handoff wire layout (position axis last, kv_quant.py).
    Same per-head amax recipe as the fused appends — byte-identical to a
    local int8 insert of the same fp block."""
    kq, ks = quantize_heads(k_blk)  # scales [L, T, kv]
    vq, vs = quantize_heads(v_blk)
    return kq, vq, ks.transpose(0, 2, 1).astype(jnp.float32), vs.transpose(0, 2, 1).astype(jnp.float32)


@jaxcheck.entry(
    name="llm.kvplane_wire_dequantize",
    shapes={"t128": _bucket_wire_dequantize},
    donate_bytes=0,
)
def wire_dequantize(k_blk, v_blk, k_scale, v_scale, dtype: str = "float32"):
    """Int8 wire block + [L, kv, T] scales -> fp twins at ``dtype`` (the
    consumer's compute dtype; static — one program per dtype). Feeds the
    local re-store of a fetched remote prefix, never a flops-dominant
    dot (the JXC003 trap stays off this path)."""
    dt = jnp.dtype(dtype)
    k = dequantize(k_blk, k_scale.transpose(0, 2, 1)).astype(dt)
    v = dequantize(v_blk, v_scale.transpose(0, 2, 1)).astype(dt)
    return k, v


def make_wire_fns():
    """Jitted (quantize, dequantize) pair for a plane client. Compile
    per bucket width (and per dtype for the dequant), exactly like the
    disagg extract programs."""
    return (
        jax.jit(wire_quantize),
        jax.jit(wire_dequantize, static_argnums=(4,)),
    )
