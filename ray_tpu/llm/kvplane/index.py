"""Cluster prefix index: stable token-hash keys + a replica-entry map.

The cluster KV plane (ray_tpu/llm/kvplane/) turns each engine's private
``PrefixCache`` into a fleet-wide tier. The glue is a CONTENT-STABLE key:
``stable_hash`` is blake2b over the prefix's token bytes, so every
replica — and the index actor — derives the identical key for the same
tokens. (Python's builtin ``hash()`` over a token tuple is salted per
process by PYTHONHASHSEED: two replicas disagree on every key, which is
exactly why the local cache used to be un-shareable.) Keys exist only at
prefix-block boundaries, mirroring the local cache's block-aligned
keying: the set of boundary keys of a prompt is what both the local
lookup and the cluster lookup walk.

``PrefixIndex`` is the cluster-side map: key -> {replica -> (n_valid,
meta, ref)}. Replicas publish their freshly cached prefix blocks as
OWNED objects on the direct plane (client.py) and register the (key,
ref) pairs here; the serve router asks ``match_replicas`` to score
candidates by longest cached prefix, and an engine that misses locally
asks ``lookup`` for the longest live remote holder.

Liveness is lease-based: every call a replica makes refreshes its
``last_seen`` stamp, and entries of a replica silent for ``ttl_s`` stop
matching (and are pruned opportunistically). A dead replica's owned
blocks die with its process anyway — the index must merely stop routing
to them, never hand out a ref whose owner is known-gone. A fetch that
races an eviction still fails cleanly: the fetch path is bounded-retry
and reports the loss back via ``report_lost``.

The class is serve-agnostic and lock-safe: under Serve it lives inside
the ``KVIndexServer`` deployment (serve/llm.py); tests and benches call
it directly in-process.
"""

from __future__ import annotations

import hashlib
import threading
import time

import numpy as np

# domain salt: a kvplane key can never collide with another subsystem's
# blake2b use of the same token bytes
_SALT = b"rt-kvplane-v1:"
_TOKEN_BYTES = 4  # tokens hash as little-endian int32


def token_bytes(token_ids) -> bytes:
    """Canonical byte encoding of a token sequence (int32 LE). The ONE
    representation both the local cache and the cluster index hash —
    shared here so they can never drift."""
    return np.asarray(token_ids, dtype=np.int32).tobytes()


def stable_hash(token_ids) -> bytes:
    """Content-stable 128-bit key for a token prefix (blake2b digest).

    Accepts a token sequence or pre-packed ``token_bytes`` output.
    Process-independent (unlike builtin ``hash``): replica A's key for a
    prefix equals replica B's and the index actor's. Collisions are
    cryptographically unlikely, and consumers still verify fetched
    blocks token-for-token before trusting them (the same guarantee the
    local cache keeps)."""
    buf = token_ids if isinstance(token_ids, (bytes, bytearray, memoryview)) else token_bytes(token_ids)
    return hashlib.blake2b(_SALT + bytes(buf), digest_size=16).digest()


def prefix_key(buf: bytes, n: int) -> bytes:
    """Key for the first ``n`` tokens of a pre-packed ``token_bytes``
    buffer — the per-boundary slice both PrefixCache and boundary_keys
    hash, factored out so the byte math lives in one place."""
    return stable_hash(buf[: _TOKEN_BYTES * n])


def boundary_keys(token_ids, block: int, *, strict: bool = True) -> list:
    """``[(n, key)]`` for every block boundary of the sequence (ascending
    n). ``strict`` (the LOOKUP side) keeps boundaries STRICTLY shorter
    than the prompt — at least one token must remain un-cached to produce
    first-token logits, matching PrefixCache.lookup's bound.
    ``strict=False`` (the PUBLISH side) includes the full length of an
    already block-aligned prefix: a published block registers under every
    boundary it covers, its own tail included."""
    ids = list(token_ids)
    buf = token_bytes(ids)
    n_max = ((len(ids) - (1 if strict else 0)) // block) * block
    return [(n, prefix_key(buf, n)) for n in range(block, n_max + 1, block)]


class PrefixIndex:
    """Cluster-wide prefix-block registry with lease-based liveness.

    Thread-safe; all methods are cheap dict work (the index never touches
    KV bytes — refs and small meta dicts only). ``time_fn`` is injectable
    for staleness tests."""

    def __init__(self, *, ttl_s: float = 30.0, time_fn=None, demand_halflife_s: float = 30.0):
        self.ttl_s = float(ttl_s)
        self.demand_halflife_s = float(demand_halflife_s)
        self._now = time_fn or time.time
        self._lock = threading.Lock()
        # key -> {replica -> {"n": int, "meta": dict, "ref": ObjectRef}}
        self._entries: dict[bytes, dict[str, dict]] = {}  # guarded-by: _lock
        # replica -> {"last_seen": float, "keys": set[bytes]}
        self._replicas: dict[str, dict] = {}  # guarded-by: _lock
        # key -> decayed demand score: every router match / miss lookup
        # that queries a boundary key bumps it; scores HALVE every
        # demand_halflife_s so top_hot tracks the current workload, not
        # all-time popularity (guarded-by: _lock)
        self._demand: dict[bytes, float] = {}
        self._demand_decayed = self._now()
        self.counts = {  # guarded-by: _lock
            "registered": 0, "unregistered": 0, "expired": 0,
            "lookups": 0, "hits": 0, "lost_reports": 0, "top_hot_calls": 0,
        }

    # -- liveness ----------------------------------------------------------
    def _touch(self, replica: str) -> None:  # holds-lock: _lock
        rec = self._replicas.setdefault(replica, {"last_seen": 0.0, "keys": set()})
        rec["last_seen"] = self._now()

    def _alive(self, replica: str, now: float) -> bool:
        rec = self._replicas.get(replica)
        return rec is not None and (now - rec["last_seen"]) <= self.ttl_s

    def heartbeat(self, replica: str) -> int:
        """Refresh the replica's lease. Returns how many keys the index
        holds for it — a replica that was pruned (network partition
        outliving the lease + an expire()) sees fewer than it published
        and re-registers its live blocks (client.maybe_heartbeat)."""
        with self._lock:
            self._touch(replica)
            return len(self._replicas[replica]["keys"])

    def expire(self) -> int:
        """Prune every entry belonging to a replica past its lease.
        Matching already ignores stale replicas, so this is garbage
        collection, not correctness; called opportunistically."""
        with self._lock:
            now = self._now()
            dead = [r for r in self._replicas if not self._alive(r, now)]
            n = 0
            for r in dead:
                n += self._drop_replica_locked(r)
            self.counts["expired"] += n
            return n

    def _drop_replica_locked(self, replica: str) -> int:  # holds-lock: _lock
        rec = self._replicas.pop(replica, None)
        if rec is None:
            return 0
        n = 0
        for key in rec["keys"]:
            holders = self._entries.get(key)
            if holders and holders.pop(replica, None) is not None:
                n += 1
                if not holders:
                    del self._entries[key]
        return n

    def drop_replica(self, replica: str) -> int:
        """Remove every entry a replica registered (explicit teardown)."""
        with self._lock:
            return self._drop_replica_locked(replica)

    # -- registration ------------------------------------------------------
    def register(self, replica: str, entries: list) -> int:
        """``entries``: [(key, n_valid, meta, ref)] — every block
        boundary of one published block aliases the SAME ref with its own
        valid length (the consumer slices). Returns how many registered."""
        with self._lock:
            self._touch(replica)
            rec = self._replicas[replica]
            for key, n, meta, ref in entries:
                self._entries.setdefault(bytes(key), {})[replica] = {
                    "n": int(n), "meta": dict(meta or {}), "ref": ref,
                }
                rec["keys"].add(bytes(key))
            self.counts["registered"] += len(entries)
            return len(entries)

    def unregister(self, replica: str, keys: list) -> int:
        """Drop a replica's entries for ``keys`` (local eviction: the
        owner is about to free the block, so the route must die first)."""
        with self._lock:
            self._touch(replica)
            rec = self._replicas.get(replica)
            n = 0
            for key in keys:
                key = bytes(key)
                holders = self._entries.get(key)
                if holders and holders.pop(replica, None) is not None:
                    n += 1
                    if not holders:
                        del self._entries[key]
                if rec is not None:
                    rec["keys"].discard(key)
            self.counts["unregistered"] += n
            return n

    def report_lost(self, replica: str, key) -> None:
        """A fetch found the block gone (evicted/owner died mid-race):
        drop the dead route so nobody else burns a retry on it."""
        with self._lock:
            self.counts["lost_reports"] += 1
            holders = self._entries.get(bytes(key))
            if holders and holders.pop(replica, None) is not None:
                rec = self._replicas.get(replica)
                if rec is not None:
                    rec["keys"].discard(bytes(key))
                if not holders:
                    del self._entries[bytes(key)]

    # -- demand ------------------------------------------------------------
    def _bump_demand_locked(self, keys) -> None:  # holds-lock: _lock
        now = self._now()
        # lazy exponential decay: halve every halflife elapsed since the
        # last decay tick, dropping dust so the dict tracks the live
        # working set instead of growing with every prompt ever seen
        if now - self._demand_decayed >= self.demand_halflife_s:
            halvings = int((now - self._demand_decayed) // self.demand_halflife_s)
            self._demand_decayed += halvings * self.demand_halflife_s
            scale = 0.5 ** min(halvings, 64)
            self._demand = {k: s for k, s in ((k, s * scale) for k, s in self._demand.items()) if s >= 0.0625}
        for _n, key in keys:
            key = bytes(key)
            self._demand[key] = self._demand.get(key, 0.0) + 1.0

    def top_hot(self, k: int = 4, exclude: str | None = None) -> list:
        """The fleet's ``k`` hottest LIVE prefix blocks by decayed demand
        — the predictive-prefetch feed (client.maybe_heartbeat): a replica
        pulls these into its local PrefixCache before they are requested,
        turning remote-tier hits into local-tier hits. Entries shaped like
        ``lookup`` hits ({"key","n","replica","meta","ref"}) so the client
        fetches them through the same path. ``exclude`` drops blocks the
        asking replica already holds (it published them); boundary keys
        aliasing the SAME published ref dedup to the longest one, since a
        single fetch + local store re-mints every shorter boundary."""
        with self._lock:
            self.counts["top_hot_calls"] += 1
            now = self._now()
            cands: list = []
            for key, score in self._demand.items():
                holders = self._entries.get(key)
                if not holders:
                    continue
                if exclude is not None and exclude in holders:
                    continue  # the asker already owns a copy of these bytes
                live = [(rep, e) for rep, e in holders.items() if self._alive(rep, now)]
                if not live:
                    continue
                rep, e = max(live, key=lambda it: self._replicas[it[0]]["last_seen"])
                cands.append((score, int(e["n"]), key, rep, e))
            # hottest first; equal-demand boundary aliases of one prompt
            # resolve to the longest (its fetch covers the shorter ones)
            cands.sort(key=lambda it: (-it[0], -it[1]))
            out: list = []
            picked: set = set()
            for score, n, key, rep, e in cands:
                alias = (rep, id(e["ref"]))
                if alias in picked:
                    continue  # shorter boundary of an already-picked block
                picked.add(alias)
                out.append({"key": bytes(key), "n": n, "replica": rep,
                            "meta": dict(e["meta"]), "ref": e["ref"], "demand": score})
                if len(out) >= int(k):
                    break
            return out

    # -- lookup ------------------------------------------------------------
    def lookup(self, keys: list, exclude: str | None = None, requester: str | None = None):
        """Longest live match for a prompt's boundary ``[(n, key)]`` list
        (ascending). Returns {"key", "n", "replica", "meta", "ref"} or
        None. ``exclude`` skips the requester's own entries (its local
        cache already missed — its published copy is the same bytes);
        ``requester`` refreshes the caller's lease for free."""
        with self._lock:
            if requester is not None:
                self._touch(requester)
            self.counts["lookups"] += 1
            self._bump_demand_locked(keys)
            now = self._now()
            for n, key in reversed(list(keys)):
                holders = self._entries.get(bytes(key))
                if not holders:
                    continue
                live = [
                    (rep, e) for rep, e in holders.items()
                    if rep != exclude and self._alive(rep, now)
                ]
                if not live:
                    continue
                # freshest lease wins: most-recently-seen holder is the
                # least likely to have died since
                rep, e = max(live, key=lambda it: self._replicas[it[0]]["last_seen"])
                self.counts["hits"] += 1
                return {"key": bytes(key), "n": e["n"], "replica": rep, "meta": dict(e["meta"]), "ref": e["ref"]}
            return None

    def match_replicas(self, keys: list) -> dict:
        """{replica -> longest matched prefix length} over live replicas —
        the router's cache-aware scoring input. Dead replicas never
        appear (the 'router never routes to them' staleness contract)."""
        with self._lock:
            self._bump_demand_locked(keys)
            now = self._now()
            out: dict[str, int] = {}
            for n, key in keys:
                for rep in self._entries.get(bytes(key), {}):
                    if self._alive(rep, now) and out.get(rep, 0) < n:
                        out[rep] = n
            return out

    def stats(self) -> dict:
        with self._lock:
            now = self._now()
            return {
                **self.counts,
                "keys": len(self._entries),
                "demand_keys": len(self._demand),
                "replicas_live": sum(1 for r in self._replicas if self._alive(r, now)),
                "replicas_known": len(self._replicas),
            }
