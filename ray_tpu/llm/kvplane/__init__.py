"""ray_tpu.llm.kvplane — cluster-wide prefix/KV reuse over the object plane.

Each engine's ``PrefixCache`` dies with its replica; at fleet scale with
shared system prompts every replica re-prefills the same prefix. This
package turns those private caches into one cluster tier:

- **index.py** — content-stable blake2b prefix keys at block boundaries
  (the SAME keying the local cache uses, so a key computed on any
  replica matches every other) and ``PrefixIndex``, the cluster map of
  key -> {replica -> (n_valid, meta, ref)} with lease-based staleness;
- **client.py** — ``KVPlaneClient``: replicas publish freshly cached
  prefix blocks as OWNED objects (direct.put_owned, the disagg handoff
  codec with ``kind=kv_prefix`` — int8 wire for int8 caches) and fetch
  remote hits zero-copy with a bounded retry budget; every failure
  degrades to local prefill, never an error;
- **routing.py** — ``CacheAwareRouter``: scores replicas by longest
  cached prefix blended with load (local tier beats remote tier beats
  cold), so shared-prefix traffic lands where its KV already lives;
- **quant.py** — fused wire quantize/dequantize programs (jaxcheck
  entries) bridging fp PrefixCache entries and the int8 wire format.

Engine integration: ``LLMEngine(kv_plane=KVPlaneClient(...))`` — a local
prefix-cache miss consults the index, fetches the longest live remote
block, scatter-ins through the existing fused insert/transparent-requant
path, and re-publishes locally. Serve integration (KVIndexServer /
KVPlaneServer / KVRouterServer, build_kvplane_deployment) lives in
ray_tpu.serve.llm. Tests: tests/test_llm_kvplane.py.
"""

from ray_tpu.llm.kvplane.client import KVPlaneClient
from ray_tpu.llm.kvplane.index import PrefixIndex, boundary_keys, stable_hash, token_bytes
from ray_tpu.llm.kvplane.routing import CacheAwareRouter, KVRouteError, rank_replicas, score_replica

__all__ = [
    "CacheAwareRouter",
    "KVPlaneClient",
    "KVRouteError",
    "PrefixIndex",
    "boundary_keys",
    "rank_replicas",
    "score_replica",
    "stable_hash",
    "token_bytes",
]
