"""Cache-aware request routing over the cluster KV plane.

vLLM/SGLang-style cache-aware routing, re-expressed over the runtime's
own index: the router scores every replica by the LONGEST prefix of the
incoming prompt already cached on it (``PrefixIndex.match_replicas``)
blended with its live load, so shared-prefix traffic lands where its KV
already lives:

- **local tier**: the top-scored replica holds the prefix — admission is
  a local PrefixCache hit (no prefill, no fetch);
- **remote tier**: load pushed the request OFF the holder — the chosen
  replica's engine fetches the block over the object plane (one transfer
  instead of a prefill forward) and re-publishes, growing the local tier
  for the next request;
- **cold**: nobody holds anything — pure load balancing, and the chosen
  replica's prefill publishes the prefix for everyone after it.

``score = cache_weight * matched/len(prompt) - load_weight * inflight``:
with the defaults a near-full prefix match outweighs several queued
requests, but a severely loaded holder still sheds to an idle peer
(which then pays one object-plane fetch, not a prefill). Ties break on
load, then on replica order (deterministic for tests).

``CacheAwareRouter`` is the serve-agnostic core (mirroring
disagg/router.py): ``submit(replica_id, prompt, params) -> dict`` is
injected — deployment-handle calls under Serve, engine closures in
tests/benches — and failures retry on the next-ranked replica up to a
bounded attempt budget.
"""

from __future__ import annotations

import threading

from ray_tpu.exceptions import serving_error


@serving_error
class KVRouteError(RuntimeError):
    """Client-visible terminal failure after the router's retry budget."""


def score_replica(matched: int, prompt_len: int, load: float, *,
                  cache_weight: float = 1.0, load_weight: float = 0.1) -> float:
    """Blend of cache affinity and load pressure (see module docstring)."""
    return cache_weight * (matched / max(prompt_len, 1)) - load_weight * load


def rank_replicas(replicas, matches: dict, loads: dict, prompt_len: int, *,
                  cache_weight: float = 1.0, load_weight: float = 0.1) -> list:
    """Replica ids best-first. Deterministic: score desc, then load asc,
    then declaration order."""
    order = {r: i for i, r in enumerate(replicas)}
    return sorted(
        replicas,
        key=lambda r: (
            -score_replica(matches.get(r, 0), prompt_len, loads.get(r, 0),
                           cache_weight=cache_weight, load_weight=load_weight),
            loads.get(r, 0),
            order[r],
        ),
    )


class CacheAwareRouter:
    """Serve-agnostic cache-aware router core.

    ``index``: PrefixIndex or a handle to one (duck-typed on ``.remote``
    like the plane client). ``submit(replica_id, prompt_token_ids,
    sampling_params) -> dict`` performs the actual generation call.
    ``replicas``: the routable replica ids, matching what each replica's
    KVPlaneClient registered under."""

    def __init__(self, index, submit, replicas, *, block: int = 64,
                 cache_weight: float = 1.0, load_weight: float = 0.1,
                 max_attempts: int = 2, index_timeout_s: float = 10.0,
                 resume_submit=None, telemetry_tags: dict | None = None):
        from ray_tpu.llm.telemetry import RouterTelemetry

        self._index = index
        self._submit = submit
        # resume_submit(replica_id, meta, ref, sampling_params) -> dict:
        # splice a preempted replica's published live_state checkpoint
        # (llm/migrate.py) on the chosen replica — the failover leg that
        # beats re-prefill (zero recomputed tokens). None = off.
        self._resume_submit = resume_submit
        self.replicas = list(replicas)
        self.block = int(block)
        self.cache_weight = float(cache_weight)
        self.load_weight = float(load_weight)
        self.max_attempts = max(1, int(max_attempts))
        self.index_timeout_s = float(index_timeout_s)
        self._lock = threading.Lock()
        self._inflight = {r: 0 for r in self.replicas}
        self.stats_counts = {
            "requests": 0, "routed_to_holder": 0, "routed_off_holder": 0,
            "cold": 0, "retries": 0, "failed": 0, "matched_tokens": 0,
            "index_errors": 0, "budget_exhausted": 0, "shed": 0,
            "migrations": 0, "resumed": 0,
        }
        # failover/shed events flow into the live serving metrics, same
        # catalog as the disagg router's
        self._tel = RouterTelemetry(telemetry_tags)

    def _matches(self, prompt) -> dict:
        """Per-replica longest cached prefix; {} when the index is down
        (the router degrades to pure load balancing, never fails)."""
        from ray_tpu.llm.kvplane.client import index_call
        from ray_tpu.llm.kvplane.index import boundary_keys

        keys = boundary_keys(prompt, self.block)
        if not keys:
            return {}
        try:
            return index_call(self._index, "match_replicas", keys, timeout_s=self.index_timeout_s) or {}
        except BaseException:  # noqa: BLE001
            with self._lock:
                self.stats_counts["index_errors"] += 1
            return {}

    def hot_prefixes(self, k: int = 4) -> list:
        """The fleet's top-k demanded prefix blocks (index.top_hot) —
        the same view replicas prefetch from, exposed router-side for
        dashboards and placement decisions. [] when the index is down."""
        from ray_tpu.llm.kvplane.client import index_call

        try:
            return index_call(self._index, "top_hot", int(k), None,
                              timeout_s=self.index_timeout_s) or []
        except BaseException:  # noqa: BLE001
            with self._lock:
                self.stats_counts["index_errors"] += 1
            return []

    def route(self, prompt_token_ids) -> tuple:
        """(ranked replica ids, matches dict) for a prompt — exposed for
        tests and for callers that submit through their own transport."""
        prompt = list(prompt_token_ids)
        matches = self._matches(prompt)
        with self._lock:
            loads = dict(self._inflight)
        ranked = rank_replicas(
            self.replicas, matches, loads, len(prompt),
            cache_weight=self.cache_weight, load_weight=self.load_weight,
        )
        return ranked, matches

    def generate(self, prompt_token_ids, sampling_params: dict | None = None) -> dict:
        """Route one request: best-scored replica first, next-ranked on
        failure, KVRouteError after the bounded attempt budget."""
        prompt = list(prompt_token_ids)
        ranked, matches = self.route(prompt)
        best_match = max(matches.values(), default=0)
        with self._lock:
            self.stats_counts["requests"] += 1
            self.stats_counts["matched_tokens"] += best_match
            if best_match <= 0:
                self.stats_counts["cold"] += 1
            elif matches.get(ranked[0], 0) >= best_match:
                self.stats_counts["routed_to_holder"] += 1
            else:
                self.stats_counts["routed_off_holder"] += 1
        from ray_tpu.serve.overload import RetryBudget, router_terminal

        priority = int((sampling_params or {}).get("priority", 0))
        budget = RetryBudget(self.max_attempts, self._tel)
        from ray_tpu.llm.migrate import migration_lost, migration_of

        last: BaseException | None = None
        attempted = 0
        attempt = 0
        ix = 0  # position in the ranked list; a failure usually advances
        mig = None  # a preempted replica's (request_id, meta, ref) checkpoint
        while ix < len(ranked):
            if not budget.try_spend():
                break
            rid = ranked[ix]
            attempted += 1
            if attempt:
                with self._lock:
                    self.stats_counts["retries"] += 1
            attempt += 1
            with self._lock:
                self._inflight[rid] += 1
            try:
                if mig is not None and self._resume_submit is not None:
                    # resume-on-peer failover leg (llm/migrate.py): the
                    # previous replica was preempted mid-decode and
                    # checkpointed this request's live state — splice it
                    # here with ZERO recomputed tokens instead of paying
                    # prompt + generated prefix in a re-prefill
                    out = self._resume_submit(rid, mig[1], mig[2], sampling_params or {})
                    with self._lock:
                        self.stats_counts["resumed"] += 1
                    self._tel.on_migration("resumed")
                    return out
                return self._submit(rid, prompt, sampling_params or {})
            except BaseException as e:  # noqa: BLE001
                last = e
                m = migration_of(e)
                if m is not None and self._resume_submit is not None:
                    with self._lock:
                        self.stats_counts["migrations"] += 1
                    mig = m
                    ix += 1  # the dying replica is done; resume on the next
                elif mig is not None and migration_lost(e):
                    # checkpoint gone before the fetch: THIS replica is
                    # healthy (it failed to borrow, not to serve) — stay
                    # on it and re-prefill from scratch next attempt
                    # (correct, just recomputes the generated prefix)
                    self._tel.on_migration("lost")
                    mig = None
                else:
                    ix += 1
            finally:
                with self._lock:
                    self._inflight[rid] -= 1
        # shared terminal epilogue (serve/overload.py): distinguishes
        # budget exhaustion from a small fleet's ranked list running out,
        # re-raises saturation as the 429, and only counts real failures
        # as failed — the ONE policy the disagg router runs too
        router_terminal(
            last, budget=budget, priority=priority,
            counters=self.stats_counts, lock=self._lock, telemetry=self._tel,
            shed_msg=f"request shed: {attempted} replicas overloaded/draining",
        )
        raise KVRouteError(
            f"request failed on {attempted} replicas "
            f"(last: {type(last).__name__}: {last})"
        ) from last

    def stats(self) -> dict:
        with self._lock:
            return {**self.stats_counts, "inflight": dict(self._inflight)}
