"""Data-integrated batch LLM inference.

Reference parity: python/ray/llm/_internal/batch/processor/
sglang_engine_proc.py:1 and vllm_engine_proc.py (ray.data.llm
build_llm_processor) — a dataset of prompts flows through a pool of
engine-holding actors and comes back as a dataset of completions, with
the Data executor handling partitioning, actor reuse, and backpressure.

TPU-native shape: the UDF actor owns a continuous-batching LLMEngine
(llm/engine.py) and each Data batch is generated with full slot
utilization; prefix caching inside the engine deduplicates shared
prompt prefixes across the whole dataset for free.
"""

from __future__ import annotations

import time

import numpy as np

from ray_tpu.llm.sampling import SamplingParams


class _EngineUDF:
    """Class-based map_batches UDF: one engine per Data actor."""

    def __init__(self, engine_factory, sampling: SamplingParams, input_column: str, output_column: str):
        self.engine = engine_factory()
        self.sampling = sampling
        self.input_column = input_column
        self.output_column = output_column
        self.tokens_out = 0
        self.wall = 0.0

    def __call__(self, batch: dict) -> dict:
        prompts = [[int(t) for t in p] for p in batch[self.input_column]]
        t0 = time.perf_counter()
        outs = self.engine.generate(prompts, self.sampling)
        self.wall += time.perf_counter() - t0
        self.tokens_out += sum(len(o.token_ids) for o in outs)
        gen = np.empty(len(outs), dtype=object)
        for i, o in enumerate(outs):
            gen[i] = list(o.token_ids)
        out = dict(batch)
        out[self.output_column] = gen
        out[self.output_column + "_finish_reason"] = np.array([o.finish_reason for o in outs])
        return out


def build_llm_processor(
    engine_factory,
    *,
    sampling: SamplingParams | None = None,
    batch_size: int = 16,
    concurrency: int = 1,
    input_column: str = "prompt",
    output_column: str = "generated",
    preprocess=None,
    postprocess=None,
):
    """Return ``processor(Dataset) -> Dataset`` running batch inference.

    ``engine_factory``: zero-arg callable building the LLMEngine inside
    each Data actor (weights load in-actor, never through the driver).
    ``concurrency``: number of engine actors (maps to map_batches
    concurrency; each actor admits ``batch_size`` prompts through its
    slot cache with continuous batching).
    """
    sampling = sampling or SamplingParams()

    def processor(ds):
        if preprocess is not None:
            ds = ds.map(preprocess)
        ds = ds.map_batches(
            _EngineUDF,
            fn_constructor_kwargs={
                "engine_factory": engine_factory,
                "sampling": sampling,
                "input_column": input_column,
                "output_column": output_column,
            },
            batch_size=batch_size,
            concurrency=concurrency,
        )
        if postprocess is not None:
            ds = ds.map(postprocess)
        return ds

    return processor
