"""ray_tpu.llm.disagg — disaggregated prefill/decode serving.

Splits the two LLM phases into separate replica pools with the KV block
shipped through the runtime's own object plane:

- prefill replicas run BATCHED prefill only (the engine's admission +
  prefill stages, decode stage never dispatched), extract each request's
  KV into a contiguous device buffer (scatter.py) and publish it as an
  OWNED object (handoff.py over core/direct.py put_owned);
- decode replicas borrow the block, scatter it into their slot cache or
  paged pool with ONE fused admission program, and continue fully
  device-resident — speculative decoding included;
- the router (router.py) admits to prefill, tracks handoff refs, binds
  each request to a decode lane, and owns the bounded retry policy for
  dead lanes and lost handoffs.

Serve integration (deployments + builder) lives in ray_tpu.serve.llm
(PrefillServer / DecodeServer / DisaggRouterServer,
build_pd_disagg_deployment). The single-engine sync loop remains the
token-identical oracle: an N_prefill=1/N_decode=1 deployment emits
exactly its tokens (tests/test_llm_disagg.py).
"""

from ray_tpu.llm.disagg.handoff import (
    HandoffError,
    HandoffLostError,
    decode as decode_handoff,
    encode as encode_handoff,
    fetch as fetch_handoff,
    publish as publish_handoff,
)
from ray_tpu.llm.disagg.router import DisaggRequestError, DisaggRouter

__all__ = [
    "DisaggRequestError",
    "DisaggRouter",
    "HandoffError",
    "HandoffLostError",
    "decode_handoff",
    "encode_handoff",
    "fetch_handoff",
    "publish_handoff",
]
