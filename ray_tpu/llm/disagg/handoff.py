"""KV handoff codec: the wire format between prefill and decode replicas.

A handoff is ONE request's prefilled state: the contiguous KV block the
prefill engine extracted (llm/disagg/scatter.py), the first-token logits,
and enough layout metadata for the decode side to validate and scatter it
into its own cache — shapes, dtype, real length, producer bucket width.

The payload rides the runtime's own object plane: ``publish`` stores it
as an OWNED object in the prefill replica's process (core/direct.py
put_owned — multi-MB arrays land in shared memory, the descriptor rides
the direct-transport frame, and same-host borrowers attach the segment
without copying the bytes over a socket). The prefill replica stays the
owner for the block's whole life: the router and the decode replica are
borrowers, and the owner frees the segment after the last borrow-release
(dead borrowers are covered by the RT_OWNED_OBJECT_LEAK_BACKSTOP_S
backstop — a crashed decode replica can never leak the block forever).

``fetch`` is the decode side: a bounded-retry borrow-get that decodes
zero-copy (read-only views into the mapped segment) and validates the
block against its metadata. A handoff that was evicted/freed before
scatter-in surfaces as ``HandoffLostError`` after the retry budget — the
router's signal to re-prefill or fail the request, never to hang.
"""

from __future__ import annotations

import time

import numpy as np

from ray_tpu.exceptions import serving_error

HANDOFF_VERSION = 1


@serving_error
class HandoffError(ValueError):
    """Malformed or inconsistent handoff payload (codec-level)."""


@serving_error
class HandoffLostError(RuntimeError):
    """The handoff object vanished (owner died / evicted / freed) before
    the decode side could scatter it in. Bounded-retry callers raise this
    after exhausting their budget; the router reacts by re-prefilling."""


def _handoff_span(name: str, payload: dict, t0: float, **attrs) -> None:
    """Record a handoff data-plane span under the trace context the wire
    dict carries (no-op unless RT_TRACING=1 and the producer traced)."""
    from ray_tpu.util import tracing

    tr = payload.get("trace")
    if not tracing.enabled() or not isinstance(tr, dict):
        return
    import uuid

    tracing.record_span(
        name, "internal", str(tr["trace_id"]), uuid.uuid4().hex[:16], tr.get("parent_id"),
        int(t0 * 1e9), time.time_ns(), dict(attrs),
    )


def _scale_shape(shape: tuple) -> tuple:
    """Expected wire scale shape [L, kv, T_pad] for a k block [L, T_pad,
    kv, hd] — one f32 per (layer, head, position), position axis last
    (llm/kv_quant.py)."""
    return (shape[0], shape[2], shape[1])


PREFIX_KIND = "kv_prefix"  # cluster KV plane (llm/kvplane/): a published
# prefix block — same wire validation, no first-token logits (the
# consumer re-attends the prompt's remaining suffix itself)

LIVE_KIND = "live_state"  # live request migration (llm/migrate.py): a
# mid-decode checkpoint's KV half — the wire prompt_token_ids are the
# COVERED tokens (prompt + emitted[:-1], exactly the n attended
# positions), and the next token comes from the peer's first decode
# step, so like a prefix block it carries no logits

# kinds whose wire carries no first-token logits
_NO_LOGITS_KINDS = (PREFIX_KIND, LIVE_KIND)


def encode(kv: dict, *, kind: str = "kv_handoff") -> dict:
    """Engine handoff payload -> self-describing wire dict.

    ``kv`` is the engine's prefill-extract product: k/v [L, T_pad, kv_h,
    hd] numpy, logits [vocab] f32, n real tokens, prompt_token_ids — and
    for an int8 producer cache also k_scale/v_scale [L, kv_h, T_pad] f32
    per-head scales; the wire then carries int8 values + scales (~half
    the bytes of a bf16 block). ``kind=PREFIX_KIND`` encodes a cluster
    prefix block instead: identical layout/validation, but logits are
    absent (a prefix is strictly shorter than any prompt it serves)."""
    k, v = np.asarray(kv["k"]), np.asarray(kv["v"])
    n = int(kv["n"])
    if k.ndim != 4 or k.shape != v.shape:
        raise HandoffError(f"KV block must be [L, T_pad, kv, hd] twins, got k{k.shape} v{v.shape}")
    if not 0 < n <= k.shape[1]:
        raise HandoffError(f"real length {n} outside block width {k.shape[1]}")
    wire = {
        "version": HANDOFF_VERSION,
        "kind": kind,
        "n": n,
        "t_pad": int(k.shape[1]),
        "shape": tuple(int(d) for d in k.shape),
        "dtype": str(k.dtype),
        "prompt_token_ids": [int(t) for t in kv["prompt_token_ids"]],
        "k": k,
        "v": v,
    }
    if kind not in _NO_LOGITS_KINDS:
        wire["logits"] = np.asarray(kv["logits"], np.float32)
    # telemetry plumbing (llm/telemetry.py): the producer's trace context
    # and original submit stamp ride the wire so the decode replica's
    # spans join the SAME trace id and TTFT spans the whole pipeline
    if isinstance(kv.get("trace"), dict) and kv["trace"].get("trace_id"):
        wire["trace"] = {"trace_id": str(kv["trace"]["trace_id"]),
                         "parent_id": kv["trace"].get("parent_id")}
    if kv.get("submitted_at") is not None:
        wire["submitted_at"] = float(kv["submitted_at"])
    if (kv.get("k_scale") is not None) != (kv.get("v_scale") is not None):
        raise HandoffError("k_scale and v_scale must be supplied together")
    if kv.get("k_scale") is not None:
        k_sc, v_sc = np.asarray(kv["k_scale"]), np.asarray(kv["v_scale"])
        if str(k.dtype) != "int8":
            raise HandoffError(f"scale tensors supplied for a non-int8 block ({k.dtype})")
        want = _scale_shape(k.shape)
        if tuple(k_sc.shape) != want or tuple(v_sc.shape) != want:
            raise HandoffError(f"scale shape must be {want} ([L, kv, T_pad]), got k{k_sc.shape} v{v_sc.shape}")
        if str(k_sc.dtype) != "float32" or str(v_sc.dtype) != "float32":
            raise HandoffError(f"scales must be float32, got k:{k_sc.dtype} v:{v_sc.dtype}")
        wire["k_scale"] = k_sc
        wire["v_scale"] = v_sc
    elif str(k.dtype) == "int8":
        raise HandoffError("int8 block without its per-head scale tensors")
    return wire


def decode(payload: dict, *, kind: str = "kv_handoff") -> dict:
    """Wire dict -> validated engine admission payload (add_prefilled
    format). Raises HandoffError on anything inconsistent — a truncated
    or foreign object must never scatter garbage into a live pool. For
    an int8 block the per-head scale tensors are validated (shape
    [L, kv, T_pad], float32) with the same severity: a garbage scale
    would silently re-scale every attended position. ``kind=PREFIX_KIND``
    decodes a cluster prefix block (no logits on the wire)."""
    if not isinstance(payload, dict) or payload.get("kind") != kind:
        raise HandoffError(f"not a {kind} payload: {type(payload).__name__}")
    if payload.get("version") != HANDOFF_VERSION:
        raise HandoffError(f"handoff version {payload.get('version')} != {HANDOFF_VERSION}")
    k, v = payload["k"], payload["v"]
    shape = tuple(payload["shape"])
    if tuple(k.shape) != shape or tuple(v.shape) != shape:
        raise HandoffError(f"block shape mismatch: meta {shape}, k {tuple(k.shape)}, v {tuple(v.shape)}")
    if str(k.dtype) != payload["dtype"]:
        raise HandoffError(f"block dtype mismatch: meta {payload['dtype']}, got {k.dtype}")
    n = int(payload["n"])
    prompt = payload["prompt_token_ids"]
    if not 0 < n <= shape[1] or n != len(prompt):
        raise HandoffError(f"length {n} inconsistent with block width {shape[1]} / prompt {len(prompt)}")
    out = {"k": k, "v": v, "n": n, "prompt_token_ids": list(prompt)}
    if kind not in _NO_LOGITS_KINDS:
        out["logits"] = payload["logits"]
    if isinstance(payload.get("trace"), dict) and payload["trace"].get("trace_id"):
        out["trace"] = dict(payload["trace"])
    if payload.get("submitted_at") is not None:
        out["submitted_at"] = float(payload["submitted_at"])
    if payload["dtype"] == "int8":
        k_sc, v_sc = payload.get("k_scale"), payload.get("v_scale")
        if k_sc is None or v_sc is None:
            raise HandoffError("int8 block without its per-head scale tensors")
        want = _scale_shape(shape)
        if tuple(k_sc.shape) != want or tuple(v_sc.shape) != want:
            raise HandoffError(f"scale shape mismatch: expected {want}, got k{tuple(k_sc.shape)} v{tuple(v_sc.shape)}")
        if str(k_sc.dtype) != "float32" or str(v_sc.dtype) != "float32":
            raise HandoffError(f"scale dtype must be float32, got k:{k_sc.dtype} v:{v_sc.dtype}")
        out["k_scale"] = k_sc
        out["v_scale"] = v_sc
    elif payload.get("k_scale") is not None or payload.get("v_scale") is not None:
        raise HandoffError(f"scale tensors on a non-int8 block ({payload['dtype']})")
    return out


def meta_of(payload: dict) -> dict:
    """Small router-facing summary (no arrays): what travels with the ref.
    Prefix blocks (PREFIX_KIND) carry no logits; everything else is the
    same accounting."""
    nbytes = int(payload["k"].nbytes + payload["v"].nbytes)
    if payload.get("logits") is not None:
        nbytes += int(payload["logits"].nbytes)
    if payload.get("k_scale") is not None:
        nbytes += int(payload["k_scale"].nbytes + payload["v_scale"].nbytes)
    return {
        "n": payload["n"],
        "t_pad": payload["t_pad"],
        "shape": tuple(payload["shape"]),
        "dtype": payload["dtype"],
        "quantized": payload.get("k_scale") is not None,
        "nbytes": nbytes,
    }


def publish(kv: dict):
    """Encode and store a handoff as an owned object in THIS process.

    Returns (meta, ref): the multi-MB payload stays owner-local (shm for
    anything over the inline threshold) and only the tiny (meta, ref)
    pair travels back to the router."""
    from ray_tpu import chaos
    from ray_tpu.core import direct as _direct

    payload = encode(kv)
    t0 = time.time()
    # chaos site: a dropped publish surfaces as the owner-side loss the
    # router's re-prefill path must absorb (inert when unarmed)
    if not chaos.apply("handoff.put"):
        raise HandoffLostError("chaos: handoff publish dropped")
    ref = _direct.put_owned(payload)
    _handoff_span("llm.handoff.put", payload, t0, nbytes=meta_of(payload)["nbytes"])
    return meta_of(payload), ref


def fetch(
    ref, meta: dict | None = None, *, timeout_s: float = 30.0, retries: int = 2,
    retry_wait_s: float = 0.2, kind: str = "kv_handoff",
) -> dict:
    """Borrow-get a published handoff with a bounded retry budget.

    The get decodes zero-copy (arrays are read-only views into the mapped
    segment — no byte copy on the borrow path; the device upload at
    scatter-in is the only copy the decode side pays). ``retries`` extra
    attempts absorb transient owner-side races; a handoff that is GONE
    (owner freed/evicted it, owner process died) raises HandoffLostError
    immediately on the loss signal after the final attempt — callers must
    never hang on a dead handoff. ``kind=PREFIX_KIND`` fetches a cluster
    prefix block under the same retry contract (the kvplane client maps
    the loss into its local-prefill fallback)."""
    from ray_tpu import chaos
    from ray_tpu.core import direct as _direct
    from ray_tpu.exceptions import GetTimeoutError, ObjectLostError

    last: BaseException | None = None
    for attempt in range(retries + 1):
        try:
            t0 = time.time()
            # chaos site, INSIDE the bounded-retry loop: each attempt is
            # one hit, so a max_hits rule can lose the first N attempts
            # and let the retry succeed (or exhaust into HandoffLostError).
            # A drop rule maps onto the native loss signal.
            if not chaos.apply("handoff.fetch"):
                raise ObjectLostError("chaos: handoff fetch dropped")
            value = _direct.get_owned_view(ref.id, timeout=timeout_s)
            payload = decode(value, kind=kind)
            if meta is not None and tuple(meta.get("shape", payload["k"].shape)) != tuple(payload["k"].shape):
                raise HandoffError(f"fetched block {payload['k'].shape} does not match routed meta {meta['shape']}")
            _handoff_span("llm.handoff.fetch", payload, t0, attempts=attempt + 1)
            return payload
        except (ObjectLostError, GetTimeoutError, ConnectionError, FileNotFoundError) as e:
            last = e
            if attempt < retries:
                time.sleep(retry_wait_s)
    raise HandoffLostError(
        f"handoff object {ref.id.hex()[:16]} lost before scatter-in "
        f"({retries + 1} attempts): {last}"
    ) from last
