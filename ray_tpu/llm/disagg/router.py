"""Disaggregated serving router: admit to prefill, bind to a decode lane.

The router is the control plane of the prefill/decode split (the data
plane is the handoff object, llm/disagg/handoff.py — the router never
touches the KV bytes). Per request it:

1. admits the prompt to the prefill pool and receives (meta, ref) — a
   tiny summary plus a borrowed reference to the owned KV block;
2. binds the handoff to a decode lane (a decode submit callable; under
   Serve this is the decode deployment handle, whose pow-2 router picks
   the replica) and waits for generation;
3. tracks every in-flight handoff ref so the block stays alive from
   publish to scatter-in, and releases it the moment the request settles
   (the owner then frees on borrow-release).

Failure policy — bounded, never hanging:

- decode lane dies after the handoff (replica crash mid-request): the
  request is retried on another lane, REUSING the same handoff if the
  block is still alive, re-prefilling if it is not; after
  ``max_attempts`` total attempts the error surfaces to the client. The
  orphaned block is not leaked: the router drops its borrow and the
  owner's backstop covers the dead replica's unregistered one.
- handoff evicted/freed before scatter-in: the decode side's bounded
  fetch raises HandoffLostError; the router re-prefills (a fresh block)
  up to the same attempt budget, then fails the request client-visibly.
- decode replica PREEMPTED mid-request (llm/migrate.py): the replica's
  drain(mode="migrate") hands the waiter a RequestMigratedError carrying
  the published checkpoint's (meta, ref) — the router RESUMES the
  request on another lane via the injected ``resume`` callable, zero
  recomputed tokens, beating re-prefill (which pays prompt + generated
  prefix). A lost checkpoint degrades to re-prefill; the whole ladder
  spends the one shared RetryBudget: migrate -> re-prefill -> typed
  error.
"""

from __future__ import annotations

import threading

from ray_tpu.llm.disagg.handoff import HandoffLostError
from ray_tpu.exceptions import serving_error


@serving_error
class DisaggRequestError(RuntimeError):
    """Client-visible terminal failure after the router's retry budget."""


def _handoff_lost(e: BaseException | None) -> bool:
    """True when ``e`` is (or wraps) a HandoffLostError. Under Serve the
    decode replica's exception crosses the wire inside TaskError: follow
    the ``.cause`` chain, and fall back to the remote traceback string
    for causes that didn't survive pickling."""
    for _ in range(8):
        if e is None:
            return False
        if isinstance(e, HandoffLostError):
            return True
        if "HandoffLostError" in getattr(e, "tb_str", ""):
            return True
        e = getattr(e, "cause", None)
    return False


class DisaggRouter:
    """Serve-agnostic core. ``prefill(prompt_token_ids) -> (meta, ref)``
    and ``decode(meta, ref, prompt_token_ids, sampling_params) -> dict``
    are injected (under Serve: deployment-handle calls; in tests: engine
    closures), so the policy is testable without a cluster."""

    def __init__(self, prefill, decode, *, resume=None, max_attempts: int = 3,
                 telemetry_tags: dict | None = None):
        from ray_tpu.llm.telemetry import RouterTelemetry

        self._prefill = prefill
        self._decode = decode
        # resume(meta, ref, sampling_params) -> dict: splice a preempted
        # replica's published live_state checkpoint on a peer (under
        # Serve: the decode handle's resume_from_migration). None = the
        # resume leg is off and migrations degrade to re-prefill.
        self._resume = resume
        self.max_attempts = max(1, int(max_attempts))
        self._lock = threading.Lock()
        self._inflight: dict[str, object] = {}  # request key -> handoff ref
        self.stats_counts = {
            "requests": 0, "prefills": 0, "decode_retries": 0,
            "handoffs_lost": 0, "failed": 0, "handoff_bytes": 0,
            "budget_exhausted": 0, "shed": 0,
            "migrations": 0, "resumed": 0,
        }
        self._seq = 0
        # control-plane events also flow into the live serving metrics
        # (llm/telemetry.py catalog) so a /metrics scrape sees the split's
        # health, not just callers polling stats()
        self._tel = RouterTelemetry(telemetry_tags)

    def stats(self) -> dict:
        with self._lock:
            return {**self.stats_counts, "inflight": len(self._inflight)}

    def _bump(self, key: str, by: int = 1):
        with self._lock:
            self.stats_counts[key] += by

    def generate(self, prompt_token_ids, sampling_params: dict | None = None) -> dict:
        """One request end to end. The failover budget is the SHARED
        per-request ``serve.overload.RetryBudget`` (one policy across the
        disagg and kvplane routers): every attempt — prefill retry,
        handoff-lost re-prefill, decode failover — spends one unit.
        Exhaustion surfaces a typed terminal error: OverloadedError when
        the last failure was a shedding/draining replica (the 429
        propagates so clients back off), DisaggRequestError otherwise."""
        from ray_tpu.llm.migrate import migration_lost, migration_of
        from ray_tpu.serve.overload import RetryBudget, router_terminal

        with self._lock:
            self.stats_counts["requests"] += 1
            self._seq += 1
            key = f"dreq-{self._seq}"
        priority = int((sampling_params or {}).get("priority", 0))
        budget = RetryBudget(self.max_attempts, self._tel)
        meta = ref = None
        mig = None  # (request_id, meta, ref) of a preempted lane's checkpoint
        last: BaseException | None = None
        try:
            while budget.try_spend():
                if mig is not None and self._resume is not None:
                    # resume-on-peer leg (recompute = 0): splice the
                    # dying replica's live_state checkpoint before ever
                    # considering a re-prefill (which would recompute
                    # prompt + the whole generated prefix)
                    try:
                        out = self._resume(mig[1], mig[2], sampling_params or {})
                        self._bump("resumed")
                        self._tel.on_migration("resumed")
                        return out
                    except BaseException as e:  # noqa: BLE001
                        last = e
                        if migration_lost(e):
                            # checkpoint gone (owner exited before the
                            # fetch): degrade to re-prefill from scratch
                            self._tel.on_migration("lost")
                            mig = None
                        # an overloaded/dead peer keeps the checkpoint —
                        # the next budget unit retries the resume
                    continue
                if ref is None:
                    try:
                        meta, ref = self._prefill(list(prompt_token_ids))
                    except BaseException as e:  # noqa: BLE001
                        last = e
                        continue
                    self._bump("prefills")
                    self._bump("handoff_bytes", int(meta.get("nbytes", 0)))
                    self._tel.on_published(int(meta.get("nbytes", 0)))
                    with self._lock:
                        self._inflight[key] = ref
                try:
                    return self._decode(meta, ref, list(prompt_token_ids), sampling_params or {})
                except BaseException as e:  # noqa: BLE001
                    last = e
                    m = migration_of(e)
                    if m is not None and self._resume is not None:
                        # the decode lane was PREEMPTED and checkpointed
                        # this request's live state: switch to the resume
                        # leg. The prefill handoff ref is KEPT — its owner
                        # (the prefill replica) is not the one dying, so
                        # if the checkpoint is lost the retry can still
                        # re-decode from the surviving block instead of
                        # re-prefilling
                        self._bump("migrations")
                        mig = m
                    elif _handoff_lost(e):
                        # block gone before scatter-in (possibly wrapped
                        # in the task layer's TaskError): this ref is
                        # dead weight — drop it and re-prefill
                        self._bump("handoffs_lost")
                        self._tel.on_lost()
                        self._drop(key)
                        meta = ref = None
                    else:
                        # decode lane failure (replica death, transport
                        # cut, or an overloaded/draining replica's shed):
                        # keep the handoff — the block lives in the
                        # PREFILL replica, so a surviving owner lets the
                        # retry skip the re-prefill entirely
                        self._bump("decode_retries")
                        self._tel.on_reused()
            # shared terminal epilogue (serve/overload.py): saturation
            # re-raises the 429 with the replica's backoff hint; real
            # failure falls through to this router's terminal class
            router_terminal(
                last, budget=budget, priority=priority,
                counters=self.stats_counts, lock=self._lock, telemetry=self._tel,
                shed_msg=(
                    f"request shed: every decode lane overloaded/draining after "
                    f"{self.max_attempts} attempts"
                ),
            )
            raise DisaggRequestError(
                f"request failed after {self.max_attempts} attempts "
                f"(last: {type(last).__name__}: {last})"
            ) from last
        finally:
            self._drop(key)

    def _drop(self, key: str):
        """Release the router's borrow of the request's handoff (the owner
        frees the block once the decode side's borrow releases too)."""
        with self._lock:
            self._inflight.pop(key, None)
