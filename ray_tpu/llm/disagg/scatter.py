"""Fused KV handoff programs: extract on the prefill side, scatter-in on
the decode side.

Four jitted entry points move a request's KV block between an engine's
cache and the contiguous handoff buffer that rides the object plane
(llm/disagg/handoff.py), one pair per KV layout:

- extract: read the block OUT of the prefill engine's cache/pool into a
  contiguous [L, T_pad, kv, hd] device buffer (slots: dynamic row slice;
  paged: page gather). Read-only over the cache — never fused with a
  scatter (the documented pool aliasing hazard, see
  paged_kv._paged_attn_batch).
- scatter-in: write a received block INTO the decode engine's cache/pool
  AND update the device-resident scheduler lanes in the same program —
  for the paged layout this fuses what was previously three dispatches
  (insert_pages + table push + length push) into ONE, so a handoff
  admission costs a single program launch on the decode hot path.

T_pad is the producer's prefill bucket (static: one compiled program per
bucket, mirroring prefill's own bucketing). Positions n..T_pad are
garbage the consumer masks by length and overwrites with appends — the
same contract as prefill's padded tail.

All four are registered as jaxcheck entries (the decode-side scatter is
on the admission hot path of every disaggregated request).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ray_tpu.lint import jaxcheck
from ray_tpu.llm.model_runner import _sds, _sds_cache, _sds_cache_q, _sds_pool, _sds_pool_q, _trace_cfg


# ---------------------------------------------------------------------------
# jaxcheck shape buckets (ShapeDtypeStructs only — nothing allocates)
# ---------------------------------------------------------------------------
def _bucket_extract_slots(B=8, S=256, T=128):
    cfg = _trace_cfg()
    return (_sds_cache(cfg, B, S), _sds((), jnp.int32)), {"T": T}


def _bucket_extract_paged(pages=64, page=16, npg=8):
    cfg = _trace_cfg()
    return (_sds_pool(cfg, pages, page), _sds((npg,), jnp.int32)), {}


def _bucket_scatter_slots(B=8, S=256, T=128):
    cfg = _trace_cfg()
    dt = jnp.dtype(cfg.dtype)
    blk = _sds((cfg.num_layers, T, cfg.num_kv_heads, cfg.hd), dt)
    return (_sds_cache(cfg, B, S), _sds((), jnp.int32), blk, blk, _sds((), jnp.int32)), {}


def _bucket_scatter_paged(B=8, pages=64, page=16, npg=8):
    cfg = _trace_cfg()
    dt = jnp.dtype(cfg.dtype)
    max_pg = pages // B * 2
    blk = _sds((cfg.num_layers, npg * page, cfg.num_kv_heads, cfg.hd), dt)
    return (
        _sds_pool(cfg, pages, page), _sds((B, max_pg), jnp.int32), _sds((B,), jnp.int32),
        _sds((), jnp.int32), _sds((max_pg,), jnp.int32), blk, blk, _sds((), jnp.int32),
    ), {}


def _bucket_extract_slots_q(B=8, S=256, T=128):
    cfg = _trace_cfg()
    return (_sds_cache_q(cfg, B, S), _sds((), jnp.int32)), {"T": T}


def _bucket_extract_paged_q(pages=64, page=16, npg=8):
    cfg = _trace_cfg()
    return (_sds_pool_q(cfg, pages, page), _sds((npg,), jnp.int32)), {}


def _bucket_scatter_slots_q(B=8, S=256, T=128):
    """Int8 producer -> int8 consumer: int8 block + wire-layout scales."""
    cfg = _trace_cfg()
    blk = _sds((cfg.num_layers, T, cfg.num_kv_heads, cfg.hd), jnp.int8)
    sc = _sds((cfg.num_layers, cfg.num_kv_heads, T), jnp.float32)
    return (_sds_cache_q(cfg, B, S), _sds((), jnp.int32), blk, blk, _sds((), jnp.int32), sc, sc), {}


def _bucket_scatter_paged_q(B=8, pages=64, page=16, npg=8):
    cfg = _trace_cfg()
    max_pg = pages // B * 2
    blk = _sds((cfg.num_layers, npg * page, cfg.num_kv_heads, cfg.hd), jnp.int8)
    sc = _sds((cfg.num_layers, cfg.num_kv_heads, npg * page), jnp.float32)
    return (
        _sds_pool_q(cfg, pages, page), _sds((B, max_pg), jnp.int32), _sds((B,), jnp.int32),
        _sds((), jnp.int32), _sds((max_pg,), jnp.int32), blk, blk, _sds((), jnp.int32), sc, sc,
    ), {}


# ---------------------------------------------------------------------------
# extract (prefill side)
# ---------------------------------------------------------------------------
@jaxcheck.entry(
    name="llm.disagg_extract_slots",
    shapes={"b8_t128": _bucket_extract_slots},
    donate_bytes=0,  # read-only over the cache: nothing to donate
)
def kv_extract_slots(cache, slot, T: int):
    """Extract one slot's first T positions as a contiguous block.

    Returns (k [L, T, kv, hd], v same); T static (per prefill bucket),
    slot traced. For an int8 cache also (k_scale [L, kv, T], v_scale) —
    the handoff wire layout, so quantized blocks leave at ~half the
    bytes. Garbage past the real length is masked downstream."""
    from ray_tpu.llm.kv_cache import extract_sequence

    return extract_sequence(cache, slot, T)


@jaxcheck.entry(
    name="llm.disagg_extract_paged",
    shapes={"p64_npg8": _bucket_extract_paged},
    donate_bytes=0,  # read-only over the pool: nothing to donate
)
def kv_extract_paged(pool, page_ids):
    """Gather a sequence's pages into a contiguous block.

    page_ids [n_pg] int32 (static length = T_pad / page_size; padding
    cells point at the trash page). Returns (k [L, n_pg*page, kv, hd],
    v same)."""
    from ray_tpu.llm.paged_kv import gather_pages

    return gather_pages(pool, page_ids)


# ---------------------------------------------------------------------------
# scatter-in (decode side)
# ---------------------------------------------------------------------------
@jaxcheck.entry(
    name="llm.disagg_scatter_slots",
    shapes={"b8_t128": _bucket_scatter_slots},
    donate=("cache",),
    donate_bytes=0,  # admission hot path: every buffer it touches counts
)
def kv_scatter_in_slots(cache, slot, k_blk, v_blk, n, k_scale=None, v_scale=None):
    """Write a handoff block into `slot` at offset 0 and set its length —
    the slot-layout scatter-in, one program per bucket width.

    k_blk/v_blk: [L, T_pad, kv, hd] (padded tail is garbage, masked by
    n); slot/n: traced scalars; k_scale/v_scale: [L, kv, T_pad] wire-
    layout scales when the block is int8. Producer/consumer cache dtypes
    may differ — kv_cache.insert_sequence requants transparently in all
    four directions (fp block quantizes into an int8 cache; int8 block
    dequantizes into an fp cache)."""
    from ray_tpu.llm.kv_cache import insert_sequence

    return insert_sequence(cache, slot, k_blk, v_blk, n, k_scale, v_scale)


@jaxcheck.entry(
    name="llm.disagg_scatter_paged",
    shapes={"b8_p64": _bucket_scatter_paged},
    donate=("pool", "tables", "lengths"),
    donate_bytes=0,
)
def kv_scatter_in_paged(pool, tables, lengths, slot, table_row, k_blk, v_blk, n, k_scale=None, v_scale=None):
    """Write a handoff block into its allocated pages AND refresh the
    device-resident scheduler lanes in ONE program: pool pages get the
    block (reshaped to whole pages), tables[slot] gets the row, and
    lengths[slot] gets the real token count — replacing the three-launch
    insert + table-push + length-push admission sequence.

    table_row: [max_pg] int32 (allocated pages first, 0 = trash beyond);
    k_blk/v_blk: [L, T_pad, kv, hd] with T_pad a page multiple;
    k_scale/v_scale: [L, kv, T_pad] wire-layout scales when the block is
    int8 (paged_kv.insert_pages requants transparently across
    producer/consumer dtype mismatches). Scatter only — the block is
    never read back in this program (aliasing hazard)."""
    from ray_tpu.llm.paged_kv import insert_pages

    T = k_blk.shape[1]
    page = pool["k"].shape[2]
    npg = T // page
    new_pool = insert_pages(pool, table_row[:npg], k_blk, v_blk, k_scale, v_scale)
    return (
        new_pool,
        tables.at[slot].set(table_row),
        lengths.at[slot].set(jnp.asarray(n, jnp.int32)),
    )


# int8 variants of all four programs (the disagg hot path with quantized
# blocks + wire scales): registered as their own entries so donation and
# the JXC003 dequant trap stay audited on the quantized path — including
# the extracts, whose int8 branch returns a different pytree (values +
# scale slices) than the fp buckets ever trace
jaxcheck.entry(
    name="llm.disagg_extract_slots_int8",
    shapes={"b8_t128": _bucket_extract_slots_q},
    donate_bytes=0,  # read-only over the cache: nothing to donate
)(kv_extract_slots)

jaxcheck.entry(
    name="llm.disagg_extract_paged_int8",
    shapes={"p64_npg8": _bucket_extract_paged_q},
    donate_bytes=0,
)(kv_extract_paged)

jaxcheck.entry(
    name="llm.disagg_scatter_slots_int8",
    shapes={"b8_t128": _bucket_scatter_slots_q},
    donate=("cache",),
    donate_bytes=0,
)(kv_scatter_in_slots)

jaxcheck.entry(
    name="llm.disagg_scatter_paged_int8",
    shapes={"b8_p64": _bucket_scatter_paged_q},
    donate=("pool", "tables", "lengths"),
    donate_bytes=0,
)(kv_scatter_in_paged)


def make_handoff_fns():
    """Jitted (extract_slots, extract_paged, scatter_slots, scatter_paged)
    closures for an engine. Extracts compile once per bucket width (T /
    page_ids length is static); scatters donate the cache/pool and the
    device lanes so admission aliases everything in place."""
    return (
        jax.jit(kv_extract_slots, static_argnums=(2,)),
        jax.jit(kv_extract_paged),
        jax.jit(kv_scatter_in_slots, donate_argnums=(0,)),
        jax.jit(kv_scatter_in_paged, donate_argnums=(0, 1, 2)),
    )
