"""Int8 KV-cache quantization: per-head amax scales, applied on append.

Decode is HBM-bandwidth-bound (bench_serve's roofline fields), and the
cache — not the weights — is the binding HBM constraint past the
threshold kv_cache.py documents, so halving cache bytes both doubles
servable concurrency at fixed HBM and shrinks the bytes every decode
step must stream. EQuARX (arxiv 2506.17615, PAPERS.md) is the TPU
precedent that aggressive quantization of bandwidth-bound tensors holds
up accuracy-wise.

Scheme: symmetric int8 with one float32 scale per (layer, position,
kv-head) — ``scale = amax(|x|, head_dim) / 127`` computed from the
exact K/V vector being appended, so no calibration pass exists and a
freshly written token is immediately self-describing. Quantization
happens INSIDE the fused append (prefill insert, decode append, spec
block append, disagg scatter-in); attention dequantizes on read at the
f32 compute dtype the score/value einsums already use, so the convert
never lands on a flops-dominant dot (the JXC003 trap — regression-locked
in tests/test_lint_rules.py).

Overhead: 4 scale bytes per head per position next to ``head_dim`` int8
bytes — cache bytes shrink by ``2*hd / (hd + 4)`` vs bf16 (1.94x at
hd=128), and the scales ride every wire format (disagg handoffs ship
int8 values + scales, halving object-plane bytes too).

Layout convention: value tensors keep their fp layout with dtype int8;
scale tensors put the POSITION axis last (``[..., kv_heads, S]``) so
their trailing dims land on (8, 128) tile multiples instead of wasting
15/16 of every tile the way a kv-heads-minor layout would (JXC006).

Quantization is idempotent at the byte level: re-quantizing a
dequantized block reproduces the same bytes (amax maps back to 127), so
a requant hop — e.g. an int8 handoff admitted by an fp consumer that
later re-prefills — cannot compound error.
"""

from __future__ import annotations

import jax.numpy as jnp

INT8_MAX = 127.0

# cache_dtype values LLMEngine accepts, normalized (anything else is a
# ValueError at engine construction, never a silent passthrough)
CACHE_DTYPES = {
    "bfloat16": "bfloat16",
    "bf16": "bfloat16",
    "float32": "float32",
    "f32": "float32",
    "int8": "int8",
}


def is_int8(dtype) -> bool:
    return str(dtype) == "int8"


def normalize_cache_dtype(dtype: str) -> str:
    """Validated, canonical cache dtype string (raises ValueError)."""
    try:
        return CACHE_DTYPES[str(dtype).lower()]
    except KeyError:
        raise ValueError(
            f"cache_dtype must be one of {sorted(set(CACHE_DTYPES))}, got {dtype!r}"
        ) from None


def quantize_heads(x):
    """Quantize over the trailing head_dim axis.

    x: [..., hd] float. Returns (q int8 [..., hd], scale f32 [...]) with
    ``scale = amax/127``; all-zero vectors (padded garbage, zeroed
    attention) quantize to q=0, scale=0 and dequantize back to exact 0.
    """
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scale = amax / INT8_MAX
    inv = jnp.where(amax > 0.0, INT8_MAX / jnp.maximum(amax, 1e-30), 0.0)
    q = jnp.clip(jnp.round(xf * inv[..., None]), -INT8_MAX, INT8_MAX).astype(jnp.int8)
    return q, scale


def dequantize(q, scale):
    """q int8 [..., hd] * scale f32 broadcast over hd -> f32 [..., hd]."""
    return q.astype(jnp.float32) * scale[..., None]


def bytes_per_token(num_layers: int, num_kv_heads: int, head_dim: int, dtype: str) -> int:
    """K+V cache bytes one token occupies, scales included — the honest
    per-token figure kv_cache_stats() and the bench roofline report."""
    if is_int8(dtype):
        return 2 * num_layers * num_kv_heads * (head_dim + 4)
    return 2 * num_layers * num_kv_heads * head_dim * jnp.dtype(dtype).itemsize
