"""Live request migration: mid-decode state over the object plane.

TPU fleets run on preemptible capacity, so the canonical failure is not a
crash but a SIGTERM-with-deadline. Replica ``drain()`` (serve/llm.py)
answers it by finishing what it can — and, before this module, ABORTING
the rest: every in-flight decode lost its whole generated prefix and the
router re-prefilled from scratch. The runtime's ownership model already
knows how to do better — a request's KV state is just bytes we can
extract, publish as an owned object, and scatter into a peer (the disagg
handoff proved the pattern for prompt KV) — so mid-decode state survives
a replica's death the same way.

The unit of migration is a **live_state wire dict**: one request's
complete resumable state —

- the KV block covering every attended position (``extract_sequence`` /
  ``gather_pages``, the same fused programs the disagg extract uses; an
  int8 cache ships int8 values + per-head scales and rides the
  transparent-requant insert path on the peer),
- the emitted tokens (and logprobs) the client has already seen,
- the lane's live PRNG key (seeded lanes carry the ADVANCED key, never a
  reset — post-splice sampling continues exactly where it left off),
- the sampling params, and the speculative controller's sticky
  effective-k / acceptance-EMA state when speculation is on —

versioned and validated on decode with the same severity as every other
wire (``MigrationError`` — a truncated or foreign object must never
scatter garbage into a live pool), published via ``direct.put_owned``.

**Splice-dedup contract.** ``engine.checkpoint_request`` first settles
the one-step-delayed emission (the in-flight fused step drains), so the
checkpoint holds every token the device has minted; the peer's
``engine.restore_request`` binds the last emitted token as the next
decode input and emits NOTHING at admission — the next client-visible
token is minted by the first decode step on the peer. The stream can
therefore neither repeat nor drop a token across the splice.

Degradation order (serve/llm.py drain(mode="migrate") + both routers):
**migrate** (recompute = 0 tokens) → **re-prefill** (recompute = prompt,
generated prefix lost) → **typed error** after the shared RetryBudget.

A checkpoint is owned by the dying replica's process: it must outlive
``drain()`` long enough for a peer to fetch it, and dies with the
process (preemption deadline semantics). A fetch that loses that race
raises ``MigrationLostError`` after its bounded retries — the routers'
signal to fall back to re-prefill, never a hang. Loss injection rides
the existing ``direct.put_owned`` / ``direct.get_owned_view`` chaos
sites; the preemption NOTICE itself is the ``serve.preempt`` site.

**Second consumer — tiered conversation KV.** The same codec now also
carries *idle eviction* (``engine.suspend_request`` / ``resume_suspended``,
ROADMAP item 3c): an idle conversation's state spills out of HBM to host
DRAM (and, via ``publish``, the object plane), and resume scatters the
block back in instead of re-prefilling. Nothing wire-level changes —
suspension is a migration whose source and destination may be the same
replica, so every validation, the splice-dedup contract and the typed
loss/degradation order above apply verbatim.
"""

from __future__ import annotations

import time

import numpy as np

from ray_tpu.exceptions import serving_error

from ray_tpu.llm.disagg import handoff as _handoff
from ray_tpu.llm.sampling import SamplingParams

LIVE_STATE_VERSION = 1
LIVE_KIND = _handoff.LIVE_KIND


@serving_error
class MigrationError(ValueError):
    """Malformed/inconsistent live_state payload, or a request whose
    state cannot be checkpointed (streaming consumer, prefill-only stub,
    sampled request with no live lane key)."""


@serving_error
class MigrationLostError(RuntimeError):
    """The published checkpoint vanished (owner process exited, object
    freed) before a peer could fetch it. Bounded-retry callers raise this
    after their budget; routers react by re-prefilling."""


@serving_error
class RequestMigratedError(RuntimeError):
    """Typed signal a migrating replica hands each in-flight waiter: the
    request did not fail — its live state was checkpointed and published,
    and ``migration_ref``/``migration_meta`` let a router resume it on a
    peer with zero recomputed tokens (the resume-on-peer failover leg)."""

    def __init__(self, request_id: str, meta: dict, ref):
        super().__init__(
            f"request {request_id} migrated: live decode state published "
            f"({meta.get('nbytes', 0)} bytes, {meta.get('emitted', 0)} tokens emitted); "
            "resume on a peer via resume_from_migration"
        )
        self.request_id = str(request_id)
        self.migration_meta = dict(meta)
        self.migration_ref = ref


def _causes(e):
    """Bounded walk of an error's wire-wrapping chain (TaskError's
    ``.cause`` links) — same traversal as serve/overload's probes."""
    for _ in range(8):
        if e is None:
            return
        yield e
        e = getattr(e, "cause", None)


def migration_of(e) -> tuple | None:
    """(request_id, meta, ref) when ``e`` is (or wraps) a
    RequestMigratedError whose checkpoint ref survived the wire; None
    otherwise. tb_str-only detection cannot recover the ref — those
    callers fall back to re-prefill, which is the correct degraded leg."""
    for err in _causes(e):
        ref = getattr(err, "migration_ref", None)
        if ref is not None:
            return (
                getattr(err, "request_id", ""),
                dict(getattr(err, "migration_meta", None) or {}),
                ref,
            )
    return None


def migration_lost(e) -> bool:
    """True when ``e`` is (or wraps) a lost/invalid checkpoint — the
    resume leg is dead and the router must fall back to re-prefill."""
    for err in _causes(e):
        if isinstance(err, (MigrationLostError, MigrationError)):
            return True
        tb = getattr(err, "tb_str", "")
        if "MigrationLostError" in tb or "MigrationError" in tb:
            return True
    return False


def _sampling_to_wire(p: SamplingParams) -> dict:
    return {
        "max_tokens": int(p.max_tokens),
        "temperature": float(p.temperature),
        "top_k": int(p.top_k),
        "top_p": float(p.top_p),
        "stop_token_ids": [int(t) for t in p.stop_token_ids],
        "seed": None if p.seed is None else int(p.seed),
        "logprobs": bool(p.logprobs),
        "priority": int(p.priority),
    }


def params_of(state: dict) -> SamplingParams:
    """Reconstruct (and validate — SamplingParams raises on garbage) the
    request's sampling params from a live_state dict."""
    sp = dict(state.get("sampling") or {})
    if not isinstance(sp.get("max_tokens"), int):
        raise MigrationError(f"live_state sampling block malformed: {sp!r}")
    sp["stop_token_ids"] = tuple(int(t) for t in sp.get("stop_token_ids", ()))
    try:
        return SamplingParams(**sp)
    except (TypeError, ValueError) as e:
        raise MigrationError(f"live_state sampling params invalid: {e}") from e


def check_state(state: dict) -> dict:
    """Validate an engine-facing live_state dict (the decode product, or
    a checkpoint handed over in-process). Raises MigrationError on
    anything inconsistent; returns the state for chaining."""
    if not isinstance(state, dict) or state.get("kind") != LIVE_KIND:
        raise MigrationError(f"not a live_state payload: {type(state).__name__}")
    prompt = state.get("prompt_token_ids")
    emitted = state.get("emitted_token_ids")
    if not isinstance(prompt, list) or not prompt:
        raise MigrationError("live_state without prompt_token_ids")
    if not isinstance(emitted, list):
        raise MigrationError("live_state without emitted_token_ids")
    params_of(state)
    hot = state.get("k") is not None
    if hot:
        if not emitted:
            raise MigrationError("hot live_state with zero emitted tokens (nothing to splice)")
        n = int(state.get("n", -1))
        if n != len(prompt) + len(emitted) - 1:
            raise MigrationError(
                f"live_state KV length {n} != prompt ({len(prompt)}) + emitted "
                f"({len(emitted)}) - 1 — the last emitted token's KV is minted by the "
                "peer's first decode step"
            )
        if n > state["k"].shape[1]:
            raise MigrationError(f"KV length {n} outside block width {state['k'].shape[1]}")
        key = state.get("rng_key")
        if key is None or np.asarray(key).dtype != np.uint32 or np.asarray(key).ndim != 1:
            raise MigrationError("hot live_state needs its lane's uint32 PRNG key data")
    spec = state.get("spec")
    if spec is not None and not isinstance(spec, dict):
        raise MigrationError(f"live_state spec block malformed: {spec!r}")
    return state


def encode(state: dict) -> dict:
    """Engine-facing live_state -> self-describing wire dict.

    The KV block half rides the handoff codec (kind=live_state): its
    ``prompt_token_ids`` on the wire are the COVERED tokens — original
    prompt + emitted[:-1], exactly the ``n`` positions the block holds —
    so the handoff layer's length/shape/scale validation applies
    unchanged and the peer can verify coverage token-for-token. The
    live half (emitted stream, PRNG key, sampling, spec state) travels
    under ``live``."""
    check_state(state)
    prompt = [int(t) for t in state["prompt_token_ids"]]
    emitted = [int(t) for t in state["emitted_token_ids"]]
    live = {
        "version": LIVE_STATE_VERSION,
        "n_prompt": len(prompt),
        "emitted_token_ids": emitted,
        "emitted_logprobs": [float(x) for x in state.get("emitted_logprobs", [])],
        "sampling": dict(state["sampling"]),
        "spec": None if state.get("spec") is None else dict(state["spec"]),
    }
    if state.get("k") is not None:
        covered = prompt + emitted[:-1]
        block = {
            "k": state["k"], "v": state["v"], "n": int(state["n"]),
            "prompt_token_ids": covered,
        }
        for extra in ("k_scale", "v_scale", "trace", "submitted_at"):
            if state.get(extra) is not None:
                block[extra] = state[extra]
        try:
            wire = _handoff.encode(block, kind=LIVE_KIND)
        except _handoff.HandoffError as e:
            raise MigrationError(str(e)) from e
        live["rng_key"] = np.asarray(state["rng_key"], np.uint32)
    else:
        # cold checkpoint (request was waiting — no bound lane, no KV):
        # the peer re-admits prompt+generated like a recompute preemption
        wire = {"version": _handoff.HANDOFF_VERSION, "kind": LIVE_KIND,
                "prompt_token_ids": prompt}
        if state.get("trace") is not None:
            wire["trace"] = dict(state["trace"])
        if state.get("submitted_at") is not None:
            wire["submitted_at"] = float(state["submitted_at"])
    wire["live"] = live
    return wire


def decode(wire: dict) -> dict:
    """Wire dict -> validated engine-facing live_state (the
    ``restore_request`` input). MigrationError on anything inconsistent:
    a truncated block, a foreign kind, drifted versions, a coverage
    mismatch between the block and the emitted stream — garbage must
    never reach a live pool."""
    if not isinstance(wire, dict) or wire.get("kind") != LIVE_KIND:
        raise MigrationError(f"not a {LIVE_KIND} wire payload: {type(wire).__name__}")
    live = wire.get("live")
    if not isinstance(live, dict) or live.get("version") != LIVE_STATE_VERSION:
        raise MigrationError(
            f"live_state version {None if not isinstance(live, dict) else live.get('version')} "
            f"!= {LIVE_STATE_VERSION}"
        )
    emitted = [int(t) for t in live.get("emitted_token_ids", [])]
    n_prompt = int(live.get("n_prompt", 0))
    if n_prompt < 1:
        raise MigrationError(f"live_state n_prompt {n_prompt} invalid")
    state = {
        "kind": LIVE_KIND,
        "emitted_token_ids": emitted,
        "emitted_logprobs": [float(x) for x in live.get("emitted_logprobs", [])],
        "sampling": dict(live.get("sampling") or {}),
        "spec": None if live.get("spec") is None else dict(live["spec"]),
    }
    if wire.get("k") is not None:
        try:
            block = _handoff.decode(wire, kind=LIVE_KIND)
        except _handoff.HandoffError as e:
            raise MigrationError(str(e)) from e
        covered = [int(t) for t in block["prompt_token_ids"]]
        if n_prompt > len(covered) or covered[n_prompt:] != emitted[:-1]:
            raise MigrationError(
                "live_state coverage mismatch: the KV block's covered tokens do not "
                "equal prompt + emitted[:-1]"
            )
        state["prompt_token_ids"] = covered[:n_prompt]
        state.update(k=block["k"], v=block["v"], n=int(block["n"]))
        if block.get("k_scale") is not None:
            state.update(k_scale=block["k_scale"], v_scale=block["v_scale"])
        # keep the wire dtype as-is: check_state REJECTS a non-uint32 key
        # (coercing here would let a corrupted key pass validation)
        state["rng_key"] = None if live.get("rng_key") is None else np.asarray(live["rng_key"])
        for extra in ("trace", "submitted_at"):
            if block.get(extra) is not None:
                state[extra] = block[extra]
    else:
        prompt = [int(t) for t in wire.get("prompt_token_ids", [])]
        if len(prompt) != n_prompt:
            raise MigrationError(f"cold live_state prompt length {len(prompt)} != n_prompt {n_prompt}")
        state["prompt_token_ids"] = prompt
        for extra in ("trace", "submitted_at"):
            if wire.get(extra) is not None:
                state[extra] = wire[extra]
    return check_state(state)


def meta_of(state: dict) -> dict:
    """Small router-facing summary (no arrays) that travels with the ref."""
    hot = state.get("k") is not None
    nbytes = 0
    if hot:
        nbytes = int(state["k"].nbytes + state["v"].nbytes)
        if state.get("k_scale") is not None:
            nbytes += int(state["k_scale"].nbytes + state["v_scale"].nbytes)
    return {
        "kind": LIVE_KIND,
        "hot": hot,
        "n": int(state.get("n", 0)) if hot else 0,
        "emitted": len(state.get("emitted_token_ids", [])),
        "prompt_tokens": len(state.get("prompt_token_ids", [])),
        "nbytes": nbytes,
    }


def state_nbytes(state: dict) -> int:
    """KV payload size of a live_state dict (0 for a cold checkpoint) —
    the spill/transfer accounting both consumers report."""
    if state.get("k") is None:
        return 0
    nbytes = int(state["k"].nbytes + state["v"].nbytes)
    if state.get("k_scale") is not None:
        nbytes += int(state["k_scale"].nbytes + state["v_scale"].nbytes)
    return nbytes


def publish(state: dict):
    """Encode a checkpoint and store it as an owned object in THIS
    process. Returns (meta, ref) — only the tiny pair travels to the
    router; the bytes stay owner-local until a peer's fetch borrows
    them. The object's lifetime is the dying replica's remaining one:
    a fetch that arrives too late sees MigrationLostError, and the leak
    backstop reclaims never-fetched checkpoints."""
    from ray_tpu.core import direct as _direct

    wire = encode(state)
    ref = _direct.put_owned(wire)
    return meta_of(state), ref


def fetch(ref, meta: dict | None = None, *, timeout_s: float = 10.0, retries: int = 2,
          retry_wait_s: float = 0.2) -> dict:
    """Borrow-get a published checkpoint with a bounded retry budget and
    full wire validation. A checkpoint that is GONE (owner exited — the
    normal post-preemption case for a late fetch) raises
    MigrationLostError after the final attempt; callers must never hang
    on a dead replica's state."""
    from ray_tpu.core import direct as _direct
    from ray_tpu.exceptions import GetTimeoutError, ObjectLostError

    last: BaseException | None = None
    for attempt in range(retries + 1):
        try:
            t0 = time.time()
            value = _direct.get_owned_view(ref.id, timeout=timeout_s)
            state = decode(value)
            if meta is not None and meta.get("emitted") is not None and int(
                meta["emitted"]
            ) != len(state["emitted_token_ids"]):
                raise MigrationError(
                    f"fetched checkpoint emitted count {len(state['emitted_token_ids'])} "
                    f"does not match routed meta {meta['emitted']}"
                )
            _handoff._handoff_span("llm.migrate.fetch", value, t0, attempts=attempt + 1)
            return state
        except (ObjectLostError, GetTimeoutError, ConnectionError, FileNotFoundError) as e:
            last = e
            if attempt < retries:
                time.sleep(retry_wait_s)
    raise MigrationLostError(
        f"live_state checkpoint {ref.id.hex()[:16]} lost before restore "
        f"({retries + 1} attempts): {last}"
    ) from last
