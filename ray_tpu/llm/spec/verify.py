"""The fused speculative verify step: one jitted program per engine tick.

Per lane the program takes the current input token t0 plus k proposals
d1..dk (padded to the STATIC width k so shapes never vary), runs the
target model over all k+1 positions in one wide forward, and:

- accepts the longest proposal prefix the target agrees with — greedy
  exact-match for temperature==0 lanes, one-hot rejection sampling
  (accept d with prob p(d), resample a rejection from p-with-d-masked)
  for temperature>0, where p is the target distribution AFTER the same
  temperature/top-k/top-p surgery `sampling.sample` applies;
- emits the accepted tokens plus one token from the target at the first
  disagreement (the bonus/replacement), so every round emits >= 1;
- appends the whole block's K/V (computed anyway) and rolls back
  rejections in O(1) by setting length = l + accepted + 1 — positions
  past the new length are dead until overwritten, exactly like the
  garbage tail of a padded prefill;
- advances the lane's token-history buffer (the drafter's input) on
  device, so draft -> verify chains without any host sync.

Layouts: the slot layout is ONE program (cache donated, functional
update inside); the paged layout splits attention+accept from the pool
scatter-append — a same-program gather+scatter on the pool buffer is
the aliasing hazard documented on `decode_attn_paged`, and speculation
does not change it. Writes past a slot row / page table land in dropped
scatters / the trash page: they can only occur in rounds whose tokens
the host has already discarded.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from ray_tpu.lint import jaxcheck
from ray_tpu.llm.model_runner import (
    TpSpec,
    _cache_pspecs,
    _mlp,
    _param_pspecs,
    _qkv,
    _sds,
    _sds_cache,
    _sds_cache_q,
    _sds_lanes,
    _sds_params,
    _sds_pool,
    _sds_pool_q,
    _shard_cfg,
    _tp2_mesh,
    _tp_embed,
    _tp_gather_logits,
    _tp_reduce,
    _tp_shard_map,
    _trace_cfg,
)
from ray_tpu.models.llama import LlamaConfig
from ray_tpu.ops.layers import apply_rope, rms_norm, rotary_embedding


def _wrap(kd):
    return jax.random.wrap_key_data(kd, impl="threefry2x32")


# ---------------------------------------------------------------------------
# acceptance + sampling (layout-independent)
# ---------------------------------------------------------------------------
def _accept_and_sample(logits, proposals, spec_k, keys, temps, top_k, top_p):
    """logits: [B, k+1, V] target logits over (t0, d1..dk); proposals:
    [B, k]. Returns (emit [B, k+1] i32, logps [B, k+1] f32, acc [B] i32,
    final [B] i32, new_keys [B, 2] u32) where emit[:, :acc] are accepted
    proposals, emit[:, acc] the bonus/replacement, and the rest garbage
    the host never reads."""
    from ray_tpu.llm.sampling import filter_logits

    B, T, V = logits.shape
    k = T - 1
    logits = logits.astype(jnp.float32)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [B, T]
    logp_full = jax.nn.log_softmax(logits, axis=-1)
    # the SAME distribution surgery sample() applies, broadcast over T
    filt = filter_logits(logits, temps[:, None], top_k[:, None], top_p[:, None])
    probs = jax.nn.softmax(filt, axis=-1)  # [B, T, V]

    # per-lane randomness: k accept draws + 1 replacement draw + next key
    def _split(kd):
        return jax.random.key_data(jax.random.split(_wrap(kd), k + 2))

    subkeys = jax.vmap(_split)(keys)  # [B, k+2, 2]
    u = jax.vmap(jax.vmap(lambda kd: jax.random.uniform(_wrap(kd), ())))(subkeys[:, :k])  # [B, k]

    p_prop = jnp.take_along_axis(probs[:, :k], proposals[..., None], axis=-1)[..., 0]  # [B, k]
    accept_greedy = proposals == greedy[:, :k]
    accept_stoch = u < p_prop  # one-hot q: accept prob = p(d)
    accept = jnp.where(temps[:, None] == 0.0, accept_greedy, accept_stoch)
    accept = accept & (jnp.arange(k, dtype=jnp.int32)[None, :] < spec_k[:, None])
    acc = jnp.sum(jnp.cumprod(accept.astype(jnp.int32), axis=1), axis=1).astype(jnp.int32)  # [B]

    # final token from the first-disagreement position's target logits
    lg_a = jnp.take_along_axis(logits, acc[:, None, None], axis=1)[:, 0]  # [B, V]
    filt_a = jnp.take_along_axis(filt, acc[:, None, None], axis=1)[:, 0]
    rejected = acc < jnp.minimum(spec_k, k)  # a proposal was examined and refused
    d_rej = jnp.take_along_axis(proposals, jnp.minimum(acc, k - 1)[:, None], axis=1)[:, 0]
    # one-hot-q residual max(p - q, 0): p with the refused token masked out
    mask_rej = jax.nn.one_hot(d_rej, V, dtype=jnp.bool_) & rejected[:, None]
    stoch_tok = jax.vmap(lambda kd, lg: jax.random.categorical(_wrap(kd), lg))(
        subkeys[:, k], jnp.where(mask_rej, -jnp.inf, filt_a)
    ).astype(jnp.int32)
    greedy_tok = jnp.argmax(lg_a, axis=-1).astype(jnp.int32)
    final = jnp.where(temps == 0.0, greedy_tok, stoch_tok)
    new_keys = subkeys[:, k + 1]

    cols = jnp.arange(k + 1, dtype=jnp.int32)[None, :]
    props_pad = jnp.pad(proposals, ((0, 0), (0, 1)))
    emit = jnp.where(cols < acc[:, None], props_pad, 0)
    emit = jnp.where(cols == acc[:, None], final[:, None], emit).astype(jnp.int32)
    # logprobs from the UNfiltered distribution, as sample() reports them
    lp_pad = jnp.pad(jnp.take_along_axis(logp_full[:, :k], proposals[..., None], axis=-1)[..., 0], ((0, 0), (0, 1)))
    lp_a = jnp.take_along_axis(logp_full, acc[:, None, None], axis=1)[:, 0]
    lp_fin = jnp.take_along_axis(lp_a, final[:, None], axis=1)[:, 0]
    logps = jnp.where(cols < acc[:, None], lp_pad, 0.0)
    logps = jnp.where(cols == acc[:, None], lp_fin[:, None], logps)
    return emit, logps, acc, final, new_keys


def _update_hist(hist, hist_len, emit, acc):
    """Append the round's emitted tokens to the history lanes. All k+1
    slots are written (past-acceptance garbage sits beyond the new valid
    length and is overwritten by the next round before it could be read);
    writes past the buffer edge are dropped — they only occur in rounds
    whose tokens the host discards anyway."""
    B, Tp1 = emit.shape
    rows = jnp.arange(B, dtype=jnp.int32)[:, None]
    hpos = hist_len[:, None] + jnp.arange(Tp1, dtype=jnp.int32)[None, :]
    return hist.at[rows, hpos].set(emit, mode="drop"), hist_len + acc + 1


# ---------------------------------------------------------------------------
# slot layout
# ---------------------------------------------------------------------------
def _forward_block_slots(params, cache, toks_blk, cfg: LlamaConfig, tpc: TpSpec | None = None):
    """Target forward over T=k+1 tokens per slot at positions
    length..length+T-1. Block K/V is written into the cache rows first
    (per-position scatter, OOB dropped) and attention reads the updated
    row with mask j <= position — the functional-update idiom
    decode_step/fused_step already rely on (no pool-style aliasing
    hazard in the slot layout). An int8 cache quantizes the block's K/V
    on the same scatter and dequantizes the row for attention, exactly
    as decode_step does per token. ``tpc``: shard_map body mode, as on
    decode_step — verify compiles SPMD like the fused step, with the
    per-layer all-reduce explicit (and optionally int8 on the wire).
    Returns (logits [B, T, V] f32, ks, vs) — plus (k_scales, v_scales)
    [L, B, kv, S] when quantized."""
    B, T = toks_blk.shape
    nh, nkv, hd = cfg.num_heads, cfg.num_kv_heads, cfg.hd
    rep = nh // nkv
    quant = "k_scale" in cache
    S = cache["k"].shape[2]
    lengths = cache["length"]
    positions = lengths[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]  # [B, T]
    cos, sin = rotary_embedding(positions, cfg.hd, cfg.rope_theta)  # [B, T, hd/2]
    x = _tp_embed(params["embed"], toks_blk, tpc)  # [B, T, H]
    rows = jnp.arange(B, dtype=jnp.int32)[:, None]
    # query i sits at position length+i and may attend cache 0..length+i
    attn_ok = (jnp.arange(S, dtype=jnp.int32)[None, None, :] <= positions[:, :, None])[:, None, None]  # [B,1,1,T,S]

    def layer_fn(x, xs):
        from ray_tpu.llm.kv_quant import quantize_heads

        if quant:
            layer, k_cache, v_cache, k_sc, v_sc = xs  # scales: [B, kv, S]
        else:
            layer, k_cache, v_cache = xs  # [B, S, kv, hd]
        xn = rms_norm(x, layer["attn_norm"], cfg.rms_eps)
        q, k_t, v_t = _qkv(xn, layer, cfg)  # [B, T, nh/nkv, hd]
        qh = apply_rope(q.transpose(0, 2, 1, 3), cos, sin)  # [B, nh, T, hd]
        kh = apply_rope(k_t.transpose(0, 2, 1, 3), cos, sin).transpose(0, 2, 1, 3)  # [B, T, nkv, hd]
        k_blk, v_blk = kh, v_t
        if quant:
            k_blk, sk = quantize_heads(k_blk)  # [B, T, kv] scales
            v_blk, sv = quantize_heads(v_blk)
            # mixed advanced/slice indexing puts the [B, T] index dims
            # first: the indexed scale slots are [B, T, kv]
            k_sc = k_sc.at[rows, :, positions].set(sk, mode="drop")
            v_sc = v_sc.at[rows, :, positions].set(sv, mode="drop")
        k_cache = k_cache.at[rows, positions].set(k_blk.astype(k_cache.dtype), mode="drop")
        v_cache = v_cache.at[rows, positions].set(v_blk.astype(v_cache.dtype), mode="drop")
        qg = qh.reshape(B, nkv, rep, T, hd)
        kc = k_cache.transpose(0, 2, 1, 3)  # [B, nkv, S, hd]
        vc = v_cache.transpose(0, 2, 1, 3)
        if quant:
            kc = kc.astype(jnp.float32) * k_sc[..., None]
            vc = vc.astype(jnp.float32) * v_sc[..., None]
        scores = jnp.einsum("bgrth,bgsh->bgrts", qg, kc, preferred_element_type=jnp.float32) / jnp.sqrt(hd)
        scores = jnp.where(attn_ok, scores, -jnp.inf)
        o = jnp.einsum("bgrts,bgsh->bgrth", jax.nn.softmax(scores, axis=-1), vc.astype(jnp.float32))
        o = o.transpose(0, 3, 1, 2, 4).reshape(B, T, nh * hd).astype(x.dtype)
        x = x + _tp_reduce(jnp.dot(o, layer["wo"]), tpc)
        x = _mlp(x, layer, cfg, tpc)
        return x, ((k_cache, v_cache, k_sc, v_sc) if quant else (k_cache, v_cache))

    xs = (params["layers"], cache["k"], cache["v"])
    if quant:
        xs += (cache["k_scale"], cache["v_scale"])
    x, ys = jax.lax.scan(layer_fn, x, xs)
    x = rms_norm(x, params["final_norm"], cfg.rms_eps)
    unembed = params["embed"].T if cfg.tie_embeddings else params["unembed"]
    logits = _tp_gather_logits(jnp.einsum("bth,hv->btv", x, unembed, preferred_element_type=jnp.float32), tpc)
    return (logits,) + tuple(ys)


def _bucket_spec_verify(B=8, S=256, k=4, H=517):
    cfg = _trace_cfg()
    tokens, keys, temps, top_k, top_p = _sds_lanes(B)
    return (
        _sds_params(cfg), _sds_cache(cfg, B, S), _sds((B, k), jnp.int32),
        tokens, keys, temps, top_k, top_p, _sds((B,), jnp.int32),
        _sds((B, H), jnp.int32), _sds((B,), jnp.int32), cfg,
    ), {}


@jaxcheck.entry(
    name="llm.spec_verify",
    shapes={"b8_s256": _bucket_spec_verify},
    donate=("cache", "tokens", "keys", "temps", "top_k", "top_p", "spec_k", "hist", "hist_len"),
    donate_bytes=0,  # the spec hot loop is audited like fused_step's
)
def spec_verify_slots(
    params,
    cache,
    proposals,  # fresh drafter output, never re-read by the host: no buffer to save by donating
    tokens,
    keys,
    temps,
    top_k,
    top_p,
    spec_k,
    hist,
    hist_len,
    cfg: LlamaConfig,
    tpc: TpSpec | None = None,
):
    """ONE program for the slot layout's speculative tick: wide target
    forward over (t0, d1..dk) -> accept/sample -> append block KV ->
    length rollback -> history append. Unlike fused_step, the sampled
    TOKEN lane is also donated: the host reads the round's results from
    the dedicated emit/logps/acc outputs, never from the token lane."""
    toks_blk = jnp.concatenate([tokens[:, None], proposals], axis=1)
    logits, *kv_out = _forward_block_slots(params, cache, toks_blk, cfg, tpc)
    emit, logps, acc, final, new_keys = _accept_and_sample(
        logits, proposals, spec_k, keys, temps, top_k, top_p
    )
    hist, hist_len = _update_hist(hist, hist_len, emit, acc)
    new_cache = {"k": kv_out[0], "v": kv_out[1], "length": cache["length"] + acc + 1}
    if len(kv_out) == 4:  # int8 cache: the scale lanes ride the rollback too
        new_cache["k_scale"], new_cache["v_scale"] = kv_out[2], kv_out[3]
    return new_cache, emit, logps, acc, final, new_keys, temps, top_k, top_p, spec_k, hist, hist_len


def _bucket_spec_verify_q(B=8, S=256, k=4, H=517):
    cfg = _trace_cfg()
    tokens, keys, temps, top_k, top_p = _sds_lanes(B)
    return (
        _sds_params(cfg), _sds_cache_q(cfg, B, S), _sds((B, k), jnp.int32),
        tokens, keys, temps, top_k, top_p, _sds((B,), jnp.int32),
        _sds((B, H), jnp.int32), _sds((B,), jnp.int32), cfg,
    ), {}


# int8-cache variant (see model_runner's llm.fused_step_int8 rationale:
# donation + the JXC003 dequant trap audited on the quantized spec path)
jaxcheck.entry(
    name="llm.spec_verify_int8",
    shapes={"b8_s256": _bucket_spec_verify_q},
    donate=("cache", "tokens", "keys", "temps", "top_k", "top_p", "spec_k", "hist", "hist_len"),
    donate_bytes=0,
)(spec_verify_slots)


def _sharded_spec_verify_slots(cfg: LlamaConfig, mesh, tp_collective: str, kv_quant: bool):
    """spec_verify_slots under shard_map over the tp axis (unjitted) —
    the verify step compiles SPMD exactly like the fused decode step."""
    from jax.sharding import PartitionSpec as P

    from ray_tpu.parallel.mesh import axis_size

    tp = axis_size(mesh, "tp")
    tpc = TpSpec("tp", tp, tp_collective)
    cache_sp = _cache_pspecs("slots", kv_quant)
    rep = P()
    return _tp_shard_map(
        partial(spec_verify_slots, cfg=_shard_cfg(cfg, tp), tpc=tpc),
        mesh,
        in_specs=(_param_pspecs(cfg, mesh), cache_sp) + (rep,) * 9,
        out_specs=(cache_sp,) + (rep,) * 11,
    )


def make_spec_verify_slots(cfg: LlamaConfig, k: int, mesh=None, tp_collective: str = "fp", kv_quant: bool = False):
    """Jit of spec_verify_slots with the production donation set (the
    static width k is baked into the proposals shape by the caller).
    With a tp>1 mesh the tick compiles under shard_map — same explicit
    collective schedule as make_fused_fns."""
    del k  # shapes carry it; one compile per configured width
    from ray_tpu.parallel.mesh import axis_size

    if mesh is not None and axis_size(mesh, "tp") > 1:
        body = _sharded_spec_verify_slots(cfg, mesh, tp_collective, kv_quant)
        return jax.jit(body, donate_argnums=(1, 3, 4, 5, 6, 7, 8, 9, 10))
    return jax.jit(partial(spec_verify_slots, cfg=cfg), donate_argnums=(1, 3, 4, 5, 6, 7, 8, 9, 10))


# ---------------------------------------------------------------------------
# paged layout
# ---------------------------------------------------------------------------
def _bucket_spec_verify_paged(B=8, pages=64, page=16, k=4, H=517):
    cfg = _trace_cfg()
    tokens, keys, temps, top_k, top_p = _sds_lanes(B)
    return (
        _sds_params(cfg), _sds_pool(cfg, pages, page), _sds((B, pages // B * 2), jnp.int32),
        _sds((B,), jnp.int32), _sds((B, k), jnp.int32),
        tokens, keys, temps, top_k, top_p, _sds((B,), jnp.int32),
        _sds((B, H), jnp.int32), _sds((B,), jnp.int32), cfg,
    ), {}


@jaxcheck.entry(
    name="llm.spec_verify_paged",
    shapes={"b8_p64": _bucket_spec_verify_paged},
    donate=("lengths", "tokens", "keys", "temps", "top_k", "top_p", "spec_k", "hist", "hist_len"),
    donate_bytes=0,
)
def spec_verify_paged(
    params,
    pool,  # read-only by design (the gather/scatter aliasing hazard); donated by the append program instead
    tables,
    lengths,
    proposals,  # fresh drafter output (see spec_verify_slots)
    tokens,
    keys,
    temps,
    top_k,
    top_p,
    spec_k,
    hist,
    hist_len,
    cfg: LlamaConfig,
    tpc: TpSpec | None = None,
    attn_impl: str = "xla",
):
    """READ-ONLY half of the paged speculative tick: block attention over
    the cached pages (prefix from the pool, the block itself in
    registers via `_paged_attn_seq`, vmapped over lanes) + accept/sample
    + write-target math; the pool scatter is spec_append_paged. Rows past
    a lane's table edge redirect to the trash page — those positions only
    arise in rounds whose tokens the host already discarded. ``tpc``:
    shard_map body mode, as on decode_step/_forward_block_slots.
    ``attn_impl``: "pallas" streams the prefix pages through the fused
    kernel (llm/pallas/paged_attn.py) — the wide-block verify rides the
    same HBM-streaming path as decode; "xla" stays the oracle."""
    from ray_tpu.llm.paged_kv import _paged_attn_seq_batch

    B, k = proposals.shape
    T = k + 1
    nh, nkv, hd = cfg.num_heads, cfg.num_kv_heads, cfg.hd
    rep = nh // nkv
    quant = "k_scale" in pool
    page = pool["k"].shape[2]
    max_pg = tables.shape[1]
    toks_blk = jnp.concatenate([tokens[:, None], proposals], axis=1)
    positions = lengths[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]  # [B, T]
    cos, sin = rotary_embedding(positions, cfg.hd, cfg.rope_theta)
    x = _tp_embed(params["embed"], toks_blk, tpc)  # [B, T, H]
    scale = 1.0 / jnp.sqrt(jnp.asarray(hd, jnp.float32))

    def layer_fn(x, xs):
        if quant:
            layer, k_pool_l, v_pool_l, k_sc_l, v_sc_l = xs
        else:
            layer, k_pool_l, v_pool_l = xs
            k_sc_l = v_sc_l = None
        xn = rms_norm(x, layer["attn_norm"], cfg.rms_eps)
        q, k_t, v_t = _qkv(xn, layer, cfg)  # [B, T, nh/nkv, hd]
        qh = apply_rope(q.transpose(0, 2, 1, 3), cos, sin)  # [B, nh, T, hd]
        kh = apply_rope(k_t.transpose(0, 2, 1, 3), cos, sin).transpose(0, 2, 1, 3)  # [B, T, nkv, hd]
        qg = qh.reshape(B, nkv, rep, T, hd)
        o = _paged_attn_seq_batch(
            qg, k_pool_l, v_pool_l, tables, lengths, kh, v_t, scale, k_sc_l, v_sc_l,
            impl=attn_impl,
        )  # [B, nkv, rep, T, hd]
        o = o.transpose(0, 3, 1, 2, 4).reshape(B, T, nh * hd).astype(x.dtype)
        x = x + _tp_reduce(jnp.dot(o, layer["wo"]), tpc)
        x = _mlp(x, layer, cfg, tpc)
        return x, (kh, v_t)

    xs = (params["layers"], pool["k"], pool["v"])
    if quant:
        xs += (pool["k_scale"], pool["v_scale"])
    x, (k_blk, v_blk) = jax.lax.scan(layer_fn, x, xs)
    x = rms_norm(x, params["final_norm"], cfg.rms_eps)
    unembed = params["embed"].T if cfg.tie_embeddings else params["unembed"]
    logits = _tp_gather_logits(jnp.einsum("bth,hv->btv", x, unembed, preferred_element_type=jnp.float32), tpc)
    emit, logps, acc, final, new_keys = _accept_and_sample(
        logits, proposals, spec_k, keys, temps, top_k, top_p
    )
    hist, hist_len = _update_hist(hist, hist_len, emit, acc)
    pg_ix = positions // page
    wp = jnp.where(
        pg_ix < max_pg,
        tables[jnp.arange(B, dtype=jnp.int32)[:, None], jnp.minimum(pg_ix, max_pg - 1)],
        0,
    )
    wo = positions % page
    return (
        emit, logps, acc, final, new_keys, k_blk, v_blk, wp, wo,
        lengths + acc + 1, temps, top_k, top_p, spec_k, hist, hist_len,
    )


def spec_append_paged(pool, wp, wo, k_blk, v_blk):
    """Scatter-only half of the paged speculative tick: write the whole
    block's K/V ([L, B, T, kv, hd]) at (wp, wo) [B, T] for every layer.
    Rejected positions land in the lane's own dead tail (or the trash
    page) and are overwritten before the length rollback could expose
    them. An int8 pool quantizes here — the append program is the
    quantizer, mirroring append_paged."""
    if "k_scale" in pool:
        from ray_tpu.llm.kv_quant import quantize_heads

        k_blk, sk = quantize_heads(k_blk)  # [L, B, T, kv] scales
        v_blk, sv = quantize_heads(v_blk)
        return {
            "k": pool["k"].at[:, wp, wo].set(k_blk),
            "v": pool["v"].at[:, wp, wo].set(v_blk),
            # [L, P, kv, page] indexed at [:, wp, :, wo] -> [B, T, L, kv]
            "k_scale": pool["k_scale"].at[:, wp, :, wo].set(sk.transpose(1, 2, 0, 3)),
            "v_scale": pool["v_scale"].at[:, wp, :, wo].set(sv.transpose(1, 2, 0, 3)),
        }
    return {
        "k": pool["k"].at[:, wp, wo].set(k_blk.astype(pool["k"].dtype)),
        "v": pool["v"].at[:, wp, wo].set(v_blk.astype(pool["v"].dtype)),
    }


def _bucket_spec_verify_paged_q(B=8, pages=64, page=16, k=4, H=517):
    cfg = _trace_cfg()
    tokens, keys, temps, top_k, top_p = _sds_lanes(B)
    return (
        _sds_params(cfg), _sds_pool_q(cfg, pages, page), _sds((B, pages // B * 2), jnp.int32),
        _sds((B,), jnp.int32), _sds((B, k), jnp.int32),
        tokens, keys, temps, top_k, top_p, _sds((B,), jnp.int32),
        _sds((B, H), jnp.int32), _sds((B,), jnp.int32), cfg,
    ), {}


jaxcheck.entry(
    name="llm.spec_verify_paged_int8",
    shapes={"b8_p64": _bucket_spec_verify_paged_q},
    donate=("lengths", "tokens", "keys", "temps", "top_k", "top_p", "spec_k", "hist", "hist_len"),
    donate_bytes=0,
)(spec_verify_paged)


def _sharded_spec_verify_paged(cfg: LlamaConfig, mesh, tp_collective: str, kv_quant: bool):
    """spec_verify_paged under shard_map over the tp axis (unjitted); the
    block K/V leaves kv-sharded for the collective-free GSPMD append."""
    from jax.sharding import PartitionSpec as P

    from ray_tpu.parallel.mesh import axis_size

    tp = axis_size(mesh, "tp")
    tpc = TpSpec("tp", tp, tp_collective)
    pool_sp = _cache_pspecs("paged", kv_quant)
    kv_blk = P(None, None, None, "tp", None)  # k_blk/v_blk: [L, B, T, kv, hd]
    rep = P()
    return _tp_shard_map(
        partial(spec_verify_paged, cfg=_shard_cfg(cfg, tp), tpc=tpc),
        mesh,
        in_specs=(_param_pspecs(cfg, mesh), pool_sp) + (rep,) * 11,
        out_specs=(rep,) * 5 + (kv_blk, kv_blk) + (rep,) * 9,
    )


def make_spec_verify_paged(cfg: LlamaConfig, k: int, mesh=None, tp_collective: str = "fp", kv_quant: bool = False,
                           attn_impl: str = "xla"):
    """(attention+accept program, scatter-append program) for the paged
    layout — two dispatches, never fused (see decode_attn_paged). With a
    tp>1 mesh the attention half compiles under shard_map, same explicit
    collective schedule as the fused step. ``attn_impl="pallas"`` puts
    the wide-block prefix attention on the fused kernel (single-device
    path only, matching make_fused_paged_fns)."""
    del k
    from ray_tpu.parallel.mesh import axis_size

    if mesh is not None and axis_size(mesh, "tp") > 1:
        attn_fn = jax.jit(
            _sharded_spec_verify_paged(cfg, mesh, tp_collective, kv_quant),
            donate_argnums=(3, 5, 6, 7, 8, 9, 10, 11, 12),
        )
    else:
        attn_fn = jax.jit(partial(spec_verify_paged, cfg=cfg, attn_impl=attn_impl),
                          donate_argnums=(3, 5, 6, 7, 8, 9, 10, 11, 12))
    append_fn = jax.jit(spec_append_paged, donate_argnums=(0,))
    return attn_fn, append_fn


# ---------------------------------------------------------------------------
# jaxcheck entries for the SHARDED verify steps (see model_runner's tp
# entries): JXC005 audits the spec tick's collectives against the
# declared tp axis, and the donation/upcast rules re-check the SPMD form.
# ---------------------------------------------------------------------------
def _bucket_spec_verify_tp(B=8, S=256, k=4, H=517):
    cfg = _trace_cfg()
    tokens, keys, temps, top_k, top_p = _sds_lanes(B)
    return (
        _sds_params(cfg), _sds_cache(cfg, B, S), _sds((B, k), jnp.int32),
        tokens, keys, temps, top_k, top_p, _sds((B,), jnp.int32),
        _sds((B, H), jnp.int32), _sds((B,), jnp.int32),
    ), {}


@jaxcheck.entry(
    name="llm.spec_verify_tp",
    shapes={"b8_s256_tp2": _bucket_spec_verify_tp},
    donate=("cache", "tokens", "keys", "temps", "top_k", "top_p", "spec_k", "hist", "hist_len"),
    donate_bytes=0,
    mesh_axes=("tp",),
)
def spec_verify_tp(
    params,
    cache,
    proposals,  # fresh drafter output, never re-read by the host: no buffer to save by donating
    tokens,
    keys,
    temps,
    top_k,
    top_p,
    spec_k,
    hist,
    hist_len,
):
    """make_spec_verify_slots(mesh=2-way tp) in registry-traceable form."""
    return _sharded_spec_verify_slots(_trace_cfg(), _tp2_mesh(), "fp", False)(
        params, cache, proposals, tokens, keys, temps, top_k, top_p, spec_k, hist, hist_len
    )


def _bucket_spec_verify_paged_tp(B=8, pages=64, page=16, k=4, H=517):
    cfg = _trace_cfg()
    tokens, keys, temps, top_k, top_p = _sds_lanes(B)
    return (
        _sds_params(cfg), _sds_pool(cfg, pages, page), _sds((B, pages // B * 2), jnp.int32),
        _sds((B,), jnp.int32), _sds((B, k), jnp.int32),
        tokens, keys, temps, top_k, top_p, _sds((B,), jnp.int32),
        _sds((B, H), jnp.int32), _sds((B,), jnp.int32),
    ), {}


@jaxcheck.entry(
    name="llm.spec_verify_paged_tp",
    shapes={"b8_p64_tp2": _bucket_spec_verify_paged_tp},
    donate=("lengths", "tokens", "keys", "temps", "top_k", "top_p", "spec_k", "hist", "hist_len"),
    donate_bytes=0,
    mesh_axes=("tp",),
)
def spec_verify_paged_tp(
    params,
    pool,  # read-only by design (the gather/scatter aliasing hazard); donated by the append program instead
    tables,
    lengths,
    proposals,  # fresh drafter output (see spec_verify_slots)
    tokens,
    keys,
    temps,
    top_k,
    top_p,
    spec_k,
    hist,
    hist_len,
):
    """make_spec_verify_paged(mesh=2-way tp)'s attention half in
    registry-traceable form (the append half is collective-free GSPMD)."""
    return _sharded_spec_verify_paged(_trace_cfg(), _tp2_mesh(), "fp", False)(
        params, pool, tables, lengths, proposals, tokens, keys, temps, top_k, top_p, spec_k, hist, hist_len
    )


# ---------------------------------------------------------------------------
# O(1) scheduler deltas for the spec lanes
# ---------------------------------------------------------------------------
def set_hist_row(hist, hist_len, spec_k, slot, row, n, k0):  # deltas donate nothing, as make_delta_fns documents
    """Admission delta: one lane's token history, valid count and
    effective k (the row upload is one [H] int32 — tiny)."""
    return hist.at[slot].set(row), hist_len.at[slot].set(n), spec_k.at[slot].set(k0)


def set_slot_scalar(arr, slot, val):
    """O(1) jitted scatter: the controller's per-lane effective-k moves."""
    return arr.at[slot].set(val)
