"""Speculation config and the per-request adaptive-k controller.

The fused verify program is compiled for ONE static width `k` (shapes
never vary); adaptivity is expressed as a per-lane *effective* k lane on
device — acceptance is masked beyond it — driven by a running
acceptance-rate EMA per request. A request whose drafter keeps missing
spends its rounds at `k_min` (bounding wasted verify positions and the
discarded-trailing-round cost); one whose suffix is predictable climbs
back to `k`. No jax imports here: this layer is pure host config/state.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class SpecConfig:
    """User-facing speculative decoding configuration.

    drafter: "ngram" (prompt-lookup over the sequence's own history —
    zero extra weights) or "model" (a smaller llama proposing greedily
    from its own KV cache; requires ``draft_config`` with the target's
    vocab, optionally ``draft_params``).

    k is the verify program's static width (proposals per round); the
    adaptive controller moves each request's effective k inside
    [k_min, k] on its acceptance EMA. ``ngram`` is the lookup n-gram
    size for the ngram drafter.
    """

    drafter: str = "ngram"
    k: int = 4
    k_min: int = 1
    ngram: int = 3
    adaptive: bool = True
    ema_alpha: float = 0.4  # weight of the newest round's acceptance rate
    raise_at: float = 0.8  # EMA >= raise_at -> effective k += 1
    lower_at: float = 0.3  # EMA < lower_at -> effective k -= 1
    draft_config: object = None  # ray_tpu.models.llama.LlamaConfig
    draft_params: object = None  # optional pretrained draft pytree
    draft_seed: int = 0

    def __post_init__(self):
        if self.drafter not in ("ngram", "model"):
            raise ValueError(f"drafter must be 'ngram' or 'model', got {self.drafter!r}")
        if self.k < 1:
            raise ValueError("k must be >= 1")
        if not 1 <= self.k_min <= self.k:
            # k_min=0 would be a one-way door: a lane at effective k 0
            # proposes nothing, so observe() gets proposed=0 forever and
            # the EMA can never recover — while still paying the full
            # k+1-wide verify forward for 1 token/round
            raise ValueError("k_min must be in [1, k]")
        if self.ngram < 1:
            raise ValueError("ngram must be >= 1")
        if not 0.0 < self.ema_alpha <= 1.0:
            raise ValueError("ema_alpha must be in (0, 1]")


class AdaptiveKController:
    """Per-request acceptance EMA -> effective k in [k_min, k].

    State survives preemption (the request id persists across recompute
    re-admissions) and is dropped on finish via ``forget``.
    """

    def __init__(self, cfg: SpecConfig):
        self.cfg = cfg
        self._state: dict[str, list] = {}  # request_id -> [ema | None, k]

    def admit(self, request_id: str) -> int:
        """Effective k for a (re)admitted request: sticky across
        preemptions, cfg.k for a fresh one."""
        return self._state.setdefault(request_id, [None, self.cfg.k])[1]

    def observe(self, request_id: str, proposed: int, accepted: int) -> int:
        """Fold one round's (proposed, accepted) into the EMA; returns the
        (possibly moved) effective k."""
        st = self._state.setdefault(request_id, [None, self.cfg.k])
        if proposed <= 0:
            return st[1]
        rate = accepted / proposed
        st[0] = rate if st[0] is None else self.cfg.ema_alpha * rate + (1.0 - self.cfg.ema_alpha) * st[0]
        if self.cfg.adaptive:
            if st[0] >= self.cfg.raise_at:
                st[1] = min(st[1] + 1, self.cfg.k)
            elif st[0] < self.cfg.lower_at:
                st[1] = max(st[1] - 1, self.cfg.k_min)
        return st[1]

    def export(self, request_id: str) -> tuple | None:
        """(ema, effective_k) for a live request — the sticky state a
        migration checkpoint carries (llm/migrate.py) so the restoring
        engine's controller continues where this one left off."""
        st = self._state.get(request_id)
        return None if st is None else (st[0], st[1])

    def restore(self, request_id: str, ema=None, k=None) -> None:
        """Seed a migrated request's sticky state under its (possibly
        new) request id; k clamps into [k_min, k] against THIS engine's
        config (a heterogeneous fleet may run narrower verify widths)."""
        kk = self.cfg.k if k is None else max(self.cfg.k_min, min(int(k), self.cfg.k))
        self._state[request_id] = [None if ema is None else float(ema), kk]

    def forget(self, request_id: str) -> None:
        self._state.pop(request_id, None)

    def current(self) -> dict:
        """{request_id: effective k} for every tracked request."""
        return {rid: st[1] for rid, st in self._state.items()}
