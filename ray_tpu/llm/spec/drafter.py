"""Drafters: propose k continuation tokens per lane, device-resident.

Both built-in drafters are DETERMINISTIC (one-hot proposal
distributions), which keeps the verify step's rejection sampling exact
without shipping a [B, k, V] q-tensor: accepting proposal d with
probability p(d) and resampling rejections from p-with-d-masked is the
one-hot special case of speculative rejection sampling, so the output
distribution still matches plain sampling token for token.

- ``NGramDrafter``: prompt-lookup decoding (zero extra weights). The
  trailing n-gram of the lane's token history is matched against the
  history itself; the k tokens after the most recent earlier occurrence
  become the proposals. Entirely jittable over the engine's device
  history lanes, so drafting never syncs the host — and ideal for
  CPU-tier tests.
- ``ModelDrafter``: a smaller llama with its OWN slot KV cache and a
  fused draft step: k+1 chained greedy decode steps under one jit (the
  extra step writes the last proposal's KV, so the draft cache tracks
  the target cache length exactly and no catch-up pass is ever needed).
  Rollback after verification is free: the next round simply overwrites
  positions past the accepted prefix, and draft attention masks by
  position, never by stale stored length.

The engine drives drafters through three hooks: ``init_slots`` (shape
the per-slot state), ``admit`` (host-side (re)admission: prefill the
draft cache), ``propose`` (device call on the hot path).
"""

from __future__ import annotations

from functools import partial
from typing import Protocol, runtime_checkable

import numpy as np

import jax
import jax.numpy as jnp

from ray_tpu.lint import jaxcheck
from ray_tpu.llm import kv_cache as kvc
from ray_tpu.llm.model_runner import _sds, _sds_cache, _sds_params, decode_step, prefill
from ray_tpu.models.llama import LlamaConfig


@runtime_checkable
class Drafter(Protocol):
    """What LLMEngine needs from a drafter implementation.

    ``supports_mesh``: whether the drafter composes with a tensor-parallel
    engine mesh. A drafter qualifies when its per-lane state is replicated
    (or absent) — the engine's hist lanes are replicated over tp and the
    verify step itself compiles SPMD, so a zero-weight drafter rides along
    unchanged. A drafter with its own sharded-model state must implement
    mesh-aware prefill/propose before flipping this on.
    """

    kind: str
    k: int
    supports_mesh: bool

    def init_slots(self, num_slots: int, max_seq_len: int, prefill_buckets: tuple) -> None: ...

    def admit(self, slot: int, tokens: list) -> None: ...

    def propose(self, hist, hist_len, lengths): ...


# ---------------------------------------------------------------------------
# prompt-lookup (n-gram) drafting
# ---------------------------------------------------------------------------
def _bucket_ngram(B=8, H=517):
    return (_sds((B, H), jnp.int32), _sds((B,), jnp.int32), 3, 4), {}


@jaxcheck.entry(
    name="llm.spec_ngram_propose",
    shapes={"b8_h517": _bucket_ngram},
    donate_bytes=0,  # read-only over the hist lanes: nothing to donate
)
def ngram_propose(hist, hist_len, n: int, k: int):
    """Prompt-lookup proposals: for each lane, find the LAST earlier
    occurrence of the trailing n-gram inside the known history and
    propose the k tokens that followed it.

    hist: [B, H] int32 token history (zero right-padding); hist_len: [B]
    valid counts. Returns proposals [B, k] int32. A lane with no match
    proposes its last token repeated — garbage proposals are harmless
    (the verify step rejects them), so no validity lane is needed.
    """
    B, H = hist.shape
    idx = jnp.arange(H, dtype=jnp.int32)

    def one(row, ln):
        pat = jax.lax.dynamic_slice(row, (jnp.maximum(ln - n, 0),), (n,))  # trailing n-gram
        # win[i] = row[i : i + n] (wrapping windows; wraps are masked below)
        win = jnp.stack([jnp.roll(row, -j) for j in range(n)], axis=1)  # [H, n]
        # a usable start needs its continuation token row[i + n] inside
        # known history AND must not be the trailing occurrence itself
        match = jnp.all(win == pat[None, :], axis=1) & (idx + n < ln)
        i_star = jnp.max(jnp.where(match, idx, -1))
        src = jnp.where(i_star >= 0, i_star + n, jnp.maximum(ln - 1, 0))
        props = jax.lax.dynamic_slice(row, (src,), (k,))  # clamped at H - k
        last = row[jnp.maximum(ln - 1, 0)]
        return jnp.where(i_star >= 0, props, jnp.full((k,), last, row.dtype))

    return jax.vmap(one)(hist, hist_len)


class NGramDrafter:
    """Prompt-lookup drafter: stateless beyond the engine's hist lanes.
    Mesh-safe: the hist/length lanes are replicated over tp and propose
    has no weights — the same jitted program runs on every shard."""

    kind = "ngram"
    supports_mesh = True

    def __init__(self, k: int = 4, n: int = 3):
        self.k = int(k)
        self.n = int(n)
        self._propose = jax.jit(partial(ngram_propose, n=self.n, k=self.k))

    def init_slots(self, num_slots: int, max_seq_len: int, prefill_buckets: tuple) -> None:
        pass

    def admit(self, slot: int, tokens: list) -> None:
        pass

    def propose(self, hist, hist_len, lengths):
        del lengths  # history is the only state prompt-lookup needs
        return self._propose(hist, hist_len)


# ---------------------------------------------------------------------------
# draft-model drafting
# ---------------------------------------------------------------------------
def _draft_trace_cfg() -> LlamaConfig:
    # production-realistic small drafter: tile-true dims ((8,128) KV
    # tiles, like the target's trace config), target vocab
    return LlamaConfig(
        vocab_size=32256, hidden_size=512, intermediate_size=1408,
        num_layers=2, num_heads=8, num_kv_heads=8, head_dim=128,
        max_seq_len=512, remat=False,
    )


def _bucket_draft(B=8, S=256, H=517):
    cfg = _draft_trace_cfg()
    return (
        _sds_params(cfg), _sds_cache(cfg, B, S), _sds((B, H), jnp.int32),
        _sds((B,), jnp.int32), _sds((B,), jnp.int32), cfg, 4,
    ), {}


@jaxcheck.entry(
    name="llm.spec_draft_steps",
    shapes={"b8_s256": _bucket_draft},
    donate=("cache",),
    donate_bytes=0,
)
def draft_steps(params, cache, hist, hist_len, lengths, cfg: LlamaConfig, k: int):
    """ONE fused program: k+1 chained greedy decode steps of the draft
    model, proposing k tokens per lane.

    The draft cache's stored length lane is OVERWRITTEN with the target's
    ``lengths`` before stepping — that is the whole rollback protocol:
    step i processes the token at position lengths+i and attends
    0..lengths+i, so stale drafted KV past the last accepted token is
    overwritten before it could ever be read. The (k+1)-th step's
    prediction is discarded but its KV write keeps the draft cache level
    with the target cache, whatever the verify step accepts.

    hist/hist_len: the engine's token-history lanes (the draft chain
    starts from hist[hist_len-1], the lane's current input token).
    Returns (proposals [B, k] int32, new draft cache).
    """
    t0 = jnp.take_along_axis(hist, jnp.maximum(hist_len - 1, 0)[:, None], axis=1)[:, 0]
    cache = {"k": cache["k"], "v": cache["v"], "length": lengths}

    def body(carry, _):
        c, tok = carry
        logits, c = decode_step(params, c, tok, cfg)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return (c, nxt), nxt

    (cache, _), outs = jax.lax.scan(body, (cache, t0), None, length=k + 1)
    return outs[:k].T, cache


class ModelDrafter:
    """Greedy draft-model drafter with its own slot KV cache.

    ``config`` must share the target's vocab; params default to a random
    init (tests/benchmarks — a real deployment passes distilled weights).
    Greedy drafting keeps the proposal distribution one-hot (see module
    docstring), so temperature>0 verification stays exact.
    """

    kind = "model"
    # the draft model's params, slot KV cache and fused draft_steps chain
    # are single-device today; the engine raises NotImplementedError on a
    # mesh rather than silently replicating a second model per chip
    supports_mesh = False

    def __init__(self, config: LlamaConfig, params=None, k: int = 4, seed: int = 0):
        from ray_tpu.models.llama import init_params

        self.cfg = config
        self.k = int(k)
        self.params = params if params is not None else init_params(config, jax.random.PRNGKey(seed))
        self._prefill = jax.jit(partial(prefill, cfg=config))
        self._insert = jax.jit(kvc.insert_sequence, donate_argnums=(0,))
        self._draft = jax.jit(partial(draft_steps, cfg=config, k=self.k), donate_argnums=(1,))
        self.cache = None
        self._buckets: tuple = ()

    def init_slots(self, num_slots: int, max_seq_len: int, prefill_buckets: tuple) -> None:
        self._buckets = tuple(prefill_buckets)
        # +k+1 headroom: the draft chain writes up to k+1 positions past
        # the target length each round, clamp-free
        self.cache = kvc.alloc(kvc.CacheConfig(
            num_layers=self.cfg.num_layers,
            num_slots=num_slots,
            max_seq_len=max_seq_len + self.k + 1,
            num_kv_heads=self.cfg.num_kv_heads,
            head_dim=self.cfg.hd,
            dtype=self.cfg.dtype,
        ))

    def admit(self, slot: int, tokens: list) -> None:
        """Prefill the draft model over the admitted sequence's tokens
        (everything already cached by the target: prompt plus any
        recompute-folded generation; NOT the freshly sampled token — that
        is the first chain input)."""
        from ray_tpu.llm.engine import _bucket

        n = len(tokens)
        T = _bucket(n, self._buckets)
        toks = np.zeros((1, T), np.int32)
        toks[0, :n] = tokens
        _, ks, vs = self._prefill(self.params, jnp.asarray(toks), jnp.asarray([n], np.int32))
        self.cache = self._insert(self.cache, slot, ks[:, 0], vs[:, 0], n)

    def propose(self, hist, hist_len, lengths):
        props, self.cache = self._draft(self.params, self.cache, hist, hist_len, lengths)
        return props
