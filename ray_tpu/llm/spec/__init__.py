"""ray_tpu.llm.spec — speculative decoding for the device-resident loop.

A cheap drafter proposes up to k continuation tokens per lane; ONE fused
jitted verify step runs the target model over all k+1 positions at once
(padded to a fixed k so shapes never vary), accepts the longest prefix the
target agrees with (greedy exact-match, or one-hot rejection sampling for
temperature > 0 — same output distribution, never the same compute), and
rolls back rejected KV in O(1) by length decrement. Greedy output is
token-identical to the non-speculative path, which stays untouched as the
equivalence oracle (tests/test_llm_spec.py).

Modules:
- controller.py: `SpecConfig` (user-facing) + per-request adaptive-k EMA.
- drafter.py: `Drafter` protocol; `NGramDrafter` (prompt-lookup, zero
  extra weights, jittable) and `ModelDrafter` (small llama with its own
  KV cache and fused draft scan).
- verify.py: the fused verify step per KV layout, plus the O(1) lane
  deltas the engine scatters at admission.

Only the config layer imports here (no jax): the engine pulls drafter and
verify modules lazily, exactly like the rest of `llm/`.
"""

from ray_tpu.llm.spec.controller import AdaptiveKController, SpecConfig

__all__ = ["AdaptiveKController", "SpecConfig"]
