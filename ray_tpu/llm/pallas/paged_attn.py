"""Pallas paged-attention decode kernel: gather -> dequant -> attend fused.

The XLA paged path (paged_kv._paged_attn_batch/_paged_attn_seq) scans the
page axis and each step GATHERS one page per lane into a fresh buffer
before attending — on a real chip that materialization is an extra
HBM round trip per page (read pool -> write gathered copy -> read copy
into the attention dot), and the int8 cache adds a separate dequant pass
over the gathered pages. This kernel deletes the materialization: a grid
over (lanes x KV pages) whose BlockSpec index map reads the device page
table directly (scalar-prefetch), so each page streams HBM -> VMEM
exactly once, dequantizes IN REGISTERS with the exact kv_quant recipe
(int8 * f32 per-head amax scale at the f32 compute dtype), and folds
into a flash-style online-softmax carry (m/l/acc). Paged decode becomes
HBM-roofline-bound on the bytes that must move — the pool pages — and
nothing else (bench_artifacts/README.md has the v5e byte math).

Scope and contracts:

- The kernel computes the PAGE-PREFIX softmax partials only: positions
  ``0..bound[b]-1`` read from the pool. The current token's K/V (decode)
  and the causal in-register chunk (spec verify / chunked prefill) are
  folded OUTSIDE the kernel by the same ``_combine`` math the XLA path
  uses — the kernel never reads the position being written this step,
  which is the third leg of the gather/scatter aliasing contract
  documented on ``decode_attn_paged`` (the attention program must stay
  read-only over the pool). ``tests/test_llm_pallas.py`` poisons the
  write target to regression-lock this.
- Math mirrors the XLA scan op-for-op (same masks, same ``_NEG``
  surrogate, same combine order), so interpret mode on CPU is
  token-identical to the XLA oracle — the equivalence tier-1 asserts.
- ``interpret=True`` (automatic off-TPU) runs the kernel through the
  Pallas interpreter: slow, but the SAME kernel body TPU compiles, so
  CPU CI exercises the real code path.

The XLA path remains the default and the fallback; engines opt in with
``attn_kernel="pallas"`` (llm/engine.py validates, and degrades with a
one-time warning — never an error — when ``kernel_supported`` says no).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ray_tpu.lint import jaxcheck
from ray_tpu.llm.paged_kv import _NEG


def _interpret_default() -> bool:
    """Interpret off-TPU: the kernel body is executed by the Pallas
    interpreter as plain jax ops (slow, exact); on TPU it compiles."""
    return jax.default_backend() != "tpu"


def kernel_supported(page_size: int, num_kv_heads: int, head_dim: int, quantized: bool = False):
    """(ok, why_not) for this config on this backend. CPU always works
    (interpret mode); TPU gets a CONSERVATIVE tile gate on the dims
    Mosaic actually tiles — the trailing two of each block: the K/V
    block ``(1, page, kvh, hd)`` tiles (kvh, hd), so ``hd`` is the
    128-lane dim and ``kvh`` the 8-sublane dim; an int8 pool's scale
    block ``(1, kvh, page)`` additionally puts ``page`` on lanes.
    Anything else has no lowering. This decision is taken ONCE at engine
    construction, so it must be strict enough that a promised kernel
    never fails to compile later — the engine turns a False into a
    one-time warning + XLA fallback, never an error."""
    try:
        from jax.experimental import pallas as pl  # noqa: F401
        from jax.experimental.pallas import tpu as pltpu  # noqa: F401
    except Exception as e:  # noqa: BLE001 — stubbed/absent pallas degrades
        return False, f"pallas unavailable: {type(e).__name__}: {e}"
    backend = jax.default_backend()
    if backend == "cpu":
        return True, ""
    if backend == "tpu":
        if head_dim % 128:
            return False, f"head_dim {head_dim} is not a multiple of the 128-lane tile"
        if num_kv_heads % 8:
            return False, f"num_kv_heads {num_kv_heads} is not a multiple of the 8-sublane tile (the K/V block's sublane dim)"
        if quantized and page_size % 128:
            return False, f"int8 pool: page_size {page_size} is not a multiple of the 128-lane tile (the scale plane's lane dim)"
        return True, ""
    return False, f"no pallas paged-attention path for backend {backend!r}"


try:  # the module must import (for the XLA-only engines) even if pallas can't
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # noqa: BLE001 — kernel_supported reports the real reason
    pl = pltpu = None


def _partials_kernel(tables_ref, bound_ref, q_ref, k_ref, v_ref, *rest, page: int, quant: bool):
    """One (lane b, page j) grid step: stream page ``tables[b, j]`` from
    HBM, dequantize in registers (int8 pools), fold into the lane's
    online-softmax carry. The carry lives in the output refs — the page
    grid dim revisits the same output block, the canonical reduction."""
    if quant:
        k_sc_ref, v_sc_ref, m_ref, l_ref, acc_ref = rest
    else:
        m_ref, l_ref, acc_ref = rest
    b = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    kp = k_ref[0].astype(jnp.float32)  # [page, kv, hd]
    vp = v_ref[0].astype(jnp.float32)
    if quant:
        # the exact kv_quant dequant the XLA path applies to gathered
        # pages — here on the in-register block, at the f32 compute
        # dtype (the convert stays off the flops-dominant dots: JXC003)
        kp = kp * k_sc_ref[0].transpose(1, 0)[..., None]  # [page, kv, 1]
        vp = vp * v_sc_ref[0].transpose(1, 0)[..., None]
    qf = q_ref[0]  # [nkv, rep, T, hd], f32, pre-scaled by the caller
    s = jnp.einsum("grth,pgh->grtp", qf, kp)  # [nkv, rep, T, page]
    pos = j * page + jax.lax.broadcasted_iota(jnp.int32, (page, 1), 0)[:, 0]
    ok = pos < bound_ref[b]  # strictly pre-existing positions only
    s = jnp.where(ok[None, None, None, :], s, _NEG)
    m_prev, l_prev, acc_prev = m_ref[0], l_ref[0], acc_ref[0]
    m_new = jnp.maximum(m_prev, s.max(axis=-1))
    alpha = jnp.exp(m_prev - m_new)
    pexp = jnp.exp(s - m_new[..., None])
    m_ref[0] = m_new
    l_ref[0] = l_prev * alpha + pexp.sum(axis=-1)
    acc_ref[0] = acc_prev * alpha[..., None] + jnp.einsum("grtp,pgh->grth", pexp, vp)


def paged_attn_partials(qf, pool_k_l, pool_v_l, tables, bound,
                        k_scale_l=None, v_scale_l=None, *, interpret: bool | None = None):
    """Online-softmax partials of ``qf`` over each lane's paged prefix.

    qf: [B, nkv, rep, T, hd] float32, already scaled by 1/sqrt(hd);
    pool_*_l: [P, page, kv, hd] (one layer; fp or int8);
    tables: [B, max_pg] int32 device page table (padding rows point at
    the trash page — masked by ``bound``); bound: [B] int32 — attend to
    pool positions ``0 .. bound[b]-1`` ONLY (lengths for decode, the
    prefix start for wide-block verify/extend). The position being
    written this step is >= bound by contract and must reach attention
    in registers via the caller's self/chunk fold, never from the pool.
    k_scale_l/v_scale_l: [P, kv, page] f32 for int8 pools.

    Returns (m [B, nkv, rep, T], l same, acc [B, nkv, rep, T, hd]) f32 —
    the same partials the XLA page scan carries, ready for the shared
    ``_combine`` + normalize tail.
    """
    if pl is None:  # pragma: no cover — kernel_supported gates real callers
        raise RuntimeError("pallas is unavailable in this jax build")
    B, nkv, rep, T, hd = qf.shape
    page = pool_k_l.shape[1]
    kvh = pool_k_l.shape[2]
    max_pg = tables.shape[1]
    quant = k_scale_l is not None
    if interpret is None:
        interpret = _interpret_default()

    kernel = functools.partial(_partials_kernel, page=page, quant=quant)
    lane = lambda b, j, tbl, bnd: (b, 0, 0, 0)  # noqa: E731
    in_specs = [
        pl.BlockSpec((1, nkv, rep, T, hd), lambda b, j, tbl, bnd: (b, 0, 0, 0, 0)),
        # the fused gather: the index map IS the page-table read, so the
        # pipeline DMAs exactly one pool page per grid step HBM -> VMEM
        pl.BlockSpec((1, page, kvh, hd), lambda b, j, tbl, bnd: (tbl[b, j], 0, 0, 0)),
        pl.BlockSpec((1, page, kvh, hd), lambda b, j, tbl, bnd: (tbl[b, j], 0, 0, 0)),
    ]
    args = [tables, bound, qf, pool_k_l, pool_v_l]
    if quant:
        in_specs += [
            pl.BlockSpec((1, kvh, page), lambda b, j, tbl, bnd: (tbl[b, j], 0, 0)),
            pl.BlockSpec((1, kvh, page), lambda b, j, tbl, bnd: (tbl[b, j], 0, 0)),
        ]
        args += [k_scale_l, v_scale_l]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # tables + bound ride SMEM ahead of the body
        grid=(B, max_pg),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, nkv, rep, T), lane),
            pl.BlockSpec((1, nkv, rep, T), lane),
            pl.BlockSpec((1, nkv, rep, T, hd), lambda b, j, tbl, bnd: (b, 0, 0, 0, 0)),
        ],
    )
    out_shape = [
        jax.ShapeDtypeStruct((B, nkv, rep, T), jnp.float32),
        jax.ShapeDtypeStruct((B, nkv, rep, T), jnp.float32),
        jax.ShapeDtypeStruct((B, nkv, rep, T, hd), jnp.float32),
    ]
    kw = {}
    if not interpret:
        # lanes are independent; the page dim carries the m/l/acc
        # reduction and must stay sequential
        kw["compiler_params"] = pltpu.TPUCompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        )
    m, l, acc = pl.pallas_call(
        kernel, grid_spec=grid_spec, out_shape=out_shape, interpret=interpret, **kw
    )(*args)
    return m, l, acc


# ---------------------------------------------------------------------------
# jaxcheck entries: the kernel traced over interpret-mode buckets (this is
# how the static pass sees the program on TPU-less CI; the pallas_call
# abstract shapes are identical either way). Shapes mirror model_runner's
# _trace_cfg pools: nkv=8, hd=128, page=16 — tile-true trailing dims so
# JXC006's (8,128) math stays meaningful. The fp entry carries both the
# decode (T=1) and wide-block (T=5, spec verify's k+1) buckets.
# ---------------------------------------------------------------------------
def _sds(shape, dtype):
    return jax.ShapeDtypeStruct(shape, dtype)


def _bucket_partials(B=8, pages=64, page=16, kv=8, hd=128, T=1, quant=False):
    qf = _sds((B, kv, 1, T, hd), jnp.float32)
    pool = _sds((pages, page, kv, hd), jnp.int8 if quant else jnp.float32)
    tables = _sds((B, 8), jnp.int32)
    bound = _sds((B,), jnp.int32)
    args = (qf, pool, pool, tables, bound)
    if quant:
        sc = _sds((pages, kv, page), jnp.float32)
        args += (sc, sc)
    return args, {}


@jaxcheck.entry(
    name="llm.paged_attn_pallas",
    shapes={
        "b8_t1_interp": _bucket_partials,
        "b8_t5_interp": lambda: _bucket_partials(T=5),
    },
)
def paged_attn_pallas(qf, pool_k_l, pool_v_l, tables, bound):
    """Registry twin of the fp kernel call (decode + wide-block buckets).
    Nothing donates: the partials feed the caller's self/chunk fold and
    qf/pool stay live past the call by design."""
    return paged_attn_partials(qf, pool_k_l, pool_v_l, tables, bound, interpret=True)


@jaxcheck.entry(
    name="llm.paged_attn_pallas_int8",
    shapes={
        "b8_t1_interp": lambda: _bucket_partials(quant=True),
        "b8_t5_interp": lambda: _bucket_partials(T=5, quant=True),
    },
)
def paged_attn_pallas_int8(qf, pool_k_l, pool_v_l, tables, bound, k_scale_l, v_scale_l):
    """Int8-pool twin: in-register dequant rides the same kernel body
    (the scale planes stream with their pages through the index map)."""
    return paged_attn_partials(
        qf, pool_k_l, pool_v_l, tables, bound, k_scale_l, v_scale_l, interpret=True
    )
