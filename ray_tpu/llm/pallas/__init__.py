"""Pallas TPU kernels for the serving hot path.

One module per kernel; each keeps an interpret-mode path (`pl.pallas_call
(..., interpret=True)`) so the kernels stay testable — and token-compared
against their XLA oracles — on CPU-only containers. The XLA programs they
replace remain the default and the fallback: a kernel here is always an
engine-validated opt-in, never a silent substitution.
"""

from ray_tpu.llm.pallas.paged_attn import kernel_supported, paged_attn_partials

__all__ = ["kernel_supported", "paged_attn_partials"]
