"""Token sampling, jit-compatible with per-slot parameters.

TPU-native replacement for the sampling-params plumbing the reference
delegates to vLLM (ref: python/ray/llm/_internal/serve/engines/vllm/
vllm_models.py:215-228 passes SamplingParams through to the engine).
Everything here is batched and static-shaped: one `sample` call handles a
whole decode batch with per-slot temperature / top-k / top-p arrays, so
continuous batching never recompiles as requests come and go.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class SamplingParams:
    """Per-request sampling configuration (user-facing)."""

    max_tokens: int = 64
    temperature: float = 0.0  # 0.0 => greedy
    top_k: int = 0  # 0 => disabled
    top_p: float = 1.0  # 1.0 => disabled
    stop_token_ids: tuple = field(default_factory=tuple)
    seed: int | None = None
    logprobs: bool = False
    # request class for admission control / load shedding
    # (serve/overload.py): 0 = lowest, shed first; higher classes only
    # shed at larger fractions of the ingress caps. Never reorders
    # admitted work — priority decides WHO sheds, not who runs first.
    priority: int = 0

    def __post_init__(self):
        if self.temperature < 0.0:
            raise ValueError("temperature must be >= 0")
        if self.priority < 0:
            raise ValueError("priority must be >= 0")
        if not 0.0 < self.top_p <= 1.0:
            raise ValueError("top_p must be in (0, 1]")
        if self.top_k < 0:
            raise ValueError("top_k must be >= 0")


def _apply_top_k(logits, top_k):
    """Mask logits outside the per-row top-k (top_k[b] == 0 disables)."""
    vocab = logits.shape[-1]
    # rank of each logit within its row (0 = largest)
    order = jnp.argsort(logits, axis=-1)[..., ::-1]
    ranks = jnp.argsort(order, axis=-1)
    k = jnp.where(top_k <= 0, vocab, top_k)[..., None]
    return jnp.where(ranks < k, logits, -jnp.inf)


def _apply_top_p(logits, top_p):
    """Nucleus filtering: keep the smallest prefix with cumprob >= top_p."""
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # keep tokens while the cumulative mass *before* them is < top_p
    keep_sorted = (cum - probs) < top_p[..., None]
    # threshold logit = smallest kept logit per row
    thresh = jnp.min(jnp.where(keep_sorted, sorted_logits, jnp.inf), axis=-1, keepdims=True)
    return jnp.where(logits >= thresh, logits, -jnp.inf)


def filter_logits(logits, temperature, top_k, top_p):
    """Temperature-scale then top-k/top-p filter logits.

    logits: [..., V]; temperature/top_p: [...] f32; top_k: [...] i32
    (0 disables). The distribution surgery shared by sample() and the
    speculative verify step (llm/spec/verify.py) — spec acceptance must
    judge proposals against exactly the distribution plain sampling
    draws from, or rejection sampling would drift off-policy.
    """
    scaled = logits / jnp.maximum(temperature, 1e-6)[..., None]
    scaled = _apply_top_k(scaled, top_k)
    return _apply_top_p(scaled, top_p)


def sample(logits, key, temperature, top_k, top_p):
    """Sample one token per row.

    logits: [B, V] f32; temperature/top_p: [B] f32; top_k: [B] i32;
    key: [B, 2] u32 per-slot PRNG keys. Returns (tokens [B] i32,
    logprobs [B] f32, new_keys [B, 2]).
    """
    logits = logits.astype(jnp.float32)
    greedy_tok = jnp.argmax(logits, axis=-1)

    def _one(lg, k, temp, tk, tp):
        k1, k2 = jax.random.split(jax.random.wrap_key_data(k, impl="threefry2x32"))
        scaled = filter_logits(lg[None], temp[None], tk[None], tp[None])[0]
        tok = jax.random.categorical(k1, scaled)
        return tok, jax.random.key_data(k2)

    sampled_tok, new_keys = jax.vmap(_one)(logits, key, temperature, top_k, top_p)
    tokens = jnp.where(temperature == 0.0, greedy_tok, sampled_tok).astype(jnp.int32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    chosen_logp = jnp.take_along_axis(logp, tokens[:, None], axis=-1)[:, 0]
    return tokens, chosen_logp, new_keys
