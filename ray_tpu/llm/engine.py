"""Continuous-batching LLM engine (the module models/llama.py promises).

Architecture (TPU-native replacement for the reference's vLLM wrapping in
python/ray/llm/_internal/serve/engines/vllm/vllm_engine.py):

- a static slot-based KV cache (kv_cache.py) or paged pool (paged_kv.py)
  compiled once;
- prompt prefill bucketed to powers of two (one compiled program per
  bucket, not per prompt length), and BATCHED: same-bucket admissions
  run as one forward with the batch dim padded to a power of two;
- a DEVICE-RESIDENT decode loop (Podracer-style): tokens, PRNG keys,
  sampling params, block tables and lengths live on device; one fused
  jitted step advances *all* slots one token (decode -> sample ->
  append-KV -> advance lengths) with the big buffers donated, scheduler
  changes land as O(1) scatter deltas, and token readback overlaps the
  next step's dispatch (emission trails the device by one step);
- a host-side scheduler does admission (waiting queue -> free slot),
  completion (eos / max_tokens / stop ids), and slot recycling between
  device steps against numpy shadow state. The device never sees dynamic
  shapes, and nothing syncs the host per decode step;
- every step() is three explicit STAGES — admission (plan: queue ->
  slot/page reservation), prefill (execute: batched forwards +
  transferred-KV / prefix-hit scatter-ins), decode (dispatch + drain).
  The stage split is what disaggregated serving (llm/disagg/) rides: a
  prefill replica runs only the first two stages (prefill-only requests
  finish with their KV extracted into a handoff block), a decode replica
  admits handoff blocks through a fused scatter-in and runs the third;
- optional speculative decoding (speculative=SpecConfig(...), llm/spec/):
  a drafter proposes up to k tokens per lane and one fused verify step
  accepts/extends them — multiple tokens per tick, greedy output
  token-identical to the plain path (which stays untouched as the
  subsystem's equivalence oracle).

`device_resident=False` (RT_LLM_DEVICE_RESIDENT=0) keeps the old
synchronous host-driven loop as the equivalence oracle. Engine steps are
cheap to drive from an actor or a Serve replica; `generate()` is the
batteries-included loop.
"""

from __future__ import annotations

import queue
import threading
import time
import warnings
from collections import deque
from dataclasses import dataclass, field

import numpy as np

from ray_tpu.llm.kvplane.index import prefix_key, token_bytes
from ray_tpu.llm.sampling import SamplingParams


@dataclass
class RequestState:
    request_id: str
    prompt_token_ids: list
    params: SamplingParams
    token_ids: list = field(default_factory=list)
    logprobs: list = field(default_factory=list)
    slot: int = -1
    finished: bool = False
    finish_reason: str | None = None
    # streaming consumers read from here
    out_queue: "queue.SimpleQueue | None" = None
    # KV computed by a remote prefill engine (disaggregation)
    prefilled: dict | None = None
    # prefill-only: run admission+prefill stages, extract the KV block
    # into a handoff (pop_handoff) and finish — never enters decode
    prefill_only: bool = False
    # paged layout: admission order (preemption picks the youngest) and
    # preemption count (observability)
    admit_seq: int = -1
    preemptions: int = 0
    # telemetry lifecycle stamps (llm/telemetry.py; host wall clocks only)
    t_submit: float = 0.0
    t_admit: float = 0.0
    t_first: float = 0.0
    t_last: float = 0.0
    itls: list = field(default_factory=list)
    # (trace_id, root_span_id, parent_span_id) when RT_TRACING=1; the
    # disagg handoff carries (trace_id, root_span_id) across replicas
    trace: tuple | None = None
    # prefix resolution cached across steps while the request is
    # head-of-line blocked (paged pool full): the lookup/fetch and its
    # hit accounting (cache counters, telemetry tiers, any object-plane
    # transfer) happen ONCE per request, never once per blocked step
    cached_pref: tuple | None = None
    # live migration (llm/migrate.py): a restored request's splice state
    # (exact PRNG key, spec controller state) consumed by _bind_resume —
    # set together with `prefilled` so the checkpointed KV block rides
    # the existing transferred-KV admission path, but the bind continues
    # generation instead of sampling a first token from shipped logits
    resume: dict | None = None
    # restore ingress wall clock (0.0 = never migrated): the splice
    # latency observed at the first post-splice token
    t_restore: float = 0.0


@dataclass
class RequestOutput:
    request_id: str
    prompt_token_ids: list
    token_ids: list
    new_token_ids: list
    finished: bool
    finish_reason: str | None = None
    logprobs: list | None = None
    streamed: bool = False  # consumer reads an out_queue, not this output


def _bucket(n: int, buckets) -> int:
    for b in buckets:
        if n <= b:
            return b
    raise ValueError(f"prompt length {n} exceeds the largest prefill bucket {buckets[-1]}")  # tpulint: disable=ERR002 — suspend_request wraps it `raise MigrationError(...) from e`; ingress callers treat it as 400-class input validation


# RequestState.cached_pref miss marker: prefix resolution ran and MISSED
# (distinct from None = not yet resolved). Cached as (_PREF_MISS, gen,
# expires_at) where gen is the local PrefixCache's store generation at
# resolution time: a blocked request must not re-pay the lookup/fetch
# every step, but a SAME-WAVE leader's store (admitted just before the
# block hit pool pressure) mints the prefix after the miss resolved — the
# generation mismatch re-resolves exactly then, so the follower still
# gets its hit when pages free. expires_at additionally time-bounds the
# miss on cluster-plane engines (another REPLICA's publish can't bump the
# local generation); local-only engines never expire it (nothing external
# can mint their keys).
_PREF_MISS = object()


class PrefixCache:
    """Hash-prefix KV reuse across requests (reference capability:
    enable_prefix_caching, python/ray/llm/_internal/serve/engines/vllm/
    vllm_models.py:215-228 — vLLM hashes fixed-size blocks; here prefixes
    are cached at block-aligned lengths as whole device arrays, matching
    the slot cache's contiguous layout, and admission re-attends the
    remaining suffix with model_runner.extend).

    Entries: stable_hash(tokens[:n]) -> (k [L, n, kv, hd], v, n) on
    device. Keys are CONTENT-STABLE blake2b digests over the token bytes
    (kvplane/index.py) — never Python's process-salted ``hash()``, whose
    PYTHONHASHSEED made the same prefix key out differently on every
    replica — so the local cache and the cluster KV plane index
    (ray_tpu/llm/kvplane/) speak one key space. LRU-evicted under a byte
    budget; ``evict_hook`` (set by the plane client) hears each evicted
    group's keys so published copies deregister-then-free before the
    bytes die. Stats drive tests and metrics.
    """

    def __init__(self, block: int = 64, max_bytes: int = 256 << 20):
        self.block = block
        self.max_bytes = max_bytes
        # called with the evicted group's key list (cluster KV plane:
        # unregister + free the published block); None = local-only cache
        self.evict_hook = None
        # store generation: bumped whenever new boundary keys mint, so a
        # cached resolution MISS (engine _PREF_MISS) knows when the cache
        # gained entries that could turn it into a hit
        self.gen = 0
        # one GROUP per stored prompt: shared (k, v) device arrays; every
        # block boundary of the prompt aliases into the group with its own
        # valid length (insert masks the padded tail, so no slicing)
        self._groups: dict = {}  # gid -> (k, v, nbytes, [keys])
        self._keys: dict = {}  # hash(prefix) -> (gid, n)
        self._order: deque = deque()  # LRU over gids: left = coldest
        self._next_gid = 0
        self._bytes = 0
        self.hits = 0
        self.misses = 0
        self.tokens_saved = 0
        self.evictions = 0

    def lookup(self, prompt_token_ids, admissible=None):
        """Longest block-aligned cached prefix STRICTLY shorter than the
        prompt (at least one token must remain to produce logits). Hits
        are verified token-for-token — a hash collision must never serve
        a foreign prompt's KV (the reference block cache exact-matches
        too). ``admissible(n) -> bool`` filters boundaries BEFORE they
        can match (the engine's suffix-overrun guard): a rejected longer
        boundary falls through to the next shorter one instead of
        discarding the whole lookup — and never inflates the hit
        counters on its way out."""
        ids = tuple(int(t) for t in prompt_token_ids)  # tuple ONCE, slice per boundary
        buf = token_bytes(ids)  # packed ONCE; each boundary hashes a slice
        n = ((len(ids) - 1) // self.block) * self.block
        while n >= self.block:
            if admissible is not None and not admissible(n):
                n -= self.block
                continue
            hit = self._keys.get(prefix_key(buf, n))
            if hit is not None:
                gid, n_valid = hit
                k, v, _, _, group_ids = self._groups[gid]
                # token-for-token verification against the group's ONE
                # stored tuple: a hash collision must never serve a
                # foreign prompt's KV (the reference block cache
                # exact-matches too)
                if group_ids[:n_valid] == ids[:n_valid]:
                    self._order.remove(gid)
                    self._order.append(gid)
                    self.hits += 1
                    self.tokens_saved += n_valid
                    return k, v, n_valid
            n -= self.block
        self.misses += 1
        return None

    def store(self, prompt_token_ids, ks, vs, buckets):
        """Cache a freshly prefilled prompt's K/V once, keyed at EVERY
        block boundary. ks/vs: [L, T_pad, kv, hd] device arrays, stored
        padded to the prefix's PREFILL BUCKET so re-insert reuses the
        already-compiled insert program (a raw per-length shape would mint
        one XLA program per distinct n). Returns ``(new_keys, pad)`` —
        the freshly minted (key, n) boundary pairs and the stored block
        width — so a cluster KV plane client can publish exactly what was
        stored (None when nothing new was cached)."""
        n_max = (len(prompt_token_ids) // self.block) * self.block
        if n_max < self.block:
            return None
        # ONE token tuple per group; boundary keys alias into it with
        # their valid length (no O(n^2/block) host tuples — lookup
        # verifies against slices of this single tuple)
        ids = tuple(int(t) for t in prompt_token_ids[:n_max])
        buf = token_bytes(ids)
        new_keys = []
        for n in range(self.block, n_max + 1, self.block):
            key = prefix_key(buf, n)
            if key not in self._keys:
                new_keys.append((key, n))
        if not new_keys:
            return None
        pad = _bucket(n_max, buckets)
        k = ks[:, :pad]
        v = vs[:, :pad]
        nbytes = int(k.nbytes) + int(v.nbytes)
        if nbytes > self.max_bytes:
            return None
        while self._bytes + nbytes > self.max_bytes and self._order:
            self._evict_one()
        gid = self._next_gid
        self._next_gid += 1
        self._groups[gid] = (k, v, nbytes, [key for key, _ in new_keys], ids)
        for key, n in new_keys:
            self._keys[key] = (gid, n)
        self._order.append(gid)
        self._bytes += nbytes
        self.gen += 1
        return new_keys, pad

    def _evict_one(self):
        gid = self._order.popleft()
        _, _, nbytes, keys, _ = self._groups.pop(gid)
        for key in keys:
            self._keys.pop(key, None)
        self._bytes -= nbytes
        self.evictions += 1
        if self.evict_hook is not None:
            # the route must die before the bytes: the hook unregisters
            # the published copy's keys and frees the owned block
            try:
                self.evict_hook(keys)
            except Exception:  # noqa: BLE001 — plane trouble never breaks eviction
                pass

    def stats(self) -> dict:
        return {
            "hits": self.hits,
            "misses": self.misses,
            "tokens_saved": self.tokens_saved,
            "evictions": self.evictions,
            "entries": len(self._groups),
            "bytes": self._bytes,
        }


class LLMEngine:
    """Continuous-batching engine over a slot KV cache.

    config: ray_tpu.models.llama.LlamaConfig; params: matching pytree (if
    None, randomly initialized — useful for tests/benchmarks).
    """

    def __init__(
        self,
        config,
        params=None,
        *,
        max_num_seqs: int = 8,
        max_seq_len: int | None = None,
        prefill_buckets: tuple | None = None,
        seed: int = 0,
        cache_dtype: str | None = None,
        mesh=None,
        tp_collective: str = "fp",
        enable_prefix_caching: bool = True,
        prefix_cache_bytes: int = 256 << 20,
        prefix_block: int = 64,
        kv_plane=None,
        prefix_fetch_deadline_s: float = 2.0,
        kv_layout: str = "slots",
        num_pages: int | None = None,
        page_size: int = 64,
        attn_kernel: str = "xla",
        device_resident: bool | None = None,
        batch_prefill: bool | None = None,
        speculative=None,
        telemetry: bool = True,
        telemetry_tags: dict | None = None,
    ):
        """kv_layout: "slots" (static per-sequence rows; llm/kv_cache.py)
        or "paged" (block-table page pool; llm/paged_kv.py — concurrency
        bounded by total pages, vLLM-class memory management). For paged,
        ``num_pages`` sizes the pool (default: the slot-equivalent HBM,
        max_num_seqs * max_seq_len / page_size) and ``page_size`` must
        divide every prefill bucket and the prefix block.

        attn_kernel: paged-attention implementation for the decode /
        spec-verify / chunked-prefill hot path (kv_layout="paged" only).
        "xla" (default) is the gather-then-attend page scan — the
        token-identical oracle; "pallas" opts into the fused
        HBM-streaming kernel (llm/pallas/paged_attn.py: page-table
        gather, int8 dequant and flash-style attend in ONE program,
        interpret mode off-TPU). Validated here: an unknown value or
        "pallas" on the slot layout raises; a config/platform the kernel
        cannot serve (kernel_supported) degrades to "xla" with a
        one-time warning, never an error. The resolved choice is
        ``engine.attn_kernel`` (bench provenance reads it).

        cache_dtype: KV-cache storage dtype, validated against
        {bfloat16/bf16, float32/f32, int8} (None = the model dtype).
        "int8" stores quantized K/V with per-layer/head amax scales
        (llm/kv_quant.py): quantize-on-append inside the fused step,
        dequantize-in-attention — ~2x the servable concurrency at fixed
        cache HBM, with the fp cache as the accuracy oracle
        (tests/test_llm_kv_int8.py).

        device_resident (default: RT_LLM_DEVICE_RESIDENT, on): the decode
        hot path keeps ALL per-step state on device — one fused jitted
        step per token, scheduler changes applied as scatter deltas, and
        token readback overlapped with the next step's dispatch (emission
        trails the device by exactly one step). Off = the synchronous
        host-driven loop (re-uploads + blocking readback per step), kept
        as the equivalence oracle. batch_prefill (default:
        RT_LLM_BATCH_PREFILL, on): same-bucket prompt prefills at
        admission run as one batched forward.

        speculative (llm.spec.SpecConfig | None): speculative decoding on
        the device-resident loop — a drafter proposes up to k tokens per
        lane and one fused verify step accepts/extends them (llm/spec/).
        Greedy output stays token-identical to speculative=None, which is
        the subsystem's equivalence oracle (tests/test_llm_spec.py).

        tp_collective: dtype of the per-layer tensor-parallel all-reduce
        on the device-resident fused/spec hot path (only meaningful with
        a tp>=2 mesh). "fp" (default) reduces exactly at the operand
        dtype; "int8" quantizes the all-reduce payload to int8 with f32
        amax scales (EQuARX, arxiv 2506.17615) — ~1/2 the ICI bytes per
        layer at bf16 operands, with the fp-collective engine as the
        accuracy oracle (tests/test_llm_tp.py).

        kv_plane (llm.kvplane.KVPlaneClient | None): joins this engine to
        the CLUSTER prefix tier (ray_tpu/llm/kvplane/). Freshly cached
        prefixes publish as owned objects on the direct plane; a local
        prefix-cache miss LAUNCHES the cluster lookup+fetch on the
        engine's fetch worker — never under the engine lock — and the
        result splices in at a later admission wave, overlapping the
        transfer with the current wave's prefill/decode work. A landed
        block (bounded retry — an evicted/lost block degrades to local
        prefill, never a hang) scatter-ins through the existing fused
        insert/transparent-requant path and re-stores + republishes
        locally so the next hit is local-tier.
        ``prefix_fetch_deadline_s`` bounds how long an admission defers
        a request on its in-flight fetch: past it the request degrades
        to a plain local prefill and the late result is discarded.
        Requires enable_prefix_caching=True (the plane IS the cache's
        cluster tier). prefix_cache_stats() grows local/remote hit
        tiers."""
        import jax
        import jax.numpy as jnp

        from ray_tpu.llm import kv_cache as kvc
        from ray_tpu.llm.model_runner import make_paged_runner_fns, make_runner_fns
        from ray_tpu.llm.sampling import sample
        from ray_tpu.models.llama import init_params

        self.config = config
        self.mesh = mesh
        if tp_collective not in ("fp", "int8"):
            raise ValueError(f"tp_collective must be 'fp' or 'int8', got {tp_collective!r}")
        self.tp_collective = tp_collective
        self.max_num_seqs = int(max_num_seqs)
        self.max_seq_len = int(max_seq_len or config.max_seq_len)
        if kv_layout not in ("slots", "paged"):
            raise ValueError(f"kv_layout must be 'slots' or 'paged', got {kv_layout!r}")
        self.kv_layout = kv_layout
        if attn_kernel not in ("xla", "pallas"):
            raise ValueError(f"attn_kernel must be 'xla' or 'pallas', got {attn_kernel!r}")
        if attn_kernel == "pallas" and kv_layout != "paged":
            raise ValueError(
                "attn_kernel='pallas' is the paged-attention kernel and needs "
                "kv_layout='paged' (the slot layout has no page gather to fuse)"
            )
        from ray_tpu.llm.kv_quant import is_int8, normalize_cache_dtype

        # validate EARLY: an unsupported string must raise here, never
        # fall through to jnp.dtype() (or worse, silently serve bf16)
        self.kv_dtype = normalize_cache_dtype(cache_dtype) if cache_dtype is not None else config.dtype
        self.kv_quant = is_int8(self.kv_dtype)
        if prefill_buckets is None:
            b, buckets = 64, []
            while b < self.max_seq_len:
                buckets.append(b)
                b *= 2
            buckets.append(self.max_seq_len)
            prefill_buckets = tuple(buckets)
        self.prefill_buckets = tuple(sorted(prefill_buckets))
        self._sample = jax.jit(sample)

        if kv_layout == "paged":
            from ray_tpu.llm import paged_kv as pkv

            if any(b % page_size for b in self.prefill_buckets):
                raise ValueError(f"page_size {page_size} must divide every prefill bucket {self.prefill_buckets}")
            if prefix_block % page_size:
                raise ValueError(f"page_size {page_size} must divide prefix_block {prefix_block}")
            max_pg = -(-self.max_seq_len // page_size)
            if num_pages is None:
                # slot-equivalent HBM: same bytes, but shared across
                # sequences instead of stranded per slot (+1 for trash)
                num_pages = self.max_num_seqs * max_pg + 1
            self._pcfg = pkv.PagedCacheConfig(
                num_layers=config.num_layers,
                num_pages=int(num_pages),
                page_size=int(page_size),
                max_pages_per_seq=max_pg,
                num_slots=self.max_num_seqs,
                num_kv_heads=config.num_kv_heads,
                head_dim=config.hd,
                dtype=self.kv_dtype,
            )
            if attn_kernel == "pallas":
                # engine-validated opt-in with a DEGRADE contract: an
                # unsupported platform/shape (or the not-yet-kernelized
                # shard_map tp path) falls back to the XLA oracle with a
                # one-time warning — serving never errors over a kernel
                from ray_tpu.llm.pallas.paged_attn import kernel_supported
                from ray_tpu.parallel.mesh import axis_size as _tp_axis

                ok, why = kernel_supported(
                    self._pcfg.page_size, config.num_kv_heads, config.hd, quantized=self.kv_quant
                )
                if ok and mesh is not None and _tp_axis(mesh, "tp") > 1:
                    ok, why = False, "the shard_map tensor-parallel path does not ride the kernel yet"
                if not ok:
                    warnings.warn(
                        f"attn_kernel='pallas' unavailable ({why}); falling back to the "
                        "XLA paged-attention path",
                        RuntimeWarning,
                        stacklevel=2,
                    )
                    attn_kernel = "xla"
            self.attn_kernel = attn_kernel
            self._prefill, self._insert, self._decode, self._extend = make_paged_runner_fns(
                config, attn_impl=attn_kernel
            )
            self._page_alloc = pkv.PageAllocator(self._pcfg.num_pages)
            self._tables = np.zeros((self.max_num_seqs, max_pg), np.int32)
            self._lengths = np.zeros((self.max_num_seqs,), np.int32)
            self._slot_pages: list[list[int]] = [[] for _ in range(self.max_num_seqs)]
            self._admit_counter = 0
        else:
            self.attn_kernel = "xla"  # slot layout: no page gather to fuse
            self._prefill, self._insert, self._decode, self._extend = make_runner_fns(config)

        cache_cfg = (
            None
            if kv_layout == "paged"
            else kvc.CacheConfig(
                num_layers=config.num_layers,
                num_slots=self.max_num_seqs,
                max_seq_len=self.max_seq_len,
                num_kv_heads=config.num_kv_heads,
                head_dim=config.hd,
                dtype=self.kv_dtype,
            )
        )
        # disaggregation plumbing: fused extract (prefill side) and
        # scatter-in (decode side) programs for both layouts, plus the
        # completed-handoff stash pop_handoff() serves (llm/disagg/)
        from ray_tpu.llm.disagg.scatter import make_handoff_fns

        (self._extract_slots, self._extract_paged,
         self._scatter_slots, self._scatter_paged) = make_handoff_fns()
        self._handoffs: dict[str, dict] = {}

        if mesh is None:
            self.params = params if params is not None else init_params(config, jax.random.PRNGKey(seed))
            if kv_layout == "paged":
                from ray_tpu.llm import paged_kv as pkv

                self.pool = pkv.alloc(self._pcfg)
            else:
                self.cache = kvc.alloc(cache_cfg)
        else:
            param_sh, cache_sh = self._mesh_shardings(mesh)
            if params is not None:
                # host/device arrays go straight to their shards
                self.params = jax.device_put(params, param_sh)
            else:
                # init SHARDED: no single device ever holds the full tree
                # (the whole point of tp for models beyond one chip's HBM)
                self.params = jax.jit(lambda k: init_params(config, k), out_shardings=param_sh)(
                    jax.random.PRNGKey(seed)
                )
            if kv_layout == "paged":
                from ray_tpu.llm import paged_kv as pkv

                self.pool = jax.jit(lambda: pkv.alloc(self._pcfg), out_shardings=cache_sh)()
            else:
                self.cache = jax.jit(lambda: kvc.alloc(cache_cfg), out_shardings=cache_sh)()
        B = self.max_num_seqs
        # per-slot device-side sampling state
        self._temps = np.zeros((B,), np.float32)
        self._top_k = np.zeros((B,), np.int32)
        self._top_p = np.ones((B,), np.float32)
        self._keys = np.array(
            jax.vmap(lambda s: jax.random.key_data(jax.random.PRNGKey(s)))(jnp.arange(B, dtype=jnp.uint32))
        ).astype(np.uint32)
        self._next_tokens = np.zeros((B,), np.int32)  # input token for next decode per slot

        self._slots: list[RequestState | None] = [None] * B
        self._waiting: deque[RequestState] = deque()
        self._requests: dict[str, RequestState] = {}  # guarded-by: _lock
        self._lock = threading.Lock()
        self._auto_id = 0
        self._prefix_cache = (
            PrefixCache(block=prefix_block, max_bytes=prefix_cache_bytes) if enable_prefix_caching else None
        )
        # cluster KV plane (llm/kvplane/): publish stored prefixes, fetch
        # remote hits, deregister on eviction. Remote-tier counters live
        # here (the PrefixCache keeps its local-tier ones).
        self._kv_plane = kv_plane
        # the FULL counter set is seeded here — including the failure and
        # async/prefetch legs — so prefix_cache_stats() tiers never change
        # shape before/after the first error (no lazy .get() minting)
        self._plane_stats = {
            "hits": 0, "tokens_saved": 0, "fetched_bytes": 0,
            "lost": 0, "published_blocks": 0, "published_bytes": 0,
            "errors": 0, "abandoned": 0,
            "prefetched_blocks": 0, "prefetched_bytes": 0, "prefetch_hits": 0,
        }
        # ASYNC cluster-tier fetch (ROADMAP item 3a): admission LAUNCHES
        # lookup+fetch+validate on the fetch worker and keeps planning;
        # the result splices in at a later wave. _fetch_state maps
        # request_id -> in-flight record, guarded-by: _lock; the record
        # dict itself is FILLED by the worker thread (plain assignments,
        # "done" flipped last — atomic under the GIL) and only read at
        # admission once "done" is observed.
        self.prefix_fetch_deadline_s = float(prefix_fetch_deadline_s)
        self._fetch_state: dict[str, dict] = {}
        self._fetch_q = None  # lazy: SimpleQueue + daemon worker on first launch
        self._fetch_thread = None
        # deadline-abandoned fetch records awaiting their worker's
        # terminal resolution: reaped (stats credit only — the request
        # already prefilled locally) at admission and on a stats read.
        # Without the reap, a client fetch budget above the engine
        # deadline means lost/errors are never counted under async.
        self._fetch_zombies: list[dict] = []  # guarded-by: _lock
        # boundary keys minted by the predictive prefetcher
        # (adopt_prefetched): local hits on them count as prefetch hits
        self._prefetched_keys: set[bytes] = set()  # guarded-by: _lock
        # tiered conversation KV (ROADMAP item 3c): suspended
        # conversations spilled out of HBM — request_id -> {"state" (host
        # DRAM tier), "meta", "ref" (object-plane tier), "nbytes", "t"}
        self._suspended: dict[str, dict] = {}  # guarded-by: _lock
        self._suspend_stats = {"suspended": 0, "resumed": 0, "spilled_bytes": 0, "dropped": 0}
        # publishes minted under the engine lock (admission self-heal,
        # remote-fetch republish, prefill store) are deferred here and
        # flushed at the step tail AFTER the lock is released: a publish
        # is serialization + put_owned + a 10s-timeout index RPC, and
        # paying that under self._lock would stall every add_request/
        # abort/stats caller behind the plane (tpulint CCR001)
        self._plane_offers: list[tuple] = []
        if kv_plane is not None:
            if self._prefix_cache is None:
                raise ValueError(
                    "kv_plane is the prefix cache's cluster tier and needs "
                    "enable_prefix_caching=True (remote hits re-store locally)"
                )
            kv_plane.attach(self)
            self._prefix_cache.evict_hook = kv_plane.on_evict
        self.preemption_count = 0

        from ray_tpu._config import get_config

        _c = get_config()
        self._device_resident = bool(_c.llm_device_resident if device_resident is None else device_resident)
        self._batch_prefill = bool(_c.llm_batch_prefill if batch_prefill is None else batch_prefill)
        # in-flight fused step awaiting host readback:
        # (tokens [B] dev, logps [B] dev, [(RequestState, slot), ...])
        self._pending = None
        # the shard_map hot path engages on a PURE tp mesh (other axes
        # would shard dims the per-shard programs assume replicated; a
        # mixed mesh falls back to the GSPMD compilation, fp collectives)
        from ray_tpu.parallel.mesh import axis_size, is_tp_only

        self._tp_fused = (
            mesh is not None and is_tp_only(mesh) and axis_size(mesh, "tp") > 1 and self._device_resident
        )
        if tp_collective == "int8" and not self._tp_fused:
            raise ValueError(
                "tp_collective='int8' quantizes the explicit shard_map all-reduce, which only "
                "exists on the device-resident fused path over a pure tp>=2 mesh "
                "(got mesh=%s, device_resident=%s)" % (getattr(mesh, "axis_names", None), self._device_resident)
            )
        if self._tp_fused and tp_collective == "int8" and config.hidden_size % axis_size(mesh, "tp"):
            raise ValueError(
                f"hidden_size ({config.hidden_size}) must divide by tp ({axis_size(mesh, 'tp')}) "
                "to chunk the int8 quantized all-reduce payload; use tp_collective='fp'"
            )
        if self._device_resident:
            from ray_tpu.llm.model_runner import make_delta_fns, make_fused_fns, make_fused_paged_fns

            tp_mesh = mesh if self._tp_fused else None
            if kv_layout == "paged":
                self._fused_attn, self._fused_append = make_fused_paged_fns(
                    config, mesh=tp_mesh, tp_collective=tp_collective, kv_quant=self.kv_quant,
                    attn_impl=self.attn_kernel,
                )
            else:
                self._fused_step = make_fused_fns(
                    config, mesh=tp_mesh, tp_collective=tp_collective, kv_quant=self.kv_quant
                )
            self._set_lane, self._set_table, self._set_table_cell = make_delta_fns()
            if mesh is None:
                _put = jnp.asarray
            else:
                from jax.sharding import NamedSharding, PartitionSpec as P

                _repl = NamedSharding(mesh, P())
                _put = lambda a: jax.device_put(a, _repl)  # noqa: E731
            # device-resident decode state; host arrays above stay as the
            # scheduler's shadow copies (never re-uploaded wholesale)
            self._dtokens = _put(self._next_tokens)
            self._dkeys = _put(self._keys)
            self._dtemps = _put(self._temps)
            self._dtopk = _put(self._top_k)
            self._dtopp = _put(self._top_p)
            if kv_layout == "paged":
                self._dtables = _put(self._tables)
                self._dlengths = _put(self._lengths)
        self._spec_cfg = None
        if speculative is not None:
            if not self._device_resident:
                raise ValueError(
                    "speculative decoding runs on the device-resident loop only "
                    "(the plain loop is kept untouched as its equivalence oracle)"
                )
            if mesh is not None and not self._tp_fused:
                raise ValueError(
                    "speculative decoding over a mesh needs the shard_map fused path "
                    f"(a pure tp>=2 mesh); got axes {getattr(mesh, 'axis_names', None)}"
                )
            self._init_spec(speculative, _put)
        # serving telemetry plane (llm/telemetry.py): flight recorder +
        # live SLO metrics + request-lifecycle tracing. Host-side only —
        # never forces a device readback (the zero-sync rule, gated at
        # <= 1.05x the uninstrumented step in tests/test_perf_smoke.py).
        # telemetry=False opts the whole plane out (A/B baselines).
        self._last_spec_drain = None
        self._tel = None
        if telemetry:
            from ray_tpu.llm.telemetry import EngineTelemetry

            self._tel = EngineTelemetry(self, telemetry_tags)
            self._tel.register_fused_entries()

    def _init_spec(self, spec_cfg, _put):
        """Speculative decoding state: drafter, adaptive-k controller,
        per-lane device history/effective-k lanes, and the fused verify
        program for this KV layout (llm/spec/)."""
        import jax
        import jax.numpy as jnp

        from ray_tpu.llm.spec import verify as specv
        from ray_tpu.llm.spec.controller import AdaptiveKController, SpecConfig
        from ray_tpu.llm.spec.drafter import ModelDrafter, NGramDrafter

        if not isinstance(spec_cfg, SpecConfig):
            raise TypeError(f"speculative must be a llm.spec.SpecConfig, got {type(spec_cfg).__name__}")
        self._spec_cfg = spec_cfg
        B, k = self.max_num_seqs, spec_cfg.k
        if spec_cfg.drafter == "model":
            dcfg = spec_cfg.draft_config
            if dcfg is None:
                raise ValueError("drafter='model' needs SpecConfig.draft_config (a smaller LlamaConfig)")
            if dcfg.vocab_size != self.config.vocab_size:
                raise ValueError(
                    f"draft vocab ({dcfg.vocab_size}) must match the target's ({self.config.vocab_size})"
                )
            self._drafter = ModelDrafter(dcfg, params=spec_cfg.draft_params, k=k, seed=spec_cfg.draft_seed)
        else:
            self._drafter = NGramDrafter(k=k, n=spec_cfg.ngram)
        if self.mesh is not None and not self._drafter.supports_mesh:
            # the verify step shards like the fused step, but a draft
            # MODEL brings its own weights + slot KV cache + fused
            # k+1-step chain, none of which is mesh-sharded yet
            raise NotImplementedError(
                f"drafter '{self._drafter.kind}' does not support tensor-parallel meshes: the "
                "draft model's params/KV cache and its fused draft_steps chain are not sharded "
                "over tp; use the zero-weight drafter='ngram' (its proposal lanes are replicated)"
            )
        self._drafter.init_slots(B, self.max_seq_len, self.prefill_buckets)
        self._controller = AdaptiveKController(spec_cfg)
        # token-history lanes: prompt + everything emitted on device, one
        # round AHEAD of host emission (the drafter's matching corpus);
        # +k+1 headroom so trailing-round writes never wrap
        self._spec_hist_width = self.max_seq_len + k + 1
        self._dhist = _put(jnp.zeros((B, self._spec_hist_width), jnp.int32))
        self._dhist_len = _put(jnp.zeros((B,), jnp.int32))
        self._dspec_k = _put(jnp.full((B,), k, jnp.int32))
        self._lane_k = np.full((B,), k, np.int32)  # host mirror, updated with the device lane
        tp_mesh = self.mesh if self._tp_fused else None
        if self.kv_layout == "paged":
            self._verify_attn, self._verify_append = specv.make_spec_verify_paged(
                self.config, k, mesh=tp_mesh, tp_collective=self.tp_collective, kv_quant=self.kv_quant,
                attn_impl=self.attn_kernel,
            )
        else:
            self._verify_step = specv.make_spec_verify_slots(
                self.config, k, mesh=tp_mesh, tp_collective=self.tp_collective, kv_quant=self.kv_quant
            )
        self._set_hist = jax.jit(specv.set_hist_row)
        self._set_slot_scalar = jax.jit(specv.set_slot_scalar)
        self._spec_rounds = self._spec_lane_rounds = 0
        self._spec_proposed = self._spec_accepted = self._spec_emitted = 0

    def spec_stats(self) -> dict:
        """Speculation counters (empty when speculative decoding is off):
        verify rounds, proposed/accepted totals, acceptance-rate and
        tokens-per-round means, and each live request's effective k."""
        with self._lock:
            if self._spec_cfg is None:
                return {}
            return {
                "drafter": self._drafter.kind,
                "k": self._spec_cfg.k,
                "rounds": self._spec_rounds,
                "lane_rounds": self._spec_lane_rounds,
                "proposed": self._spec_proposed,
                "accepted": self._spec_accepted,
                "emitted": self._spec_emitted,
                "acceptance_rate": self._spec_accepted / max(self._spec_proposed, 1),
                # per LANE per round: the per-sequence tokens/step multiplier
                "mean_tokens_per_round": self._spec_emitted / max(self._spec_lane_rounds, 1),
                "k_per_request": {
                    rid: kk for rid, kk in self._controller.current().items() if rid in self._requests
                },
            }

    def telemetry(self) -> dict:
        """Flight-recorder snapshot (llm/telemetry.py): per-step ring
        (phase, wall ms, occupancy, queue depth, spec accounting,
        recompile sentinel), finished-request lifecycle records (TTFT /
        queue-wait / per-token ITL samples), recompile counts, tags.
        Empty dict when the engine was built with telemetry=False."""
        if self._tel is None:
            return {}
        return self._tel.snapshot()

    def kv_cache_stats(self) -> dict:
        """KV-cache accounting (the HBM side of serving capacity): cache
        dtype and layout, honest bytes/token (per-head scales included
        for int8), allocated vs occupied HBM, and slot/page occupancy.
        Sits next to spec_stats()/prefix_cache_stats() on the engine and
        the serve replica."""
        from ray_tpu.llm.kv_quant import bytes_per_token

        cfg = self.config
        per_tok = bytes_per_token(cfg.num_layers, cfg.num_kv_heads, cfg.hd, self.kv_dtype)
        with self._lock:
            arrs = self.pool if self.kv_layout == "paged" else self.cache
            allocated = int(sum(int(a.nbytes) for name, a in arrs.items() if name != "length"))
            out = {
                "layout": self.kv_layout,
                "dtype": self.kv_dtype,
                "quantized": self.kv_quant,
                "attn_kernel": self.attn_kernel,
                "bytes_per_token": int(per_tok),
                "allocated_bytes": allocated,
                "slots_total": self.max_num_seqs,
                "slots_in_use": sum(1 for s in self._slots if s is not None),
            }
            if self.kv_layout == "paged":
                # host shadow lengths: exact for every bound lane, no sync
                occupied = int(self._lengths.sum())
                out["page_size"] = self._pcfg.page_size
                out["pages_total"] = self._pcfg.num_pages - 1  # page 0 = trash
                out["pages_free"] = self._page_alloc.free_pages
            else:
                occupied = sum(
                    len(s.prompt_token_ids) + len(s.token_ids) for s in self._slots if s is not None
                )
            out["occupied_tokens"] = occupied
            out["occupied_bytes"] = occupied * int(per_tok)
            return out

    def _mesh_shardings(self, mesh):
        """Tensor-parallel serving (reference capability: the vLLM engine's
        tensor_parallel_size, llm/_internal/serve/engines/vllm/
        vllm_models.py:215-228 — here expressed as GSPMD shardings, no
        NCCL): weights shard by the model's logical axes (heads/kv_heads/
        mlp/vocab -> tp), the KV cache shards its kv_heads dim, and the
        SAME jitted prefill/decode programs compile SPMD over the mesh —
        XLA inserts the tp collectives on ICI."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ray_tpu.models.llama import param_logical_axes
        from ray_tpu.parallel.mesh import ShardingRules, axis_or_none, mesh_axes

        tp = axis_or_none(mesh, "tp")
        tp_size = max(mesh_axes(mesh).get("tp", 1), 1)
        # validate EVERY tp-sharded model dim up front with an actionable
        # message — an indivisible q-head count or MLP width used to fail
        # deep inside GSPMD partitioning with an inscrutable HLO error
        if self.config.num_kv_heads % tp_size != 0:
            raise ValueError(
                f"num_kv_heads ({self.config.num_kv_heads}) must divide by tp ({tp_size}) to shard "
                "the KV cache; pick tp from the divisors of num_kv_heads (or replicate KV by "
                "raising num_kv_heads to match)"
            )
        if self.config.num_heads % tp_size != 0:
            raise ValueError(
                f"num_heads ({self.config.num_heads}) must divide by tp ({tp_size}) to shard the "
                "attention projections (wq/wo split by head); pick tp from the divisors of num_heads"
            )
        if self.config.intermediate_size % tp_size != 0:
            raise ValueError(
                f"intermediate_size ({self.config.intermediate_size}) must divide by tp ({tp_size}) "
                "to shard the MLP (w_gate/w_up/w_down split on the hidden dim); pad "
                "intermediate_size to a multiple of tp"
            )
        if self.config.vocab_size % tp_size != 0:
            raise ValueError(
                f"vocab_size ({self.config.vocab_size}) must divide by tp ({tp_size}) to shard the "
                "embed/unembed tables (and the shard_map decode path's logits gather); pad the "
                "vocab to a multiple of tp"
            )
        rules = ShardingRules()
        param_sh = jax.tree.map(
            lambda axes: NamedSharding(mesh, rules.spec(axes, mesh)),
            param_logical_axes(self.config),
            is_leaf=lambda x: isinstance(x, tuple),
        )
        # both layouts put kv_heads at axis 3: slot rows [L,B,S,kv,hd],
        # paged pool [L,P,page,kv,hd]
        kv_s = NamedSharding(mesh, P(None, None, None, tp, None))
        if getattr(self, "kv_layout", "slots") == "paged":
            cache_sh = {"k": kv_s, "v": kv_s}
        else:
            cache_sh = {"k": kv_s, "v": kv_s, "length": NamedSharding(mesh, P())}
        if getattr(self, "kv_quant", False):
            # scale tensors put kv_heads at axis 2 ([L,B,kv,S] / [L,P,kv,page])
            sc_s = NamedSharding(mesh, P(None, None, tp, None))
            cache_sh["k_scale"] = cache_sh["v_scale"] = sc_s
        return param_sh, cache_sh

    # ------------------------------------------------------------- admission

    def add_request(
        self,
        prompt_token_ids,
        params: SamplingParams | None = None,
        request_id: str | None = None,
        stream: bool = False,
        out_queue=None,
        submitted_at: float | None = None,
    ) -> str:
        """``out_queue`` lets a streaming caller supply its own queue and
        hold a reference BEFORE admission — the request may finish (and be
        dropped from the registry) before add_request even returns to a
        caller racing the stepping thread. ``submitted_at`` (time.time())
        backdates the telemetry clock to the true ingress arrival when a
        front-end queued the request before admitting it here."""
        params = params or SamplingParams()
        with self._lock:
            if request_id is None:
                request_id = f"req-{self._auto_id}"
                self._auto_id += 1
            if len(prompt_token_ids) + params.max_tokens > self.max_seq_len:
                raise ValueError(  # tpulint: disable=ERR002 — request-shape validation at admission: 400-class caller error, not a fleet fault
                    f"prompt ({len(prompt_token_ids)}) + max_tokens ({params.max_tokens}) "
                    f"exceeds max_seq_len ({self.max_seq_len})"
                )
            if self.kv_layout == "paged":
                T = _bucket(len(prompt_token_ids), self.prefill_buckets)
                need = min(T // self._pcfg.page_size + 1, self._pcfg.max_pages_per_seq)
                if need > self._pcfg.num_pages - 1:
                    raise ValueError(  # tpulint: disable=ERR002 — pool-sizing validation at admission: config error the operator must fix, not a serving fault
                        f"prompt needs {need} pages but the pool has "
                        f"{self._pcfg.num_pages - 1}; raise num_pages"
                    )
            st = RequestState(request_id, list(prompt_token_ids), params)
            if stream or out_queue is not None:
                st.out_queue = out_queue if out_queue is not None else queue.SimpleQueue()
            if self._tel is not None:
                self._tel.on_submit(st, submitted_at)
            self._requests[request_id] = st
            self._waiting.append(st)
            return request_id

    def prefix_cache_stats(self) -> dict:
        """Prefix-reuse accounting. Flat keys are the LOCAL cache's
        legacy counters (hits/misses/tokens_saved/evictions/entries/
        bytes); with a cluster KV plane attached the dict grows hit
        TIERS — ``local`` (this replica's cache) and ``remote`` (blocks
        fetched over the object plane: hits, tokens_saved, fetched_bytes,
        lost, published_*) — plus the plane client's own counters under
        ``plane``. Empty dict when prefix caching is off."""
        with self._lock:
            if self._prefix_cache is None:
                return {}
            self._reap_fetch_zombies_locked()
            out = self._prefix_cache.stats()
            out["local"] = {"hits": out["hits"], "tokens_saved": out["tokens_saved"]}
            if self._kv_plane is not None:
                out["remote"] = dict(self._plane_stats, inflight_fetches=len(self._fetch_state))
                out["plane"] = self._kv_plane.stats()
            return out

    # ------------------------------------------- prefill/decode disaggregation

    def add_prefill_request(
        self, prompt_token_ids, request_id: str | None = None, submitted_at: float | None = None
    ) -> str:
        """PREFILL-ONLY admission (disaggregated serving, llm/disagg/).

        The request rides the normal admission + prefill stages — batching
        into the same bucketed forwards as everything else admitted that
        step, prefix-cache reuse included — then finishes with reason
        "handoff": its KV block is extracted into a contiguous buffer
        (fused extract program) and stashed for ``pop_handoff``, and the
        slot/pages recycle immediately. It never enters the decode stage."""
        with self._lock:
            if request_id is None:
                request_id = f"req-{self._auto_id}"
                self._auto_id += 1
            n = len(prompt_token_ids)
            if not 0 < n <= self.prefill_buckets[-1]:
                raise ValueError(f"prompt length {n} outside prefill buckets (max {self.prefill_buckets[-1]})")
            if self.kv_layout == "paged":
                T = _bucket(n, self.prefill_buckets)
                need = min(T // self._pcfg.page_size + 1, self._pcfg.max_pages_per_seq)
                if need > self._pcfg.num_pages - 1:
                    raise ValueError(
                        f"prompt needs {need} pages but the pool has "
                        f"{self._pcfg.num_pages - 1}; raise num_pages"
                    )
            st = RequestState(request_id, list(prompt_token_ids), SamplingParams(max_tokens=1), prefill_only=True)
            if self._tel is not None:
                self._tel.on_submit(st, submitted_at)
            self._requests[request_id] = st
            self._waiting.append(st)
            return request_id

    def pop_handoff(self, request_id: str) -> dict | None:
        """Claim a finished prefill-only request's handoff payload
        (None until the prefill stage has run it). Payload format is
        ``add_prefilled``'s input: k/v [L, T_pad, kv, hd] host arrays,
        n, first-token logits, prompt_token_ids."""
        with self._lock:
            return self._handoffs.pop(request_id, None)

    def prefill_handoff(self, prompt_token_ids, submitted_at: float | None = None) -> dict:
        """Blocking convenience (single-threaded drivers: tests, bench):
        admit a prefill-only request and step until its handoff is ready.
        ``submitted_at`` backdates the telemetry clock to the true ingress
        arrival (it rides the handoff, so the decode side's TTFT spans
        the whole pipeline)."""
        rid = self.add_prefill_request(prompt_token_ids, submitted_at=submitted_at)
        while True:
            outs = self.step()
            kv = self.pop_handoff(rid)
            if kv is not None:
                return kv
            for o in outs:
                if o.request_id == rid and o.finished:
                    raise RuntimeError(f"prefill-only request failed: {o.finish_reason}")

    def prefill_remote(self, prompt_token_ids) -> dict:
        """Prefill-only: compute the prompt's KV and first-token logits and
        return them as HOST arrays for a decode engine to admit
        (reference: python/ray/llm/tests/serve/.../prefill_decode_disagg/ —
        vLLM KV-connector handoff; here the payload rides the object store
        between a prefill replica and its decode replicas)."""
        import jax.numpy as jnp

        n = len(prompt_token_ids)
        T = _bucket(n, self.prefill_buckets)
        toks = np.zeros((1, T), np.int32)
        toks[0, :n] = prompt_token_ids
        logits, ks, vs = self._prefill(self.params, jnp.asarray(toks), jnp.asarray([n], np.int32))
        return {
            "k": np.asarray(ks[:, 0]),
            "v": np.asarray(vs[:, 0]),
            "n": n,
            "logits": np.asarray(logits[0]),
            "prompt_token_ids": list(prompt_token_ids),
        }

    def add_prefilled(
        self,
        kv: dict,
        params: SamplingParams | None = None,
        request_id: str | None = None,
        stream: bool = False,
        out_queue=None,
    ) -> str:
        """Admit a sequence whose prefill ran on another engine; decoding
        starts from the transferred KV without touching the prompt again."""
        params = params or SamplingParams()
        with self._lock:
            if request_id is None:
                request_id = f"req-{self._auto_id}"
                self._auto_id += 1
            prompt = list(kv["prompt_token_ids"])
            if len(prompt) + params.max_tokens > self.max_seq_len:
                raise ValueError(
                    f"prompt ({len(prompt)}) + max_tokens ({params.max_tokens}) "
                    f"exceeds max_seq_len ({self.max_seq_len})"
                )
            st = RequestState(request_id, prompt, params, prefilled=kv)
            if stream or out_queue is not None:
                st.out_queue = out_queue if out_queue is not None else queue.SimpleQueue()
            if self._tel is not None:
                # a handoff payload carries the ORIGINAL submit stamp and
                # trace context, so TTFT spans the whole pipeline and one
                # trace id stitches prefill and decode replicas
                tr = kv.get("trace")
                self._tel.on_submit(
                    st,
                    kv.get("submitted_at"),
                    parent_trace=(tr["trace_id"], tr.get("parent_id")) if isinstance(tr, dict) else None,
                )
            self._requests[request_id] = st
            self._waiting.append(st)
            return request_id

    def abort_request(self, request_id: str) -> bool:
        with self._lock:
            st = self._requests.get(request_id)
            if st is None or st.finished:
                return False
            self._finish(st, "aborted")
            return True

    def has_unfinished(self) -> bool:
        with self._lock:
            return bool(self._waiting) or any(s is not None for s in self._slots) or self._pending is not None

    @property
    def num_waiting(self) -> int:
        return len(self._waiting)

    @property
    def num_running(self) -> int:
        return sum(1 for s in self._slots if s is not None)

    def host_load(self) -> dict:
        """Load snapshot for admission control (serve/overload.py): queue
        depth, slot occupancy, occupied/queued/capacity tokens — all host
        scheduler shadow state, never a device array (the telemetry
        plane's zero-sync rule applies to the actuator too). Queued
        demand counts each waiting request's prompt + max_tokens: the
        admission caps bound BACKLOG, not just live occupancy."""
        with self._lock:
            waiting = len(self._waiting)
            # max_tokens bounds TOTAL generated tokens, so a preempted
            # requeued request's footprint stays prompt + max_tokens
            # (its already-generated tokens are part of that budget, not
            # additional demand)
            queued_tokens = 0
            queued_gen_tokens = 0
            for st in self._waiting:
                queued_tokens += len(st.prompt_token_ids) + st.params.max_tokens
                queued_gen_tokens += st.params.max_tokens
            slots_in_use = sum(1 for s in self._slots if s is not None)
            if self.kv_layout == "paged":
                occupied = int(self._lengths.sum())
                capacity = (self._pcfg.num_pages - 1) * self._pcfg.page_size
            else:
                occupied = sum(
                    len(s.prompt_token_ids) + len(s.token_ids) for s in self._slots if s is not None
                )
                capacity = self.max_num_seqs * self.max_seq_len
        return {
            "queue_depth": waiting,
            "queued_tokens": queued_tokens,
            "queued_gen_tokens": queued_gen_tokens,
            "slots_in_use": slots_in_use,
            "slots_total": self.max_num_seqs,
            "occupied_tokens": occupied,
            "capacity_tokens": capacity,
        }

    def release_handoffs(self) -> int:
        """Drop every stashed (never-popped) handoff payload. Replica
        drain calls this after admission stops: nothing will ever pop
        them, and the host arrays would otherwise pin their bytes for
        the replica's remaining life. Returns how many were dropped."""
        with self._lock:
            n = len(self._handoffs)
            self._handoffs.clear()
            return n

    # ------------------------------------------------------- live migration

    def checkpoint_request(self, request_id: str) -> dict:
        """Extract one in-flight request's COMPLETE resumable state
        (llm/migrate.py): the KV block covering every attended position
        via the fused extract programs (int8 caches ship int8 values +
        per-head wire scales), the emitted token/logprob stream, the
        lane's live PRNG key, the sampling params, and the speculative
        controller's sticky EMA/effective-k. A peer engine's
        ``restore_request`` continues generation token-identically.

        The one-step-delayed emission is settled FIRST: the in-flight
        fused step (or speculative round) drains here, so the checkpoint
        holds every token the device has minted — the splice-dedup half
        of the migration contract (restore emits nothing at admission;
        the next token comes from the peer's first decode step).

        Pure snapshot: the request keeps running locally until the
        caller finishes it (``finish_migrated``). Raises MigrationError
        for state that cannot move — a finished/unknown request, a
        prefill-only stub (its handoff already IS the transferable
        state), a streaming consumer, or a WAITING sampled request with
        generated tokens (its live key existed only on a bound lane; a
        cold re-admission would resample the suffix — the router's
        re-prefill leg is the token-identical fallback there)."""
        with self._lock:
            return self._checkpoint_locked(request_id)

    def _checkpoint_locked(self, request_id: str) -> dict:
        # holds-lock: _lock — shared by checkpoint_request (migration)
        # and suspend_request (tiered conversation KV), which must
        # checkpoint AND finish under ONE lock acquisition so no decode
        # step can advance the state between snapshot and retirement
        from ray_tpu.llm.migrate import LIVE_KIND, MigrationError

        st = self._requests.get(request_id)
        if st is None or st.finished:
            raise MigrationError(f"request {request_id!r} is not in flight")
        if st.prefill_only:
            raise MigrationError("prefill-only requests hand off, they do not migrate")
        if st.out_queue is not None:
            raise MigrationError(
                "streaming requests cannot migrate (the consumer holds a live token queue)"
            )
        if self._device_resident and self._pending is not None:
            prev, self._pending = self._pending, None
            if self._spec_cfg is not None:
                self._drain_spec(prev)
            else:
                self._drain(prev)
            if st.finished:
                raise MigrationError(
                    f"request {request_id!r} finished while settling the in-flight step"
                )
        p = st.params
        state: dict = {
            "kind": LIVE_KIND,
            "prompt_token_ids": list(st.prompt_token_ids),
            "emitted_token_ids": list(st.token_ids),
            "emitted_logprobs": [float(x) for x in st.logprobs],
            "sampling": {
                "max_tokens": int(p.max_tokens),
                "temperature": float(p.temperature),
                "top_k": int(p.top_k),
                "top_p": float(p.top_p),
                "stop_token_ids": [int(t) for t in p.stop_token_ids],
                "seed": None if p.seed is None else int(p.seed),
                "logprobs": bool(p.logprobs),
                "priority": int(p.priority),
            },
            "spec": None,
        }
        if st.t_submit:
            state["submitted_at"] = float(st.t_submit)
        if st.trace is not None:
            state["trace"] = {"trace_id": st.trace[0], "parent_id": st.trace[1]}
        if self._spec_cfg is not None:
            exp = self._controller.export(request_id)
            if exp is not None:
                state["spec"] = {"ema": exp[0], "k": int(exp[1])}
        if st.slot < 0:
            # COLD checkpoint: the request is waiting (queued or
            # recompute-preempted) — no bound lane, no live KV/key.
            # The peer re-admits prompt+generated exactly like a
            # local recompute preemption: token-identical for greedy
            # (and for fresh requests with nothing generated yet).
            if st.token_ids and p.temperature > 0.0:
                raise MigrationError(
                    "cannot cold-checkpoint a sampled request with generated tokens "
                    "(its live PRNG key exists only on a bound lane); the router's "
                    "re-prefill leg is the token-identical fallback"
                )
            if self._tel is not None:
                self._tel.on_migration("checkpointed", 0)
            return state
        slot = st.slot
        l = len(st.prompt_token_ids) + len(st.token_ids) - 1
        # the authoritative cache length must agree with the host
        # view before the block can claim to cover l positions
        if self.kv_layout == "paged":
            l_auth = int(self._lengths[slot])
        else:
            l_auth = int(np.asarray(self.cache["length"][slot]))
        if l_auth != l:
            raise MigrationError(
                f"inconsistent decode state for {request_id!r}: cache length "
                f"{l_auth} != prompt + emitted - 1 = {l}"
            )
        T = _bucket(l, self.prefill_buckets)
        if self.kv_layout == "paged":
            page = self._pcfg.page_size
            # table cells past the allocated pages are 0 (trash):
            # the gather's tail is garbage the peer masks by length
            row = np.asarray(self._tables[slot][: T // page], np.int32)
            out = self._extract_paged(self.pool, row)
        else:
            out = self._extract_slots(self.cache, np.int32(slot), T)
        state.update(k=np.asarray(out[0]), v=np.asarray(out[1]), n=l)
        if len(out) == 4:
            state.update(k_scale=np.asarray(out[2]), v_scale=np.asarray(out[3]))
        # the LIVE key: on the device-resident loop it advanced on
        # device (seeded lanes included — restore must continue the
        # sequence, never reset from the seed); sync keeps it on host
        if self._device_resident:
            state["rng_key"] = np.asarray(self._dkeys[slot]).astype(np.uint32)
        else:
            state["rng_key"] = np.asarray(self._keys[slot], np.uint32)
        if self._tel is not None:
            nbytes = int(state["k"].nbytes + state["v"].nbytes)
            if state.get("k_scale") is not None:
                nbytes += int(state["k_scale"].nbytes + state["v_scale"].nbytes)
            self._tel.on_migration("checkpointed", nbytes)
        return state

    # ------------------------------------------------ tiered conversation KV

    def suspend_request(self, request_id: str, *, publish: bool = True) -> dict:
        """Spill an IDLE conversation's KV out of HBM (ROADMAP item 3c):
        checkpoint the request through the migration codec (fused
        extract, int8 wire, live PRNG key) and retire its slot/pages,
        keeping the state in host DRAM — and, with ``publish=True``, on
        the object plane too (``migrate.publish``), so any replica can
        resume it. ``resume_suspended`` scatters the block back in
        instead of re-prefilling: resume cost is one transfer, flat in
        history length.

        Checkpoint + retire happen under ONE lock acquisition (no decode
        step can advance the state in between); the plane publish runs
        OUTSIDE the lock, and a publish failure degrades to the DRAM
        tier (ref=None), never an error. Raises MigrationError when the
        request cannot suspend (unknown/finished, streaming, prefill-
        only, cold-sampled-with-tokens) or when a chaos rule at
        ``llm.suspend`` drops the spill decision — in every refusal the
        conversation is untouched and still RUNNING."""
        from ray_tpu import chaos
        from ray_tpu.llm import migrate as _mig

        # the chaos gate sits OUTSIDE the lock and BEFORE the snapshot:
        # an injected drop/fault models "the spill path is down" and must
        # degrade to the typed refusal with zero request state mutated
        try:
            ok = chaos.apply("llm.suspend")
        except _mig.MigrationError:
            raise
        except Exception as e:  # noqa: BLE001 — injected fault, typed on the way out
            raise _mig.MigrationError(f"suspend of {request_id!r} faulted: {e}") from e
        if not ok:
            raise _mig.MigrationError(f"suspend of {request_id!r} dropped (chaos)")
        with self._lock:
            state = self._checkpoint_locked(request_id)
            st = self._requests[request_id]
            self._finish(st, "suspended")
            nbytes = _mig.state_nbytes(state)
            self._suspend_stats["suspended"] += 1
            self._suspend_stats["spilled_bytes"] += nbytes
            rec = {"state": state, "meta": None, "ref": None, "nbytes": nbytes, "t": time.time()}
            self._suspended[request_id] = rec
        if self._tel is not None:
            self._tel.on_kv_spill(nbytes)
        if publish:
            try:
                meta, ref = _mig.publish(state)
                with self._lock:
                    rec["ref"], rec["meta"] = ref, meta
            except Exception:  # tpulint: disable=ERR001 — noqa: BLE001 — plane publish is opportunism: the DRAM tier copy stays valid, resume still works
                pass
        return {"request_id": request_id, "nbytes": nbytes, "published": rec["ref"] is not None}

    def resume_suspended(
        self, request_id: str, stream: bool = False, out_queue=None
    ) -> str:
        """Re-admit a suspended conversation under its ORIGINAL request
        id: the spilled block scatters back in through the transferred-KV
        admission path (restore_request — exact PRNG key, no re-prefill,
        no token re-emission), racing concurrent admission safely
        because restore just appends to the waiting queue under the
        lock. Prefers the DRAM copy; falls back to fetching the plane
        ref. Raises MigrationError for an unknown suspension or when
        both tiers are gone (MigrationLostError from the fetch)."""
        from ray_tpu.llm import migrate as _mig

        with self._lock:
            rec = self._suspended.pop(request_id, None)
        if rec is None:
            raise _mig.MigrationError(f"no suspended conversation {request_id!r}")
        state = rec["state"]
        if state is None:
            try:
                state = _mig.fetch(rec["ref"], rec["meta"])
            except Exception:
                with self._lock:
                    self._suspend_stats["dropped"] += 1
                raise
        try:
            rid = self.restore_request(
                state, request_id=request_id, stream=stream, out_queue=out_queue
            )
        except Exception:
            with self._lock:  # refused restore: keep the record claimable
                self._suspended.setdefault(request_id, rec)
            raise
        with self._lock:
            self._suspend_stats["resumed"] += 1
        return rid

    def suspended_requests(self) -> list:
        """Request ids currently spilled to the conversation-KV tier."""
        with self._lock:
            return sorted(self._suspended)

    def drop_suspended(self, request_id: str) -> bool:
        """Discard a suspended conversation (client gone, TTL expired):
        frees the DRAM copy; the plane ref ages out with its owner."""
        with self._lock:
            rec = self._suspended.pop(request_id, None)
            if rec is not None:
                self._suspend_stats["dropped"] += 1
            return rec is not None

    def suspend_stats(self) -> dict:
        with self._lock:
            return dict(self._suspend_stats, held=len(self._suspended))

    def finish_migrated(self, request_id: str) -> bool:
        """Finish a checkpointed request locally with reason "migrated"
        (its continuation now lives on a peer): slot/pages recycle, spec
        state drops, stream consumers get their sentinel. The abort
        twin for the migration path — telemetry counts the reason
        separately so evacuations never read as error-rate."""
        with self._lock:
            st = self._requests.get(request_id)
            if st is None or st.finished:
                return False
            self._finish(st, "migrated")
            return True

    def restore_request(
        self,
        state,
        request_id: str | None = None,
        stream: bool = False,
        out_queue=None,
    ) -> str:
        """Splice a checkpointed request into THIS engine and continue
        generation token-identically (llm/migrate.py). ``state`` is the
        validated live_state dict — or an ObjectRef straight off the
        object plane (fetched + decoded here, bounded retry).

        A HOT checkpoint scatters its KV block through the existing
        transferred-KV admission path (fused scatter-in, transparent
        requant across producer/consumer cache dtypes), then
        ``_bind_resume`` rebinds the lane from the checkpoint: exact
        PRNG key, last emitted token as the next decode input, sticky
        spec k — and emits NOTHING (no dup, no drop at the splice). A
        COLD checkpoint re-admits prompt+generated like a recompute
        preemption. Raises MigrationError when the state cannot fit this
        engine's geometry."""
        from ray_tpu.llm import migrate as _mig

        if not isinstance(state, dict):
            state = _mig.fetch(state)
        _mig.check_state(state)
        params = _mig.params_of(state)
        prompt = [int(t) for t in state["prompt_token_ids"]]
        emitted = [int(t) for t in state["emitted_token_ids"]]
        hot = state.get("k") is not None
        with self._lock:
            if request_id is None:
                request_id = f"req-{self._auto_id}"
                self._auto_id += 1
            if len(prompt) + params.max_tokens > self.max_seq_len:
                raise _mig.MigrationError(
                    f"prompt ({len(prompt)}) + max_tokens ({params.max_tokens}) "
                    f"exceeds this engine's max_seq_len ({self.max_seq_len})"
                )
            st = RequestState(request_id, prompt, params)
            st.token_ids = list(emitted)
            st.logprobs = [float(x) for x in state.get("emitted_logprobs", [])]
            st.t_restore = time.time()
            if stream or out_queue is not None:
                st.out_queue = out_queue if out_queue is not None else queue.SimpleQueue()
            nbytes = 0
            if hot:
                T_pad = int(state["k"].shape[1])
                if T_pad > self.max_seq_len:
                    raise _mig.MigrationError(
                        f"checkpoint block width {T_pad} exceeds this engine's cache row "
                        f"({self.max_seq_len}); the producer's bucket ladder is wider"
                    )
                if self.kv_layout == "paged":
                    page = self._pcfg.page_size
                    need = min(-(-T_pad // page) + 1, self._pcfg.max_pages_per_seq)
                    if need > self._pcfg.num_pages - 1:
                        raise _mig.MigrationError(
                            f"checkpoint needs {need} pages but the pool has "
                            f"{self._pcfg.num_pages - 1}"
                        )
                pref = {"k": state["k"], "v": state["v"], "n": int(state["n"]),
                        "prompt_token_ids": prompt}
                if state.get("k_scale") is not None:
                    pref["k_scale"] = state["k_scale"]
                    pref["v_scale"] = state["v_scale"]
                st.prefilled = pref
                st.resume = {
                    "rng_key": np.asarray(state["rng_key"], np.uint32),
                    "spec": state.get("spec"),
                }
                nbytes = int(state["k"].nbytes + state["v"].nbytes)
                if state.get("k_scale") is not None:
                    nbytes += int(state["k_scale"].nbytes + state["v_scale"].nbytes)
            elif self._spec_cfg is not None and state.get("spec"):
                # cold restore: the sticky spec state still survives (the
                # eventual bind's _spec_admit reads it back from the
                # controller under the NEW request id)
                sp = state["spec"]
                self._controller.restore(request_id, sp.get("ema"), sp.get("k"))
            if self._tel is not None:
                tr = state.get("trace")
                self._tel.on_submit(
                    st,
                    state.get("submitted_at"),
                    parent_trace=(tr["trace_id"], tr.get("parent_id")) if isinstance(tr, dict) else None,
                )
                self._tel.on_migration("restored", nbytes)
            self._requests[request_id] = st
            self._waiting.append(st)
            return request_id

    # --------------------------------------------------------------- engine

    def _finish(self, st: RequestState, reason: str):
        st.finished = True
        st.finish_reason = reason
        # a prefix fetch still in flight for this request is orphaned:
        # drop the record (the worker's writes into it become no-ops)
        self._fetch_state.pop(st.request_id, None)
        if self._tel is not None:
            self._tel.on_finish(st, reason)
        if st.prefill_only and reason != "handoff":
            # aborted/errored prefill-only request: drop any stashed block
            # (nobody will ever pop it)
            self._handoffs.pop(st.request_id, None)
        if self._spec_cfg is not None:
            self._controller.forget(st.request_id)
        if st.slot >= 0:
            if self.kv_layout == "paged":
                self._release_slot_pages(st.slot)
            self._slots[st.slot] = None
            st.slot = -1
        if st.out_queue is not None:
            st.out_queue.put(None)  # sentinel

    # ------------------------------------------------------ paged plumbing
    def _push_table(self, slot: int):
        """Scatter one slot's block-table row + length into the device
        decode state (the delta that replaces whole-array re-uploads)."""
        import jax.numpy as jnp

        self._dtables, self._dlengths = self._set_table(
            self._dtables,
            self._dlengths,
            np.int32(slot),
            jnp.asarray(self._tables[slot]),
            np.int32(self._lengths[slot]),
        )

    def _release_slot_pages(self, slot: int):
        self._page_alloc.free(self._slot_pages[slot])
        self._slot_pages[slot] = []
        self._tables[slot, :] = 0
        self._lengths[slot] = 0
        if self._device_resident:
            # point the lane at the trash page so in-flight/idle steps
            # scatter harmlessly instead of into recycled pages
            self._push_table(slot)

    def _preempt_for(self, need: int, exclude: RequestState | None = None) -> bool:
        """Recompute-preemption (vLLM's default policy): the YOUNGEST
        running sequence frees its pages and re-queues with its generated
        tokens folded into the prompt. Returns True once >= need pages
        are free."""
        while self._page_alloc.free_pages < need:
            victims = [s for s in self._slots if s is not None and s is not exclude]
            if not victims:
                return False
            victim = max(victims, key=lambda s: s.admit_seq)
            victim.preemptions += 1
            self.preemption_count += 1
            slot = victim.slot
            self._release_slot_pages(slot)
            self._slots[slot] = None
            victim.slot = -1
            self._waiting.appendleft(victim)
        return True

    def _paged_grow(self):
        """Before a decode step: any sequence whose upcoming appends
        cross into unallocated pages gets them (preempting the youngest
        OTHER sequence when the pool is dry; a sequence that cannot grow
        at all preempts itself back to waiting). Plain decode looks ahead
        one token; a speculative lane needs up to k+1 appends for the
        still-pending round plus k+1 for the round about to dispatch,
        capped at the request's own prompt+max_tokens budget (KV past it
        is never attended, so those writes may land in the trash page)."""
        page = self._pcfg.page_size
        spec = self._spec_cfg is not None
        pending_k: dict = {}
        if self._device_resident and self._pending is not None:
            for entry in self._pending[-1]:  # lanes: (st, slot[, k_eff])
                pending_k[id(entry[0])] = entry[2] if len(entry) > 2 else 0
        for st in [s for s in self._slots if s is not None]:
            if st.slot < 0 or self._slots[st.slot] is not st:
                continue  # preempted by an earlier iteration's _preempt_for
            if id(st) in pending_k and len(st.token_ids) + 1 >= st.params.max_tokens:
                # the not-yet-drained round finishes this sequence at
                # max_tokens: this call's step is its discarded trailing
                # step — never grow (let alone PREEMPT a live sequence)
                # for it; the unallocated-page write lands in the trash
                # page. Matches the sync oracle, where the finish would
                # already have freed the slot.
                continue
            slot = st.slot
            l = int(self._lengths[slot])
            if spec:
                look = int(self._lane_k[slot]) + 1
                if id(st) in pending_k:
                    look += pending_k[id(st)] + 1
                budget = len(st.prompt_token_ids) + st.params.max_tokens
                horizon = min(l + look, max(budget, l))
            else:
                horizon = l + 1
            if horizon <= l:
                continue
            target_pg = (horizon - 1) // page + 1
            if not spec and target_pg > self._pcfg.max_pages_per_seq:
                self._finish(st, "length")  # cache row exhausted
                continue
            target_pg = min(target_pg, self._pcfg.max_pages_per_seq)
            while len(self._slot_pages[slot]) < target_pg:
                got = self._page_alloc.alloc(1)
                if got is None and self._preempt_for(1, exclude=st):
                    got = self._page_alloc.alloc(1)
                if got is None:
                    # nothing left to preempt: this sequence itself re-queues
                    st.preemptions += 1
                    self.preemption_count += 1
                    self._release_slot_pages(slot)
                    self._slots[slot] = None
                    st.slot = -1
                    self._waiting.appendleft(st)
                    break
                pg_ix = len(self._slot_pages[slot])
                self._slot_pages[slot].extend(got)
                self._tables[slot, pg_ix] = got[0]
                if self._device_resident:
                    self._dtables = self._set_table_cell(
                        self._dtables, np.int32(slot), np.int32(pg_ix), np.int32(got[0])
                    )

    def _pages_needed(self, st: RequestState, pref, prompt) -> int | None:
        """Pages a request needs to admit (prompt bucket + one decode
        headroom page). None = can never fit; the request is finished with
        an error instead of spinning in the admission loop forever."""
        page = self._pcfg.page_size
        n = len(prompt)
        if st.prefilled is not None:
            # the transferred KV is bucket-padded; pages cover the padding
            # too (garbage tail is masked by length, overwritten by appends)
            T_pad = -(-int(st.prefilled["k"].shape[1]) // page) * page
            need = T_pad // page + 1
        elif pref is not None:
            n_p = pref[2]
            Tm = _bucket(n - n_p, self.prefill_buckets)
            need = (n_p + Tm) // page + 1
        else:
            T = _bucket(n, self.prefill_buckets)
            need = T // page + 1
        # the +1 decode-headroom page must not overflow the table row
        # (a prompt bucket that already fills it grows via _paged_grow,
        # which finishes the sequence at the row edge)
        need = min(need, self._pcfg.max_pages_per_seq)
        if need > self._pcfg.num_pages - 1:
            self._finish(st, f"error: needs {need} pages, pool holds {self._pcfg.num_pages - 1}")
            return None
        return need

    def _stage_admission(self) -> list:  # holds-lock: _lock (step pipeline)
        """ADMISSION stage (planning only, no forwards): admit every
        waiting request that fits right now (FIFO; a head-of-line request
        that cannot get pages blocks the wave — vLLM semantics: waiting
        requests wait for free blocks, ADMISSION never preempts running
        sequences). Reserves slots/pages and resolves prefix-cache hits;
        returns the wave of (st, slot, pref, pages, prompt) plans the
        prefill stage executes."""
        wave: list[tuple] = []  # (st, slot, pref, pages, prompt)
        if self._fetch_zombies:
            self._reap_fetch_zombies_locked()
        # requests skipped THIS wave on an in-flight async prefix fetch:
        # re-queued at the front (original order) after the loop so they
        # keep FIFO priority without blocking followers behind a transfer
        deferred: list[RequestState] = []
        while self._waiting and None in self._slots:
            st = self._waiting[0]
            if st.finished:  # aborted while waiting
                self._waiting.popleft()
                self._fetch_state.pop(st.request_id, None)
                continue
            slot = self._slots.index(None)
            # preempted sequences resume with generated tokens as prompt tail
            prompt = st.prompt_token_ids + st.token_ids
            # pref: (k, v, n_valid, k_scale, v_scale) — scales None except
            # for an int8-wire block fetched over the cluster KV plane
            # (the fused insert requants transparently either way). The
            # resolution caches on the request so a head-of-line wait
            # (paged pool full -> break below) never re-looks-up, never
            # refetches, and counts its hit exactly once per request
            pref = None
            if st.prefilled is None and self._prefix_cache is not None and not st.token_ids:
                cached = st.cached_pref
                if cached is not None and cached[0] is _PREF_MISS and (
                    cached[1] != self._prefix_cache.gen or time.time() >= cached[2]
                ):
                    cached = None  # keys minted / miss lease lapsed: re-resolve
                if cached is not None:
                    pref = None if cached[0] is _PREF_MISS else cached
                else:
                    # suffix-overrun guard, applied INSIDE the lookup so a
                    # rejected longest boundary falls through to the next
                    # shorter LOCAL one (never off to a remote fetch of
                    # bytes this replica already holds)
                    local = self._prefix_cache.lookup(
                        prompt, admissible=lambda n_p: self._prefix_fits(n_p, len(prompt))
                    )
                    if local is not None:
                        pref = local + (None, None)
                        if self._tel is not None:
                            self._tel.on_prefix_hit("local", local[2])
                        if self._prefetched_keys:
                            # attribution: a hit served by a block the
                            # predictive prefetcher pulled in ahead of
                            # demand (cheap: only computed while any
                            # prefetched key is live in the cache)
                            kb = prefix_key(token_bytes(tuple(int(t) for t in prompt)), local[2])
                            if kb in self._prefetched_keys:
                                self._plane_stats["prefetch_hits"] += 1
                                if self._tel is not None:
                                    self._tel.on_prefetch_hit()
                        if self._kv_plane is not None:
                            # publish self-heal: a boundary whose original
                            # publish failed transiently would otherwise
                            # stay cluster-invisible forever (store never
                            # re-mints cached keys) — the client filters
                            # already-published bounds, so this is a cheap
                            # no-op in steady state
                            self._plane_publish(prompt[: local[2]], local[0], local[1])
                    elif self._kv_plane is not None:
                        # cluster tier, ASYNC (ROADMAP item 3a): the
                        # lookup+fetch runs on the engine's fetch worker,
                        # NEVER under this lock. First sight launches it
                        # and DEFERS the request (followers keep
                        # admitting, their prefills overlap the
                        # transfer); a landed result splices in here; a
                        # fetch outliving its deadline abandons to a
                        # plain local prefill — zero hangs by
                        # construction. Any failure inside degrades to
                        # pref = None.
                        rec = self._fetch_state.get(st.request_id)
                        if rec is None and not self._kv_plane.index_down():
                            rec = self._launch_prefix_fetch(st.request_id, prompt)
                        if rec is not None:
                            if rec["done"]:
                                pref = self._splice_prefix_fetch(st, rec, prompt)
                            elif time.time() < rec["deadline"]:
                                self._waiting.popleft()
                                deferred.append(st)
                                continue
                            else:
                                # wedged plane: abandon the fetch. The
                                # record moves to the zombie list so the
                                # worker's TERMINAL resolution still
                                # lands in the stats (with the default
                                # client fetch budget above the engine
                                # deadline, lost/errors would otherwise
                                # NEVER be credited under async)
                                self._fetch_state.pop(st.request_id, None)
                                self._plane_stats["abandoned"] += 1
                                self._fetch_zombies.append(rec)
                    if pref is None:
                        # plane engines re-check after a short lease: a
                        # PEER's publish can't bump the local generation
                        exp = (time.time() + 1.0) if self._kv_plane is not None else float("inf")
                        st.cached_pref = (_PREF_MISS, self._prefix_cache.gen, exp)
                    else:
                        st.cached_pref = pref
            pages = None
            if self.kv_layout == "paged":
                need = self._pages_needed(st, pref, prompt)
                if need is None:
                    self._waiting.popleft()  # finished with an error
                    continue
                if self._page_alloc.free_pages < need:
                    break  # pool full: head-of-line waits
                pages = self._page_alloc.alloc(need)
                if pages is None:
                    break
            self._waiting.popleft()
            st.cached_pref = None  # admission consumes the cached resolution
            self._slots[slot] = st  # reserve; _bind_slot fills the rest
            wave.append((st, slot, pref, pages, prompt))
        for st in reversed(deferred):
            self._waiting.appendleft(st)  # original FIFO order restored
        return wave

    def _prefix_fits(self, n_p: int, prompt_len: int) -> bool:
        """Suffix-overrun admissibility for a prefix boundary: the
        bucket-padded remaining suffix must fit the cache row, or the
        extend's dynamic_update_slice would CLAMP the start and silently
        corrupt the prefix. The ONE predicate both the local lookup and
        the remote candidate filter apply — the two tiers can never
        disagree on admissibility."""
        return n_p + _bucket(prompt_len - n_p, self.prefill_buckets) <= self.max_seq_len

    # ------------------------------------------------ async cluster fetch

    def _ensure_fetch_worker(self):  # holds-lock: _lock (via admission)
        if self._fetch_thread is not None and self._fetch_thread.is_alive():
            return
        self._fetch_q = queue.SimpleQueue()
        t = threading.Thread(target=self._fetch_worker, daemon=True, name="llm-prefix-fetch")
        self._fetch_thread = t
        t.start()

    def _fetch_worker(self):
        """Drains prefix-fetch jobs OFF the engine lock: the index RPC,
        the multi-MB object-plane transfer, the token verification and
        the dequant all run here while step() keeps prefilling/decoding —
        the transfer overlaps compute instead of serializing admission
        (ROADMAP item 3a; "The Big Send-off" schedules transfers against
        compute the same way)."""
        while True:
            job = self._fetch_q.get()
            if job is None:
                return
            rec, prompt = job
            try:
                self._run_prefix_fetch(rec, prompt)
            except BaseException:  # noqa: BLE001 — a dying worker would wedge every deferral
                rec["error"] = True
                rec["done"] = True

    def _launch_prefix_fetch(self, request_id: str, prompt) -> dict:
        """Mint the in-flight record and hand the job to the fetch
        worker (called at admission, under the engine lock — the launch
        is a queue put, nothing blocking). The record is the ONLY shared
        state: the worker fills it lock-free and flips ``done`` last;
        admission reads it once ``done`` is observed, or abandons it at
        ``deadline`` (a wedged plane degrades to local prefill)."""
        rec = {
            "request_id": request_id, "done": False, "error": False, "lost": False,
            "pref": None, "restore": None, "nbytes": 0, "n_p": 0,
            "t0": time.time(), "t1": 0.0,
            "deadline": time.time() + self.prefix_fetch_deadline_s,
        }
        self._fetch_state[request_id] = rec
        self._ensure_fetch_worker()
        self._fetch_q.put((rec, [int(t) for t in prompt]))
        return rec

    def _run_prefix_fetch(self, rec: dict, prompt: list) -> None:
        """One cluster-tier resolution, STRICTLY lock-free (runs on the
        fetch worker; a bench's synchronous shim may call it inline):
        candidates, index lookup, object-plane fetch, token verify and
        dequant fill ``rec`` — every engine-state mutation (counters,
        cache re-store, republish) waits for ``_splice_prefix_fetch``
        under the lock. EVERY failure mode (index down, block evicted,
        owner dead, token mismatch, dequant error) degrades to a plain
        local prefill, never an engine error or a hang."""
        try:
            self._resolve_remote_prefix(rec, prompt)
        except Exception:  # noqa: BLE001 — the plane is an accelerator, never a dependency
            rec["error"] = True
        rec["t1"] = time.time()
        if self._tel is not None:
            # the fetch span lands in the flight recorder: overlap with
            # concurrent step records is the item-3a evidence
            self._tel.on_prefix_fetch(rec["t0"], rec["t1"], rec["n_p"], rec["pref"] is not None)
        rec["done"] = True

    def _resolve_remote_prefix(self, rec: dict, prompt: list) -> None:
        from ray_tpu.llm.kvplane.index import boundary_keys

        block = self._prefix_cache.block
        # candidate boundaries whose bucket-padded suffix still fits the
        # cache row (the SAME _prefix_fits guard as the local-hit path)
        cands = [
            (n, key) for n, key in boundary_keys(prompt, block)
            if self._prefix_fits(n, len(prompt))
        ]
        if not cands:
            return
        hit = self._kv_plane.lookup(cands)
        if hit is None:
            return
        # producer-bucket width gate BEFORE the transfer: the routed
        # meta already carries the block shape, so a producer whose
        # bucket ladder is narrower than our pad for this boundary
        # (heterogeneous fleet config) costs nothing, not a multi-MB
        # fetch discarded post-hoc
        shape = tuple(hit.get("meta", {}).get("shape") or ())
        if len(shape) > 1 and shape[1] < _bucket(int(hit["n"]), self.prefill_buckets):
            return
        payload = self._kv_plane.fetch(hit)
        if payload is None:
            # evicted/lost remote block after the bounded retries: the
            # client already reported the dead route to the index
            rec["lost"] = True
            return
        n_p = int(hit["n"])
        # token-for-token verification — the same collision guarantee the
        # local cache keeps: a hash collision (or a stale publish) must
        # never serve a foreign prompt's KV. The prompt snapshot is the
        # launch-time one, which cannot drift: only token-less requests
        # (st.prefilled is None, no generated tokens) ever launch.
        if payload["n"] < n_p or payload["prompt_token_ids"][:n_p] != [int(t) for t in prompt[:n_p]]:
            return
        pad = _bucket(n_p, self.prefill_buckets)
        if payload["k"].shape[1] < pad:
            return  # producer's bucket ladder narrower than ours
        k_w, v_w = payload["k"][:, :pad], payload["v"][:, :pad]
        k_sc, v_sc = payload.get("k_scale"), payload.get("v_scale")
        if k_sc is not None:
            k_sc, v_sc = k_sc[:, :, :pad], v_sc[:, :, :pad]
        wire_int8 = str(k_w.dtype) == "int8"
        rec["n_p"] = n_p
        rec["nbytes"] = int(hit.get("meta", {}).get("nbytes") or (k_w.nbytes + v_w.nbytes))
        # dequant for the local re-store is PURE compute — do it here on
        # the worker; only when a later local hit reproduces the same
        # cache bytes: fp wire re-inserts exactly; int8 wire dequantized
        # re-quantizes byte-identically into an int8 cache (kv_quant
        # idempotence) — an fp cache re-storing a dequantized int8 block
        # would drift from its own prefill oracle
        if wire_int8 == self.kv_quant:
            import jax.numpy as jnp

            if wire_int8:
                rec["restore"] = self._kv_plane.dequantize_wire(k_w, v_w, k_sc, v_sc)
            else:
                rec["restore"] = (jnp.asarray(k_w), jnp.asarray(v_w))
        rec["pref"] = (k_w, v_w, n_p, k_sc, v_sc)

    def _reap_fetch_zombies_locked(self) -> None:  # holds-lock: _lock
        # credit the terminal resolution of
        # deadline-abandoned fetches once the worker finishes. A landed
        # hit counts NOTHING here (the request already prefilled locally
        # and the bytes are discarded — "abandoned" is its record);
        # lost/error keep their meaning: the plane lost a routed block /
        # the resolution faulted, whether or not anyone waited for it.
        if not self._fetch_zombies:
            return
        live = []
        for rec in self._fetch_zombies:
            if not rec["done"]:
                live.append(rec)
            elif rec["error"]:
                self._plane_stats["errors"] += 1
            elif rec["lost"]:
                self._plane_stats["lost"] += 1
        self._fetch_zombies = live

    def _splice_prefix_fetch(self, st: RequestState, rec: dict, prompt):
        """Apply a landed fetch at admission (under the engine lock):
        counters and telemetry, the local PrefixCache re-store, and the
        republish offer — everything the lock-free worker deferred.
        Returns the pref tuple ``(k, v, n_valid, k_scale, v_scale)`` for
        the fused insert/transparent-requant path, or None (miss/lost/
        error: the request degrades to a plain local prefill)."""
        self._fetch_state.pop(st.request_id, None)
        if rec["error"]:
            self._plane_stats["errors"] += 1
            return None
        if rec["lost"]:
            self._plane_stats["lost"] += 1
            return None
        pref = rec["pref"]
        if pref is None:
            return None
        n_p = int(pref[2])
        self._plane_stats["hits"] += 1
        self._plane_stats["tokens_saved"] += n_p
        self._plane_stats["fetched_bytes"] += rec["nbytes"]
        if self._tel is not None:
            self._tel.on_prefix_hit("remote", n_p, rec["nbytes"])
        if rec["restore"] is not None:
            k_fp, v_fp = rec["restore"]
            stored = self._prefix_cache.store(prompt[:n_p], k_fp, v_fp, self.prefill_buckets)
            if stored is not None:
                # proven_reuse: THIS replica just fetched the block over
                # the plane — the fetch itself is reuse evidence, so the
                # republish bypasses publish_min_hits (holding it back
                # would hide a live second holder from the index until
                # this replica's own local hits re-prove what the
                # cluster already demonstrated)
                self._plane_publish(prompt[:n_p], k_fp, v_fp, *stored, proven_reuse=True)
        return pref

    def adopt_prefetched(self, prompt_token_ids, k_fp, v_fp) -> int:
        """Install a PREDICTIVELY fetched hot block into the local prefix
        cache (KVPlaneClient's prefetch worker, ROADMAP item 3b): the
        fleet's top-k demanded prefixes become LOCAL-tier hits before any
        request here asks for them. ``k_fp``/``v_fp`` are float arrays
        (the worker already dequantized an int8 wire); the cache store
        re-quantizes under kv_quant exactly like a remote-fetch re-store,
        so later local hits reproduce the prefill oracle byte-for-byte.
        Returns the adopted bytes (0 when the cache refused — duplicate,
        too-wide block, prefix caching off). The boundary keys minted
        here are remembered so the FIRST local hit they serve counts as a
        prefetch hit (the uplift evidence), and the block republishes
        under this replica (proven_reuse — the fleet demanded it)."""
        ids = [int(t) for t in prompt_token_ids]
        with self._lock:
            if self._prefix_cache is None or not ids:
                return 0
            stored = self._prefix_cache.store(ids, k_fp, v_fp, self.prefill_buckets)
            if stored is None:
                return 0
            nbytes = int(k_fp.nbytes + v_fp.nbytes)
            self._plane_stats["prefetched_blocks"] += 1
            self._plane_stats["prefetched_bytes"] += nbytes
            self._prefetched_keys.add(prefix_key(token_bytes(ids), len(ids)))
            self._plane_publish(ids, k_fp, v_fp, *stored, proven_reuse=True)
        # the publish itself (owned object + index RPC) runs lock-free,
        # same as the step tail — the prefetch worker is not a stepper,
        # so nobody else would flush this offer promptly
        self._flush_plane_offers()
        return nbytes

    def _plane_publish(self, prompt, ks, vs, new_keys=None, pad=None, proven_reuse=False):
        """Queue a prefix-block publish for the cluster plane. Every
        caller runs under the engine lock (admission self-heal, the
        remote-fetch republish, the prefill store path), so the actual
        publish — serialization, ``put_owned``, a timeout-bounded index
        RPC — is deferred to ``_flush_plane_offers()`` at the step tail,
        outside the lock. The offer holds references to the same arrays
        the prefix cache just stored, so nothing is copied and the block
        is still published by the time ``step()`` returns."""
        block = self._prefix_cache.block
        n_max = (len(prompt) // block) * block
        if n_max < block:
            return
        self._plane_offers.append((list(prompt), ks, vs, new_keys, pad, proven_reuse))

    def _flush_plane_offers(self):
        """Publish queued prefix blocks (owned object + index
        registration) — called from the step tail with the engine lock
        RELEASED. ``new_keys`` scopes registration to the boundaries the
        local cache just minted (the store path); None lets the client
        cover every still-unpublished boundary (the local-hit self-heal
        after a transient publish failure). ``proven_reuse`` bypasses the
        client's publish_min_hits policy (the remote-fetch republish
        path). Failures degrade silently — the client counts them;
        serving never depends on the plane."""
        if not self._plane_offers:
            return
        with self._lock:
            offers, self._plane_offers = self._plane_offers, []
        block = self._prefix_cache.block
        for prompt, ks, vs, new_keys, pad, proven_reuse in offers:
            n_max = (len(prompt) // block) * block
            pad = int(ks.shape[1]) if pad is None else pad
            nbytes = self._kv_plane.publish(
                [int(t) for t in prompt[:n_max]], ks[:, :pad], vs[:, :pad],
                bounds=None if new_keys is None else [(n, key) for key, n in new_keys],
                proven_reuse=proven_reuse,
            )
            if nbytes:
                with self._lock:
                    self._plane_stats["published_blocks"] += 1
                    self._plane_stats["published_bytes"] += nbytes

    def _stage_prefill(self, wave: list) -> list:
        """PREFILL stage (execution): run the admission wave's forwards.
        Plain prefills sharing a bucket run as ONE batched forward instead
        of B=1 dispatches; transferred-KV and prefix-hit requests scatter
        in without re-attending cached tokens; prefill-only requests
        complete into handoff blocks inside _bind_slot. Returns the
        admitted RequestStates."""
        admitted: list[RequestState] = []
        if not wave:
            return admitted
        self._t_prefill_start = time.time()  # telemetry: wave prefill span start
        plains: list[tuple] = []
        for st, slot, pref, pages, prompt in wave:
            if self.kv_layout == "paged":
                self._slot_pages[slot] = pages
                self._tables[slot, :] = 0
                self._tables[slot, : len(pages)] = pages
            if st.prefilled is not None or pref is not None:
                if self.kv_layout == "paged":
                    self._admit_special_paged(st, slot, pref, prompt)
                else:
                    self._admit_special_slots(st, slot, pref, prompt)
            else:
                plains.append((st, slot, prompt))
            admitted.append(st)
        if plains:
            for group in self._bucket_groups(plains):
                self._admit_prefill_batch(group)
        return admitted

    def _bucket_groups(self, plains):
        """Group (st, slot, prompt) triples by prefill bucket; without
        batch_prefill every request is its own group."""
        if not self._batch_prefill:
            return [[p] for p in plains]
        groups: dict[int, list] = {}
        for item in plains:
            T = _bucket(len(item[2]), self.prefill_buckets)
            groups.setdefault(T, []).append(item)
        return list(groups.values())

    def _admit_prefill_batch(self, group):
        """One batched forward prefills every prompt in the group (all in
        the same length bucket). The batch dimension is padded to a power
        of two so compile count stays (buckets x log2(max_num_seqs));
        padding rows carry length 1 and produce garbage that is never
        inserted. This is how forward-only prefill reaches training-step
        MXU utilization instead of B=1 dispatch overhead."""
        import jax.numpy as jnp

        T = _bucket(max(len(p) for _, _, p in group), self.prefill_buckets)
        B = len(group)
        Bp = 1 << (B - 1).bit_length()
        toks = np.zeros((Bp, T), np.int32)
        lens = np.ones((Bp,), np.int32)
        for i, (_, _, prompt) in enumerate(group):
            toks[i, : len(prompt)] = prompt
            lens[i] = len(prompt)
        logits, ks, vs = self._prefill(self.params, jnp.asarray(toks), jnp.asarray(lens))
        for i, (st, slot, prompt) in enumerate(group):
            n = len(prompt)
            if self._prefix_cache is not None and not st.token_ids:
                stored = self._prefix_cache.store(prompt, ks[:, i], vs[:, i], self.prefill_buckets)
                if stored is not None and self._kv_plane is not None:
                    # the block every other replica would re-prefill —
                    # publish it to the cluster tier (llm/kvplane/)
                    self._plane_publish(prompt, ks[:, i], vs[:, i], *stored)
            if self.kv_layout == "paged":
                page = self._pcfg.page_size
                table_row = jnp.asarray(self._tables[slot])
                self.pool = self._insert(self.pool, table_row[: T // page], ks[:, i], vs[:, i])
                self._lengths[slot] = n
                if self._device_resident:
                    self._push_table(slot)
            else:
                self.cache = self._insert(self.cache, slot, ks[:, i], vs[:, i], n)
            self._bind_slot(st, slot, logits[i : i + 1])

    def _admit_special_paged(self, st: RequestState, slot: int, pref, prompt):
        """Paged admission for transferred-KV / prefix-cache-hit requests
        (pages already allocated and mirrored into the host table)."""
        import jax.numpy as jnp

        page = self._pcfg.page_size
        n = len(prompt)
        table_row = jnp.asarray(self._tables[slot])
        if st.prefilled is not None:
            kv = st.prefilled
            st.prefilled = None
            t_scatter = time.time()
            kn, vn, n_real = kv["k"], kv["v"], int(kv["n"])
            T_pad = -(-int(kn.shape[1]) // page) * page
            k_pad = np.zeros((kn.shape[0], T_pad) + tuple(kn.shape[2:]), kn.dtype)
            v_pad = np.zeros_like(k_pad)
            k_pad[:, : kn.shape[1]] = kn
            v_pad[:, : vn.shape[1]] = vn
            scales = ()
            if kv.get("k_scale") is not None:  # int8 payload: pad the wire
                # scales ([L, kv, T]) to the same page multiple
                ks_w, vs_w = kv["k_scale"], kv["v_scale"]
                ks_pad = np.zeros(ks_w.shape[:2] + (T_pad,), np.float32)
                vs_pad = np.zeros_like(ks_pad)
                ks_pad[..., : ks_w.shape[2]] = ks_w
                vs_pad[..., : vs_w.shape[2]] = vs_w
                scales = (jnp.asarray(ks_pad), jnp.asarray(vs_pad))
            if self._device_resident:
                # ONE fused scatter-in (llm/disagg/scatter.py): pool pages
                # + device table row + device length lane in a single
                # program — the handoff admission hot path
                self.pool, self._dtables, self._dlengths = self._scatter_paged(
                    self.pool, self._dtables, self._dlengths, np.int32(slot),
                    table_row, jnp.asarray(k_pad), jnp.asarray(v_pad), np.int32(n_real), *scales,
                )
                self._lengths[slot] = n_real
                if self._tel is not None:
                    self._tel.on_scatter_in(st, t_scatter)
                if st.resume is not None:
                    self._bind_resume(st, slot)
                else:
                    self._bind_slot(st, slot, jnp.asarray(kv["logits"])[None])
                return
            self.pool = self._insert(
                self.pool, table_row[: T_pad // page], jnp.asarray(k_pad), jnp.asarray(v_pad), *scales
            )
            # a live-state restore ships no logits: the bind below
            # splices instead of sampling a first token
            logits = None if st.resume is not None else jnp.asarray(kv["logits"])[None]
            self._lengths[slot] = n_real
            if self._tel is not None:
                self._tel.on_scatter_in(st, t_scatter)
        else:
            k_p, v_p, n_p, k_sc, v_sc = pref
            m = n - n_p
            Tm = _bucket(m, self.prefill_buckets)
            # the cache stores K/V at the ORIGINAL prompt's bucket width;
            # the hit may be any block-aligned prefix of it — slice to the
            # matched length (page-aligned: page_size divides prefix_block).
            # A cluster-plane remote hit arrives with wire-layout scales
            # when the producer cache was int8; insert_pages requants
            # transparently exactly like the disagg scatter-in.
            scales = () if k_sc is None else (jnp.asarray(k_sc[:, :, :n_p]), jnp.asarray(v_sc[:, :, :n_p]))
            self.pool = self._insert(
                self.pool, table_row[: n_p // page], jnp.asarray(k_p)[:, :n_p], jnp.asarray(v_p)[:, :n_p],
                *scales,
            )
            toks = np.zeros((Tm,), np.int32)
            toks[:m] = prompt[n_p:]
            logits, self.pool = self._extend(
                self.params, self.pool, table_row, jnp.asarray(n_p, np.int32), jnp.asarray(toks), jnp.asarray(m, np.int32)
            )
            logits = logits[None]
            self._lengths[slot] = n
        if self._device_resident:
            self._push_table(slot)
        if st.resume is not None:
            self._bind_resume(st, slot)
        else:
            self._bind_slot(st, slot, logits)

    def _admit_special_slots(self, st: RequestState, slot: int, pref, prompt):
        """Slot-layout admission for transferred-KV / prefix-cache-hit
        requests."""
        import jax.numpy as jnp

        n = len(prompt)
        if st.prefilled is not None:
            # disaggregated admission: KV arrived from a prefill engine.
            # Device-resident mode scatters through the audited disagg
            # program; the sync oracle keeps the legacy insert. An int8
            # payload carries its wire-layout scales; producer/consumer
            # dtype mismatches requant transparently inside the program.
            kv = st.prefilled
            st.prefilled = None
            t_scatter = time.time()
            k_sc, v_sc = kv.get("k_scale"), kv.get("v_scale")
            scales = (jnp.asarray(k_sc), jnp.asarray(v_sc)) if k_sc is not None else ()
            if self._device_resident:
                self.cache = self._scatter_slots(
                    self.cache, np.int32(slot), jnp.asarray(kv["k"]), jnp.asarray(kv["v"]),
                    np.int32(int(kv["n"])), *scales,
                )
            else:
                self.cache = self._insert(
                    self.cache, slot, jnp.asarray(kv["k"]), jnp.asarray(kv["v"]), int(kv["n"]), *scales
                )
            if self._tel is not None:
                self._tel.on_scatter_in(st, t_scatter)
            # a live-state restore ships no logits: the bind below
            # splices instead of sampling a first token
            logits = None if st.resume is not None else jnp.asarray(kv["logits"])[None]
        else:
            # reuse the cached prefix KV; re-attend only the suffix. A
            # cluster-plane remote hit carries wire-layout scales when the
            # producer cache was int8 — insert_sequence requants
            # transparently, same contract as the disagg scatter-in.
            k_p, v_p, n_p, k_sc, v_sc = pref
            m = n - n_p
            Tm = _bucket(m, self.prefill_buckets)
            scales = () if k_sc is None else (jnp.asarray(k_sc), jnp.asarray(v_sc))
            self.cache = self._insert(self.cache, slot, jnp.asarray(k_p), jnp.asarray(v_p), n_p, *scales)
            toks = np.zeros((Tm,), np.int32)
            toks[:m] = prompt[n_p:]
            logits, self.cache = self._extend(
                self.params, self.cache, slot, jnp.asarray(toks), jnp.asarray(m, np.int32)
            )
            logits = logits[None]
        # sample the first generated token from the prefill logits (a
        # live-state restore splices instead: no sample, no emit)
        if st.resume is not None:
            self._bind_resume(st, slot)
        else:
            self._bind_slot(st, slot, logits)

    def _bind_slot(self, st: RequestState, slot: int, logits):
        import jax
        import jax.numpy as jnp

        st.slot = slot
        st.admit_seq = self._admit_counter = getattr(self, "_admit_counter", 0) + 1
        self._slots[slot] = st
        if self._tel is not None:
            self._tel.on_bind(st, getattr(self, "_t_prefill_start", st.t_submit))
        if st.prefill_only:
            # prefill replica path: the block leaves, the slot recycles,
            # decode never sees this request
            self._complete_handoff(st, slot, logits)
            return
        p = st.params
        self._temps[slot] = p.temperature
        self._top_k[slot] = p.top_k
        self._top_p[slot] = p.top_p
        if p.seed is not None:
            self._keys[slot] = np.asarray(jax.random.key_data(jax.random.PRNGKey(p.seed)))  # tpulint: disable=CCR002 — seeded lane key init: host PRNG material, one-time per admission
        elif self._device_resident:
            # the lane's key lives on device (advanced by every fused
            # step); pull its current value for the first-token sample.
            # This blocks on the not-yet-drained in-flight step if one is
            # pending — the price of exact key parity with the sync
            # oracle, paid only on seedless admissions and bounded by one
            # step per admission (the prefill about to run dwarfs it).
            self._keys[slot] = np.asarray(self._dkeys[slot])  # tpulint: disable=CCR002 — documented first-sample key pull: bounded one pending step per seedless admission
        tok, logp, key = self._sample(
            logits,
            jnp.asarray(self._keys[slot : slot + 1]),
            jnp.asarray(self._temps[slot : slot + 1]),
            jnp.asarray(self._top_k[slot : slot + 1]),
            jnp.asarray(self._top_p[slot : slot + 1]),
        )
        self._keys[slot] = np.asarray(key[0])  # tpulint: disable=CCR002 — post-sample key readback rides the prefill's own sync point
        token = int(tok[0])
        if self._device_resident:
            # lane delta: first input token, advanced key, sampling params
            self._dtokens, self._dkeys, self._dtemps, self._dtopk, self._dtopp = self._set_lane(
                self._dtokens,
                self._dkeys,
                self._dtemps,
                self._dtopk,
                self._dtopp,
                np.int32(slot),
                np.int32(token),
                self._keys[slot],
                np.float32(p.temperature),
                np.int32(p.top_k),
                np.float32(p.top_p),
            )
        spec_hist = (st.prompt_token_ids + st.token_ids + [token]) if self._spec_cfg is not None else None
        self._emit(st, token, float(logp[0]))  # tpulint: disable=CCR002 — first-token emit: prefill output is already host-synced here
        if spec_hist is not None:
            self._spec_admit(st, slot, spec_hist)

    def _bind_resume(self, st: RequestState, slot: int):
        """Splice a restored live-state request into the decode loop
        (llm/migrate.py): bind the slot and every lane from the
        CHECKPOINTED state — the exact (already-advanced) PRNG key, the
        last emitted token as the next decode input, the sticky spec
        effective-k — and emit NOTHING. The checkpoint settled the
        source's in-flight step, so the next client-visible token is
        minted by the first decode step here: the stream can neither
        repeat nor drop a token across the splice."""
        st.slot = slot
        st.admit_seq = self._admit_counter = getattr(self, "_admit_counter", 0) + 1
        self._slots[slot] = st
        if self._tel is not None:
            self._tel.on_bind(st, getattr(self, "_t_prefill_start", st.t_submit))
        rs = st.resume
        st.resume = None
        p = st.params
        self._temps[slot] = p.temperature
        self._top_k[slot] = p.top_k
        self._top_p[slot] = p.top_p
        # the checkpointed key, NEVER re-derived from the seed: a seeded
        # lane's key advanced once per sample at the source, and the
        # oracle's post-splice draws continue that sequence
        self._keys[slot] = np.asarray(rs["rng_key"], np.uint32)  # tpulint: disable=CCR002 — checkpoint splice: rs is host state from llm/migrate.py, not a device array
        token = int(st.token_ids[-1])
        self._next_tokens[slot] = token
        if self._device_resident:
            self._dtokens, self._dkeys, self._dtemps, self._dtopk, self._dtopp = self._set_lane(
                self._dtokens,
                self._dkeys,
                self._dtemps,
                self._dtopk,
                self._dtopp,
                np.int32(slot),
                np.int32(token),
                self._keys[slot],
                np.float32(p.temperature),
                np.int32(p.top_k),
                np.float32(p.top_p),
            )
        if self._spec_cfg is not None:
            spec = rs.get("spec") or {}
            self._controller.restore(st.request_id, spec.get("ema"), spec.get("k"))
            # history = prompt + everything emitted; the drafter caches
            # hist[:-1] — exactly the positions the restored block covers
            self._spec_admit(st, slot, st.prompt_token_ids + st.token_ids)

    def _complete_handoff(self, st: RequestState, slot: int, logits):
        """Finish a prefill-only request: extract its KV block into a
        contiguous buffer with the fused extract program for this layout
        (llm/disagg/scatter.py — slots: dynamic row slice; paged: page
        gather), stash the handoff payload, free the slot/pages. The
        block ships at the prompt's prefill-bucket width; the tail past
        the real length is garbage the decode side masks by length (the
        same contract as prefill's own padding). An int8 producer ships
        int8 values + per-head scales ([L, kv, T] wire layout) — ~half
        the object-plane bytes of a bf16 block."""
        import jax.numpy as jnp

        t_extract = time.time()
        prompt = st.prompt_token_ids
        n = len(prompt)
        T = _bucket(n, self.prefill_buckets)
        if self.kv_layout == "paged":
            page = self._pcfg.page_size
            row = np.asarray(self._tables[slot][: T // page], np.int32)
            out = self._extract_paged(self.pool, jnp.asarray(row))
        else:
            out = self._extract_slots(self.cache, np.int32(slot), T)
        payload = {
            "k": np.asarray(out[0]),
            "v": np.asarray(out[1]),
            "n": n,
            "logits": np.asarray(logits[0], np.float32),
            "prompt_token_ids": list(prompt),
        }
        if len(out) == 4:
            payload["k_scale"] = np.asarray(out[2])
            payload["v_scale"] = np.asarray(out[3])
        if self._tel is not None:
            # stamps trace context + original submit time into the payload
            # (handoff.py carries them on the wire) and accounts the bytes
            self._tel.on_handoff_extract(st, payload, t_extract)
        self._handoffs[st.request_id] = payload
        self._finish(st, "handoff")

    def _spec_admit(self, st: RequestState, slot: int, hist_tokens: list):
        """Spec lane state for a freshly admitted sequence: the token
        history row (prompt + recompute-folded generation + the first
        sampled token), the controller's sticky effective k, and the
        drafter's own prefill. A request that finished at admission
        (stop/max_tokens on the first token) never drafts."""
        import jax.numpy as jnp

        if st.finished or st.slot != slot:
            return
        n = len(hist_tokens)
        row = np.zeros((self._spec_hist_width,), np.int32)
        row[:n] = hist_tokens
        k0 = self._controller.admit(st.request_id)
        self._lane_k[slot] = k0
        self._dhist, self._dhist_len, self._dspec_k = self._set_hist(
            self._dhist, self._dhist_len, self._dspec_k,
            np.int32(slot), jnp.asarray(row), np.int32(n), np.int32(k0),
        )
        # the drafter caches everything the target has cached: the full
        # admitted prompt, NOT the fresh token (the first chain input)
        self._drafter.admit(slot, hist_tokens[:-1])

    def _emit(self, st: RequestState, token: int, logp: float):
        st.token_ids.append(token)
        st.logprobs.append(logp)
        if self._tel is not None:
            self._tel.on_emit(st)
        if st.out_queue is not None:
            st.out_queue.put(token)
        if st.slot >= 0:
            self._next_tokens[st.slot] = token
        if token in st.params.stop_token_ids:
            self._finish(st, "stop")
        elif len(st.token_ids) >= st.params.max_tokens:
            self._finish(st, "length")

    def step(self) -> list[RequestOutput]:
        """Admit what fits, advance decode one step, return per-request
        deltas.

        Device-resident mode (default): the fused jitted step is
        DISPATCHED before the previous step's tokens are read back, so
        step N's host transfer overlaps step N+1's device compute —
        emission (streaming tokens, finish detection, slot recycling)
        trails the device by exactly one step, and each sequence runs up
        to one discarded trailing step. Under speculation that trailing
        step would cost a whole drafter round (up to k verifications), so
        wasted work is capped: a round whose every lane is guaranteed to
        finish from the still-pending round is skipped outright, and a
        finished lane never enters another round — at most ONE drafter
        round ever runs past a request's finish detection.
        """
        tel = self._tel
        t0 = time.perf_counter() if tel is not None else 0.0
        try:
            with self._lock:
                self._last_spec_drain = None
                self._step_emitted = 0
                wave = self._stage_admission()
                admitted = self._stage_prefill(wave)
                if self.kv_layout == "paged":
                    self._paged_grow()
                reported = self._stage_decode(admitted)
                outs = self._build_outputs(reported)
                if tel is not None:
                    tel.on_step(t0, len(admitted), self._step_emitted, self._last_spec_drain)
            if self._kv_plane is not None:
                # publish the step's minted prefix blocks and refresh the
                # cluster-index lease (throttled) — both outside the
                # engine lock, so a slow plane/index can never stall
                # admissions or any lock-holding caller
                self._flush_plane_offers()
                self._kv_plane.maybe_heartbeat()
            return outs
        except BaseException as exc:
            # postmortem: persist the flight ring as JSONL in the session
            # dir before the error surfaces (serve marks the replica
            # unhealthy; the ring is the step history that led here)
            if tel is not None:
                tel.dump_on_error(exc)
            raise

    def _stage_decode(self, admitted: list) -> list:
        """DECODE stage: advance every occupied slot one tick. Device-
        resident mode dispatches the fused (or speculative) step and
        drains the PREVIOUS one; sync mode is the blocking oracle loop.
        Prefill-only requests never reach here — they finished (and freed
        their slot) inside the prefill stage."""
        if self._device_resident:
            prev = self._pending
            self._pending = None
            if self._spec_cfg is not None:
                self._dispatch_spec(prev)
                emitted = self._drain_spec(prev)
            else:
                self._dispatch_fused()
                emitted = self._drain(prev)
            self._step_emitted = len(emitted)
            return admitted + emitted
        # sync mode: every active lane (just-admitted ones included)
        # emitted a token this step — the returned list IS the emit set
        reported = self._sync_decode()
        self._step_emitted = len(reported)
        return reported

    def _dispatch_fused(self):
        """Launch the fused device step for the current occupancy; never
        blocks on results (stored in self._pending for the next call)."""
        active = [s for s in self._slots if s is not None]
        if not active:
            return
        # the fused programs donate the sampling lanes and hand them back
        # as passthrough outputs (zero-copy aliases); rebind the handles
        if self.kv_layout == "paged":
            (toks, logps, self._dkeys, k_new, v_new, wp, wo, self._dlengths,
             self._dtemps, self._dtopk, self._dtopp) = self._fused_attn(
                self.params,
                self.pool,
                self._dtables,
                self._dlengths,
                self._dtokens,
                self._dkeys,
                self._dtemps,
                self._dtopk,
                self._dtopp,
            )
            self.pool = self._fused_append(self.pool, wp, wo, k_new, v_new)
            for st in active:
                self._lengths[st.slot] += 1  # host shadow, no upload
        else:
            (self.cache, toks, logps, self._dkeys,
             self._dtemps, self._dtopk, self._dtopp) = self._fused_step(
                self.params,
                self.cache,
                self._dtokens,
                self._dkeys,
                self._dtemps,
                self._dtopk,
                self._dtopp,
            )
        self._dtokens = toks
        self._pending = (toks, logps, [(st, st.slot) for st in active])

    def _drain(self, pending) -> list:
        """Read back and emit the PREVIOUS step's tokens (blocks only on
        work that overlapped the current step's dispatch)."""
        if pending is None:
            return []
        toks_d, logps_d, lanes = pending
        toks = np.asarray(toks_d)  # tpulint: disable=CCR002 — sanctioned one-step-delayed drain readback (overlaps next step's compute)
        logps = np.asarray(logps_d)  # tpulint: disable=CCR002 — sanctioned one-step-delayed drain readback (overlaps next step's compute)
        emitted = []
        for st, slot in lanes:
            if st.finished:
                continue  # aborted (or finished) between dispatch and drain
            self._emit(st, int(toks[slot]), float(logps[slot]))  # tpulint: disable=CCR002 — reads the already-drained host array
            emitted.append(st)
        return emitted

    def _dispatch_spec(self, prev):
        """Launch one speculative round (draft -> fused verify) for the
        current occupancy; never blocks on results. The drafter reads the
        device history/length lanes the PREVIOUS verify step wrote, so
        draft chains on verify without any host round trip."""
        active = [s for s in self._slots if s is not None]
        if not active:
            return
        if prev is not None:
            # wasted-work cap: the pending round emits >= 1 token per
            # lane, so a lane within one token of max_tokens is finished
            # no matter what drains — if EVERY active lane is, this round
            # could only produce discarded tokens; skip it entirely
            pend = {id(entry[0]) for entry in prev[3]}
            if all(
                id(s) in pend and len(s.token_ids) + 1 >= s.params.max_tokens for s in active
            ):
                return
        lengths_lane = self._dlengths if self.kv_layout == "paged" else self.cache["length"]
        props = self._drafter.propose(self._dhist, self._dhist_len, lengths_lane)
        if self.kv_layout == "paged":
            (emit, logps, acc, toks, self._dkeys, k_blk, v_blk, wp, wo, self._dlengths,
             self._dtemps, self._dtopk, self._dtopp, self._dspec_k,
             self._dhist, self._dhist_len) = self._verify_attn(
                self.params,
                self.pool,
                self._dtables,
                self._dlengths,
                props,
                self._dtokens,
                self._dkeys,
                self._dtemps,
                self._dtopk,
                self._dtopp,
                self._dspec_k,
                self._dhist,
                self._dhist_len,
            )
            self.pool = self._verify_append(self.pool, wp, wo, k_blk, v_blk)
        else:
            (self.cache, emit, logps, acc, toks, self._dkeys,
             self._dtemps, self._dtopk, self._dtopp, self._dspec_k,
             self._dhist, self._dhist_len) = self._verify_step(
                self.params,
                self.cache,
                props,
                self._dtokens,
                self._dkeys,
                self._dtemps,
                self._dtopk,
                self._dtopp,
                self._dspec_k,
                self._dhist,
                self._dhist_len,
            )
        self._dtokens = toks
        self._spec_rounds += 1
        lanes = [(st, st.slot, int(self._lane_k[st.slot])) for st in active]
        self._pending = (emit, logps, acc, lanes)

    def _drain_spec(self, pending) -> list:
        """Read back and emit the PREVIOUS speculative round: up to
        accepted+1 tokens per lane, stopping at finish (stop ids /
        max_tokens mid-round) and, for the paged layout, at the cache
        row's capacity — the same point the plain path's page growth
        finishes a row-exhausted sequence with reason 'length'."""
        if pending is None:
            return []
        emit_d, logps_d, acc_d, lanes = pending
        emit = np.asarray(emit_d)  # tpulint: disable=CCR002 — sanctioned one-round-delayed spec drain readback
        logps = np.asarray(logps_d)  # tpulint: disable=CCR002 — sanctioned one-round-delayed spec drain readback
        acc = np.asarray(acc_d)  # tpulint: disable=CCR002 — sanctioned one-round-delayed spec drain readback
        row_cap = (
            self._pcfg.max_pages_per_seq * self._pcfg.page_size if self.kv_layout == "paged" else None
        )
        emitted = []
        for st, slot, k_eff in lanes:
            if st.finished:
                continue  # aborted (or finished) between dispatch and drain
            a = int(acc[slot])
            n_new = a + 1
            cap = n_new
            if row_cap is not None:
                owns = self._slots[slot] is st
                if owns:
                    # a recompute-preempted lane's shadow was already
                    # reset; only a live occupant mirrors the device's
                    # length advance
                    cap = max(row_cap - int(self._lengths[slot]), 0)
                    self._lengths[slot] += n_new
            self._spec_proposed += k_eff
            self._spec_accepted += a
            self._spec_lane_rounds += 1
            for i in range(min(n_new, cap)):
                self._emit(st, int(emit[slot, i]), float(logps[slot, i]))  # tpulint: disable=CCR002 — reads the already-drained host array
                self._spec_emitted += 1
                if st.finished:
                    break
            if not st.finished and cap < n_new:
                # accepted tokens past the row edge had their KV dropped
                # to the trash page; the plain path would have finished
                # this row at the same token
                self._finish(st, "length")
            if not st.finished:
                new_k = self._controller.observe(st.request_id, k_eff, a)
                if st.slot == slot and new_k != self._lane_k[slot]:
                    self._lane_k[slot] = new_k
                    self._dspec_k = self._set_slot_scalar(self._dspec_k, np.int32(slot), np.int32(new_k))
            emitted.append(st)
        if emitted and self._tel is not None:
            # per-round accounting for the flight record (host ints only:
            # acc was already read back as part of this drain)
            self._last_spec_drain = (
                int(sum(entry[2] for entry in lanes)),
                int(sum(int(acc[entry[1]]) for entry in lanes)),
            )
        return emitted

    def _sync_decode(self) -> list:
        """The synchronous host-driven step (device_resident=False): full
        re-upload of scheduler state, blocking readback before return.
        Kept as the decode-equivalence oracle and host-debug mode."""
        import jax.numpy as jnp

        active = [s for s in self._slots if s is not None]
        if not active:
            return []
        if self.kv_layout == "paged":
            logits, self.pool, _ = self._decode(
                self.params,
                self.pool,
                jnp.asarray(self._tables),
                jnp.asarray(self._lengths),
                jnp.asarray(self._next_tokens),
            )
            for st in active:
                self._lengths[st.slot] += 1
        else:
            logits, self.cache = self._decode(self.params, self.cache, jnp.asarray(self._next_tokens))
        toks, logps, keys = self._sample(
            logits,
            jnp.asarray(self._keys),
            jnp.asarray(self._temps),
            jnp.asarray(self._top_k),
            jnp.asarray(self._top_p),
        )
        toks = np.asarray(toks)  # tpulint: disable=CCR002 — sync mode: the whole point is an in-step readback
        logps = np.asarray(logps)  # tpulint: disable=CCR002 — sync mode: the whole point is an in-step readback
        self._keys = np.array(keys)  # tpulint: disable=CCR002 — sync mode: the whole point is an in-step readback
        for st in active:
            self._emit(st, int(toks[st.slot]), float(logps[st.slot]))  # tpulint: disable=CCR002 — sync mode: reads the just-synced host array
        return active

    def _build_outputs(self, reported: list) -> list[RequestOutput]:  # holds-lock: _lock
        """Per-request deltas for everything that changed this step."""
        outputs: list[RequestOutput] = []
        seen: set = set()
        for st in reported:
            if st.request_id in seen:
                continue
            seen.add(st.request_id)
            outputs.append(
                RequestOutput(
                    request_id=st.request_id,
                    prompt_token_ids=st.prompt_token_ids,
                    token_ids=list(st.token_ids),
                    new_token_ids=st.token_ids[-1:],
                    finished=st.finished,
                    finish_reason=st.finish_reason,
                    logprobs=list(st.logprobs) if st.params.logprobs else None,
                    streamed=st.out_queue is not None,
                )
            )
        # also report requests finished outside the decode path (aborts,
        # admission errors)
        for st in list(self._requests.values()):
            if st.finished and st.request_id not in seen and st.request_id in self._requests:
                outputs.append(
                    RequestOutput(
                        request_id=st.request_id,
                        prompt_token_ids=st.prompt_token_ids,
                        token_ids=list(st.token_ids),
                        new_token_ids=[],
                        finished=True,
                        finish_reason=st.finish_reason,
                        logprobs=list(st.logprobs) if st.params.logprobs else None,
                        streamed=st.out_queue is not None,
                    )
                )
                del self._requests[st.request_id]
        for o in outputs:
            if o.finished and o.request_id in self._requests:
                del self._requests[o.request_id]
        return outputs

    def generate(self, prompts, params: SamplingParams | list | None = None) -> list[RequestOutput]:
        """Blocking batch generation with continuous batching underneath."""
        import numbers

        if len(prompts) == 0:
            return []
        # a single prompt is a sequence of token ids — including numpy
        # integer ids from tokenizers/arrays, hence Integral not int
        single = isinstance(prompts[0], numbers.Integral)
        if single:
            prompts = [prompts]
        if params is None or isinstance(params, SamplingParams):
            params = [params or SamplingParams()] * len(prompts)
        ids = [self.add_request(p, sp) for p, sp in zip(prompts, params)]
        finals: dict[str, RequestOutput] = {}
        while self.has_unfinished():
            for out in self.step():
                if out.finished:
                    finals[out.request_id] = out
        results = [finals[i] for i in ids]
        return results[0] if single else results
