"""Cache-aware Llama forward passes: prefill and single-token decode.

Both are pure functions over the same parameter pytree as
ray_tpu.models.llama (training and serving share weights); layers are
iterated with `lax.scan` so compile time is constant in depth and the KV
cache rides the scan as stacked per-layer xs/ys.

Prefill runs the causal flash path on one (padded) prompt and returns the
per-layer K/V to be inserted into a cache slot. Decode advances every slot
by one token against the full cache with a length mask. This replaces the
vLLM engine the reference wraps (ref: python/ray/llm/_internal/serve/
engines/vllm/vllm_engine.py) with a jit-native implementation.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from functools import partial

import jax
import jax.numpy as jnp

from ray_tpu.lint import jaxcheck
from ray_tpu.models.llama import LlamaConfig
from ray_tpu.ops.flash_attention import flash_attention
from ray_tpu.ops.layers import apply_rope, rms_norm, rotary_embedding


# ---------------------------------------------------------------------------
# Tensor parallelism over the ICI mesh: the fused decode hot path is
# re-expressed under shard_map so the per-layer TP all-reduce is an
# EXPLICIT psum the runtime controls (instead of a GSPMD-inserted
# collective), which is what makes the opt-in int8 quantized all-reduce
# (collective/ici.quantized_psum, EQuARX arxiv 2506.17615) expressible at
# all. tpc=None keeps every function byte-for-byte the single-device
# program it was — the tp=1 engine stays the token-identical oracle.
# ---------------------------------------------------------------------------
@dataclass(frozen=True)
class TpSpec:
    """Static description of the tensor-parallel axis a sharded step runs
    over: closed into the shard_map body, never traced."""

    axis: str = "tp"
    size: int = 1
    collective: str = "fp"  # "fp" (exact psum) | "int8" (quantized wire)


def _tp_reduce(x, tpc: TpSpec | None):
    """The per-layer TP all-reduce (attention-out and MLP-out partials).
    fp: exact lax.psum; int8: EQuARX-style quantized reduce-scatter +
    all-gather with int8 wire payload (~1/2 the ICI bytes at bf16)."""
    if tpc is None:
        return x
    if tpc.collective == "int8":
        from ray_tpu.collective.ici import quantized_psum

        return quantized_psum(x, tpc.axis)
    return jax.lax.psum(x, tpc.axis)


def _tp_embed(embed, tokens, tpc: TpSpec | None):
    """Token lookup against a vocab-row-sharded embedding: each shard
    gathers locally (clipped), masks out-of-shard rows, and one small
    [B, H] fp psum assembles the vectors — once per step, not per layer,
    so it stays full precision in both collective modes."""
    if tpc is None:
        return jnp.take(embed, tokens, axis=0)
    v_loc = embed.shape[0]
    loc = tokens - jax.lax.axis_index(tpc.axis) * v_loc
    ok = (loc >= 0) & (loc < v_loc)
    x = jnp.take(embed, jnp.clip(loc, 0, v_loc - 1), axis=0)
    x = jnp.where(ok[..., None], x, jnp.zeros((), x.dtype))
    return jax.lax.psum(x, tpc.axis)


def _tp_gather_logits(logits, tpc: TpSpec | None):
    """Vocab-sharded unembed partials -> full logits on every shard (the
    sampler needs the whole distribution). fp all-gather in both modes:
    it runs once per step and logit precision feeds top-k/top-p surgery."""
    if tpc is None:
        return logits
    return jax.lax.all_gather(logits, tpc.axis, axis=logits.ndim - 1, tiled=True)


def _shard_cfg(cfg: LlamaConfig, tp: int) -> LlamaConfig:
    """Per-shard view of the model config for shard_map bodies: head
    counts divide by tp (the local arrays carry the divided dims), and
    head_dim is pinned so the hd property stops deriving it from the
    now-wrong hidden/num_heads ratio."""
    return replace(
        cfg,
        num_heads=cfg.num_heads // tp,
        num_kv_heads=cfg.num_kv_heads // tp,
        head_dim=cfg.hd,
    )


def _tp_shard_map(f, mesh, in_specs, out_specs):
    """jax.shard_map where available; jax.experimental fallback on 0.4.x
    (same shim as parallel/pipeline.py). check_rep=False: lane outputs are
    replicated by construction (every shard computes the full sampler on
    the gathered logits), not by inference."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, axis_names={"tp"})
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=False)


def _param_pspecs(cfg: LlamaConfig, mesh):
    """PartitionSpec pytree for the llama params over this mesh — the
    same logical-axes -> mesh-axes lowering the engine's GSPMD shardings
    use, so shard_map consumes the engine's arrays without resharding."""
    from ray_tpu.models.llama import param_logical_axes
    from ray_tpu.parallel.mesh import ShardingRules

    rules = ShardingRules()
    return jax.tree.map(
        lambda axes: rules.spec(axes, mesh),
        param_logical_axes(cfg),
        is_leaf=lambda x: isinstance(x, tuple),
    )


def _cache_pspecs(kv_layout: str, kv_quant: bool):
    """PartitionSpecs for the KV cache/pool pytree (kv_heads on tp; the
    int8 scale lanes shard their kv axis too) — mirrors
    engine._mesh_shardings."""
    from jax.sharding import PartitionSpec as P

    kv = P(None, None, None, "tp", None)
    specs = {"k": kv, "v": kv} if kv_layout == "paged" else {"k": kv, "v": kv, "length": P()}
    if kv_quant:
        specs["k_scale"] = specs["v_scale"] = P(None, None, "tp", None)
    return specs


# ---------------------------------------------------------------------------
# jaxcheck shape buckets: production-realistic abstract shapes (tile-true
# head_dim/hidden so JXC006's (8,128) math is meaningful; ShapeDtypeStructs
# only — nothing here allocates). B is the slot count, S the KV horizon.
# The _sds*/_trace_cfg helpers double as the bucket toolkit for the
# speculative entries in llm/spec/ (drafter.py / verify.py).
# ---------------------------------------------------------------------------
def _trace_cfg() -> LlamaConfig:
    return LlamaConfig(
        vocab_size=32256, hidden_size=1024, intermediate_size=2816,
        num_layers=4, num_heads=8, num_kv_heads=8, head_dim=128,
        max_seq_len=512, remat=False,
    )


def _sds(shape, dtype):
    return jax.ShapeDtypeStruct(shape, dtype)


def _sds_params(cfg: LlamaConfig):
    from ray_tpu.models.llama import init_params

    return jax.eval_shape(lambda: init_params(cfg, jax.random.PRNGKey(0)))


def _sds_cache(cfg: LlamaConfig, B: int, S: int):
    dt = jnp.dtype(cfg.dtype)
    kv = _sds((cfg.num_layers, B, S, cfg.num_kv_heads, cfg.hd), dt)
    return {"k": kv, "v": kv, "length": _sds((B,), jnp.int32)}


def _sds_pool(cfg: LlamaConfig, pages: int, page: int):
    dt = jnp.dtype(cfg.dtype)
    kv = _sds((cfg.num_layers, pages, page, cfg.num_kv_heads, cfg.hd), dt)
    return {"k": kv, "v": kv}


def _sds_cache_q(cfg: LlamaConfig, B: int, S: int):
    """Int8-cache bucket twin of _sds_cache: int8 values + f32 per-head
    scales with the position axis last (the kv_quant.py tile layout)."""
    kv = _sds((cfg.num_layers, B, S, cfg.num_kv_heads, cfg.hd), jnp.int8)
    sc = _sds((cfg.num_layers, B, cfg.num_kv_heads, S), jnp.float32)
    return {"k": kv, "v": kv, "k_scale": sc, "v_scale": sc, "length": _sds((B,), jnp.int32)}


def _sds_pool_q(cfg: LlamaConfig, pages: int, page: int):
    kv = _sds((cfg.num_layers, pages, page, cfg.num_kv_heads, cfg.hd), jnp.int8)
    sc = _sds((cfg.num_layers, pages, cfg.num_kv_heads, page), jnp.float32)
    return {"k": kv, "v": kv, "k_scale": sc, "v_scale": sc}


def _sds_lanes(B: int):
    """(tokens, keys, temps, top_k, top_p) slot lanes."""
    return (
        _sds((B,), jnp.int32), _sds((B, 2), jnp.uint32), _sds((B,), jnp.float32),
        _sds((B,), jnp.int32), _sds((B,), jnp.float32),
    )


def _bucket_prefill(B=8, T=128):
    cfg = _trace_cfg()
    return (_sds_params(cfg), _sds((B, T), jnp.int32), _sds((B,), jnp.int32), cfg), {}


def _bucket_decode(B=8, S=256):
    cfg = _trace_cfg()
    return (_sds_params(cfg), _sds_cache(cfg, B, S), _sds((B,), jnp.int32), cfg), {}


def _bucket_fused(B=8, S=256):
    cfg = _trace_cfg()
    return (_sds_params(cfg), _sds_cache(cfg, B, S)) + _sds_lanes(B) + (cfg,), {}


def _bucket_paged_fused(B=8, pages=64, page=16):
    cfg = _trace_cfg()
    tables = _sds((B, pages // B * 2), jnp.int32)
    lengths = _sds((B,), jnp.int32)
    tokens, keys, temps, top_k, top_p = _sds_lanes(B)
    return (
        _sds_params(cfg), _sds_pool(cfg, pages, page), tables, lengths,
        tokens, keys, temps, top_k, top_p, cfg,
    ), {}


def _bucket_fused_q(B=8, S=256):
    cfg = _trace_cfg()
    return (_sds_params(cfg), _sds_cache_q(cfg, B, S)) + _sds_lanes(B) + (cfg,), {}


def _bucket_paged_fused_q(B=8, pages=64, page=16):
    cfg = _trace_cfg()
    tables = _sds((B, pages // B * 2), jnp.int32)
    lengths = _sds((B,), jnp.int32)
    tokens, keys, temps, top_k, top_p = _sds_lanes(B)
    return (
        _sds_params(cfg), _sds_pool_q(cfg, pages, page), tables, lengths,
        tokens, keys, temps, top_k, top_p, cfg,
    ), {}


def _bucket_set_lane(B=8):
    tokens, keys, temps, top_k, top_p = _sds_lanes(B)
    scalars = (
        _sds((), jnp.int32), _sds((), jnp.int32), _sds((2,), jnp.uint32),
        _sds((), jnp.float32), _sds((), jnp.int32), _sds((), jnp.float32),
    )
    return (tokens, keys, temps, top_k, top_p) + scalars, {}


def _qkv(xn, layer, cfg: LlamaConfig):
    B, T, _ = xn.shape
    nh, nkv, hd = cfg.num_heads, cfg.num_kv_heads, cfg.hd
    q = jnp.dot(xn, layer["wq"]).reshape(B, T, nh, hd)
    k = jnp.dot(xn, layer["wk"]).reshape(B, T, nkv, hd)
    v = jnp.dot(xn, layer["wv"]).reshape(B, T, nkv, hd)
    return q, k, v


def _mlp(x, layer, cfg: LlamaConfig, tpc: TpSpec | None = None):
    xn = rms_norm(x, layer["mlp_norm"], cfg.rms_eps)
    g = jnp.dot(xn, layer["w_gate"])
    u = jnp.dot(xn, layer["w_up"])
    return x + _tp_reduce(jnp.dot(jax.nn.silu(g) * u, layer["w_down"]), tpc)


@jaxcheck.entry(
    name="llm.prefill",
    shapes={"b8_t128": _bucket_prefill, "b8_t256": lambda: _bucket_prefill(T=256)},
)
def prefill(params, tokens, length, cfg: LlamaConfig):
    """Run the prompt through the model, returning last-token logits + K/V.

    tokens: [B, T_pad] int32 (right-padded); length: [B] int32 real lengths.
    Returns (logits [B, vocab] f32, k [L, B, T_pad, kv, hd], v same).
    Padded positions produce garbage K/V that later attention masks out.
    """
    B, T = tokens.shape
    positions = jnp.arange(T, dtype=jnp.int32)
    cos, sin = rotary_embedding(positions, cfg.hd, cfg.rope_theta)
    x = jnp.take(params["embed"], tokens, axis=0)

    def layer_fn(x, layer):
        xn = rms_norm(x, layer["attn_norm"], cfg.rms_eps)
        q, k, v = _qkv(xn, layer, cfg)
        qh = apply_rope(q.transpose(0, 2, 1, 3), cos, sin)
        kh = apply_rope(k.transpose(0, 2, 1, 3), cos, sin)
        o = flash_attention(qh, kh, v.transpose(0, 2, 1, 3), True, None, cfg.attention_impl)
        o = o.transpose(0, 2, 1, 3).reshape(B, T, cfg.num_heads * cfg.hd)
        x = x + jnp.dot(o, layer["wo"])
        x = _mlp(x, layer, cfg)
        # cache stores rope'd keys (decode appends rope'd keys too)
        return x, (kh.transpose(0, 2, 1, 3), v)

    if cfg.remat:
        layer_fn = jax.checkpoint(layer_fn, policy=getattr(jax.checkpoint_policies, cfg.remat_policy))
    x, (ks, vs) = jax.lax.scan(layer_fn, x, params["layers"])

    x = rms_norm(x, params["final_norm"], cfg.rms_eps)
    # only the last real token's logits matter: gather before the unembed
    # matmul so prefill does a [B, H] x [H, V] instead of [B*T, H] x [H, V]
    x_last = jnp.take_along_axis(x, (length - 1)[:, None, None], axis=1)[:, 0]
    unembed = params["embed"].T if cfg.tie_embeddings else params["unembed"]
    logits = jnp.dot(x_last, unembed, preferred_element_type=jnp.float32)
    return logits, ks, vs


@jaxcheck.entry(
    name="llm.decode_step",
    shapes={"b8_s256": _bucket_decode},
    donate=("cache",),
)
def decode_step(params, cache, tokens, cfg: LlamaConfig, tpc: TpSpec | None = None):
    """Advance every slot one token.

    tokens: [slots] int32 (next input token per slot, garbage for empty
    slots); cache: kv_cache pytree. Returns (logits [slots, vocab] f32,
    new cache). The new token is written at position cache.length[b] and
    attends to positions 0..length[b] inclusive.

    An int8 cache (k_scale/v_scale present) quantizes the appended token
    INSIDE this program and dequantizes the row for attention at the f32
    compute dtype the score/value einsums already use (kv_quant.py) —
    same program count, roughly half the cache bytes streamed.

    With ``tpc`` set this is the per-shard body of a shard_map over the
    tp axis (cfg is the DIVIDED per-shard view from _shard_cfg): heads
    and the MLP hidden dim are local, and the attention-out / MLP-out
    partial sums all-reduce explicitly via _tp_reduce — the collective
    the runtime owns and (opt-in) quantizes. tpc=None is bit-for-bit the
    single-device program.

    CONTRACT: the speculative draft scan (llm/spec/drafter.py
    draft_steps) chains this k+1 times inside one program with an
    overridden length lane — masking must stay a pure function of the
    carried cache (no cross-call state), so chained and single-step use
    trace identically.
    """
    B = tokens.shape[0]
    nh, nkv, hd = cfg.num_heads, cfg.num_kv_heads, cfg.hd
    rep = nh // nkv
    quant = "k_scale" in cache
    lengths = cache["length"]
    cos, sin = rotary_embedding(lengths[:, None], cfg.hd, cfg.rope_theta)  # [B, 1, hd/2]
    x = _tp_embed(params["embed"], tokens[:, None], tpc)  # [B, 1, H]
    S = cache["k"].shape[2]
    # mask: new token sits at index `length`, may attend to 0..length
    attn_ok = (jnp.arange(S, dtype=jnp.int32)[None, :] <= lengths[:, None])[:, None, None]  # [B,1,1,S]

    def layer_fn(x, xs):
        from ray_tpu.llm.kv_cache import append_scale_layer, append_token_layer
        from ray_tpu.llm.kv_quant import quantize_heads

        if quant:
            layer, k_cache, v_cache, k_sc, v_sc = xs  # scales: [B, nkv, S]
        else:
            layer, k_cache, v_cache = xs  # k/v_cache: [B, S, nkv, hd]
        xn = rms_norm(x, layer["attn_norm"], cfg.rms_eps)
        q, k_t, v_t = _qkv(xn, layer, cfg)  # q: [B,1,nh,hd]
        qh = apply_rope(q.transpose(0, 2, 1, 3), cos, sin).transpose(0, 2, 1, 3)  # [B,1,nh,hd]
        kh = apply_rope(k_t.transpose(0, 2, 1, 3), cos, sin).transpose(0, 2, 1, 3)

        write_pos = jnp.minimum(lengths, S - 1)
        k_tok, v_tok = kh[:, 0], v_t[:, 0]
        if quant:
            k_tok, sk = quantize_heads(k_tok)  # [B, kv, hd] i8, [B, kv] f32
            v_tok, sv = quantize_heads(v_tok)
            k_sc = append_scale_layer(k_sc, sk, write_pos)
            v_sc = append_scale_layer(v_sc, sv, write_pos)
        k_cache, v_cache = append_token_layer(k_cache, v_cache, k_tok, v_tok, write_pos)
        # GQA attention against the cache: head h uses kv head h // rep
        qg = qh[:, 0].reshape(B, nkv, rep, hd)
        kc = k_cache.transpose(0, 2, 1, 3)  # [B,nkv,S,hd]
        vc = v_cache.transpose(0, 2, 1, 3)
        if quant:
            kc = kc.astype(jnp.float32) * k_sc[..., None]
            vc = vc.astype(jnp.float32) * v_sc[..., None]
        scores = jnp.einsum("bgrh,bgsh->bgrs", qg, kc, preferred_element_type=jnp.float32) / jnp.sqrt(hd)
        scores = jnp.where(attn_ok, scores, -jnp.inf)  # [B,1,1,S] bcast
        probs = jax.nn.softmax(scores, axis=-1)
        o = jnp.einsum("bgrs,bgsh->bgrh", probs, vc.astype(jnp.float32)).reshape(B, 1, nh * hd).astype(x.dtype)
        x = x + _tp_reduce(jnp.dot(o, layer["wo"]), tpc)
        x = _mlp(x, layer, cfg, tpc)
        return x, ((k_cache, v_cache, k_sc, v_sc) if quant else (k_cache, v_cache))

    xs = (params["layers"], cache["k"], cache["v"])
    if quant:
        xs += (cache["k_scale"], cache["v_scale"])
    x, ys = jax.lax.scan(layer_fn, x, xs)
    x = rms_norm(x[:, 0], params["final_norm"], cfg.rms_eps)
    unembed = params["embed"].T if cfg.tie_embeddings else params["unembed"]
    logits = _tp_gather_logits(jnp.dot(x, unembed, preferred_element_type=jnp.float32), tpc)
    if quant:
        ks, vs, kscs, vscs = ys
        new_cache = {"k": ks, "v": vs, "k_scale": kscs, "v_scale": vscs, "length": lengths + 1}
    else:
        ks, vs = ys
        new_cache = {"k": ks, "v": vs, "length": lengths + 1}
    return logits, new_cache


def extend(params, cache, slot, tokens, length, cfg: LlamaConfig):
    """Chunked prefill for ONE slot whose cache already holds a prefix.

    The primitive behind prefix-cache reuse and prefill/decode
    disaggregation (reference capabilities:
    python/ray/llm/_internal/serve/engines/vllm/vllm_models.py:215-228
    enable_prefix_caching, llm/tests/serve/.../prefill_decode_disagg/):
    the suffix attends to the already-cached prefix plus itself causally,
    with RoPE positions offset by the prefix length.

    tokens: [T_pad] int32 (right-padded suffix); length: [] int32 real
    suffix length; slot: [] int32. The cache's length[slot] is the prefix
    length `start`. Writes suffix K/V at start..start+length, returns
    (logits [vocab] f32 at the last real token, new cache) with
    length[slot] = start + length.
    """
    T = tokens.shape[0]
    nh, nkv, hd = cfg.num_heads, cfg.num_kv_heads, cfg.hd
    rep = nh // nkv
    quant = "k_scale" in cache
    S = cache["k"].shape[2]
    slot = jnp.asarray(slot, jnp.int32)
    start = cache["length"][slot]
    positions = start + jnp.arange(T, dtype=jnp.int32)
    cos, sin = rotary_embedding(positions, cfg.hd, cfg.rope_theta)
    x = jnp.take(params["embed"], tokens[None, :], axis=0)  # [1, T, H]
    # token i (at absolute pos start+i) sees cache pos j iff j <= start+i;
    # stale cache beyond the suffix is masked out by the same bound
    attn_ok = (jnp.arange(S, dtype=jnp.int32)[None, :] <= positions[:, None])[None, None]  # [1,1,T,S]
    zero = jnp.zeros((), jnp.int32)

    def layer_fn(x, xs):
        from ray_tpu.llm.kv_quant import quantize_heads

        if quant:
            layer, k_row, v_row, k_sc, v_sc = xs  # scales: [nkv, S]
        else:
            layer, k_row, v_row = xs  # [S, nkv, hd] for this slot
        xn = rms_norm(x, layer["attn_norm"], cfg.rms_eps)
        q, k_t, v_t = _qkv(xn, layer, cfg)  # [1, T, nh/nkv, hd]
        qh = apply_rope(q.transpose(0, 2, 1, 3), cos, sin)  # [1, nh, T, hd]
        kh = apply_rope(k_t.transpose(0, 2, 1, 3), cos, sin).transpose(0, 2, 1, 3)  # [1, T, nkv, hd]
        k_suf, v_suf = kh[0], v_t[0]  # [T, nkv, hd]
        if quant:
            k_suf, sk = quantize_heads(k_suf)  # sk: [T, nkv]
            v_suf, sv = quantize_heads(v_suf)
            k_sc = jax.lax.dynamic_update_slice(k_sc, sk.T, (zero, start))
            v_sc = jax.lax.dynamic_update_slice(v_sc, sv.T, (zero, start))
        k_row = jax.lax.dynamic_update_slice(k_row, k_suf.astype(k_row.dtype), (start, zero, zero))
        v_row = jax.lax.dynamic_update_slice(v_row, v_suf.astype(v_row.dtype), (start, zero, zero))
        qg = qh[0].reshape(nkv, rep, T, hd)
        kc = k_row.transpose(1, 0, 2)  # [nkv, S, hd]
        vc = v_row.transpose(1, 0, 2)
        if quant:
            kc = kc.astype(jnp.float32) * k_sc[..., None]
            vc = vc.astype(jnp.float32) * v_sc[..., None]
        scores = jnp.einsum("grth,gsh->grts", qg, kc, preferred_element_type=jnp.float32) / jnp.sqrt(hd)
        scores = jnp.where(attn_ok[0], scores, -jnp.inf)  # [nkv, rep, T, S] vs [1, T, S]
        probs = jax.nn.softmax(scores, axis=-1)
        o = jnp.einsum("grts,gsh->grth", probs, vc.astype(jnp.float32))
        o = o.transpose(2, 0, 1, 3).reshape(1, T, nh * hd).astype(x.dtype)
        x = x + jnp.dot(o, layer["wo"])
        x = _mlp(x, layer, cfg)
        return x, ((k_row, v_row, k_sc, v_sc) if quant else (k_row, v_row))

    xs = (params["layers"], cache["k"][:, slot], cache["v"][:, slot])  # [L, S, nkv, hd]
    if quant:
        xs += (cache["k_scale"][:, slot], cache["v_scale"][:, slot])  # [L, nkv, S]
    x, ys = jax.lax.scan(layer_fn, x, xs)
    x = rms_norm(x[0], params["final_norm"], cfg.rms_eps)  # [T, H]
    x_last = x[jnp.maximum(length - 1, 0)]
    unembed = params["embed"].T if cfg.tie_embeddings else params["unembed"]
    logits = jnp.dot(x_last, unembed, preferred_element_type=jnp.float32)
    if quant:
        k_new, v_new, ksc_new, vsc_new = ys
    else:
        k_new, v_new = ys
    k = jax.lax.dynamic_update_slice(cache["k"], k_new[:, None], (zero, slot, zero, zero, zero))
    v = jax.lax.dynamic_update_slice(cache["v"], v_new[:, None], (zero, slot, zero, zero, zero))
    lens = cache["length"].at[slot].set(start + length)
    if quant:
        ksc = jax.lax.dynamic_update_slice(cache["k_scale"], ksc_new[:, None], (zero, slot, zero, zero))
        vsc = jax.lax.dynamic_update_slice(cache["v_scale"], vsc_new[:, None], (zero, slot, zero, zero))
        return logits, {"k": k, "v": v, "k_scale": ksc, "v_scale": vsc, "length": lens}
    return logits, {"k": k, "v": v, "length": lens}


def decode_attn_paged(params, pool, tables, lengths, tokens, cfg: LlamaConfig, tpc: TpSpec | None = None,
                      attn_impl: str = "xla"):
    """READ-ONLY half of the paged decode step: attention over the cached
    pages plus the current token's K/V in registers. Returns
    (logits [slots, vocab] f32, k_new [L, slots, kv, hd], v_new same) —
    the scatter into the pool is a SEPARATE program (append_paged).

    The split is deliberate: a single program that both gathers from and
    scatters into the pool buffer was observed to corrupt reads
    nondeterministically on the XLA CPU runtime (in-place scatter racing
    page gathers). Keeping each program one-directional removes the
    aliasing hazard on every backend and costs one extra dispatch.

    ``tpc``: shard_map body mode, exactly as on decode_step — per-shard
    cfg, explicit all-reduce of the attention/MLP partials.

    ``attn_impl``: "xla" (default — the token-identical oracle) or
    "pallas" (llm/pallas/paged_attn.py: the page gather, int8 dequant
    and online-softmax attend fused into one HBM-streaming kernel; the
    scatter half below is untouched, so the aliasing split holds). A
    static string bound at jit time, engine-validated.
    """
    B = tokens.shape[0]
    nh, nkv, hd = cfg.num_heads, cfg.num_kv_heads, cfg.hd
    rep = nh // nkv
    quant = "k_scale" in pool
    cos, sin = rotary_embedding(lengths[:, None], cfg.hd, cfg.rope_theta)
    x = _tp_embed(params["embed"], tokens[:, None], tpc)  # [B, 1, H]
    scale = 1.0 / jnp.sqrt(jnp.asarray(hd, jnp.float32))

    from ray_tpu.llm.paged_kv import _paged_attn_batch

    def layer_fn(x, xs):
        if quant:
            layer, k_pool_l, v_pool_l, k_sc_l, v_sc_l = xs  # scales: [P, kv, page]
        else:
            layer, k_pool_l, v_pool_l = xs  # [P, page, kv, hd]
            k_sc_l = v_sc_l = None
        xn = rms_norm(x, layer["attn_norm"], cfg.rms_eps)
        q, k_t, v_t = _qkv(xn, layer, cfg)  # [B, 1, nh/nkv, hd]
        qh = apply_rope(q.transpose(0, 2, 1, 3), cos, sin).transpose(0, 2, 1, 3)
        kh = apply_rope(k_t.transpose(0, 2, 1, 3), cos, sin).transpose(0, 2, 1, 3)
        qg = qh[:, 0].reshape(B, nkv, rep, hd)
        o = _paged_attn_batch(qg, k_pool_l, v_pool_l, tables, lengths, scale, k_self=kh[:, 0], v_self=v_t[:, 0],
                              k_scale_l=k_sc_l, v_scale_l=v_sc_l, impl=attn_impl)
        o = o.reshape(B, 1, nh * hd).astype(x.dtype)
        x = x + _tp_reduce(jnp.dot(o, layer["wo"]), tpc)
        x = _mlp(x, layer, cfg, tpc)
        return x, (kh[:, 0], v_t[:, 0])

    xs = (params["layers"], pool["k"], pool["v"])
    if quant:
        xs += (pool["k_scale"], pool["v_scale"])
    x, (k_new, v_new) = jax.lax.scan(layer_fn, x, xs)
    x = rms_norm(x[:, 0], params["final_norm"], cfg.rms_eps)
    unembed = params["embed"].T if cfg.tie_embeddings else params["unembed"]
    logits = _tp_gather_logits(jnp.dot(x, unembed, preferred_element_type=jnp.float32), tpc)
    return logits, k_new, v_new


def append_paged(pool, write_page, write_off, k_new, v_new):
    """Scatter-only half of the paged decode step: write each slot's new
    token K/V at (write_page[b], write_off[b]) for every layer. An int8
    pool quantizes here — the append program IS the quantizer, so the
    attention half stays read-only and the aliasing split holds."""
    if "k_scale" in pool:
        from ray_tpu.llm.kv_quant import quantize_heads

        k_new, sk = quantize_heads(k_new)  # [L, B, kv, hd] i8, [L, B, kv] f32
        v_new, sv = quantize_heads(v_new)
        return {
            "k": pool["k"].at[:, write_page, write_off].set(k_new),
            "v": pool["v"].at[:, write_page, write_off].set(v_new),
            # scale layout [L, P, kv, page]: advanced indices split by the
            # kv slice, so the indexed result is [B, L, kv]
            "k_scale": pool["k_scale"].at[:, write_page, :, write_off].set(sk.transpose(1, 0, 2)),
            "v_scale": pool["v_scale"].at[:, write_page, :, write_off].set(sv.transpose(1, 0, 2)),
        }
    return {
        "k": pool["k"].at[:, write_page, write_off].set(k_new.astype(pool["k"].dtype)),
        "v": pool["v"].at[:, write_page, write_off].set(v_new.astype(pool["v"].dtype)),
    }


def decode_write_targets(tables, lengths, page: int):
    """(write_page [B], write_off [B]) for each slot's next token (trash
    page for rows past the table edge)."""
    B = lengths.shape[0]
    page_ix = jnp.minimum(lengths // page, tables.shape[1] - 1)
    write_page = tables[jnp.arange(B, dtype=jnp.int32), page_ix]
    return write_page, lengths % page


def extend_write_targets(table_row, start, T: int, page: int):
    """(write_page [T], write_off [T]) for a suffix chunk at absolute
    positions start..start+T-1."""
    positions = jnp.asarray(start, jnp.int32) + jnp.arange(T, dtype=jnp.int32)
    page_ix = jnp.minimum(positions // page, table_row.shape[0] - 1)
    return table_row[page_ix], positions % page


def decode_step_paged(params, pool, tables, lengths, tokens, cfg: LlamaConfig, attn_impl: str = "xla"):
    """Convenience wrapper: attention program + append program (two
    dispatches; see decode_attn_paged for why they must stay separate).
    Returns (logits, new pool, lengths+1)."""
    write_page, write_off = decode_write_targets(tables, lengths, pool["k"].shape[2])
    logits, k_new, v_new = decode_attn_paged(params, pool, tables, lengths, tokens, cfg, attn_impl=attn_impl)
    pool = append_paged(pool, write_page, write_off, k_new, v_new)
    return logits, pool, lengths + 1


def extend_attn_paged(params, pool, table_row, start, tokens, length, cfg: LlamaConfig,
                      attn_impl: str = "xla"):
    """READ-ONLY half of paged chunked-prefill: the suffix attends to the
    cached prefix pages plus itself causally (in registers). Returns
    (logits [vocab] f32 at the last real token, k_chunk [L, T, kv, hd],
    v_chunk same); the pool scatter is a separate program. ``attn_impl``
    "pallas" streams the prefix pages through the fused kernel (B=1 lane
    batch); the causal chunk stays in registers either way."""
    T = tokens.shape[0]
    nh, nkv, hd = cfg.num_heads, cfg.num_kv_heads, cfg.hd
    rep = nh // nkv
    quant = "k_scale" in pool
    start = jnp.asarray(start, jnp.int32)
    positions = start + jnp.arange(T, dtype=jnp.int32)
    cos, sin = rotary_embedding(positions, cfg.hd, cfg.rope_theta)
    x = jnp.take(params["embed"], tokens[None, :], axis=0)  # [1, T, H]
    scale = 1.0 / jnp.sqrt(jnp.asarray(hd, jnp.float32))

    from ray_tpu.llm.paged_kv import _paged_attn_seq, _paged_attn_seq_batch

    def layer_fn(x, xs):
        if quant:
            layer, k_pool_l, v_pool_l, k_sc_l, v_sc_l = xs
        else:
            layer, k_pool_l, v_pool_l = xs
            k_sc_l = v_sc_l = None
        xn = rms_norm(x, layer["attn_norm"], cfg.rms_eps)
        q, k_t, v_t = _qkv(xn, layer, cfg)  # [1, T, nh/nkv, hd]
        qh = apply_rope(q.transpose(0, 2, 1, 3), cos, sin)  # [1, nh, T, hd]
        kh = apply_rope(k_t.transpose(0, 2, 1, 3), cos, sin).transpose(0, 2, 1, 3)  # [1, T, nkv, hd]
        qg = qh[0].reshape(nkv, rep, T, hd)
        if attn_impl == "pallas":
            o = _paged_attn_seq_batch(
                qg[None], k_pool_l, v_pool_l, table_row[None], start[None], kh, v_t, scale,
                k_scale_l=k_sc_l, v_scale_l=v_sc_l, impl=attn_impl,
            )[0]
        else:
            o = _paged_attn_seq(qg, k_pool_l, v_pool_l, table_row, start, kh[0], v_t[0], scale,
                                k_scale_l=k_sc_l, v_scale_l=v_sc_l)
        o = o.transpose(2, 0, 1, 3).reshape(1, T, nh * hd).astype(x.dtype)
        x = x + jnp.dot(o, layer["wo"])
        x = _mlp(x, layer, cfg)
        return x, (kh[0], v_t[0])

    xs = (params["layers"], pool["k"], pool["v"])
    if quant:
        xs += (pool["k_scale"], pool["v_scale"])
    x, (k_chunk, v_chunk) = jax.lax.scan(layer_fn, x, xs)
    x = rms_norm(x[0], params["final_norm"], cfg.rms_eps)  # [T, H]
    x_last = x[jnp.maximum(length - 1, 0)]
    unembed = params["embed"].T if cfg.tie_embeddings else params["unembed"]
    logits = jnp.dot(x_last, unembed, preferred_element_type=jnp.float32)
    return logits, k_chunk, v_chunk


def append_chunk_paged(pool, write_page, write_off, k_chunk, v_chunk):
    """Scatter-only half of paged chunked-prefill: write the suffix K/V
    rows (write_page/write_off: [T]) for every layer. An int8 pool
    quantizes here, exactly as append_paged does for decode."""
    if "k_scale" in pool:
        from ray_tpu.llm.kv_quant import quantize_heads

        k_chunk, sk = quantize_heads(k_chunk)  # [L, T, kv, hd] i8, [L, T, kv] f32
        v_chunk, sv = quantize_heads(v_chunk)
        return {
            "k": pool["k"].at[:, write_page, write_off].set(k_chunk),
            "v": pool["v"].at[:, write_page, write_off].set(v_chunk),
            "k_scale": pool["k_scale"].at[:, write_page, :, write_off].set(sk.transpose(1, 0, 2)),
            "v_scale": pool["v_scale"].at[:, write_page, :, write_off].set(sv.transpose(1, 0, 2)),
        }
    return {
        "k": pool["k"].at[:, write_page, write_off].set(k_chunk.astype(pool["k"].dtype)),
        "v": pool["v"].at[:, write_page, write_off].set(v_chunk.astype(pool["v"].dtype)),
    }


def extend_paged(params, pool, table_row, start, tokens, length, cfg: LlamaConfig, attn_impl: str = "xla"):
    """Convenience wrapper: attention program + chunk append program (two
    dispatches; see decode_attn_paged for the split rationale). Returns
    (logits [vocab] f32 at the last real token, new pool)."""
    write_page, write_off = extend_write_targets(table_row, start, tokens.shape[0], pool["k"].shape[2])
    logits, k_chunk, v_chunk = extend_attn_paged(params, pool, table_row, start, tokens, length, cfg,
                                                 attn_impl=attn_impl)
    pool = append_chunk_paged(pool, write_page, write_off, k_chunk, v_chunk)
    return logits, pool


@jaxcheck.entry(
    name="llm.fused_step",
    shapes={"b8_s256": _bucket_fused},
    donate=("cache", "keys", "temps", "top_k", "top_p"),
    donate_bytes=0,  # the whole hot loop is audited: every lane buffer counts
)
def fused_step(
    params,
    cache,
    tokens,  # tpulint: disable=JXC001 — the previous step's sampled-token output; the engine still holds it for the delayed host readback, so donating it would poison the in-flight transfer
    keys,
    temps,
    top_k,
    top_p,
    cfg: LlamaConfig,
    tpc: TpSpec | None = None,
):
    """ONE program for the slot layout's whole decode hot path: decode ->
    sample -> append-KV -> advance lengths. Nothing in it touches the
    host; the engine reads tokens back asynchronously one step behind the
    dispatch (device-resident loop).

    The sampling lanes (keys, temps, top_k, top_p) are donated and handed
    back as passthrough outputs — XLA aliases them in place (zero copies)
    and the engine rebinds its handles each step, so every buffer the
    loop touches stays device-resident with exactly one live copy.
    tokens is deliberately NOT donated (see inline disable above).

    With ``tpc`` this is the shard_map body over the tp mesh: the lanes
    are replicated, the sampler runs identically on every shard over the
    all-gathered logits, and the ONE-program-per-token invariant extends
    across chips — the all-reduce lives inside this jitted step.
    """
    from ray_tpu.llm.sampling import sample

    logits, cache = decode_step(params, cache, tokens, cfg, tpc)
    toks, logps, new_keys = sample(logits, keys, temps, top_k, top_p)
    return cache, toks, logps, new_keys, temps, top_k, top_p


# int8-cache variant of the SAME program (quantize-on-append inside
# decode_step, dequantize-in-attention): its own registry entry so the
# donation audit and the JXC003 bf16->f32-before-dot trap are checked on
# the quantized hot path too (the dequant is an int8->f32 convert feeding
# the attention einsums at their existing compute dtype, and must never
# drift onto the flops-dominant dots — regression-locked in
# tests/test_lint_rules.py).
jaxcheck.entry(
    name="llm.fused_step_int8",
    shapes={"b8_s256": _bucket_fused_q},
    donate=("cache", "keys", "temps", "top_k", "top_p"),
    donate_bytes=0,
)(fused_step)


def _sharded_fused_slots(cfg: LlamaConfig, mesh, tp_collective: str, kv_quant: bool):
    """The slot fused step under shard_map over the tp axis (unjitted):
    params/cache enter at their engine shardings, lanes replicated, and
    the per-layer all-reduce is the explicit _tp_reduce psum."""
    from jax.sharding import PartitionSpec as P

    from ray_tpu.parallel.mesh import axis_size

    tp = axis_size(mesh, "tp")
    tpc = TpSpec("tp", tp, tp_collective)
    cache_sp = _cache_pspecs("slots", kv_quant)
    rep = P()
    return _tp_shard_map(
        partial(fused_step, cfg=_shard_cfg(cfg, tp), tpc=tpc),
        mesh,
        in_specs=(_param_pspecs(cfg, mesh), cache_sp, rep, rep, rep, rep, rep),
        out_specs=(cache_sp, rep, rep, rep, rep, rep, rep),
    )


def make_fused_fns(cfg: LlamaConfig, mesh=None, tp_collective: str = "fp", kv_quant: bool = False):
    """Jit of fused_step with the production donation set. With a tp>1
    mesh the step compiles as ONE SPMD program via shard_map — the
    per-layer tp all-reduce is an explicit psum inside it, quantized to
    int8 on the wire when tp_collective="int8"."""
    from ray_tpu.parallel.mesh import axis_size

    if mesh is not None and axis_size(mesh, "tp") > 1:
        return jax.jit(_sharded_fused_slots(cfg, mesh, tp_collective, kv_quant), donate_argnums=(1, 3, 4, 5, 6))
    return jax.jit(partial(fused_step, cfg=cfg), donate_argnums=(1, 3, 4, 5, 6))


@jaxcheck.entry(
    name="llm.paged_fused_step",
    shapes={"b8_p64": _bucket_paged_fused},
    donate=("lengths", "keys", "temps", "top_k", "top_p"),
    donate_bytes=0,
)
def paged_fused_step(
    params,
    pool,  # read-only by design (the gather/scatter aliasing hazard); donated by the append program instead
    tables,
    lengths,
    tokens,  # tpulint: disable=JXC001 — feeds the delayed host readback (same rationale as fused_step)
    keys,
    temps,
    top_k,
    top_p,
    cfg: LlamaConfig,
    tpc: TpSpec | None = None,
    attn_impl: str = "xla",
):
    """READ-ONLY half of the paged device-resident step: attention +
    sample + write-target math; the scatter-append into the pool is a
    SEPARATE program (append_paged) — see decode_attn_paged for the
    gather/scatter aliasing hazard that forbids fusing them. Sampling
    lanes are donated-and-passed-through exactly as in fused_step.
    ``tpc``: shard_map body mode (see fused_step). ``attn_impl``:
    "pallas" rides the fused HBM-streaming kernel for the page attention
    (engine opt-in, see decode_attn_paged); the append program is
    untouched either way."""
    from ray_tpu.llm.sampling import sample

    write_page, write_off = decode_write_targets(tables, lengths, pool["k"].shape[2])
    logits, k_new, v_new = decode_attn_paged(params, pool, tables, lengths, tokens, cfg, tpc,
                                             attn_impl=attn_impl)
    toks, logps, new_keys = sample(logits, keys, temps, top_k, top_p)
    return toks, logps, new_keys, k_new, v_new, write_page, write_off, lengths + 1, temps, top_k, top_p


# int8-pool variant (see llm.fused_step_int8's rationale); the pool stays
# undonated/read-only here — the append program is the quantizer
jaxcheck.entry(
    name="llm.paged_fused_step_int8",
    shapes={"b8_p64": _bucket_paged_fused_q},
    donate=("lengths", "keys", "temps", "top_k", "top_p"),
    donate_bytes=0,
)(paged_fused_step)


def _sharded_fused_paged(cfg: LlamaConfig, mesh, tp_collective: str, kv_quant: bool):
    """paged_fused_step under shard_map over the tp axis (unjitted). The
    pool enters read-only at its engine sharding; the new-token K/V
    leaves kv-sharded for the (GSPMD, collective-free) append program."""
    from jax.sharding import PartitionSpec as P

    from ray_tpu.parallel.mesh import axis_size

    tp = axis_size(mesh, "tp")
    tpc = TpSpec("tp", tp, tp_collective)
    pool_sp = _cache_pspecs("paged", kv_quant)
    kv_new = P(None, None, "tp", None)  # k_new/v_new: [L, B, kv, hd]
    rep = P()
    return _tp_shard_map(
        partial(paged_fused_step, cfg=_shard_cfg(cfg, tp), tpc=tpc),
        mesh,
        in_specs=(_param_pspecs(cfg, mesh), pool_sp, rep, rep, rep, rep, rep, rep, rep),
        out_specs=(rep, rep, rep, kv_new, kv_new, rep, rep, rep, rep, rep, rep),
    )


def make_fused_paged_fns(cfg: LlamaConfig, mesh=None, tp_collective: str = "fp", kv_quant: bool = False,
                         attn_impl: str = "xla"):
    """Device-resident decode step for the paged layout: TWO programs
    (attention+sample, then scatter-append), neither of which ever syncs
    with the host. tables is read every step and mutated only by
    scheduler deltas. With a tp>1 mesh the attention half compiles under
    shard_map (explicit per-layer all-reduce, optionally int8 on the
    wire); the append half stays a plain GSPMD jit — its scatter is
    elementwise per kv-head, so partitioning it needs no collectives and
    the documented gather/scatter program split is untouched.
    ``attn_impl="pallas"``: the attention half's page loop runs as the
    fused HBM-streaming kernel (single-device path only — the engine
    degrades to "xla" on tp meshes)."""
    from ray_tpu.parallel.mesh import axis_size

    if mesh is not None and axis_size(mesh, "tp") > 1:
        attn_fn = jax.jit(_sharded_fused_paged(cfg, mesh, tp_collective, kv_quant), donate_argnums=(3, 5, 6, 7, 8))
    else:
        attn_fn = jax.jit(partial(paged_fused_step, cfg=cfg, attn_impl=attn_impl), donate_argnums=(3, 5, 6, 7, 8))
    append_fn = jax.jit(append_paged, donate_argnums=(0,))
    return attn_fn, append_fn


@jaxcheck.entry(
    name="llm.delta_set_lane",
    shapes={"b8": _bucket_set_lane},
    donate_bytes=0,
)
def set_lane(tokens, keys, temps, top_k, top_p, slot, token, key, temp, tk, tp):  # tpulint: disable=JXC001 — delta fns deliberately donate nothing: the engine may still hold every one of these buffers for an in-flight step's delayed readback when a scheduler delta lands
    """O(1) jitted scatter for admission: write one slot's lane state."""
    return (
        tokens.at[slot].set(token),
        keys.at[slot].set(key),
        temps.at[slot].set(temp),
        top_k.at[slot].set(tk),
        top_p.at[slot].set(tp),
    )


def set_table(tables, lengths, slot, row, length):
    return tables.at[slot].set(row), lengths.at[slot].set(length)


def set_table_cell(tables, slot, pg_ix, page):
    return tables.at[slot, pg_ix].set(page)


def make_delta_fns():
    """Jitted scatter updates for scheduler deltas on device-resident
    decode state (admission / eviction / page growth). Each compiles once
    (slot/index are traced scalars) and touches O(1) elements — the
    replacement for re-uploading whole host arrays every step. Nothing is
    donated (see set_lane's inline rationale)."""
    return jax.jit(set_lane), jax.jit(set_table), jax.jit(set_table_cell)


# ---------------------------------------------------------------------------
# jaxcheck entries for the SHARDED serving path: the fused steps traced
# over a real 2-way tp mesh (the tracing env guarantees >= 8 virtual CPU
# devices), so JXC005 finally audits the serving-path collectives against
# their declared mesh axes — psum/all_gather/all_to_all/axis_index must
# all run over 'tp' and nothing else, and the donation/padding/upcast
# rules re-check the program in its multi-chip form.
# ---------------------------------------------------------------------------
def _tp2_mesh():
    import numpy as np
    from jax.sharding import Mesh

    devs = jax.devices()
    if len(devs) < 2:
        raise RuntimeError("tp entries trace over 2 devices; the tracing env provides 8 virtual CPU devices")
    return Mesh(np.asarray(devs[:2]), ("tp",))


def _bucket_fused_tp(B=8, S=256):
    cfg = _trace_cfg()
    return (_sds_params(cfg), _sds_cache(cfg, B, S)) + _sds_lanes(B), {}


def _bucket_paged_fused_tp(B=8, pages=64, page=16):
    cfg = _trace_cfg()
    tables = _sds((B, pages // B * 2), jnp.int32)
    lengths = _sds((B,), jnp.int32)
    tokens, keys, temps, top_k, top_p = _sds_lanes(B)
    return (
        _sds_params(cfg), _sds_pool(cfg, pages, page), tables, lengths,
        tokens, keys, temps, top_k, top_p,
    ), {}


@jaxcheck.entry(
    name="llm.fused_step_tp",
    shapes={"b8_s256_tp2": _bucket_fused_tp},
    donate=("cache", "keys", "temps", "top_k", "top_p"),
    donate_bytes=0,
    mesh_axes=("tp",),
)
def fused_step_tp(
    params,
    cache,
    tokens,  # tpulint: disable=JXC001 — same delayed-readback rationale as fused_step's token lane
    keys,
    temps,
    top_k,
    top_p,
):
    """make_fused_fns(mesh=2-way tp) in registry-traceable form: the fp
    collective schedule (explicit per-layer psum over 'tp')."""
    return _sharded_fused_slots(_trace_cfg(), _tp2_mesh(), "fp", False)(
        params, cache, tokens, keys, temps, top_k, top_p
    )


@jaxcheck.entry(
    name="llm.fused_step_tp_int8c",
    shapes={"b8_s256_tp2": _bucket_fused_tp},
    donate=("cache", "keys", "temps", "top_k", "top_p"),
    donate_bytes=0,
    mesh_axes=("tp",),
)
def fused_step_tp_int8c(
    params,
    cache,
    tokens,  # tpulint: disable=JXC001 — same delayed-readback rationale as fused_step's token lane
    keys,
    temps,
    top_k,
    top_p,
):
    """The int8-collective variant (tp_collective="int8"): the per-layer
    all-reduce ships int8 + f32 amax scales over ICI. The dequants feed
    residual adds and the exact f32 chunk accumulate — never a
    flops-dominant dot, so JXC003 stays clean by construction here."""
    return _sharded_fused_slots(_trace_cfg(), _tp2_mesh(), "int8", False)(
        params, cache, tokens, keys, temps, top_k, top_p
    )


@jaxcheck.entry(
    name="llm.paged_fused_step_tp",
    shapes={"b8_p64_tp2": _bucket_paged_fused_tp},
    donate=("lengths", "keys", "temps", "top_k", "top_p"),
    donate_bytes=0,
    mesh_axes=("tp",),
)
def paged_fused_step_tp(
    params,
    pool,  # read-only by design (the gather/scatter aliasing hazard); donated by the append program instead
    tables,
    lengths,
    tokens,  # tpulint: disable=JXC001 — same delayed-readback rationale as fused_step's token lane
    keys,
    temps,
    top_k,
    top_p,
):
    """make_fused_paged_fns(mesh=2-way tp)'s attention half in
    registry-traceable form (the append half is collective-free GSPMD)."""
    return _sharded_fused_paged(_trace_cfg(), _tp2_mesh(), "fp", False)(
        params, pool, tables, lengths, tokens, keys, temps, top_k, top_p
    )


def make_runner_fns(cfg: LlamaConfig):
    """Jitted (prefill, insert, decode, extend) closures for an engine."""
    from ray_tpu.llm import kv_cache as kvc

    prefill_fn = jax.jit(partial(prefill, cfg=cfg))
    insert_fn = jax.jit(kvc.insert_sequence, donate_argnums=(0,))
    decode_fn = jax.jit(partial(decode_step, cfg=cfg), donate_argnums=(1,))
    extend_fn = jax.jit(partial(extend, cfg=cfg), donate_argnums=(1,))
    return prefill_fn, insert_fn, decode_fn, extend_fn


def make_paged_runner_fns(cfg: LlamaConfig, attn_impl: str = "xla"):
    """Jitted (prefill, insert_pages, decode, extend) for a paged engine.

    Decode/extend each compile as TWO programs — read-only attention and
    scatter-only append — never fused (jitting the combined wrapper would
    reintroduce the same-program gather+scatter aliasing hazard; see
    decode_attn_paged). ``attn_impl`` selects the page-attention body of
    both read-only halves ("xla" oracle / "pallas" fused kernel)."""
    from ray_tpu.llm import paged_kv as pkv

    prefill_fn = jax.jit(partial(prefill, cfg=cfg))
    insert_fn = jax.jit(pkv.insert_pages, donate_argnums=(0,))
    attn_fn = jax.jit(partial(decode_attn_paged, cfg=cfg, attn_impl=attn_impl))
    append_fn = jax.jit(append_paged, donate_argnums=(0,))
    ext_attn_fn = jax.jit(partial(extend_attn_paged, cfg=cfg, attn_impl=attn_impl))
    ext_append_fn = jax.jit(append_chunk_paged, donate_argnums=(0,))

    def decode_fn(params, pool, tables, lengths, tokens):
        write_page, write_off = decode_write_targets(tables, lengths, pool["k"].shape[2])
        logits, k_new, v_new = attn_fn(params, pool, tables, lengths, tokens)
        pool = append_fn(pool, write_page, write_off, k_new, v_new)
        return logits, pool, lengths + 1

    def extend_fn(params, pool, table_row, start, tokens, length):
        write_page, write_off = extend_write_targets(table_row, start, tokens.shape[0], pool["k"].shape[2])
        logits, k_chunk, v_chunk = ext_attn_fn(params, pool, table_row, start, tokens, length)
        pool = ext_append_fn(pool, write_page, write_off, k_chunk, v_chunk)
        return logits, pool

    return prefill_fn, insert_fn, decode_fn, extend_fn
