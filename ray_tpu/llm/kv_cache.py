"""Slot-based KV cache for continuous-batching decode.

Static-shaped by design: XLA compiles the decode step once for the whole
serving lifetime. The cache is a pytree of stacked per-layer arrays

    k, v: [L, slots, max_seq_len, kv_heads, head_dim]
    length: [slots] int32   (tokens currently valid per slot; 0 = empty)

A "slot" is one concurrent sequence. Admission = prefill writes a new
sequence's K/V into a free slot at offset 0; decode appends one token per
active slot per step via per-slot dynamic_update_slice. This is the
TPU-native answer to vLLM's paged KV blocks (ref capability:
python/ray/llm/_internal/serve/engines/vllm/vllm_models.py:215-228):
on TPU, static shapes + donation beat dynamic paging because XLA aliases
the cache in-place and the MXU sees one fixed program.

Measured (v5e chip, 1.1B-param llama, bf16 cache, 2026-07-31): cache HBM
is exactly linear in slots x max_seq_len as the shape predicts — 0.69 GiB
at 8x2048, 2.75 GiB at 8x8192 or 32x2048 — and per-decode-step wall time
was FLAT across those configs (the dispatch path, not the MXU, bounds a
single tunneled chip, so extra slots are nearly free throughput: 8 slots
21.7 tok/s -> 32 slots 84.0 tok/s at identical step latency). Against
~16 GiB HBM minus ~2.2 GiB weights, the static design holds 8 slots to
~32K tokens or 32 slots to ~8K; past that working set (e.g. 32 slots x
32K = 11 GiB + activations) is where block paging or prefix sharing
becomes necessary rather than merely nice — the quantified threshold the
earlier qualitative claim needed.

Int8 cache (``dtype="int8"``, llm/kv_quant.py) moves that threshold by
``2*hd/(hd+4)``: per token per layer the cache stores ``2*kv*(hd + 4)``
bytes (int8 values + one f32 per-head scale) instead of ``2*kv*hd*2``
bf16 bytes — 1.94x fewer at hd=128. The 11 GiB 32x32K working set above
drops to ~5.7 GiB, so the same ~13.8 GiB budget that capped bf16 at 32
slots x 8K holds int8 at 32 slots to ~16K or ~62 slots at 8K — and since
decode is HBM-bandwidth-bound, the bytes each step streams shrink by the
same factor. Quantization happens on append inside the fused step;
attention dequantizes on read (scale layout [L, B, kv, S]: position axis
last, so scale tiles waste nothing — see kv_quant.py).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from ray_tpu.llm.kv_quant import dequantize, is_int8, quantize_heads


@dataclass(frozen=True)
class CacheConfig:
    num_layers: int
    num_slots: int
    max_seq_len: int
    num_kv_heads: int
    head_dim: int
    dtype: str = "bfloat16"  # bf16/f32 variants, or "int8" (kv_quant.py)


def alloc(cfg: CacheConfig) -> dict:
    shape = (cfg.num_layers, cfg.num_slots, cfg.max_seq_len, cfg.num_kv_heads, cfg.head_dim)
    if is_int8(cfg.dtype):
        # per-head scales with the position axis LAST ([L, B, kv, S]) so
        # the trailing dims stay on (8,128) tile multiples (kv_quant.py)
        sshape = (cfg.num_layers, cfg.num_slots, cfg.num_kv_heads, cfg.max_seq_len)
        return {
            "k": jnp.zeros(shape, dtype=jnp.int8),
            "v": jnp.zeros(shape, dtype=jnp.int8),
            "k_scale": jnp.zeros(sshape, dtype=jnp.float32),
            "v_scale": jnp.zeros(sshape, dtype=jnp.float32),
            "length": jnp.zeros((cfg.num_slots,), dtype=jnp.int32),
        }
    dt = jnp.dtype(cfg.dtype)
    return {
        "k": jnp.zeros(shape, dtype=dt),
        "v": jnp.zeros(shape, dtype=dt),
        "length": jnp.zeros((cfg.num_slots,), dtype=jnp.int32),
    }


def insert_sequence(cache: dict, slot, k_new, v_new, length, k_scale=None, v_scale=None):
    """Write a prefilled sequence into `slot` at offset 0.

    k_new/v_new: [L, T_pad, kv_heads, head_dim] (padded tail is garbage and
    stays masked by `length`). slot/length: traced scalars — one compiled
    program serves every slot and every prefill bucket.

    Dtype adaptation is transparent in all four directions: fp block into
    an int8 cache quantizes here (prefill writes quantized blocks); an
    int8 block (+ ``k_scale``/``v_scale`` [L, kv, T_pad], the handoff wire
    layout) into an int8 cache copies bytes; int8 into an fp cache
    dequantizes; fp into fp is the original path.
    """
    zero = jnp.zeros((), dtype=jnp.int32)
    start = (zero, jnp.asarray(slot, jnp.int32), zero, zero, zero)
    quant = "k_scale" in cache
    if not quant and k_scale is not None:  # int8 block -> fp cache
        k_new = dequantize(k_new, k_scale.transpose(0, 2, 1))
        v_new = dequantize(v_new, v_scale.transpose(0, 2, 1))
        k_scale = v_scale = None
    if quant:
        if k_scale is None:  # fp block -> quantize on insert
            k_new, sk = quantize_heads(k_new)  # sk: [L, T, kv]
            v_new, sv = quantize_heads(v_new)
            k_scale, v_scale = sk.transpose(0, 2, 1), sv.transpose(0, 2, 1)
        s_start = (zero, jnp.asarray(slot, jnp.int32), zero, zero)
        k_sc = jax.lax.dynamic_update_slice(cache["k_scale"], k_scale[:, None].astype(jnp.float32), s_start)
        v_sc = jax.lax.dynamic_update_slice(cache["v_scale"], v_scale[:, None].astype(jnp.float32), s_start)
    k = jax.lax.dynamic_update_slice(cache["k"], k_new[:, None].astype(cache["k"].dtype), start)
    v = jax.lax.dynamic_update_slice(cache["v"], v_new[:, None].astype(cache["v"].dtype), start)
    lens = cache["length"].at[slot].set(jnp.asarray(length, jnp.int32))
    if quant:
        return {"k": k, "v": v, "k_scale": k_sc, "v_scale": v_sc, "length": lens}
    return {"k": k, "v": v, "length": lens}


def append_token_layer(k_layer, v_layer, k_t, v_t, lengths):
    """Append one token's K/V per slot at position lengths[b].

    k_layer/v_layer: [slots, S, kv, hd]; k_t/v_t: [slots, kv, hd].
    Inactive slots are written too (at their stale length) — harmless, the
    attention mask never reads past `length`.
    """

    def _upd(cache_b, t_b, pos):
        return jax.lax.dynamic_update_slice(
            cache_b, t_b[None].astype(cache_b.dtype), (pos, jnp.zeros((), jnp.int32), jnp.zeros((), jnp.int32))
        )

    k = jax.vmap(_upd)(k_layer, k_t, lengths)
    v = jax.vmap(_upd)(v_layer, v_t, lengths)
    return k, v


def append_scale_layer(scale_layer, s_t, lengths):
    """Per-slot scale append companion to append_token_layer.

    scale_layer: [slots, kv, S] (position axis last); s_t: [slots, kv];
    lengths: [slots] write positions.
    """

    def _upd(sc_b, s_b, pos):
        return jax.lax.dynamic_update_slice(sc_b, s_b[:, None], (jnp.zeros((), jnp.int32), pos))

    return jax.vmap(_upd)(scale_layer, s_t, lengths)


def extract_sequence(cache: dict, slot, T: int):
    """Read one slot's first ``T`` cached positions as a contiguous block.

    Inverse of insert_sequence: returns (k [L, T, kv, hd], v same) — the
    disaggregated-prefill extract primitive (llm/disagg/) — plus, for an
    int8 cache, (k_scale [L, kv, T], v_scale same): the handoff wire
    layout, so quantized blocks ship self-describing at ~half the bytes.
    ``T`` is static (one compiled program per prefill bucket, like
    insert); ``slot`` is a traced scalar. Positions past the slot's real
    length are garbage the consumer masks by length, exactly as
    prefill's padded tail."""
    zero = jnp.zeros((), dtype=jnp.int32)
    start = (zero, jnp.asarray(slot, jnp.int32), zero, zero, zero)
    L, _, _, kv, hd = cache["k"].shape
    size = (L, 1, T, kv, hd)
    k = jax.lax.dynamic_slice(cache["k"], start, size)[:, 0]
    v = jax.lax.dynamic_slice(cache["v"], start, size)[:, 0]
    if "k_scale" in cache:
        s_start = (zero, jnp.asarray(slot, jnp.int32), zero, zero)
        s_size = (L, 1, kv, T)
        k_sc = jax.lax.dynamic_slice(cache["k_scale"], s_start, s_size)[:, 0]
        v_sc = jax.lax.dynamic_slice(cache["v_scale"], s_start, s_size)[:, 0]
        return k, v, k_sc, v_sc
    return k, v


def free_slot(cache: dict, slot: int) -> dict:
    """Mark a slot empty (host-side bookkeeping mirrors this)."""
    return {**cache, "length": cache["length"].at[slot].set(0)}
