"""Slot-based KV cache for continuous-batching decode.

Static-shaped by design: XLA compiles the decode step once for the whole
serving lifetime. The cache is a pytree of stacked per-layer arrays

    k, v: [L, slots, max_seq_len, kv_heads, head_dim]
    length: [slots] int32   (tokens currently valid per slot; 0 = empty)

A "slot" is one concurrent sequence. Admission = prefill writes a new
sequence's K/V into a free slot at offset 0; decode appends one token per
active slot per step via per-slot dynamic_update_slice. This is the
TPU-native answer to vLLM's paged KV blocks (ref capability:
python/ray/llm/_internal/serve/engines/vllm/vllm_models.py:215-228):
on TPU, static shapes + donation beat dynamic paging because XLA aliases
the cache in-place and the MXU sees one fixed program.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class CacheConfig:
    num_layers: int
    num_slots: int
    max_seq_len: int
    num_kv_heads: int
    head_dim: int
    dtype: str = "bfloat16"


def alloc(cfg: CacheConfig) -> dict:
    shape = (cfg.num_layers, cfg.num_slots, cfg.max_seq_len, cfg.num_kv_heads, cfg.head_dim)
    dt = jnp.dtype(cfg.dtype)
    return {
        "k": jnp.zeros(shape, dtype=dt),
        "v": jnp.zeros(shape, dtype=dt),
        "length": jnp.zeros((cfg.num_slots,), dtype=jnp.int32),
    }


def insert_sequence(cache: dict, slot, k_new, v_new, length):
    """Write a prefilled sequence into `slot` at offset 0.

    k_new/v_new: [L, T_pad, kv_heads, head_dim] (padded tail is garbage and
    stays masked by `length`). slot/length: traced scalars — one compiled
    program serves every slot and every prefill bucket.
    """
    zero = jnp.zeros((), dtype=jnp.int32)
    start = (zero, jnp.asarray(slot, jnp.int32), zero, zero, zero)
    k = jax.lax.dynamic_update_slice(cache["k"], k_new[:, None].astype(cache["k"].dtype), start)
    v = jax.lax.dynamic_update_slice(cache["v"], v_new[:, None].astype(cache["v"].dtype), start)
    lens = cache["length"].at[slot].set(jnp.asarray(length, jnp.int32))
    return {"k": k, "v": v, "length": lens}


def append_token_layer(k_layer, v_layer, k_t, v_t, lengths):
    """Append one token's K/V per slot at position lengths[b].

    k_layer/v_layer: [slots, S, kv, hd]; k_t/v_t: [slots, kv, hd].
    Inactive slots are written too (at their stale length) — harmless, the
    attention mask never reads past `length`.
    """

    def _upd(cache_b, t_b, pos):
        return jax.lax.dynamic_update_slice(
            cache_b, t_b[None].astype(cache_b.dtype), (pos, jnp.zeros((), jnp.int32), jnp.zeros((), jnp.int32))
        )

    k = jax.vmap(_upd)(k_layer, k_t, lengths)
    v = jax.vmap(_upd)(v_layer, v_t, lengths)
    return k, v


def free_slot(cache: dict, slot: int) -> dict:
    """Mark a slot empty (host-side bookkeeping mirrors this)."""
    return {**cache, "length": cache["length"].at[slot].set(0)}
