"""Exception types (reference: python/ray/exceptions.py)."""

from __future__ import annotations

import traceback


class RayTpuError(Exception):
    pass


class TaskError(RayTpuError):
    """A task raised an exception; re-raised at ray_tpu.get().

    Reference semantics: RayTaskError wraps the user exception with the
    remote traceback (python/ray/exceptions.py).
    """

    def __init__(self, cause: BaseException | None = None, tb_str: str = "", task_desc: str = ""):
        self.cause = cause
        self.tb_str = tb_str
        self.task_desc = task_desc
        super().__init__(f"task {task_desc} failed:\n{tb_str}")

    @classmethod
    def from_exception(cls, e: BaseException, task_desc: str = ""):
        return cls(cause=e, tb_str="".join(traceback.format_exception(type(e), e, e.__traceback__)), task_desc=task_desc)

    def __reduce__(self):
        import pickle

        cause = self.cause
        if cause is not None:
            try:
                pickle.dumps(cause)
            except Exception:
                cause = None  # unpicklable user exception: keep the traceback string only
        return (_rebuild_task_error, (cause, self.tb_str, self.task_desc))


def _rebuild_task_error(cause, tb_str, task_desc):
    return TaskError(cause=cause, tb_str=tb_str, task_desc=task_desc)


class WorkerCrashedError(RayTpuError):
    """The worker process executing the task died unexpectedly."""


class ActorDiedError(RayTpuError):
    def __init__(self, actor_id=None, reason: str = ""):
        self.actor_id = actor_id
        self.reason = reason
        super().__init__(f"actor {actor_id} died: {reason}")


class ActorUnavailableError(RayTpuError):
    """Actor temporarily unreachable (restarting)."""


class ObjectLostError(RayTpuError):
    """Object was evicted/lost and could not be reconstructed from lineage."""


class ObjectReconstructionError(ObjectLostError):
    pass


class GetTimeoutError(RayTpuError, TimeoutError):
    pass


class NodeDiedError(RayTpuError):
    pass


class RuntimeEnvSetupError(RayTpuError):
    pass


class PlacementGroupUnschedulableError(RayTpuError):
    pass


class PendingCallsLimitExceeded(RayTpuError):
    pass


class OutOfMemoryError(RayTpuError):
    pass
