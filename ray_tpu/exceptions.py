"""Exception types (reference: python/ray/exceptions.py).

Besides the core runtime errors this module owns the **serving-error
taxonomy registry**: ``SERVING_ERRORS`` maps every typed error a client
(or a router probe) may observe to its HTTP status code and a retryable
flag. The table is a static literal keyed by CLASS NAME — name-keyed so
the wire-traceback fallback in ``serve.overload.http_error_of`` (for
causes that did not survive pickling) can classify errors without
importing their (possibly jax-heavy) defining modules, and so
``scripts/lint_gate.py``'s chaos-coverage cross-check can audit it by
loading this module alone. Defining modules bind their classes to the
table with the ``@serving_error`` decorator, which refuses unregistered
names and stamps ``status_code``/``retryable`` on the class — one table,
audited in both directions (the ERR002 lint rule polices the raise
sites; the decorator polices the registrations).
"""

from __future__ import annotations

import traceback
from dataclasses import dataclass


@dataclass(frozen=True)
class ServingErrorSpec:
    """How one typed serving error crosses the HTTP boundary."""

    status_code: int
    retryable: bool  # may the client/router retry (elsewhere or later)?


# class name -> spec. Static literal ON PURPOSE (see module docstring):
# adding a typed error means adding a row here AND decorating the class
# with @serving_error — the decorator raises on names missing from this
# table, and tests/test_llm_chaos.py locks table<->class agreement.
SERVING_ERRORS: dict[str, ServingErrorSpec] = {
    # admission / shedding (serve/overload.py)
    "OverloadedError": ServingErrorSpec(429, retryable=True),
    "ReplicaDrainingError": ServingErrorSpec(429, retryable=True),
    # replica stepper death (serve/overload.py): another replica serves
    "StepperDiedError": ServingErrorSpec(503, retryable=True),
    # object plane / ownership (this module)
    "ObjectLostError": ServingErrorSpec(503, retryable=True),
    "ObjectReconstructionError": ServingErrorSpec(503, retryable=True),
    "GetTimeoutError": ServingErrorSpec(504, retryable=True),
    "ActorDiedError": ServingErrorSpec(503, retryable=True),
    "ActorUnavailableError": ServingErrorSpec(503, retryable=True),
    "WorkerCrashedError": ServingErrorSpec(503, retryable=True),
    # live migration (llm/migrate.py): a lost checkpoint fails over, a
    # malformed one is a hard fault (garbage must never reach a pool)
    "MigrationError": ServingErrorSpec(500, retryable=False),
    "MigrationLostError": ServingErrorSpec(503, retryable=True),
    "RequestMigratedError": ServingErrorSpec(503, retryable=True),
    # disagg handoff codec (llm/disagg/handoff.py)
    "HandoffError": ServingErrorSpec(500, retryable=False),
    "HandoffLostError": ServingErrorSpec(503, retryable=True),
    # router terminal failures (llm/disagg/router.py, llm/kvplane/routing.py)
    "DisaggRequestError": ServingErrorSpec(500, retryable=False),
    "KVRouteError": ServingErrorSpec(500, retryable=False),
    # injected faults (chaos.py) that escape a degradation path
    "ChaosError": ServingErrorSpec(500, retryable=False),
}


def serving_error(cls):
    """Class decorator binding a taxonomy class to its registered spec.
    Refuses names missing from ``SERVING_ERRORS`` (registration is the
    table row, not the decorator) and stamps ``status_code``/``retryable``
    so probes can read them off instances without a table lookup."""
    spec = SERVING_ERRORS.get(cls.__name__)
    if spec is None:
        raise KeyError(
            f"{cls.__name__} is not in exceptions.SERVING_ERRORS — add its "
            "(status_code, retryable) row before decorating"
        )
    cls.status_code = spec.status_code
    cls.retryable = spec.retryable
    return cls


def serving_error_spec(e) -> ServingErrorSpec | None:
    """Spec for an exception instance/class, by MRO name lookup (so a
    subclass of a registered error inherits its row unless it has its
    own); None for anything outside the taxonomy."""
    t = e if isinstance(e, type) else type(e)
    for base in t.__mro__:
        spec = SERVING_ERRORS.get(base.__name__)
        if spec is not None:
            return spec
    return None


class RayTpuError(Exception):
    pass


class TaskError(RayTpuError):
    """A task raised an exception; re-raised at ray_tpu.get().

    Reference semantics: RayTaskError wraps the user exception with the
    remote traceback (python/ray/exceptions.py).
    """

    def __init__(self, cause: BaseException | None = None, tb_str: str = "", task_desc: str = ""):
        self.cause = cause
        self.tb_str = tb_str
        self.task_desc = task_desc
        super().__init__(f"task {task_desc} failed:\n{tb_str}")

    @classmethod
    def from_exception(cls, e: BaseException, task_desc: str = ""):
        return cls(cause=e, tb_str="".join(traceback.format_exception(type(e), e, e.__traceback__)), task_desc=task_desc)

    def __reduce__(self):
        import pickle

        cause = self.cause
        if cause is not None:
            try:
                pickle.dumps(cause)
            except Exception:
                cause = None  # unpicklable user exception: keep the traceback string only
        return (_rebuild_task_error, (cause, self.tb_str, self.task_desc))


def _rebuild_task_error(cause, tb_str, task_desc):
    return TaskError(cause=cause, tb_str=tb_str, task_desc=task_desc)


@serving_error
class WorkerCrashedError(RayTpuError):
    """The worker process executing the task died unexpectedly."""


@serving_error
class ActorDiedError(RayTpuError):
    def __init__(self, actor_id=None, reason: str = ""):
        self.actor_id = actor_id
        self.reason = reason
        super().__init__(f"actor {actor_id} died: {reason}")


@serving_error
class ActorUnavailableError(RayTpuError):
    """Actor temporarily unreachable (restarting)."""


@serving_error
class ObjectLostError(RayTpuError):
    """Object was evicted/lost and could not be reconstructed from lineage."""


@serving_error
class ObjectReconstructionError(ObjectLostError):
    pass


@serving_error
class GetTimeoutError(RayTpuError, TimeoutError):
    pass


class NodeDiedError(RayTpuError):
    pass


class RuntimeEnvSetupError(RayTpuError):
    pass


class PlacementGroupUnschedulableError(RayTpuError):
    pass


class PendingCallsLimitExceeded(RayTpuError):
    pass


class OutOfMemoryError(RayTpuError):
    pass
