"""Search space primitives.

Reference parity: python/ray/tune/search/sample.py (Domain/Categorical/
Float/Integer, tune.choice/uniform/loguniform/randint/grid_search) +
variant_generator grid expansion.
"""

from __future__ import annotations

import numpy as np


class Domain:
    def sample(self, rng: np.random.Generator):
        raise NotImplementedError


class Categorical(Domain):
    def __init__(self, categories):
        self.categories = list(categories)

    def sample(self, rng):
        return self.categories[int(rng.integers(0, len(self.categories)))]


class Float(Domain):
    def __init__(self, lower, upper, log=False, q=None):
        self.lower, self.upper, self.log, self.q = lower, upper, log, q

    def sample(self, rng):
        if self.log:
            v = float(np.exp(rng.uniform(np.log(self.lower), np.log(self.upper))))
        else:
            v = float(rng.uniform(self.lower, self.upper))
        if self.q:
            v = round(v / self.q) * self.q
        return v


class Integer(Domain):
    def __init__(self, lower, upper, log=False):
        self.lower, self.upper, self.log = lower, upper, log

    def sample(self, rng):
        if self.log:
            return int(np.exp(rng.uniform(np.log(self.lower), np.log(self.upper))))
        return int(rng.integers(self.lower, self.upper))


class GridSearch:
    def __init__(self, values):
        self.values = list(values)


class Normal(Domain):
    def __init__(self, mean=0.0, sd=1.0):
        self.mean, self.sd = mean, sd

    def sample(self, rng):
        return float(rng.normal(self.mean, self.sd))


class SampleFrom(Domain):
    """fn(config_so_far) — called with the partially resolved config
    (reference: tune.sample_from receives the spec)."""

    def __init__(self, fn):
        self.fn = fn

    def sample(self, rng, config=None):
        return self.fn(config)


def choice(categories) -> Categorical:
    return Categorical(categories)


def uniform(lower, upper) -> Float:
    return Float(lower, upper)


def quniform(lower, upper, q) -> Float:
    return Float(lower, upper, q=q)


def loguniform(lower, upper) -> Float:
    return Float(lower, upper, log=True)


def randint(lower, upper) -> Integer:
    return Integer(lower, upper)


def lograndint(lower, upper) -> Integer:
    return Integer(lower, upper, log=True)


def randn(mean=0.0, sd=1.0) -> Normal:
    return Normal(mean, sd)


def grid_search(values) -> GridSearch:
    return GridSearch(values)


def sample_from(fn) -> SampleFrom:
    return SampleFrom(fn)


def expand_grid(space: dict) -> list[dict]:
    """Cartesian product over grid_search entries; other keys pass through."""
    import itertools

    grid_keys = [k for k, v in space.items() if isinstance(v, GridSearch)]
    if not grid_keys:
        return [dict(space)]
    combos = itertools.product(*[space[k].values for k in grid_keys])
    out = []
    for combo in combos:
        d = dict(space)
        for k, v in zip(grid_keys, combo):
            d[k] = v
        out.append(d)
    return out


def resolve(space: dict, rng: np.random.Generator) -> dict:
    """Sample every Domain leaf; pass literals through. SampleFrom leaves
    see the config resolved so far (declaration order)."""
    out = {}
    for k, v in space.items():
        if isinstance(v, SampleFrom):
            out[k] = v.sample(rng, out)
        elif isinstance(v, Domain):
            out[k] = v.sample(rng)
        elif isinstance(v, dict):
            out[k] = resolve(v, rng)
        elif isinstance(v, GridSearch):
            raise ValueError("grid_search must be expanded before resolve()")
        else:
            out[k] = v
    return out
