"""Searchers: basic variants (grid x random), Optuna adapter, limiter.

Reference parity: python/ray/tune/search/ — basic_variant.py,
optuna/optuna_search.py, concurrency_limiter.py.
"""

from __future__ import annotations

import numpy as np

from ray_tpu.tune.search_space import expand_grid, resolve


class Searcher:
    def set_search_properties(self, metric, mode, space):
        self.metric, self.mode, self.space = metric, mode, space

    def suggest(self, trial_id: str) -> dict | None:
        """None = search exhausted."""
        raise NotImplementedError

    def on_trial_complete(self, trial_id: str, result: dict | None = None, error: bool = False):
        pass


class BasicVariantGenerator(Searcher):
    """Grid expansion x num_samples random sampling (reference:
    search/basic_variant.py)."""

    def __init__(self, num_samples: int = 1, seed: int | None = None):
        self.num_samples = num_samples
        self.rng = np.random.default_rng(seed)
        self._queue: list[dict] | None = None

    def set_search_properties(self, metric, mode, space):
        super().set_search_properties(metric, mode, space)
        self._queue = []
        for _ in range(self.num_samples):
            for variant in expand_grid(space):
                self._queue.append(variant)

    def suggest(self, trial_id):
        if not self._queue:
            return None
        variant = self._queue.pop(0)
        return resolve(variant, self.rng)


class OptunaSearch(Searcher):
    """Optuna TPE adapter (reference: search/optuna/optuna_search.py).
    Requires `optuna` (not baked into this image — gated import)."""

    def __init__(self, metric=None, mode=None, seed=None, num_samples: int = 64):
        try:
            import optuna
        except ImportError as e:
            raise ImportError(
                "OptunaSearch requires the 'optuna' package, which is not "
                "installed in this environment"
            ) from e
        self._optuna = optuna
        self.metric = metric
        self.mode = mode
        self.seed = seed
        self.remaining = num_samples
        self._trials: dict[str, object] = {}

    def set_search_properties(self, metric, mode, space):
        # the searcher's own explicit settings win over TuneConfig fallbacks
        super().set_search_properties(self.metric or metric, self.mode or mode or "max", space)
        sampler = self._optuna.samplers.TPESampler(seed=self.seed)
        direction = "maximize" if self.mode == "max" else "minimize"
        self._study = self._optuna.create_study(sampler=sampler, direction=direction)

    def suggest(self, trial_id):
        if self.remaining <= 0:
            return None
        self.remaining -= 1
        from ray_tpu.tune.search_space import Categorical, Float, Integer

        ot = self._study.ask()
        self._trials[trial_id] = ot
        config = {}
        for k, v in self.space.items():
            if isinstance(v, Categorical):
                config[k] = ot.suggest_categorical(k, v.categories)
            elif isinstance(v, Float):
                config[k] = ot.suggest_float(k, v.lower, v.upper, log=v.log)
            elif isinstance(v, Integer):
                config[k] = ot.suggest_int(k, v.lower, v.upper - 1, log=v.log)
            else:
                config[k] = v
        return config

    def on_trial_complete(self, trial_id, result=None, error=False):
        ot = self._trials.pop(trial_id, None)
        if ot is None:
            return
        if error or result is None or self.metric not in result:
            self._study.tell(ot, state=self._optuna.trial.TrialState.FAIL)
        else:
            self._study.tell(ot, result[self.metric])


class ConcurrencyLimiter(Searcher):
    """Caps in-flight suggests (reference: search/concurrency_limiter.py)."""

    def __init__(self, searcher: Searcher, max_concurrent: int):
        self.searcher = searcher
        self.max_concurrent = max_concurrent
        self._live: set[str] = set()

    def set_search_properties(self, metric, mode, space):
        self.searcher.set_search_properties(metric, mode, space)

    def suggest(self, trial_id):
        if len(self._live) >= self.max_concurrent:
            return "__WAIT__"
        cfg = self.searcher.suggest(trial_id)
        if cfg is not None and cfg != "__WAIT__":
            self._live.add(trial_id)
        return cfg

    def on_trial_complete(self, trial_id, result=None, error=False):
        self._live.discard(trial_id)
        self.searcher.on_trial_complete(trial_id, result=result, error=error)


class TPESearcher(Searcher):
    """Tree-structured Parzen Estimator search (reference: the BO half of
    BOHB — tune/search/bohb uses the same KDE-over-good/bad-split model;
    Bergstra et al. 2011). Completed trials split at the gamma quantile
    into good/bad sets; each numeric dimension gets a kernel density
    estimate per set, and suggestions maximize the density ratio
    l_good(x)/l_bad(x) over sampled candidates. Categorical dimensions
    use smoothed category frequencies. Compose with ASHAScheduler for
    the BOHB setup (multi-fidelity HyperBand elimination + model-based
    proposals):

        tune.TuneConfig(search_alg=tune.TPESearcher(num_samples=32),
                        scheduler=tune.ASHAScheduler(...))
    """

    def __init__(
        self,
        num_samples: int = 16,
        *,
        metric: str | None = None,
        mode: str | None = None,
        n_startup_trials: int = 6,
        gamma: float = 0.25,
        n_candidates: int = 64,
        seed: int | None = None,
    ):
        self.metric = metric
        self.mode = mode
        self.remaining = num_samples
        self.n_startup = int(n_startup_trials)
        self.gamma = float(gamma)
        self.n_candidates = int(n_candidates)
        self.rng = np.random.default_rng(seed)
        self._configs: dict[str, dict] = {}
        self._observed: list[tuple[dict, float]] = []

    def set_search_properties(self, metric, mode, space):
        super().set_search_properties(self.metric or metric, self.mode or mode or "max", space)
        for k, v in space.items():
            if isinstance(v, dict):
                raise ValueError(
                    f"TPESearcher supports flat search spaces; flatten nested key {k!r} "
                    "(or use BasicVariantGenerator/OptunaSearch)"
                )

    # -- observation feed --
    def on_trial_complete(self, trial_id, result=None, error=False):
        cfg = self._configs.pop(trial_id, None)
        if cfg is None or error or result is None or self.metric not in result:
            return
        score = float(result[self.metric])
        self._observed.append((cfg, score if self.mode == "max" else -score))

    # -- model --
    def _split(self):
        ranked = sorted(self._observed, key=lambda cv: cv[1], reverse=True)
        k = max(1, int(len(ranked) * self.gamma))
        return [c for c, _ in ranked[:k]], [c for c, _ in ranked[k:]] or [c for c, _ in ranked[:k]]

    @staticmethod
    def _kde_logpdf(xs: np.ndarray, obs: np.ndarray, lo: float, hi: float) -> np.ndarray:
        """1-d Gaussian KDE with Scott bandwidth, floored to 10% of range."""
        bw = max(1.06 * (np.std(obs) + 1e-12) * len(obs) ** -0.2, 0.1 * (hi - lo), 1e-12)
        d = (xs[:, None] - obs[None, :]) / bw
        return np.log(np.exp(-0.5 * d * d).sum(1) + 1e-300)

    def _score_dim(self, domain, cand_vals, good_cfgs, bad_cfgs, key):
        from ray_tpu.tune.search_space import Categorical, Float, Integer

        if isinstance(domain, Categorical):
            cats = list(domain.categories)
            def freq(cfgs):
                counts = np.array([sum(1 for c in cfgs if c.get(key) == cat) for cat in cats], np.float64)
                p = (counts + 1.0) / (counts.sum() + len(cats))  # Laplace smoothing
                return {cat: np.log(pi) for cat, pi in zip(cats, p)}
            lg, lb = freq(good_cfgs), freq(bad_cfgs)
            return np.array([lg[v] - lb[v] for v in cand_vals])
        if isinstance(domain, (Float, Integer)):
            log = bool(getattr(domain, "log", False))
            tx = (lambda a: np.log(np.asarray(a, np.float64))) if log else (lambda a: np.asarray(a, np.float64))
            lo, hi = tx(domain.lower), tx(domain.upper)
            xs = tx(cand_vals)
            g = self._kde_logpdf(xs, tx([c[key] for c in good_cfgs]), lo, hi)
            b = self._kde_logpdf(xs, tx([c[key] for c in bad_cfgs]), lo, hi)
            return g - b
        return np.zeros(len(cand_vals))

    # -- suggestion --
    def suggest(self, trial_id):
        from ray_tpu.tune.search_space import Domain, SampleFrom

        if self.remaining <= 0:
            return None
        self.remaining -= 1
        dims = {k: v for k, v in self.space.items() if isinstance(v, Domain) and not isinstance(v, SampleFrom)}
        derived = {k: v for k, v in self.space.items() if isinstance(v, SampleFrom)}
        fixed = {k: v for k, v in self.space.items() if not isinstance(v, Domain)}
        if len(self._observed) < self.n_startup or not dims:
            cfg = {**fixed, **{k: d.sample(self.rng) for k, d in dims.items()}}
        else:
            good, bad = self._split()
            cands = [{k: d.sample(self.rng) for k, d in dims.items()} for _ in range(self.n_candidates)]
            total = np.zeros(self.n_candidates)
            for k, d in dims.items():
                total += self._score_dim(d, [c[k] for c in cands], good, bad, k)
            cfg = {**fixed, **cands[int(np.argmax(total))]}
        for k, d in derived.items():
            # sample_from fns see the partially-resolved config (they are
            # DERIVED values, not searched dimensions — excluded from the
            # TPE model on both the suggest and observe sides)
            cfg[k] = d.sample(self.rng, cfg)
        self._configs[trial_id] = cfg
        return dict(cfg)


class BayesOptSearcher(Searcher):
    """Gaussian-process Bayesian optimization with Expected Improvement
    (reference capability: tune/search/bayesopt/bayesopt_search.py wraps
    the external bayesian-optimization package; here the GP — RBF kernel
    with jitter over unit-cube-normalized inputs — and the EI acquisition
    are implemented natively, so the searcher works with zero extra
    dependencies).

    Numeric dimensions normalize to [0, 1] (log-aware); categoricals
    one-hot into the kernel. Suggestions before ``n_startup_trials``
    observations are random; afterwards EI is maximized over
    ``n_candidates`` sampled points.
    """

    def __init__(
        self,
        num_samples: int = 16,
        *,
        metric: str | None = None,
        mode: str | None = None,
        n_startup_trials: int = 5,
        n_candidates: int = 256,
        xi: float = 0.01,
        noise: float = 1e-4,
        seed: int | None = None,
    ):
        self.metric = metric
        self.mode = mode
        self.remaining = num_samples
        self.n_startup = int(n_startup_trials)
        self.n_candidates = int(n_candidates)
        self.xi = float(xi)
        self.noise = float(noise)
        self.rng = np.random.default_rng(seed)
        self._configs: dict[str, dict] = {}
        self._observed: list[tuple[dict, float]] = []

    def set_search_properties(self, metric, mode, space):
        super().set_search_properties(self.metric or metric, self.mode or mode or "max", space)
        for k, v in space.items():
            if isinstance(v, dict):
                raise ValueError(f"BayesOptSearcher supports flat search spaces; flatten nested key {k!r}")

    def on_trial_complete(self, trial_id, result=None, error=False):
        cfg = self._configs.pop(trial_id, None)
        if cfg is None or error or result is None or self.metric not in result:
            return
        score = float(result[self.metric])
        self._observed.append((cfg, score if self.mode == "max" else -score))

    # -- featurization: config dict -> unit-cube vector --
    def _dims(self):
        from ray_tpu.tune.search_space import Categorical, Domain, Float, Integer, SampleFrom

        out = []
        for k, v in self.space.items():
            if isinstance(v, (Float, Integer)):
                out.append((k, v, "num"))
            elif isinstance(v, Categorical):
                out.append((k, v, "cat"))
            elif isinstance(v, Domain) and not isinstance(v, SampleFrom):
                out.append((k, v, "other"))
        return out

    def _encode(self, cfg, dims):
        feats = []
        for k, d, kind in dims:
            if kind == "num":
                log = bool(getattr(d, "log", False))
                lo, hi = (np.log(d.lower), np.log(d.upper)) if log else (d.lower, d.upper)
                x = np.log(cfg[k]) if log else cfg[k]
                feats.append((float(x) - lo) / max(hi - lo, 1e-12))
            elif kind == "cat":
                cats = list(d.categories)
                one = [0.0] * len(cats)
                if cfg[k] in cats:
                    one[cats.index(cfg[k])] = 1.0
                feats.extend(one)
            else:
                feats.append(0.0)
        return np.asarray(feats, np.float64)

    @staticmethod
    def _rbf(a, b, ls=0.2):
        d2 = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
        return np.exp(-0.5 * d2 / (ls * ls))

    def suggest(self, trial_id):
        from ray_tpu.tune.search_space import Domain, SampleFrom

        if self.remaining <= 0:
            return None
        self.remaining -= 1
        dims = self._dims()
        searched = {k for k, _, _ in dims}
        derived = {k: v for k, v in self.space.items() if isinstance(v, SampleFrom)}
        fixed = {k: v for k, v in self.space.items() if not isinstance(v, Domain) and k not in searched}

        def random_cfg():
            return {**fixed, **{k: d.sample(self.rng) for k, d, _ in dims}}

        if len(self._observed) < self.n_startup or not dims:
            cfg = random_cfg()
        else:
            X = np.stack([self._encode(c, dims) for c, _ in self._observed])
            y = np.asarray([s for _, s in self._observed], np.float64)
            y_mean, y_std = y.mean(), max(y.std(), 1e-12)
            yn = (y - y_mean) / y_std
            K = self._rbf(X, X) + self.noise * np.eye(len(X))
            try:
                L = np.linalg.cholesky(K)
            except np.linalg.LinAlgError:
                L = np.linalg.cholesky(K + 1e-6 * np.eye(len(X)))
            alpha = np.linalg.solve(L.T, np.linalg.solve(L, yn))
            cands = [random_cfg() for _ in range(self.n_candidates)]
            Xc = np.stack([self._encode(c, dims) for c in cands])
            Ks = self._rbf(Xc, X)  # [C, N]
            mu = Ks @ alpha
            v = np.linalg.solve(L, Ks.T)  # [N, C]
            var = np.maximum(1.0 - (v * v).sum(0), 1e-12)
            sigma = np.sqrt(var)
            best = yn.max()
            z = (mu - best - self.xi) / sigma
            # EI = sigma * (z*Phi(z) + phi(z)) without scipy
            phi = np.exp(-0.5 * z * z) / np.sqrt(2 * np.pi)
            from math import erf

            Phi = 0.5 * (1.0 + np.vectorize(erf)(z / np.sqrt(2.0)))
            ei = sigma * (z * Phi + phi)
            cfg = cands[int(np.argmax(ei))]
        for k, d in derived.items():
            cfg[k] = d.sample(self.rng, cfg)
        self._configs[trial_id] = cfg
        return cfg
