"""Searchers: basic variants (grid x random), Optuna adapter, limiter.

Reference parity: python/ray/tune/search/ — basic_variant.py,
optuna/optuna_search.py, concurrency_limiter.py.
"""

from __future__ import annotations

import numpy as np

from ray_tpu.tune.search_space import expand_grid, resolve


class Searcher:
    def set_search_properties(self, metric, mode, space):
        self.metric, self.mode, self.space = metric, mode, space

    def suggest(self, trial_id: str) -> dict | None:
        """None = search exhausted."""
        raise NotImplementedError

    def on_trial_complete(self, trial_id: str, result: dict | None = None, error: bool = False):
        pass


class BasicVariantGenerator(Searcher):
    """Grid expansion x num_samples random sampling (reference:
    search/basic_variant.py)."""

    def __init__(self, num_samples: int = 1, seed: int | None = None):
        self.num_samples = num_samples
        self.rng = np.random.default_rng(seed)
        self._queue: list[dict] | None = None

    def set_search_properties(self, metric, mode, space):
        super().set_search_properties(metric, mode, space)
        self._queue = []
        for _ in range(self.num_samples):
            for variant in expand_grid(space):
                self._queue.append(variant)

    def suggest(self, trial_id):
        if not self._queue:
            return None
        variant = self._queue.pop(0)
        return resolve(variant, self.rng)


class OptunaSearch(Searcher):
    """Optuna TPE adapter (reference: search/optuna/optuna_search.py).
    Requires `optuna` (not baked into this image — gated import)."""

    def __init__(self, metric=None, mode=None, seed=None, num_samples: int = 64):
        try:
            import optuna
        except ImportError as e:
            raise ImportError(
                "OptunaSearch requires the 'optuna' package, which is not "
                "installed in this environment"
            ) from e
        self._optuna = optuna
        self.metric = metric
        self.mode = mode
        self.seed = seed
        self.remaining = num_samples
        self._trials: dict[str, object] = {}

    def set_search_properties(self, metric, mode, space):
        # the searcher's own explicit settings win over TuneConfig fallbacks
        super().set_search_properties(self.metric or metric, self.mode or mode or "max", space)
        sampler = self._optuna.samplers.TPESampler(seed=self.seed)
        direction = "maximize" if self.mode == "max" else "minimize"
        self._study = self._optuna.create_study(sampler=sampler, direction=direction)

    def suggest(self, trial_id):
        if self.remaining <= 0:
            return None
        self.remaining -= 1
        from ray_tpu.tune.search_space import Categorical, Float, Integer

        ot = self._study.ask()
        self._trials[trial_id] = ot
        config = {}
        for k, v in self.space.items():
            if isinstance(v, Categorical):
                config[k] = ot.suggest_categorical(k, v.categories)
            elif isinstance(v, Float):
                config[k] = ot.suggest_float(k, v.lower, v.upper, log=v.log)
            elif isinstance(v, Integer):
                config[k] = ot.suggest_int(k, v.lower, v.upper - 1, log=v.log)
            else:
                config[k] = v
        return config

    def on_trial_complete(self, trial_id, result=None, error=False):
        ot = self._trials.pop(trial_id, None)
        if ot is None:
            return
        if error or result is None or self.metric not in result:
            self._study.tell(ot, state=self._optuna.trial.TrialState.FAIL)
        else:
            self._study.tell(ot, result[self.metric])


class ConcurrencyLimiter(Searcher):
    """Caps in-flight suggests (reference: search/concurrency_limiter.py)."""

    def __init__(self, searcher: Searcher, max_concurrent: int):
        self.searcher = searcher
        self.max_concurrent = max_concurrent
        self._live: set[str] = set()

    def set_search_properties(self, metric, mode, space):
        self.searcher.set_search_properties(metric, mode, space)

    def suggest(self, trial_id):
        if len(self._live) >= self.max_concurrent:
            return "__WAIT__"
        cfg = self.searcher.suggest(trial_id)
        if cfg is not None and cfg != "__WAIT__":
            self._live.add(trial_id)
        return cfg

    def on_trial_complete(self, trial_id, result=None, error=False):
        self._live.discard(trial_id)
        self.searcher.on_trial_complete(trial_id, result=result, error=error)
