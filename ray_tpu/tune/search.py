"""Searchers: basic variants (grid x random), Optuna adapter, limiter.

Reference parity: python/ray/tune/search/ — basic_variant.py,
optuna/optuna_search.py, concurrency_limiter.py.
"""

from __future__ import annotations

import numpy as np

from ray_tpu.tune.search_space import expand_grid, resolve


class Searcher:
    def set_search_properties(self, metric, mode, space):
        self.metric, self.mode, self.space = metric, mode, space

    def suggest(self, trial_id: str) -> dict | None:
        """None = search exhausted."""
        raise NotImplementedError

    def on_trial_complete(self, trial_id: str, result: dict | None = None, error: bool = False):
        pass


class BasicVariantGenerator(Searcher):
    """Grid expansion x num_samples random sampling (reference:
    search/basic_variant.py)."""

    def __init__(self, num_samples: int = 1, seed: int | None = None):
        self.num_samples = num_samples
        self.rng = np.random.default_rng(seed)
        self._queue: list[dict] | None = None

    def set_search_properties(self, metric, mode, space):
        super().set_search_properties(metric, mode, space)
        self._queue = []
        for _ in range(self.num_samples):
            for variant in expand_grid(space):
                self._queue.append(variant)

    def suggest(self, trial_id):
        if not self._queue:
            return None
        variant = self._queue.pop(0)
        return resolve(variant, self.rng)


class OptunaSearch(Searcher):
    """Optuna TPE adapter (reference: search/optuna/optuna_search.py).
    Requires `optuna` (not baked into this image — gated import)."""

    def __init__(self, metric=None, mode=None, seed=None, num_samples: int = 64):
        try:
            import optuna
        except ImportError as e:
            raise ImportError(
                "OptunaSearch requires the 'optuna' package, which is not "
                "installed in this environment"
            ) from e
        self._optuna = optuna
        self.metric = metric
        self.mode = mode
        self.seed = seed
        self.remaining = num_samples
        self._trials: dict[str, object] = {}

    def set_search_properties(self, metric, mode, space):
        # the searcher's own explicit settings win over TuneConfig fallbacks
        super().set_search_properties(self.metric or metric, self.mode or mode or "max", space)
        sampler = self._optuna.samplers.TPESampler(seed=self.seed)
        direction = "maximize" if self.mode == "max" else "minimize"
        self._study = self._optuna.create_study(sampler=sampler, direction=direction)

    def suggest(self, trial_id):
        if self.remaining <= 0:
            return None
        self.remaining -= 1
        from ray_tpu.tune.search_space import Categorical, Float, Integer

        ot = self._study.ask()
        self._trials[trial_id] = ot
        config = {}
        for k, v in self.space.items():
            if isinstance(v, Categorical):
                config[k] = ot.suggest_categorical(k, v.categories)
            elif isinstance(v, Float):
                config[k] = ot.suggest_float(k, v.lower, v.upper, log=v.log)
            elif isinstance(v, Integer):
                config[k] = ot.suggest_int(k, v.lower, v.upper - 1, log=v.log)
            else:
                config[k] = v
        return config

    def on_trial_complete(self, trial_id, result=None, error=False):
        ot = self._trials.pop(trial_id, None)
        if ot is None:
            return
        if error or result is None or self.metric not in result:
            self._study.tell(ot, state=self._optuna.trial.TrialState.FAIL)
        else:
            self._study.tell(ot, result[self.metric])


class ConcurrencyLimiter(Searcher):
    """Caps in-flight suggests (reference: search/concurrency_limiter.py)."""

    def __init__(self, searcher: Searcher, max_concurrent: int):
        self.searcher = searcher
        self.max_concurrent = max_concurrent
        self._live: set[str] = set()

    def set_search_properties(self, metric, mode, space):
        self.searcher.set_search_properties(metric, mode, space)

    def suggest(self, trial_id):
        if len(self._live) >= self.max_concurrent:
            return "__WAIT__"
        cfg = self.searcher.suggest(trial_id)
        if cfg is not None and cfg != "__WAIT__":
            self._live.add(trial_id)
        return cfg

    def on_trial_complete(self, trial_id, result=None, error=False):
        self._live.discard(trial_id)
        self.searcher.on_trial_complete(trial_id, result=result, error=error)


class TPESearcher(Searcher):
    """Tree-structured Parzen Estimator search (reference: the BO half of
    BOHB — tune/search/bohb uses the same KDE-over-good/bad-split model;
    Bergstra et al. 2011). Completed trials split at the gamma quantile
    into good/bad sets; each numeric dimension gets a kernel density
    estimate per set, and suggestions maximize the density ratio
    l_good(x)/l_bad(x) over sampled candidates. Categorical dimensions
    use smoothed category frequencies. Compose with ASHAScheduler for
    the BOHB setup (multi-fidelity HyperBand elimination + model-based
    proposals):

        tune.TuneConfig(search_alg=tune.TPESearcher(num_samples=32),
                        scheduler=tune.ASHAScheduler(...))
    """

    def __init__(
        self,
        num_samples: int = 16,
        *,
        metric: str | None = None,
        mode: str | None = None,
        n_startup_trials: int = 6,
        gamma: float = 0.25,
        n_candidates: int = 64,
        seed: int | None = None,
    ):
        self.metric = metric
        self.mode = mode
        self.remaining = num_samples
        self.n_startup = int(n_startup_trials)
        self.gamma = float(gamma)
        self.n_candidates = int(n_candidates)
        self.rng = np.random.default_rng(seed)
        self._configs: dict[str, dict] = {}
        self._observed: list[tuple[dict, float]] = []

    def set_search_properties(self, metric, mode, space):
        super().set_search_properties(self.metric or metric, self.mode or mode or "max", space)
        for k, v in space.items():
            if isinstance(v, dict):
                raise ValueError(
                    f"TPESearcher supports flat search spaces; flatten nested key {k!r} "
                    "(or use BasicVariantGenerator/OptunaSearch)"
                )

    # -- observation feed --
    def on_trial_complete(self, trial_id, result=None, error=False):
        cfg = self._configs.pop(trial_id, None)
        if cfg is None or error or result is None or self.metric not in result:
            return
        score = float(result[self.metric])
        self._observed.append((cfg, score if self.mode == "max" else -score))

    # -- model --
    def _split(self):
        ranked = sorted(self._observed, key=lambda cv: cv[1], reverse=True)
        k = max(1, int(len(ranked) * self.gamma))
        return [c for c, _ in ranked[:k]], [c for c, _ in ranked[k:]] or [c for c, _ in ranked[:k]]

    @staticmethod
    def _kde_logpdf(xs: np.ndarray, obs: np.ndarray, lo: float, hi: float) -> np.ndarray:
        """1-d Gaussian KDE with Scott bandwidth, floored to 10% of range."""
        bw = max(1.06 * (np.std(obs) + 1e-12) * len(obs) ** -0.2, 0.1 * (hi - lo), 1e-12)
        d = (xs[:, None] - obs[None, :]) / bw
        return np.log(np.exp(-0.5 * d * d).sum(1) + 1e-300)

    def _score_dim(self, domain, cand_vals, good_cfgs, bad_cfgs, key):
        from ray_tpu.tune.search_space import Categorical, Float, Integer

        if isinstance(domain, Categorical):
            cats = list(domain.categories)
            def freq(cfgs):
                counts = np.array([sum(1 for c in cfgs if c.get(key) == cat) for cat in cats], np.float64)
                p = (counts + 1.0) / (counts.sum() + len(cats))  # Laplace smoothing
                return {cat: np.log(pi) for cat, pi in zip(cats, p)}
            lg, lb = freq(good_cfgs), freq(bad_cfgs)
            return np.array([lg[v] - lb[v] for v in cand_vals])
        if isinstance(domain, (Float, Integer)):
            log = bool(getattr(domain, "log", False))
            tx = (lambda a: np.log(np.asarray(a, np.float64))) if log else (lambda a: np.asarray(a, np.float64))
            lo, hi = tx(domain.lower), tx(domain.upper)
            xs = tx(cand_vals)
            g = self._kde_logpdf(xs, tx([c[key] for c in good_cfgs]), lo, hi)
            b = self._kde_logpdf(xs, tx([c[key] for c in bad_cfgs]), lo, hi)
            return g - b
        return np.zeros(len(cand_vals))

    # -- suggestion --
    def suggest(self, trial_id):
        from ray_tpu.tune.search_space import Domain, SampleFrom

        if self.remaining <= 0:
            return None
        self.remaining -= 1
        dims = {k: v for k, v in self.space.items() if isinstance(v, Domain) and not isinstance(v, SampleFrom)}
        derived = {k: v for k, v in self.space.items() if isinstance(v, SampleFrom)}
        fixed = {k: v for k, v in self.space.items() if not isinstance(v, Domain)}
        if len(self._observed) < self.n_startup or not dims:
            cfg = {**fixed, **{k: d.sample(self.rng) for k, d in dims.items()}}
        else:
            good, bad = self._split()
            cands = [{k: d.sample(self.rng) for k, d in dims.items()} for _ in range(self.n_candidates)]
            total = np.zeros(self.n_candidates)
            for k, d in dims.items():
                total += self._score_dim(d, [c[k] for c in cands], good, bad, k)
            cfg = {**fixed, **cands[int(np.argmax(total))]}
        for k, d in derived.items():
            # sample_from fns see the partially-resolved config (they are
            # DERIVED values, not searched dimensions — excluded from the
            # TPE model on both the suggest and observe sides)
            cfg[k] = d.sample(self.rng, cfg)
        self._configs[trial_id] = cfg
        return dict(cfg)
