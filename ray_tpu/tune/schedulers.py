"""Trial schedulers: FIFO, ASHA, median stopping, PBT.

Reference parity: python/ray/tune/schedulers/ — hyperband.py (ASHA rungs,
successive halving with eta), median_stopping_rule.py, pbt.py (truncation
exploit + perturb explore). Decisions are returned to the controller per
reported result.
"""

from __future__ import annotations

import numpy as np

CONTINUE = "CONTINUE"
STOP = "STOP"
PAUSE = "PAUSE"


class TrialScheduler:
    def on_trial_result(self, controller, trial, result: dict) -> str:
        return CONTINUE

    def on_trial_complete(self, controller, trial):
        pass


class FIFOScheduler(TrialScheduler):
    pass


class ASHAScheduler(TrialScheduler):
    """Asynchronous successive halving (reference: schedulers/hyperband.py
    / async_hyperband): rungs at grace_period * reduction_factor^k; a trial
    reaching a rung stops unless in the top 1/reduction_factor of metric
    values recorded at that rung."""

    def __init__(
        self,
        metric: str = None,
        mode: str = "max",
        max_t: int = 100,
        grace_period: int = 1,
        reduction_factor: int = 4,
        time_attr: str = "training_iteration",
    ):
        self.metric = metric
        self.mode = mode
        self.max_t = max_t
        self.grace = grace_period
        self.eta = reduction_factor
        self.time_attr = time_attr
        self.rungs: dict[int, dict[str, float]] = {}  # rung -> trial -> value
        r = grace_period
        while r < max_t:
            self.rungs[r] = {}
            r *= reduction_factor

    def _sign(self, v):
        return v if self.mode == "max" else -v

    def on_trial_result(self, controller, trial, result):
        t = result.get(self.time_attr, trial.iteration)
        metric = result.get(self.metric)
        if metric is None:
            return CONTINUE
        if t >= self.max_t:
            return STOP
        for rung in sorted(self.rungs, reverse=True):
            if t >= rung:
                # record on the FIRST result at-or-past the milestone, so
                # time_attrs that skip exact rung values still participate
                if trial.trial_id not in self.rungs[rung]:
                    self.rungs[rung][trial.trial_id] = self._sign(metric)
                # re-evaluate the trial's recorded value at its latest rung
                # every report: a trial that passed a rung early (before
                # peers arrived) still stops once the cutoff moves above it
                vals = self.rungs[rung]
                if trial.trial_id not in vals or len(vals) < self.eta:
                    return CONTINUE
                cutoff = np.percentile(list(vals.values()), (1 - 1 / self.eta) * 100)
                return CONTINUE if vals[trial.trial_id] >= cutoff else STOP
        return CONTINUE


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose running-mean metric is worse than the median of
    other trials' running means at the same step (reference:
    schedulers/median_stopping_rule.py)."""

    def __init__(self, metric=None, mode="max", grace_period=1, min_samples_required=3, time_attr="training_iteration"):
        self.metric = metric
        self.mode = mode
        self.grace = grace_period
        self.min_samples = min_samples_required
        self.time_attr = time_attr
        self.histories: dict[str, list[float]] = {}

    def on_trial_result(self, controller, trial, result):
        metric = result.get(self.metric)
        t = result.get(self.time_attr, trial.iteration)
        if metric is None:
            return CONTINUE
        h = self.histories.setdefault(trial.trial_id, [])
        h.append(float(metric))
        if t <= self.grace:
            return CONTINUE
        # other trials' running means so far (clipped to this trial's step
        # when they are ahead; used as-is when behind — poll order must not
        # decide whether a comparison happens)
        means = [
            float(np.mean(v[: len(h)]))
            for k, v in self.histories.items()
            if k != trial.trial_id and len(v) > self.grace
        ]
        if len(means) < self.min_samples:
            return CONTINUE
        med = float(np.median(means))
        mine = float(np.mean(h))
        worse = mine < med if self.mode == "max" else mine > med
        return STOP if worse else CONTINUE


class PopulationBasedTraining(TrialScheduler):
    """PBT (reference: schedulers/pbt.py): every perturbation_interval, the
    bottom-quantile trial clones the checkpoint + config of a top-quantile
    trial (exploit), then perturbs mutation hyperparams (explore: x1.2 /
    x0.8, or resample)."""

    def __init__(
        self,
        metric=None,
        mode="max",
        perturbation_interval: int = 5,
        hyperparam_mutations: dict | None = None,
        quantile_fraction: float = 0.25,
        resample_probability: float = 0.25,
        time_attr: str = "training_iteration",
        seed: int | None = None,
    ):
        self.metric = metric
        self.mode = mode
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.resample_p = resample_probability
        self.time_attr = time_attr
        self.rng = np.random.default_rng(seed)
        self.last_perturb: dict[str, int] = {}

    def _score(self, trial):
        v = trial.metric_at(self.metric)
        if v is None:
            return None
        return v if self.mode == "max" else -v

    def on_trial_result(self, controller, trial, result):
        t = result.get(self.time_attr, trial.iteration)
        if t - self.last_perturb.get(trial.trial_id, 0) < self.interval:
            return CONTINUE
        self.last_perturb[trial.trial_id] = t
        trials = [tr for tr in controller.trials if self._score(tr) is not None]
        if len(trials) < 2:
            return CONTINUE
        ranked = sorted(trials, key=self._score)
        k = max(1, int(len(ranked) * self.quantile))
        bottom = ranked[:k]
        top = ranked[-k:]
        if trial in bottom and trial not in top:
            donor = top[int(self.rng.integers(0, len(top)))]
            if donor.checkpoint_path is None:
                return CONTINUE  # nothing to exploit yet; keep training
            new_config = self._explore(dict(donor.config))
            controller.request_exploit(trial, donor, new_config)
            return PAUSE  # controller restarts the trial with the new state
        return CONTINUE

    def _explore(self, config: dict) -> dict:
        for k, spec in self.mutations.items():
            if self.rng.random() < self.resample_p or k not in config:
                if isinstance(spec, list):
                    config[k] = spec[int(self.rng.integers(0, len(spec)))]
                elif callable(spec):
                    config[k] = spec()
                else:
                    config[k] = spec.sample(self.rng)
            elif isinstance(config[k], (int, float)) and not isinstance(config[k], bool):
                factor = 1.2 if self.rng.random() > 0.5 else 0.8
                config[k] = type(config[k])(config[k] * factor)
            elif isinstance(spec, list):
                config[k] = spec[int(self.rng.integers(0, len(spec)))]
        return config
