"""Trial schedulers: FIFO, ASHA, median stopping, PBT.

Reference parity: python/ray/tune/schedulers/ — hyperband.py (ASHA rungs,
successive halving with eta), median_stopping_rule.py, pbt.py (truncation
exploit + perturb explore). Decisions are returned to the controller per
reported result.
"""

from __future__ import annotations

import numpy as np

CONTINUE = "CONTINUE"
STOP = "STOP"
PAUSE = "PAUSE"


class TrialScheduler:
    def on_trial_result(self, controller, trial, result: dict) -> str:
        return CONTINUE

    def on_trial_complete(self, controller, trial):
        pass


class FIFOScheduler(TrialScheduler):
    pass


class ASHAScheduler(TrialScheduler):
    """Asynchronous successive halving (reference: schedulers/hyperband.py
    / async_hyperband): rungs at grace_period * reduction_factor^k; a trial
    reaching a rung stops unless in the top 1/reduction_factor of metric
    values recorded at that rung."""

    def __init__(
        self,
        metric: str = None,
        mode: str = "max",
        max_t: int = 100,
        grace_period: int = 1,
        reduction_factor: int = 4,
        time_attr: str = "training_iteration",
    ):
        self.metric = metric
        self.mode = mode
        self.max_t = max_t
        self.grace = grace_period
        self.eta = reduction_factor
        self.time_attr = time_attr
        self.rungs: dict[int, dict[str, float]] = {}  # rung -> trial -> value
        r = grace_period
        while r < max_t:
            self.rungs[r] = {}
            r *= reduction_factor

    def _sign(self, v):
        return v if self.mode == "max" else -v

    def on_trial_result(self, controller, trial, result):
        t = result.get(self.time_attr, trial.iteration)
        metric = result.get(self.metric)
        if metric is None:
            return CONTINUE
        if t >= self.max_t:
            return STOP
        for rung in sorted(self.rungs, reverse=True):
            if t >= rung:
                # record on the FIRST result at-or-past the milestone, so
                # time_attrs that skip exact rung values still participate
                if trial.trial_id not in self.rungs[rung]:
                    self.rungs[rung][trial.trial_id] = self._sign(metric)
                # re-evaluate the trial's recorded value at its latest rung
                # every report: a trial that passed a rung early (before
                # peers arrived) still stops once the cutoff moves above it
                vals = self.rungs[rung]
                if trial.trial_id not in vals or len(vals) < self.eta:
                    return CONTINUE
                cutoff = np.percentile(list(vals.values()), (1 - 1 / self.eta) * 100)
                return CONTINUE if vals[trial.trial_id] >= cutoff else STOP
        return CONTINUE


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose running-mean metric is worse than the median of
    other trials' running means at the same step (reference:
    schedulers/median_stopping_rule.py)."""

    def __init__(self, metric=None, mode="max", grace_period=1, min_samples_required=3, time_attr="training_iteration"):
        self.metric = metric
        self.mode = mode
        self.grace = grace_period
        self.min_samples = min_samples_required
        self.time_attr = time_attr
        self.histories: dict[str, list[float]] = {}

    def on_trial_result(self, controller, trial, result):
        metric = result.get(self.metric)
        t = result.get(self.time_attr, trial.iteration)
        if metric is None:
            return CONTINUE
        h = self.histories.setdefault(trial.trial_id, [])
        h.append(float(metric))
        if t <= self.grace:
            return CONTINUE
        # other trials' running means so far (clipped to this trial's step
        # when they are ahead; used as-is when behind — poll order must not
        # decide whether a comparison happens)
        means = [
            float(np.mean(v[: len(h)]))
            for k, v in self.histories.items()
            if k != trial.trial_id and len(v) > self.grace
        ]
        if len(means) < self.min_samples:
            return CONTINUE
        med = float(np.median(means))
        mine = float(np.mean(h))
        worse = mine < med if self.mode == "max" else mine > med
        return STOP if worse else CONTINUE


class PopulationBasedTraining(TrialScheduler):
    """PBT (reference: schedulers/pbt.py): every perturbation_interval, the
    bottom-quantile trial clones the checkpoint + config of a top-quantile
    trial (exploit), then perturbs mutation hyperparams (explore: x1.2 /
    x0.8, or resample)."""

    def __init__(
        self,
        metric=None,
        mode="max",
        perturbation_interval: int = 5,
        hyperparam_mutations: dict | None = None,
        quantile_fraction: float = 0.25,
        resample_probability: float = 0.25,
        time_attr: str = "training_iteration",
        seed: int | None = None,
    ):
        self.metric = metric
        self.mode = mode
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.resample_p = resample_probability
        self.time_attr = time_attr
        self.rng = np.random.default_rng(seed)
        self.last_perturb: dict[str, int] = {}

    def _score(self, trial):
        v = trial.metric_at(self.metric)
        if v is None:
            return None
        return v if self.mode == "max" else -v

    def on_trial_result(self, controller, trial, result):
        t = result.get(self.time_attr, trial.iteration)
        if t - self.last_perturb.get(trial.trial_id, 0) < self.interval:
            return CONTINUE
        self.last_perturb[trial.trial_id] = t
        trials = [tr for tr in controller.trials if self._score(tr) is not None]
        if len(trials) < 2:
            return CONTINUE
        ranked = sorted(trials, key=self._score)
        k = max(1, int(len(ranked) * self.quantile))
        bottom = ranked[:k]
        top = ranked[-k:]
        if trial in bottom and trial not in top:
            donor = top[int(self.rng.integers(0, len(top)))]
            if donor.checkpoint_path is None:
                return CONTINUE  # nothing to exploit yet; keep training
            new_config = self._explore(dict(donor.config))
            controller.request_exploit(trial, donor, new_config)
            return PAUSE  # controller restarts the trial with the new state
        return CONTINUE

    def _explore(self, config: dict) -> dict:
        for k, spec in self.mutations.items():
            if self.rng.random() < self.resample_p or k not in config:
                if isinstance(spec, list):
                    config[k] = spec[int(self.rng.integers(0, len(spec)))]
                elif callable(spec):
                    config[k] = spec()
                else:
                    config[k] = spec.sample(self.rng)
            elif isinstance(config[k], (int, float)) and not isinstance(config[k], bool):
                factor = 1.2 if self.rng.random() > 0.5 else 0.8
                config[k] = type(config[k])(config[k] * factor)
            elif isinstance(spec, list):
                config[k] = spec[int(self.rng.integers(0, len(spec)))]
        return config


class PB2(PopulationBasedTraining):
    """Population Based Bandits (reference: tune/schedulers/pb2.py; Parker-
    Holder et al. 2020): PBT where EXPLORE is not a random x0.8/x1.2
    perturbation but a GP-bandit suggestion — a Gaussian process is fit on
    (hyperparams -> observed reward improvement) across the population's
    recent perturbation intervals, and the exploited trial's new config
    maximizes the UCB acquisition over the bounded search space. Much more
    sample-efficient than PBT at small population sizes, where random
    perturbations rarely hit good regions.

    hyperparam_bounds: {name: (low, high)} continuous bounds (the PB2
    formulation is continuous); pass hyperparam_mutations for any
    categorical params to keep them on PBT's resample/perturb explore.
    """

    def __init__(
        self,
        metric=None,
        mode="max",
        perturbation_interval: int = 5,
        hyperparam_bounds: dict | None = None,
        quantile_fraction: float = 0.25,
        time_attr: str = "training_iteration",
        seed: int | None = None,
        ucb_kappa: float = 2.0,
        num_candidates: int = 256,
        hyperparam_mutations: dict | None = None,
    ):
        super().__init__(
            metric=metric,
            mode=mode,
            perturbation_interval=perturbation_interval,
            hyperparam_mutations=hyperparam_mutations or {},
            quantile_fraction=quantile_fraction,
            time_attr=time_attr,
            seed=seed,
        )
        if not hyperparam_bounds:
            raise ValueError("PB2 requires hyperparam_bounds={name: (low, high), ...}")
        self.bounds = {k: (float(lo), float(hi)) for k, (lo, hi) in hyperparam_bounds.items()}
        self.kappa = float(ucb_kappa)
        self.num_candidates = int(num_candidates)
        # observations: rows of (normalized hyperparams, reward delta)
        self._obs_x: list[list[float]] = []
        self._obs_y: list[float] = []
        self._last_score: dict[str, float] = {}

    # -- data collection: reward improvement per interval, tagged with the
    # config that produced it --
    def on_trial_result(self, controller, trial, result):
        score = self._score(trial)
        if score is not None:
            t = result.get(self.time_attr, trial.iteration)
            # snapshot the score only at interval BOUNDARIES: y is then
            # the whole interval's improvement under trial.config, not a
            # single noisy step delta
            if t - self.last_perturb.get(trial.trial_id, 0) >= self.interval:
                prev = self._last_score.get(trial.trial_id)
                if prev is not None:
                    self._obs_x.append(self._normalize(trial.config))
                    self._obs_y.append(score - prev)
                    if len(self._obs_y) > 512:  # bounded memory, recent wins
                        self._obs_x.pop(0)
                        self._obs_y.pop(0)
                self._last_score[trial.trial_id] = score
        decision = super().on_trial_result(controller, trial, result)
        if decision == PAUSE:
            # exploited: the trial resumes from the DONOR's checkpoint, so
            # its next score jump reflects the clone, not training under
            # the suggested config — drop the baseline or the GP learns
            # self-confirming inflated improvements
            self._last_score.pop(trial.trial_id, None)
        return decision

    def _normalize(self, config: dict) -> list[float]:
        out = []
        for k, (lo, hi) in self.bounds.items():
            v = float(config.get(k, lo))
            out.append((v - lo) / max(hi - lo, 1e-12))
        return out

    def _denormalize(self, x, config: dict | None = None) -> dict:
        out = {}
        for xi, (k, (lo, hi)) in zip(x, self.bounds.items()):
            v = lo + float(xi) * (hi - lo)
            # integer-valued hyperparams (batch size, layer count) keep
            # their type across exploits, like PBT's type-preserving explore
            if config is not None and isinstance(config.get(k), int) and not isinstance(config.get(k), bool):
                v = int(round(v))
            out[k] = v
        return out

    # -- GP-UCB explore for bounded params (categoricals first go
    # through PBT's resample/perturb when hyperparam_mutations given) --
    def _explore(self, config: dict) -> dict:
        if self.mutations:
            config = super()._explore(dict(config))
        cand = self.rng.random((self.num_candidates, len(self.bounds)))
        if len(self._obs_y) >= 3:
            X = np.asarray(self._obs_x, dtype=np.float64)
            y = np.asarray(self._obs_y, dtype=np.float64)
            y = (y - y.mean()) / (y.std() + 1e-9)
            # GP with RBF kernel (PB2 uses a time-varying SE kernel; the
            # bounded-recency observation window plays the decay role)
            ls = 0.2
            noise = 1e-2

            def k_rbf(A, B):
                d2 = ((A[:, None, :] - B[None, :, :]) ** 2).sum(-1)
                return np.exp(-d2 / (2 * ls * ls))

            K = k_rbf(X, X) + noise * np.eye(len(X))
            try:
                L = np.linalg.cholesky(K)
                alpha = np.linalg.solve(L.T, np.linalg.solve(L, y))
                Ks = k_rbf(X, cand)  # [n_obs, n_cand]
                mu = Ks.T @ alpha
                v = np.linalg.solve(L, Ks)
                var = np.clip(1.0 - (v * v).sum(0), 1e-9, None)
                ucb = mu + self.kappa * np.sqrt(var)
                best = cand[int(np.argmax(ucb))]
            except np.linalg.LinAlgError:
                best = cand[int(self.rng.integers(0, len(cand)))]
        else:
            best = cand[int(self.rng.integers(0, len(cand)))]
        new = dict(config)
        new.update(self._denormalize(best, config))
        return new


class DistributeResources:
    """Default resources_allocation_function (reference:
    tune/schedulers/resource_changing_scheduler.py DistributeResources):
    split the cluster's CPUs evenly across unfinished trials — finished
    trials release their share, so survivors grow over time.

    The integer remainder goes to the earliest live trials in submission
    order, NOT to the best-ranked ones (a deliberate deviation from the
    reference): a metric-rank flip between two trials' reports would make
    BOTH claim the same slack CPU, and the oversubscribed relaunch could
    never be placed — deadlocking the experiment. Submission order is
    stable between reports, so the proposed totals never exceed the
    cluster."""

    def __init__(self, metric: str | None = None, mode: str = "max"):
        # metric/mode kept for call-site compatibility with the reference
        # signature; allocation is metric-independent (see class docstring)
        self.metric = metric
        self.mode = mode

    def __call__(self, controller, trial, result: dict) -> dict | None:
        import ray_tpu

        total = int(ray_tpu.cluster_resources().get("CPU", 1))
        live = [t for t in controller.trials if not t.is_finished]
        if not live:
            return None
        # while the searcher may still suggest trials, keep one 1-CPU slot
        # free: growing the lone live trial to the whole cluster would make
        # a later suggestion unplaceable (and the controller's blocking
        # poll would never shrink the hog)
        slots = len(live) if getattr(controller, "_exhausted", True) else len(live) + 1
        if total < slots:
            return None
        base, slack = divmod(total, slots)
        bonus = 1 if trial in live[:slack] else 0
        return {"CPU": base + bonus}


class ResourceChangingScheduler(TrialScheduler):
    """Wrap a base scheduler and grow/shrink each trial's resources at
    checkpoint boundaries (reference:
    tune/schedulers/resource_changing_scheduler.py).

    Every `reallocate_interval` results per trial, the allocation function
    proposes a resource dict; if it differs from the trial's current one,
    the trial is PAUSED (checkpointing it) and relaunched by the
    controller with the new footprint — the same pause/resume seam PBT
    exploitation uses, so no new trial-actor machinery."""

    def __init__(self, base_scheduler: TrialScheduler | None = None, resources_allocation_function=None, metric: str | None = None, mode: str | None = None, reallocate_interval: int = 1):
        # metric/mode default to None (NOT "max") so a base scheduler's own
        # explicit mode survives construction; the Tuner injects the
        # experiment's metric/mode into None attributes, which the setters
        # below then propagate
        self.base = base_scheduler or FIFOScheduler()
        self.alloc = resources_allocation_function or DistributeResources(metric, mode or "max")
        self.interval = max(1, reallocate_interval)
        self._since: dict[str, int] = {}
        self.metric = metric  # via the propagating setters below
        self.mode = mode

    # the Tuner injects its metric/mode into the scheduler when unset
    # (tuner.py); this wrapper IS the experiment's scheduler, so those
    # values must reach the wrapped scheduler and the default allocator
    # too — or a metric-less base ASHA silently no-ops (result.get(None)).
    # A base constructed with an EXPLICIT metric is treated as fully
    # self-configured: neither its metric nor its mode is ever overwritten
    # (the user may deliberately schedule on a different metric than the
    # experiment reports best on).
    def _base_self_configured(self) -> bool:
        return getattr(self.base, "metric", None) is not None and not getattr(self, "_base_adopted", False)

    @property
    def metric(self):
        return self._metric

    @metric.setter
    def metric(self, value):
        self._metric = value
        if value is not None:
            if hasattr(self.base, "metric") and not self._base_self_configured():
                self.base.metric = value
                self._base_adopted = True  # keep following wrapper updates
            if isinstance(self.alloc, DistributeResources):
                self.alloc.metric = value

    @property
    def mode(self):
        return self._mode

    @mode.setter
    def mode(self, value):
        self._mode = value
        if value is not None:
            if hasattr(self.base, "mode") and not self._base_self_configured():
                self.base.mode = value
            if isinstance(self.alloc, DistributeResources):
                self.alloc.mode = value

    def on_trial_result(self, controller, trial, result):
        decision = self.base.on_trial_result(controller, trial, result)
        if decision != CONTINUE:
            return decision
        current = trial.resources or controller.resources
        if not isinstance(current, dict):
            # PlacementGroupFactory trials gang-reserve a fixed footprint;
            # _start_trial ignores per-trial overrides there, so pausing
            # would only burn progress — no-op (the reference's PGF path
            # rebuilds factories instead; out of scope here)
            return CONTINUE
        if trial.checkpoint_path is None:
            # resizing relaunches from the last checkpoint; without one the
            # trial would restart from scratch (same guard as PBT exploit)
            return CONTINUE
        self._since[trial.trial_id] = self._since.get(trial.trial_id, 0) + 1
        if self._since[trial.trial_id] < self.interval:
            return CONTINUE
        self._since[trial.trial_id] = 0
        new = self.alloc(controller, trial, result)
        if new is None:
            return CONTINUE
        if new == {k: current.get(k) for k in new}:
            return CONTINUE
        # merge: keys the allocator didn't mention (e.g. TPU) keep their
        # current values rather than being dropped
        trial.resources = {**current, **new}
        return PAUSE

    def on_trial_complete(self, controller, trial):
        self.base.on_trial_complete(controller, trial)
