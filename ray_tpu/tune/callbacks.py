"""Tune/Train logger callbacks (reference: tune/logger/* + AIR
integrations air/integrations/wandb.py, mlflow.py).

File-based loggers work offline out of the box (JSON lines, CSV,
TensorBoard via torch's SummaryWriter); network-backed integrations
(wandb/mlflow) are gated imports with clear errors since this image has
no egress.

    run_config = RunConfig(callbacks=[JsonLoggerCallback(),
                                      CSVLoggerCallback(),
                                      TensorBoardLoggerCallback()])
"""

from __future__ import annotations

import csv
import json
import os


class Callback:
    """Experiment-lifecycle hooks (reference: tune/callback.py)."""

    def setup(self, run_dir: str):
        pass

    def log_trial_result(self, trial, result: dict):
        pass

    def log_trial_end(self, trial):
        pass

    def on_experiment_end(self, trials: list):
        pass


class JsonLoggerCallback(Callback):
    """result.json: one JSON line per reported result per trial
    (reference: tune/logger/json.py)."""

    def setup(self, run_dir: str):
        self.run_dir = run_dir
        self._files: dict[str, object] = {}

    def _file(self, trial):
        f = self._files.get(trial.trial_id)
        if f is None:
            d = os.path.join(self.run_dir, trial.trial_id)
            os.makedirs(d, exist_ok=True)
            f = self._files[trial.trial_id] = open(os.path.join(d, "result.json"), "a", buffering=1)
        return f

    def log_trial_result(self, trial, result: dict):
        self._file(trial).write(json.dumps(result, default=str) + "\n")

    def log_trial_end(self, trial):
        f = self._files.pop(trial.trial_id, None)
        if f is not None:
            f.close()


class CSVLoggerCallback(Callback):
    """progress.csv per trial (reference: tune/logger/csv.py)."""

    def setup(self, run_dir: str):
        self.run_dir = run_dir
        self._writers: dict[str, tuple] = {}

    def log_trial_result(self, trial, result: dict):
        entry = self._writers.get(trial.trial_id)
        flat = {k: v for k, v in result.items() if not isinstance(v, (dict, list))}
        if entry is None:
            d = os.path.join(self.run_dir, trial.trial_id)
            os.makedirs(d, exist_ok=True)
            f = open(os.path.join(d, "progress.csv"), "a", buffering=1, newline="")
            w = csv.DictWriter(f, fieldnames=sorted(flat))
            w.writeheader()
            entry = self._writers[trial.trial_id] = (f, w)
        f, w = entry
        w.writerow({k: flat.get(k, "") for k in w.fieldnames})

    def log_trial_end(self, trial):
        entry = self._writers.pop(trial.trial_id, None)
        if entry is not None:
            entry[0].close()


class TensorBoardLoggerCallback(Callback):
    """TB event files per trial via torch's SummaryWriter (offline; view
    with tensorboard --logdir <run_dir>). Reference: tune/logger/
    tensorboardx.py."""

    def setup(self, run_dir: str):
        self.run_dir = run_dir
        self._writers: dict[str, object] = {}

    def _writer(self, trial):
        w = self._writers.get(trial.trial_id)
        if w is None:
            from torch.utils.tensorboard import SummaryWriter

            w = self._writers[trial.trial_id] = SummaryWriter(
                log_dir=os.path.join(self.run_dir, trial.trial_id)
            )
        return w

    def log_trial_result(self, trial, result: dict):
        w = self._writer(trial)
        step = int(result.get("training_iteration", 0))
        for k, v in result.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                w.add_scalar(k, v, global_step=step)

    def log_trial_end(self, trial):
        w = self._writers.pop(trial.trial_id, None)
        if w is not None:
            w.close()


class WandbLoggerCallback(Callback):
    """Gated: network-backed experiment tracking is not supported in this
    deployment (zero egress) — raises unconditionally rather than ever
    degrading into a silent no-op logger."""

    def __init__(self, *a, **kw):
        raise NotImplementedError(
            "WandbLoggerCallback is not supported in this deployment (no "
            "egress). Use JsonLoggerCallback/CSVLoggerCallback/"
            "TensorBoardLoggerCallback."
        )


class MLflowLoggerCallback(Callback):
    """Gated like WandbLoggerCallback."""

    def __init__(self, *a, **kw):
        raise NotImplementedError(
            "MLflowLoggerCallback is not supported in this deployment (no "
            "egress). Use JsonLoggerCallback/CSVLoggerCallback/"
            "TensorBoardLoggerCallback."
        )
