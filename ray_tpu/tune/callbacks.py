"""Tune/Train logger callbacks (reference: tune/logger/* + AIR
integrations air/integrations/wandb.py, mlflow.py).

File-based loggers work offline out of the box (JSON lines, CSV,
TensorBoard via torch's SummaryWriter). Wandb/MLflow run in FILE-BACKED
modes only: WandbLoggerCallback writes wandb's offline run-directory
layout (sync later with `wandb sync`), MLflowLoggerCallback writes the
mlruns/ file-store layout (`mlflow ui --backend-store-uri file://...`);
online modes / remote tracking URIs raise — this image has no egress.

    run_config = RunConfig(callbacks=[JsonLoggerCallback(),
                                      CSVLoggerCallback(),
                                      TensorBoardLoggerCallback()])
"""

from __future__ import annotations

import csv
import json
import os


class Callback:
    """Experiment-lifecycle hooks (reference: tune/callback.py)."""

    def setup(self, run_dir: str):
        pass

    def log_trial_result(self, trial, result: dict):
        pass

    def log_trial_end(self, trial):
        pass

    def on_experiment_end(self, trials: list):
        pass


class JsonLoggerCallback(Callback):
    """result.json: one JSON line per reported result per trial
    (reference: tune/logger/json.py)."""

    def setup(self, run_dir: str):
        self.run_dir = run_dir
        self._files: dict[str, object] = {}

    def _file(self, trial):
        f = self._files.get(trial.trial_id)
        if f is None:
            d = os.path.join(self.run_dir, trial.trial_id)
            os.makedirs(d, exist_ok=True)
            f = self._files[trial.trial_id] = open(os.path.join(d, "result.json"), "a", buffering=1)
        return f

    def log_trial_result(self, trial, result: dict):
        self._file(trial).write(json.dumps(result, default=str) + "\n")

    def log_trial_end(self, trial):
        f = self._files.pop(trial.trial_id, None)
        if f is not None:
            f.close()


class CSVLoggerCallback(Callback):
    """progress.csv per trial (reference: tune/logger/csv.py)."""

    def setup(self, run_dir: str):
        self.run_dir = run_dir
        self._writers: dict[str, tuple] = {}

    def log_trial_result(self, trial, result: dict):
        entry = self._writers.get(trial.trial_id)
        flat = {k: v for k, v in result.items() if not isinstance(v, (dict, list))}
        if entry is None:
            d = os.path.join(self.run_dir, trial.trial_id)
            os.makedirs(d, exist_ok=True)
            f = open(os.path.join(d, "progress.csv"), "a", buffering=1, newline="")
            w = csv.DictWriter(f, fieldnames=sorted(flat))
            w.writeheader()
            entry = self._writers[trial.trial_id] = (f, w)
        f, w = entry
        w.writerow({k: flat.get(k, "") for k in w.fieldnames})

    def log_trial_end(self, trial):
        entry = self._writers.pop(trial.trial_id, None)
        if entry is not None:
            entry[0].close()


class TensorBoardLoggerCallback(Callback):
    """TB event files per trial via torch's SummaryWriter (offline; view
    with tensorboard --logdir <run_dir>). Reference: tune/logger/
    tensorboardx.py."""

    def setup(self, run_dir: str):
        self.run_dir = run_dir
        self._writers: dict[str, object] = {}

    def _writer(self, trial):
        w = self._writers.get(trial.trial_id)
        if w is None:
            from torch.utils.tensorboard import SummaryWriter

            w = self._writers[trial.trial_id] = SummaryWriter(
                log_dir=os.path.join(self.run_dir, trial.trial_id)
            )
        return w

    def log_trial_result(self, trial, result: dict):
        w = self._writer(trial)
        step = int(result.get("training_iteration", 0))
        for k, v in result.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                w.add_scalar(k, v, global_step=step)

    def log_trial_end(self, trial):
        w = self._writers.pop(trial.trial_id, None)
        if w is not None:
            w.close()


class WandbLoggerCallback(Callback):
    """File-backed OFFLINE mode only (reference: air/integrations/wandb.py
    WandbLoggerCallback with WANDB_MODE=offline): per-trial run
    directories in the wandb offline layout — wandb-metadata.json,
    config.json, and an append-only wandb-history.jsonl of results —
    syncable later with `wandb sync <dir>` from a machine with egress.
    Online mode is rejected explicitly: this deployment has none."""

    def __init__(self, project: str = "ray_tpu", group: str | None = None, mode: str = "offline", dir: str | None = None, **kw):
        if mode != "offline":
            raise NotImplementedError(
                "only mode='offline' is supported in this deployment (no egress); "
                "sync the offline run directories later with `wandb sync`"
            )
        self.project = project
        self.group = group
        self.dir = dir
        self._runs: dict[str, str] = {}

    def setup(self, run_dir: str):
        import os

        self.root = self.dir or os.path.join(run_dir, "wandb")
        os.makedirs(self.root, exist_ok=True)

    def _run_dir(self, trial) -> str:
        import json
        import os
        import time

        d = self._runs.get(trial.trial_id)
        if d is None:
            stamp = time.strftime("%Y%m%d_%H%M%S")
            d = self._runs[trial.trial_id] = os.path.join(self.root, f"offline-run-{stamp}-{trial.trial_id}")
            os.makedirs(os.path.join(d, "files"), exist_ok=True)
            with open(os.path.join(d, "files", "wandb-metadata.json"), "w") as f:
                json.dump({"project": self.project, "group": self.group, "run_id": trial.trial_id, "mode": "offline"}, f)
            with open(os.path.join(d, "files", "config.json"), "w") as f:
                json.dump({k: {"value": v} for k, v in (trial.config or {}).items()}, f, default=str)
        return d

    def log_trial_result(self, trial, result: dict):
        import json
        import os

        d = self._run_dir(trial)
        row = {k: v for k, v in result.items() if isinstance(v, (int, float, str, bool))}
        row["_step"] = int(result.get("training_iteration", 0))
        with open(os.path.join(d, "files", "wandb-history.jsonl"), "a") as f:
            f.write(json.dumps(row, default=str) + "\n")

    def log_trial_end(self, trial):
        import json
        import os

        d = self._runs.get(trial.trial_id)
        if d:
            with open(os.path.join(d, "files", "wandb-summary.json"), "w") as f:
                json.dump({"state": "finished"}, f)


class MLflowLoggerCallback(Callback):
    """File-backed local tracking only (reference:
    air/integrations/mlflow.py with a file:// tracking URI): the standard
    mlruns/ directory layout — one run directory per trial with params/,
    metrics/ (timestamped series files), and tags/ — readable by
    `mlflow ui --backend-store-uri file://...`. Remote tracking URIs are
    rejected: this deployment has no egress."""

    def __init__(self, tracking_uri: str | None = None, experiment_name: str = "ray_tpu", **kw):
        if tracking_uri and not tracking_uri.startswith("file:"):
            raise NotImplementedError(
                "only file:// tracking URIs are supported in this deployment (no egress)"
            )
        self.tracking_uri = tracking_uri
        self.experiment_name = experiment_name
        self._runs: dict[str, str] = {}

    def setup(self, run_dir: str):
        import os

        if self.tracking_uri:
            from urllib.parse import urlparse

            # handles both file:///abs and RFC 8089 file:/abs forms
            base = urlparse(self.tracking_uri).path
        else:
            base = os.path.join(run_dir, "mlruns")
        self.root = os.path.join(base, "0")  # experiment id 0
        os.makedirs(self.root, exist_ok=True)
        import json

        with open(os.path.join(self.root, "meta.yaml"), "w") as f:
            f.write(f"experiment_id: '0'\nname: {self.experiment_name}\nlifecycle_stage: active\n")

    def _run_dir(self, trial) -> str:
        import os

        d = self._runs.get(trial.trial_id)
        if d is None:
            d = self._runs[trial.trial_id] = os.path.join(self.root, trial.trial_id)
            for sub in ("params", "metrics", "tags"):
                os.makedirs(os.path.join(d, sub), exist_ok=True)
            for k, v in (trial.config or {}).items():
                with open(os.path.join(d, "params", str(k)), "w") as f:
                    f.write(str(v))
        return d

    def log_trial_result(self, trial, result: dict):
        import os
        import time

        d = self._run_dir(trial)
        ts = int(time.time() * 1000)
        step = int(result.get("training_iteration", 0))
        for k, v in result.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                safe = str(k).replace("/", "_")
                with open(os.path.join(d, "metrics", safe), "a") as f:
                    f.write(f"{ts} {v} {step}\n")

    def log_trial_end(self, trial):
        import os

        d = self._runs.get(trial.trial_id)
        if d:
            with open(os.path.join(d, "tags", "mlflow.runStatus"), "w") as f:
                f.write("FINISHED")
