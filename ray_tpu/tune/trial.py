"""Trial state (reference: python/ray/tune/experiment/trial.py)."""

from __future__ import annotations

import uuid
from dataclasses import dataclass, field

PENDING = "PENDING"
RUNNING = "RUNNING"
PAUSED = "PAUSED"
TERMINATED = "TERMINATED"
ERROR = "ERROR"


@dataclass
class Trial:
    config: dict
    trial_id: str = field(default_factory=lambda: uuid.uuid4().hex[:8])
    status: str = PENDING
    last_result: dict | None = None
    metrics_history: list = field(default_factory=list)
    checkpoint_path: str | None = None
    error: str | None = None
    iteration: int = 0
    # PBT bookkeeping
    restore_config: dict | None = None
    # per-trial resource override (ResourceChangingScheduler); None ->
    # the controller-wide resources_per_trial
    resources: dict | None = None

    @property
    def is_finished(self) -> bool:
        return self.status in (TERMINATED, ERROR)

    def metric_at(self, metric: str):
        if self.last_result is None:
            return None
        return self.last_result.get(metric)
