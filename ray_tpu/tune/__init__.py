"""ray_tpu.tune: hyperparameter optimization on trial actors.

Reference parity: python/ray/tune (35 KLoC, SURVEY.md §2.4) — Tuner.fit
over a TuneController managing trial actors, searchers (grid/random,
Optuna), schedulers (ASHA, PBT, median stopping), experiment checkpoints,
Train integration (Tuner(trainer)).
"""

from ray_tpu.util.usage import record_library_usage as _rlu

_rlu("tune")

from ray_tpu.train.session import report  # shared session API  # noqa: F401
from ray_tpu.train.session import get_checkpoint  # noqa: F401
from ray_tpu.train._checkpoint import Checkpoint  # noqa: F401
from ray_tpu.tune.result_grid import ResultGrid
from ray_tpu.tune.schedulers import (
    ASHAScheduler,
    DistributeResources,
    FIFOScheduler,
    MedianStoppingRule,
    PB2,
    PopulationBasedTraining,
    ResourceChangingScheduler,
    TrialScheduler,
)
from ray_tpu.tune.search import (
    BasicVariantGenerator,
    BayesOptSearcher,
    ConcurrencyLimiter,
    OptunaSearch,
    Searcher,
    TPESearcher,
)
from ray_tpu.tune.search_space import (
    choice,
    grid_search,
    lograndint,
    loguniform,
    quniform,
    randint,
    randn,
    sample_from,
    uniform,
)
from ray_tpu.tune.callbacks import (  # noqa: F401
    Callback,
    CSVLoggerCallback,
    JsonLoggerCallback,
    MLflowLoggerCallback,
    TensorBoardLoggerCallback,
    WandbLoggerCallback,
)
from ray_tpu.tune.resources import PlacementGroupFactory, with_resources
from ray_tpu.tune.tuner import TuneConfig, Tuner, run, with_parameters

__all__ = [
    "ASHAScheduler",
    "BasicVariantGenerator",
    "Checkpoint",
    "ConcurrencyLimiter",
    "FIFOScheduler",
    "MedianStoppingRule",
    "OptunaSearch",
    "PB2",
    "PopulationBasedTraining",
    "ResourceChangingScheduler",
    "DistributeResources",
    "ResultGrid",
    "Searcher",
    "TPESearcher",
    "BayesOptSearcher",
    "TrialScheduler",
    "TuneConfig",
    "Tuner",
    "Callback",
    "PlacementGroupFactory",
    "with_resources",
    "CSVLoggerCallback",
    "JsonLoggerCallback",
    "MLflowLoggerCallback",
    "TensorBoardLoggerCallback",
    "WandbLoggerCallback",
    "choice",
    "get_checkpoint",
    "grid_search",
    "lograndint",
    "loguniform",
    "quniform",
    "randint",
    "randn",
    "report",
    "run",
    "sample_from",
    "uniform",
    "with_parameters",
]
