"""TuneController: the trial-driving loop.

Reference parity: python/ray/tune/execution/tune_controller.py — launch
trial actors under resource limits, consume reported results, route them
through scheduler (stop/pause) and searcher (adaptive suggestion), commit
checkpoints, restart exploited (PBT) trials from donor checkpoints.
Trials run on the AIR actor-manager pattern (air/execution/_internal/
actor_manager.py) — here directly on ray_tpu actors.
"""

from __future__ import annotations

import os
import queue
import shutil
import threading
import traceback
import uuid

import ray_tpu
from ray_tpu.train import context as _train_ctx
from ray_tpu.train._checkpoint import Checkpoint
from ray_tpu.tune import schedulers as sched
from ray_tpu.tune.trial import ERROR, PAUSED, PENDING, RUNNING, TERMINATED, Trial

POLL_INTERVAL_S = float(os.environ.get("RT_TUNE_POLL_INTERVAL_S", "0.05"))


def _stage_root() -> str:
    """Session-scoped checkpoint staging dir: concurrent experiments (or
    other users' leftovers) can never collide on trial ids (ADVICE fix)."""
    pid = os.environ.get("RT_SESSION_PID", str(os.getpid()))
    return os.path.join("/tmp", "ray_tpu", f"session_{pid}", "trial_stage")


@ray_tpu.remote(max_concurrency=4)
class TrialActor:
    """Runs one trial's function in a thread; reports stream out via poll
    (same topology as train's TrainWorker)."""

    def __init__(self, trial_id: str, experiment_name: str):
        self.trial_id = trial_id
        self.experiment_name = experiment_name
        self._reports: queue.Queue = queue.Queue()
        self._status = "idle"

    def run(self, fn, config: dict, latest_checkpoint_path: str | None, trial_pg_hex: str | None = None):
        if trial_pg_hex:
            # the trial's gang reservation: a WorkerGroup spawned inside
            # this trial schedules its workers into bundles 1..N instead
            # of reserving a second placement group
            os.environ["RT_TRIAL_PG"] = trial_pg_hex
        ckpt = Checkpoint(latest_checkpoint_path) if latest_checkpoint_path else None
        ctx = _train_ctx.TrainContext(
            world_size=1,
            world_rank=0,
            local_rank=0,
            local_world_size=1,
            node_rank=0,
            experiment_name=self.experiment_name,
            trial_name=self.trial_id,
            trial_id=self.trial_id,
            report_fn=self._on_report,
            latest_checkpoint=ckpt,
        )
        _train_ctx.set_context(ctx)
        self._status = "running"
        try:
            fn(config)
            self._status = "finished"
        except BaseException:  # noqa: BLE001
            self._status = "error"
            raise RuntimeError(f"trial {self.trial_id} failed:\n{traceback.format_exc()}")
        return self.trial_id

    def _on_report(self, seq, metrics, checkpoint, checkpoint_dir_name):
        # stage checkpoint content NOW, inside the report call: report()
        # returns to user code which may delete the source dir (e.g. a
        # TemporaryDirectory) long before the controller polls
        staged = None
        if checkpoint is not None and os.path.isdir(checkpoint.path):
            staged = os.path.join(_stage_root(), self.trial_id, f"seq{seq}")
            shutil.copytree(checkpoint.path, staged, dirs_exist_ok=True)
        self._reports.put({"seq": seq, "metrics": metrics, "checkpoint_path": staged})

    def poll(self):
        out = []
        while True:
            try:
                out.append(self._reports.get_nowait())
            except queue.Empty:
                break
        return {"status": self._status, "reports": out}


class TuneController:
    def __init__(
        self,
        trainable,
        *,
        searcher,
        scheduler=None,
        metric: str | None = None,
        mode: str = "max",
        max_concurrent: int | None = None,
        run_dir: str,
        experiment_name: str,
        resources_per_trial: dict | None = None,
        max_failures_per_trial: int = 0,
        callbacks: list | None = None,
    ):
        self.trainable = trainable
        self.searcher = searcher
        self.scheduler = scheduler or sched.FIFOScheduler()
        self.metric = metric
        self.mode = mode
        self.max_concurrent = max_concurrent or 4
        self.run_dir = run_dir
        self.experiment_name = experiment_name
        self.resources = resources_per_trial or {"CPU": 1}
        self.max_failures = max_failures_per_trial
        self.trials: list[Trial] = []
        self._actors: dict[str, object] = {}
        self._run_refs: dict[str, object] = {}
        # PG-backed trials: trial_id -> PlacementGroup; trials whose gang
        # reservation is still PENDING wait here, not in RUNNING
        self._trial_pgs: dict[str, object] = {}
        self._awaiting_pg: list[Trial] = []
        self._failures: dict[str, int] = {}
        self._pending: dict[str, list] = {}  # undelivered reports per trial
        self._exhausted = False
        self._dirty = False
        os.makedirs(run_dir, exist_ok=True)
        self.callbacks = list(callbacks or [])
        self._cb_warned: set = set()
        for cb in self.callbacks:
            cb.setup(run_dir)

    # ---------------- experiment snapshots ----------------
    # Reference: tune/execution/experiment_state.py — periodic experiment
    # checkpoints enabling Tuner.restore after a crash/interrupt.
    SNAPSHOT_NAME = "experiment_state.pkl"
    SNAPSHOT_MIN_INTERVAL_S = 5.0  # reference throttles periodic snapshots too

    def save_snapshot(self, force: bool = False):
        import time as _time

        if not force and _time.monotonic() - getattr(self, "_last_snapshot_ts", 0.0) < self.SNAPSHOT_MIN_INTERVAL_S:
            return
        self._last_snapshot_ts = _time.monotonic()
        import cloudpickle

        state = {
            "trials": self.trials,
            "searcher": self.searcher,
            "scheduler": self.scheduler,
            "exhausted": self._exhausted,
            "failures": self._failures,
            "metric": self.metric,
            "mode": self.mode,
            "max_concurrent": self.max_concurrent,
            "max_failures": self.max_failures,
        }
        path = os.path.join(self.run_dir, self.SNAPSHOT_NAME)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            cloudpickle.dump(state, f)
        os.replace(tmp, path)
        self._dirty = False

    def load_snapshot(self, state: dict, *, resume_errored: bool = False, restart_errored: bool = False):
        """Adopt a saved experiment: live trials resume from their last
        checkpoint; terminal ones keep their results."""
        self.trials = state["trials"]
        for t in self.trials:
            # snapshots from before the Trial.resources field unpickle
            # without it (dataclass __init__ is skipped on unpickle)
            if not hasattr(t, "resources"):
                t.resources = None
        self.searcher = state["searcher"]
        if state.get("scheduler") is not None:
            self.scheduler = state["scheduler"]
        self._exhausted = state["exhausted"]
        self._failures = dict(state.get("failures", {}))
        self.max_concurrent = state.get("max_concurrent", self.max_concurrent)
        self.max_failures = state.get("max_failures", self.max_failures)
        for t in self.trials:
            if t.status in (RUNNING, PENDING):
                # RUNNING was in flight when the snapshot landed; PENDING
                # was queued for a gang reservation that died with the old
                # controller — both resume via the paused path
                t.status = PAUSED
            elif t.status == ERROR and restart_errored:
                t.status = PAUSED
                t.checkpoint_path = None
                t.iteration = 0
                t.metrics_history = []
                t.last_result = None  # stale scores must not feed PBT/grids
                t.error = None
                self._failures.pop(t.trial_id, None)
            elif t.status == ERROR and resume_errored:
                t.status = PAUSED
                t.error = None
                self._failures.pop(t.trial_id, None)

    def _notify(self, method: str, *args):
        """Dispatch one callback hook; a failing logger warns once instead
        of silently eating every record or killing the experiment."""
        import logging

        for cb in self.callbacks:
            try:
                getattr(cb, method)(*args)
            except Exception:
                key = (type(cb).__name__, method)
                if key not in self._cb_warned:
                    self._cb_warned.add(key)
                    logging.getLogger("ray_tpu.tune").warning(
                        "callback %s.%s failed; suppressing further errors",
                        *key,
                        exc_info=True,
                    )

    # ---------------- PBT hook ----------------
    def request_exploit(self, trial: Trial, donor: Trial, new_config: dict):
        trial.restore_config = new_config
        trial.checkpoint_path = donor.checkpoint_path

    # ---------------- main loop ----------------
    def run(self) -> list[Trial]:
        while True:
            # paused trials (PBT exploits, failure retries) get freed slots
            # BEFORE new suggestions — the population keeps training
            self._resume_paused()
            self._poll_awaiting_pg()
            self._maybe_launch()
            running = [t for t in self.trials if t.status == RUNNING]
            paused = [t for t in self.trials if t.status == PAUSED]
            waiting = self._awaiting_pg
            if not running and not paused and not waiting and self._exhausted:
                break
            if not running and not paused and not waiting and not self._exhausted and not self._maybe_launch():
                break
            self._poll_running()
            if self._dirty:
                self.save_snapshot()
        self.save_snapshot(force=True)
        self._notify("on_experiment_end", self.trials)
        return self.trials

    def _maybe_launch(self) -> bool:
        launched = False
        while self._active_count() < self.max_concurrent and not self._exhausted:
            tid = uuid.uuid4().hex[:8]
            cfg = self.searcher.suggest(tid)
            if cfg == "__WAIT__":
                break
            if cfg is None:
                self._exhausted = True
                break
            trial = Trial(config=cfg, trial_id=tid)
            self.trials.append(trial)
            self._start_trial(trial)
            launched = True
        return launched

    def _start_trial(self, trial: Trial):
        from ray_tpu.tune.resources import PlacementGroupFactory

        if isinstance(self.resources, PlacementGroupFactory):
            # gang-reserve the trial's WHOLE footprint (driver + workers)
            # atomically (reference: tune/execution/placement_groups.py);
            # a trial that doesn't fit stays PENDING, never oversubscribes
            pg = self._trial_pgs.get(trial.trial_id)
            if pg is None:
                pg = self.resources.create(name=f"trial-{trial.trial_id}")
                self._trial_pgs[trial.trial_id] = pg
            if not pg.wait(timeout_seconds=0.05):
                trial.status = PENDING
                if trial not in self._awaiting_pg:
                    self._awaiting_pg.append(trial)
                return
            head = self.resources.head_bundle
            opts = {
                "num_cpus": head.get("CPU", 1),
                "placement_group": pg,
                "placement_group_bundle_index": 0,
            }
            if head.get("TPU"):
                opts["num_tpus"] = head["TPU"]
            pg_hex = pg.id.hex()
        else:
            # per-trial override (ResourceChangingScheduler) wins over the
            # experiment-wide resources_per_trial; getattr covers Trial
            # objects unpickled from pre-`resources`-field snapshots
            res = getattr(trial, "resources", None) or self.resources
            opts = {"num_cpus": res.get("CPU", 1)}
            if res.get("TPU"):
                opts["num_tpus"] = res["TPU"]
            pg_hex = None
        actor = TrialActor.options(**opts).remote(trial.trial_id, self.experiment_name)
        config = trial.restore_config if trial.restore_config else trial.config
        trial.config = config
        trial.restore_config = None
        ref = actor.run.remote(self.trainable, config, trial.checkpoint_path, pg_hex)
        self._actors[trial.trial_id] = actor
        self._run_refs[trial.trial_id] = ref
        trial.status = RUNNING

    def _poll_awaiting_pg(self):
        """Retry PENDING gang reservations (capacity frees when finished
        trials return their placement groups). A reservation the CLUSTER
        cannot hold at all fails the trial instead of hanging the
        experiment silently (the autoscaler may still grow the cluster —
        infeasibility is judged against current total capacity)."""
        import time as _time

        for trial in list(self._awaiting_pg):
            pg = self._trial_pgs.get(trial.trial_id)
            if pg is not None and pg.wait(timeout_seconds=0.05):
                self._awaiting_pg.remove(trial)
                self._start_trial(trial)
                continue
            first = getattr(trial, "_pg_wait_since", None)
            if first is None:
                trial._pg_wait_since = _time.monotonic()
                continue
            if _time.monotonic() - first > 5.0 and self._pg_infeasible():
                trial.error = (
                    f"trial placement group {self.resources!r} exceeds total cluster "
                    "capacity; it can never be placed"
                )
                self._stop_trial(trial, ERROR)

    def _pg_infeasible(self) -> bool:
        import ray_tpu

        total = ray_tpu.cluster_resources()
        need = self.resources.required_resources()
        return any(total.get(k, 0) < v for k, v in need.items() if v > 0)

    def _stop_trial(self, trial: Trial, status: str):
        actor = self._actors.pop(trial.trial_id, None)
        self._run_refs.pop(trial.trial_id, None)
        if trial in self._awaiting_pg:
            self._awaiting_pg.remove(trial)
        pg = self._trial_pgs.pop(trial.trial_id, None)
        if pg is not None:
            # return the gang reservation (paused trials re-reserve on
            # resume — holding bundles while paused would starve the
            # population, reference releases on pause too)
            from ray_tpu.util.placement_group import remove_placement_group

            try:
                remove_placement_group(pg)
            except Exception:
                pass
        # stale reports die with the run — including their staged
        # checkpoint copies (otherwise /tmp accumulates one per dropped
        # report on STOP/PAUSE decisions)
        for rep in self._pending.pop(trial.trial_id, []) or []:
            src = rep.get("checkpoint_path")
            if src and "/trial_stage/" in src:
                shutil.rmtree(src, ignore_errors=True)
        if trial.is_finished or status in (TERMINATED, ERROR):
            shutil.rmtree(os.path.join(_stage_root(), trial.trial_id), ignore_errors=True)
        if actor is not None:
            try:
                ray_tpu.kill(actor)
            except Exception:
                pass
        trial.status = status
        self._dirty = True
        if trial.is_finished:
            self.searcher.on_trial_complete(trial.trial_id, result=trial.last_result, error=status == ERROR)
            self.scheduler.on_trial_complete(self, trial)
            self._notify("log_trial_end", trial)

    def _active_count(self) -> int:
        """Trials consuming a concurrency slot: RUNNING plus those whose
        gang reservation is queued (they hold a slot so max_concurrent
        bounds total admission, not just placed trials)."""
        return sum(t.status == RUNNING for t in self.trials) + len(self._awaiting_pg)

    def _resume_paused(self):
        for trial in self.trials:
            if trial.status == PAUSED and self._active_count() < self.max_concurrent:
                self._start_trial(trial)

    def _poll_running(self):
        """One scheduler decision per trial per tick: trials advance in
        lockstep even when a fast trial's reports all arrived at once, so
        comparative schedulers (ASHA/median/PBT) see contemporaneous
        snapshots (the reference delivers results one at a time too)."""
        running = [t for t in self.trials if t.status == RUNNING]
        if not running:
            return
        refs = [self._run_refs[t.trial_id] for t in running]
        ray_tpu.wait(refs, num_returns=len(refs), timeout=POLL_INTERVAL_S)
        for trial in running:
            actor = self._actors.get(trial.trial_id)
            if actor is None:
                continue
            pending = self._pending.setdefault(trial.trial_id, [])
            try:
                p = ray_tpu.get(actor.poll.remote())
                pending.extend(p["reports"])
            except Exception:
                trial.error = "actor died"
                self._finish_or_retry(trial)
                continue
            decision = sched.CONTINUE
            if pending:
                decision = self._process_report(trial, pending.pop(0))
            if decision == sched.STOP:
                self._stop_trial(trial, TERMINATED)
                continue
            if decision == sched.PAUSE:
                self._stop_trial(trial, PAUSED)
                continue
            # completion check: only once every report has been consumed
            ref = self._run_refs.get(trial.trial_id)
            if not pending and ref is not None:
                ready, _ = ray_tpu.wait([ref], num_returns=1, timeout=0)
                if ready:
                    # the run may have finished (and enqueued reports)
                    # between our poll above and this check — drain again
                    try:
                        pending.extend(ray_tpu.get(actor.poll.remote())["reports"])
                    except Exception:
                        pass
                    if pending:
                        continue  # process them on subsequent ticks
                    try:
                        ray_tpu.get(ref)
                        self._stop_trial(trial, TERMINATED)
                    except Exception as e:
                        trial.error = str(e)
                        self._finish_or_retry(trial)

    def _process_report(self, trial: Trial, rep: dict) -> str:
        trial.iteration += 1
        metrics = dict(rep["metrics"])
        metrics.setdefault("training_iteration", trial.iteration)
        metrics["trial_id"] = trial.trial_id
        if rep["checkpoint_path"]:
            trial.checkpoint_path = self._commit_checkpoint(trial, rep["checkpoint_path"])
        trial.last_result = metrics
        trial.metrics_history.append(metrics)
        self._dirty = True
        self._notify("log_trial_result", trial, metrics)
        return self.scheduler.on_trial_result(self, trial, metrics)

    def _finish_or_retry(self, trial: Trial):
        n = self._failures.get(trial.trial_id, 0)
        if n < self.max_failures:
            self._failures[trial.trial_id] = n + 1
            self._stop_trial(trial, PAUSED)  # requeue from last checkpoint
        else:
            self._stop_trial(trial, ERROR)

    def _commit_checkpoint(self, trial: Trial, src: str) -> str:
        dest = os.path.join(self.run_dir, trial.trial_id, f"checkpoint_{trial.iteration:06d}")
        os.makedirs(dest, exist_ok=True)
        if os.path.isdir(src):
            shutil.copytree(src, dest, dirs_exist_ok=True)
            if "/trial_stage/" in src:
                shutil.rmtree(src, ignore_errors=True)  # reap the staging copy
        return dest
