"""Trial resource requests: flat dicts or gang-reserved placement groups.

Reference parity: python/ray/tune/execution/placement_groups.py
(PlacementGroupFactory) — a trial that spawns its own worker actors (a
Tuner over a Trainer) reserves ALL its capacity atomically up front:
bundle 0 hosts the trial driver, bundles 1..N host its workers. Without
this, N-worker trials admitted on flat CPU counts oversubscribe the
cluster and thrash; with it, trials that don't fit stay PENDING until a
whole gang frees up.
"""

from __future__ import annotations


class PlacementGroupFactory:
    """Recipe for a trial's placement group.

    PlacementGroupFactory([{"CPU": 1}, {"CPU": 1}, {"CPU": 1}])
    reserves one driver bundle + two worker bundles per trial; the
    trial's train WorkerGroup schedules its workers into bundles 1..N
    (plumbed via the trial context)."""

    def __init__(self, bundles: list[dict], strategy: str = "PACK"):
        if not bundles or any(not b for b in bundles):
            raise ValueError("bundles must be a non-empty list of non-empty resource dicts")
        self.bundles = [dict(b) for b in bundles]
        self.strategy = strategy

    @property
    def head_bundle(self) -> dict:
        return self.bundles[0]

    def create(self, name: str = ""):
        from ray_tpu.util.placement_group import placement_group

        return placement_group(self.bundles, strategy=self.strategy, name=name)

    def required_resources(self) -> dict:
        out: dict = {}
        for b in self.bundles:
            for k, v in b.items():
                out[k] = out.get(k, 0) + v
        return out

    def __repr__(self):
        return f"PlacementGroupFactory({self.bundles}, strategy={self.strategy!r})"


def with_resources(trainable, resources):
    """Return a copy of the trainable carrying a resource request (dict
    or PlacementGroupFactory); the original is untouched so it can be
    reused with different resources (reference: tune.with_resources)."""
    import copy
    import functools

    if callable(trainable) and not hasattr(trainable, "fit"):

        @functools.wraps(trainable)
        def wrapped(*a, **kw):
            return trainable(*a, **kw)

        wrapped._tune_resources = resources
        return wrapped
    clone = copy.copy(trainable)  # trainers: shallow copy, new attr only
    clone._tune_resources = resources
    return clone
