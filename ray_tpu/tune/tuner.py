"""Tuner: the public entry (reference: python/ray/tune/tuner.py +
tune/tune.py:1161 run()).
"""

from __future__ import annotations

import os
from dataclasses import dataclass

from ray_tpu.tune.result_grid import ResultGrid
from ray_tpu.tune.search import BasicVariantGenerator, Searcher
from ray_tpu.tune.tune_controller import TuneController


@dataclass
class TuneConfig:
    metric: str | None = None
    mode: str = "max"
    num_samples: int = 1
    max_concurrent_trials: int | None = None
    search_alg: Searcher | None = None
    scheduler: object = None
    seed: int | None = None


class Tuner:
    def __init__(
        self,
        trainable,
        *,
        param_space: dict | None = None,
        tune_config: TuneConfig | None = None,
        run_config=None,
    ):
        from ray_tpu.train.config import RunConfig

        self.trainable = trainable
        self.param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config or RunConfig()
        self._restore_state: dict | None = None
        self._restore_opts: dict = {}

    # ---------------- restore ----------------
    @staticmethod
    def can_restore(path: str) -> bool:
        from ray_tpu.tune.tune_controller import TuneController

        return os.path.exists(os.path.join(path, TuneController.SNAPSHOT_NAME))

    @classmethod
    def restore(
        cls,
        path: str,
        trainable,
        *,
        resume_errored: bool = False,
        restart_errored: bool = False,
        param_space: dict | None = None,
        run_config=None,
    ) -> "Tuner":
        """Resume an experiment from its run_dir snapshot (reference:
        Tuner.restore + tune/execution/experiment_state.py). Live trials
        continue from their last committed checkpoint; errored trials are
        resumed/restarted per the flags; finished trials keep results."""
        import cloudpickle

        from ray_tpu.train.config import RunConfig
        from ray_tpu.tune.tune_controller import TuneController

        snap_path = os.path.join(path, TuneController.SNAPSHOT_NAME)
        with open(snap_path, "rb") as f:
            state = cloudpickle.load(f)
        if run_config is None:
            run_config = RunConfig()
        # the experiment identity always comes from the snapshot path;
        # everything else (callbacks, failure config) is re-suppliable
        run_config.name = os.path.basename(os.path.normpath(path))
        run_config.storage_path = os.path.dirname(os.path.normpath(path))
        tuner = cls(
            trainable,
            param_space=param_space,
            tune_config=TuneConfig(metric=state.get("metric"), mode=state.get("mode", "max")),
            run_config=run_config,
        )
        tuner._restore_state = state
        tuner._restore_opts = {"resume_errored": resume_errored, "restart_errored": restart_errored}
        return tuner

    def fit(self) -> ResultGrid:
        import ray_tpu

        if not ray_tpu.is_initialized():
            ray_tpu.init()
        tc = self.tune_config
        searcher = tc.search_alg or BasicVariantGenerator(num_samples=tc.num_samples, seed=tc.seed)
        searcher.set_search_properties(tc.metric, tc.mode, self.param_space)
        scheduler = tc.scheduler
        if scheduler is not None:
            # schedulers built without an explicit metric inherit TuneConfig's
            # (otherwise result.get(None) silently degrades them to FIFO)
            if getattr(scheduler, "metric", "absent") is None:
                scheduler.metric = tc.metric
            if getattr(scheduler, "mode", None) is None:
                scheduler.mode = tc.mode

        trainable, resources = _normalize_trainable(self.trainable)
        run_dir = os.path.join(self.run_config.storage_path, self.run_config.name)
        controller = TuneController(
            trainable,
            searcher=searcher,
            scheduler=scheduler,
            metric=tc.metric,
            mode=tc.mode,
            max_concurrent=tc.max_concurrent_trials,
            run_dir=run_dir,
            experiment_name=self.run_config.name,
            resources_per_trial=resources,
            max_failures_per_trial=self.run_config.failure_config.max_failures,
            callbacks=list(self.run_config.callbacks or []),
        )
        if self._restore_state is not None:
            controller.load_snapshot(self._restore_state, **self._restore_opts)
            self._restore_state = None
        trials = controller.run()
        return ResultGrid(trials, run_dir)


def _normalize_trainable(trainable):
    """Function trainables run as-is; a DataParallelTrainer instance becomes
    a function that re-fits with the trial's config merged into
    train_loop_config (reference: Tuner(trainer) integration)."""
    from ray_tpu.train.trainer import DataParallelTrainer

    if isinstance(trainable, DataParallelTrainer):
        base = trainable

        def fit_trainer(config):
            from ray_tpu.train import context as _ctx
            from ray_tpu.train import report

            merged = dict(base.train_loop_config or {})
            merged.update(config)
            trainer = type(base)(
                base.train_loop_per_worker,
                train_loop_config=merged,
                scaling_config=base.scaling_config,
                run_config=base.run_config,
                backend_config=base.backend_config,
                datasets=base.datasets,
            )
            outer_ctx = _ctx.get_context()
            result = trainer.fit(raise_on_error=False)
            _ctx.set_context(outer_ctx)  # trainer.fit clears worker ctx driver-side
            if result.error is not None:
                raise result.error
            for m in result.metrics_history:
                report(dict(m))

        explicit = getattr(base, "_tune_resources", None)
        if explicit is not None:
            return fit_trainer, explicit
        sc = base.scaling_config
        if sc.use_tpu and sc.topology:
            # slice trainers gang-reserve through their SlicePlacementGroup
            # (util/tpu.py); a CPU trial PG would double-book and gate
            # admission on the wrong footprint
            return fit_trainer, {"CPU": 0.5}
        # gang-reserve the trainer's WHOLE footprint per trial: driver
        # bundle + one bundle per train worker (reference:
        # tune/execution/placement_groups.py resource_dict_to_pg_factory;
        # flat driver-only CPUs let N-worker trials oversubscribe)
        from ray_tpu.tune.resources import PlacementGroupFactory

        bundles = [{"CPU": 0.5}] + [dict(sc._worker_resources) for _ in range(sc.num_workers)]
        return fit_trainer, PlacementGroupFactory(bundles)
    if callable(trainable):
        return trainable, getattr(trainable, "_tune_resources", {"CPU": 1})
    raise TypeError(f"unsupported trainable: {type(trainable)}")


def with_parameters(fn, **params):
    """Bind large objects to a trainable (reference: tune.with_parameters)."""
    import functools

    @functools.wraps(fn)
    def wrapped(config):
        return fn(config, **params)

    return wrapped


def run(trainable, *, config=None, num_samples=1, metric=None, mode="max", scheduler=None, search_alg=None, **kw):
    """Legacy API (reference: tune.run, tune/tune.py:1161)."""
    t = Tuner(
        trainable,
        param_space=config,
        tune_config=TuneConfig(
            metric=metric, mode=mode, num_samples=num_samples, scheduler=scheduler, search_alg=search_alg
        ),
    )
    return t.fit()
