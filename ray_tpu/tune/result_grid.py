"""ResultGrid (reference: python/ray/tune/result_grid.py)."""

from __future__ import annotations

from ray_tpu.train._checkpoint import Checkpoint
from ray_tpu.train.result import Result
from ray_tpu.tune.trial import ERROR, Trial


class ResultGrid:
    def __init__(self, trials: list[Trial], path: str):
        self._trials = trials
        self.path = path
        self._results = [
            Result(
                metrics=t.last_result,
                checkpoint=Checkpoint(t.checkpoint_path) if t.checkpoint_path else None,
                path=path,
                error=RuntimeError(t.error) if t.error else None,
                metrics_history=t.metrics_history,
                config=dict(t.config) if t.config else None,
            )
            for t in trials
        ]

    def __len__(self):
        return len(self._results)

    def __getitem__(self, i) -> Result:
        return self._results[i]

    def __iter__(self):
        return iter(self._results)

    @property
    def errors(self):
        return [r.error for r in self._results if r.error]

    @property
    def num_errors(self):
        return len(self.errors)

    def get_best_result(self, metric: str | None = None, mode: str = "max") -> Result:
        best, best_v = None, None
        for r in self._results:
            if r.metrics is None or metric not in r.metrics:
                continue
            v = float(r.metrics[metric])
            if best_v is None or (v > best_v if mode == "max" else v < best_v):
                best, best_v = r, v
        if best is None:
            raise ValueError(f"no trial reported metric {metric!r}")
        return best

    def get_dataframe(self):
        import pandas as pd

        rows = []
        for t, r in zip(self._trials, self._results):
            row = dict(r.metrics or {})
            row.update({f"config/{k}": v for k, v in t.config.items()})
            row["trial_id"] = t.trial_id
            row["status"] = t.status
            rows.append(row)
        return pd.DataFrame(rows)
