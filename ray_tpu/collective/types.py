"""Collective types (reference: python/ray/util/collective/types.py:35-57 —
backends NCCL/gloo/NIXL there; here the backends are TPU-native)."""

from __future__ import annotations

from enum import Enum


class Backend(str, Enum):
    # rendezvous-actor backend: tensors exchanged through the object store
    # (host memory / DCN) — works anywhere, any process topology
    OBJECT_STORE = "object_store"
    # alias kept for API compatibility with code written for gloo
    GLOO = "gloo"
    # XLA backend: for jax.Array collectives the op is a tiny jitted program
    # over a shared mesh (ICI); requires all ranks in one jax process OR
    # jax.distributed multi-host init
    XLA = "xla"

    @staticmethod
    def normalize(b: "Backend | str") -> "Backend":
        b = Backend(b) if not isinstance(b, Backend) else b
        if b == Backend.GLOO:
            return Backend.OBJECT_STORE
        if b in (Backend.OBJECT_STORE, Backend.XLA):
            return b
        raise ValueError(f"unsupported backend {b} (NCCL/MPI are not part of a TPU build)")


class ReduceOp(str, Enum):
    SUM = "sum"
    PRODUCT = "product"
    MIN = "min"
    MAX = "max"
    MEAN = "mean"


def apply_reduce(op: ReduceOp, arrays: list):
    import numpy as np

    op = ReduceOp(op)
    stack = np.stack([np.asarray(a) for a in arrays])
    if op == ReduceOp.SUM:
        return stack.sum(axis=0)
    if op == ReduceOp.PRODUCT:
        return stack.prod(axis=0)
    if op == ReduceOp.MIN:
        return stack.min(axis=0)
    if op == ReduceOp.MAX:
        return stack.max(axis=0)
    if op == ReduceOp.MEAN:
        return stack.mean(axis=0)
    raise ValueError(op)
