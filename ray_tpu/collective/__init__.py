from ray_tpu.util.usage import record_library_usage as _rlu

_rlu("collective")
from ray_tpu.collective.collective import (  # noqa: F401
    allgather,
    allreduce,
    barrier,
    broadcast,
    cleanup_group_actor,
    create_collective_group,
    declare_collective_group,
    destroy_collective_group,
    get_rank,
    get_world_size,
    init_collective_group,
    recv,
    reduce,
    reducescatter,
    send,
)
from ray_tpu.collective.types import Backend, ReduceOp  # noqa: F401
