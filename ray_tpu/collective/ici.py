"""ICI collective backend: XLA-compiled collectives over local mesh devices.

The host-side collective API (collective.py) moves tensors through the shm
object store — the DCN/control plane. When the participating "ranks" are
the chips of one host (one PJRT client), the right data plane is ICI via a
single jitted XLA program; these helpers wrap that for driver-held
per-device arrays. (Inside jit/shard_map, just use lax.psum/all_gather —
see ray_tpu.parallel; this module is for eager host code that owns one
array per chip, e.g. a parameter server pushing to device replicas.)

Reference shape: util/collective/collective_group/nccl_collective_group.py
(a real device backend for the same API) — here the "backend" is XLA +
GSPMD, no NCCL.
"""

from __future__ import annotations

import functools

import numpy as np

from ray_tpu.collective.types import ReduceOp
from ray_tpu.lint import jaxcheck


def _bucket_reduce(W=8, rows=256, cols=1024):
    import jax
    import jax.numpy as jnp

    return (jax.ShapeDtypeStruct((W, rows, cols), jnp.float32),), {}


@jaxcheck.entry(
    name="collective.ici.reduce_stacked",
    shapes={"w8_256x1024": _bucket_reduce},
    # no explicit collective primitives: the all-reduce is GSPMD-inserted
    # by the P('d') -> P() resharding, so the jaxpr must stay collective-
    # free and host-free — exactly what JXC002/JXC005 assert here
    mesh_axes=(),
)
def _reduce_sum_stacked(x):
    return x.sum(axis=0)


_REDUCERS = {
    ReduceOp.SUM: _reduce_sum_stacked,
    ReduceOp.PRODUCT: lambda x: x.prod(axis=0),
    ReduceOp.MIN: lambda x: x.min(axis=0),
    ReduceOp.MAX: lambda x: x.max(axis=0),
}


def _mesh_for(n: int):
    import jax
    from jax.sharding import Mesh

    devices = jax.local_devices()[:n]
    return Mesh(np.asarray(devices), ("d",))


@functools.lru_cache(maxsize=32)
def _reduce_prog(n: int, op: ReduceOp):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = _mesh_for(n)
    return jax.jit(
        _REDUCERS[op],
        in_shardings=NamedSharding(mesh, P("d")),
        out_shardings=NamedSharding(mesh, P()),
    )


def _stack(per_device):
    """Per-device arrays -> one [W, ...] array sharded over the 1D mesh
    without leaving the devices."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    n = len(per_device)
    mesh = _mesh_for(n)
    shape = (n,) + tuple(per_device[0].shape)
    shards = [a[None] for a in per_device]  # [1, ...] views on each device
    return jax.make_array_from_single_device_arrays(
        shape, NamedSharding(mesh, P("d")), shards
    )


def _unstack(replicated, n: int):
    """Replicated output -> the per-device arrays (no copies)."""
    shards = sorted(replicated.addressable_shards, key=lambda s: s.device.id)
    return [s.data for s in shards[:n]]


def allreduce(per_device, op: ReduceOp = ReduceOp.SUM):
    """per_device: list of same-shape jax.Arrays, one per local device.
    Returns the reduced array materialized on every participating device.
    One XLA program; the all-reduce rides ICI."""
    n = len(per_device)
    out = _reduce_prog(n, op)(_stack(per_device))
    return _unstack(out, n)


@functools.lru_cache(maxsize=32)
def _gather_prog(n: int):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = _mesh_for(n)
    return jax.jit(
        lambda x: x,
        in_shardings=NamedSharding(mesh, P("d")),
        out_shardings=NamedSharding(mesh, P()),  # resharding = all-gather
    )


def allgather(per_device):
    """Returns on every device the stacked [W, ...] of all inputs."""
    n = len(per_device)
    return _unstack(_gather_prog(n)(_stack(per_device)), n)


@functools.lru_cache(maxsize=32)
def _reducescatter_prog(n: int, op: ReduceOp):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = _mesh_for(n)
    return jax.jit(
        _REDUCERS[op],
        in_shardings=NamedSharding(mesh, P("d")),
        out_shardings=NamedSharding(mesh, P("d")),  # shard rows of the result
    )


def reducescatter(per_device, op: ReduceOp = ReduceOp.SUM):
    """Reduce then scatter row-shards back: device i gets rows i*k:(i+1)*k
    of the reduction (inputs' leading dim must divide by world size)."""
    n = len(per_device)
    out = _reducescatter_prog(n, op)(_stack(per_device))
    shards = sorted(out.addressable_shards, key=lambda s: s.device.id)
    return [s.data for s in shards[:n]]


def broadcast(array, n_devices: int):
    """One array -> materialized on each of the first n local devices."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = _mesh_for(n_devices)
    out = jax.device_put(array, NamedSharding(mesh, P()))
    return _unstack(out, n_devices)


# ---------------------------------------------------------------------------
# quantized in-program collectives (shard_map bodies)
#
# Unlike everything above (eager helpers over driver-held per-device
# arrays), these run INSIDE a traced shard_map body with a bound axis
# name — they are the explicit collective schedule of the tensor-parallel
# serving hot path (llm/model_runner.py), owned by the runtime instead of
# left implicit in GSPMD.
# ---------------------------------------------------------------------------
def quantized_psum(x, axis_name: str):
    """EQuARX-style int8 all-reduce (arxiv 2506.17615): the all-reduce is
    decomposed into its reduce-scatter + all-gather halves with the bulk
    payload quantized to int8 on the wire for BOTH phases.

    x: [..., H] local partial sum with H % axis_size == 0. Each shard
    splits its partial into `axis_size` chunks along the trailing axis and
    quantizes each chunk symmetrically to int8 with one f32 amax scale per
    chunk row (the kv_quant.py recipe — scale computed from the exact
    vector being shipped, no calibration). An all-to-all routes chunk j's
    int8 partials (plus their tiny f32 scales) to shard j, which
    dequantizes and accumulates its owned chunk EXACTLY in f32, then
    requantizes the reduced chunk once for the int8 all-gather back.

    Wire bytes per shard ≈ 2·(n-1)/n · (|x|·1 byte + scale rows·4 bytes)
    vs 2·(n-1)/n · |x|·itemsize for the fp psum — ~1/2 the ICI bytes at
    bf16 operands, ~1/4 at f32. Quantization error is bounded by the two
    int8 roundings (inner accumulation is exact f32).
    """
    import jax
    import jax.numpy as jnp

    from ray_tpu.llm.kv_quant import quantize_heads

    n = jax.lax.psum(1, axis_name)  # static axis size under shard_map
    H = x.shape[-1]
    if H % n:
        raise ValueError(f"quantized_psum needs trailing dim {H} divisible by axis size {n}")
    chunks = x.reshape(x.shape[:-1] + (n, H // n))  # [..., n, C]
    q, s = quantize_heads(chunks)  # int8 [..., n, C], f32 [..., n]
    d = q.ndim - 2
    # route chunk j (int8 + scale) to shard j: the reduce-scatter half
    qx = jax.lax.all_to_all(q, axis_name, split_axis=d, concat_axis=d, tiled=True)
    sx = jax.lax.all_to_all(s, axis_name, split_axis=s.ndim - 1, concat_axis=s.ndim - 1, tiled=True)
    owned = jnp.sum(qx.astype(jnp.float32) * sx[..., None], axis=d)  # exact f32 accumulate
    # one requant of the reduced chunk, then the int8 all-gather half
    q2, s2 = quantize_heads(owned)
    qf = jax.lax.all_gather(q2, axis_name, axis=d, tiled=False)  # [..., n, C]
    sf = jax.lax.all_gather(s2, axis_name, axis=s2.ndim, tiled=False)  # [..., n]
    out = (qf.astype(jnp.float32) * sf[..., None]).reshape(x.shape)
    return out.astype(x.dtype)


# primitives that put bytes on the wire. Per-chip ring wire bytes as a
# multiple of the traced OPERAND's bytes: all-reduce moves 2(n-1)/n of
# its (full-size) operand, one-directional exchanges over full-size
# operands (all-to-all, reduce-scatter) move (n-1)/n — but all_gather's
# operand is the PRE-gather local shard, of which a ring ships (n-1)
# full copies per chip, so it gets n x the (n-1)/n factor.
_WIRE_PRIMS = {"psum": 2.0, "all_to_all": 1.0, "psum_scatter": 1.0, "reduce_scatter": 1.0}


def _wire_factor(prim: str, axis_size: int) -> float:
    if prim == "all_gather":
        return float(axis_size - 1)
    return _WIRE_PRIMS[prim] * (axis_size - 1) / max(axis_size, 1)


def collective_wire_report(closed_jaxpr, axis_size: int) -> dict:
    """Per-execution ICI wire bytes of every collective in a traced
    program, by operand dtype — the bytes-on-the-wire evidence for the
    quantized-collective A/B (CPU cannot show the ICI wall-clock win, so
    the jaxpr IS the measurement). Descends scan bodies multiplying by
    the trip count, so a per-layer psum inside the layer scan counts L
    times. Returns {"bytes_by_dtype": {dtype: bytes}, "total_bytes": n,
    "ops": [{prim, dtype, shape, count, wire_bytes}, ...]}."""
    import math as _math

    from jax import core as _core

    by_dtype: dict[str, float] = {}
    ops: list[dict] = []

    def _walk(jx, mult: float):
        for eqn in jx.eqns:
            pname = eqn.primitive.name
            if (pname in _WIRE_PRIMS or pname == "all_gather") and eqn.invars:
                for iv in eqn.invars:
                    aval = getattr(iv, "aval", None)
                    if aval is None:
                        continue
                    try:
                        nbytes = int(_math.prod(aval.shape)) * aval.dtype.itemsize
                    except (AttributeError, TypeError):
                        continue
                    wire = nbytes * _wire_factor(pname, axis_size) * mult
                    dt = str(aval.dtype)
                    by_dtype[dt] = by_dtype.get(dt, 0.0) + wire
                    ops.append({
                        "prim": pname, "dtype": dt, "shape": list(aval.shape),
                        "count": mult, "wire_bytes": int(wire),
                    })
            sub_mult = mult
            if pname == "scan":
                sub_mult = mult * int(eqn.params.get("length", 1))
            for v in eqn.params.values():
                for item in v if isinstance(v, (tuple, list)) else (v,):
                    if isinstance(item, _core.ClosedJaxpr):
                        _walk(item.jaxpr, sub_mult)
                    elif isinstance(item, _core.Jaxpr):
                        _walk(item, sub_mult)

    _walk(closed_jaxpr.jaxpr, 1.0)
    return {
        "bytes_by_dtype": {k: int(v) for k, v in sorted(by_dtype.items())},
        "total_bytes": int(sum(by_dtype.values())),
        "ops": ops,
    }
