"""ICI collective backend: XLA-compiled collectives over local mesh devices.

The host-side collective API (collective.py) moves tensors through the shm
object store — the DCN/control plane. When the participating "ranks" are
the chips of one host (one PJRT client), the right data plane is ICI via a
single jitted XLA program; these helpers wrap that for driver-held
per-device arrays. (Inside jit/shard_map, just use lax.psum/all_gather —
see ray_tpu.parallel; this module is for eager host code that owns one
array per chip, e.g. a parameter server pushing to device replicas.)

Reference shape: util/collective/collective_group/nccl_collective_group.py
(a real device backend for the same API) — here the "backend" is XLA +
GSPMD, no NCCL.
"""

from __future__ import annotations

import functools

import numpy as np

from ray_tpu.collective.types import ReduceOp
from ray_tpu.lint import jaxcheck


def _bucket_reduce(W=8, rows=256, cols=1024):
    import jax
    import jax.numpy as jnp

    return (jax.ShapeDtypeStruct((W, rows, cols), jnp.float32),), {}


@jaxcheck.entry(
    name="collective.ici.reduce_stacked",
    shapes={"w8_256x1024": _bucket_reduce},
    # no explicit collective primitives: the all-reduce is GSPMD-inserted
    # by the P('d') -> P() resharding, so the jaxpr must stay collective-
    # free and host-free — exactly what JXC002/JXC005 assert here
    mesh_axes=(),
)
def _reduce_sum_stacked(x):
    return x.sum(axis=0)


_REDUCERS = {
    ReduceOp.SUM: _reduce_sum_stacked,
    ReduceOp.PRODUCT: lambda x: x.prod(axis=0),
    ReduceOp.MIN: lambda x: x.min(axis=0),
    ReduceOp.MAX: lambda x: x.max(axis=0),
}


def _mesh_for(n: int):
    import jax
    from jax.sharding import Mesh

    devices = jax.local_devices()[:n]
    return Mesh(np.asarray(devices), ("d",))


@functools.lru_cache(maxsize=32)
def _reduce_prog(n: int, op: ReduceOp):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = _mesh_for(n)
    return jax.jit(
        _REDUCERS[op],
        in_shardings=NamedSharding(mesh, P("d")),
        out_shardings=NamedSharding(mesh, P()),
    )


def _stack(per_device):
    """Per-device arrays -> one [W, ...] array sharded over the 1D mesh
    without leaving the devices."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    n = len(per_device)
    mesh = _mesh_for(n)
    shape = (n,) + tuple(per_device[0].shape)
    shards = [a[None] for a in per_device]  # [1, ...] views on each device
    return jax.make_array_from_single_device_arrays(
        shape, NamedSharding(mesh, P("d")), shards
    )


def _unstack(replicated, n: int):
    """Replicated output -> the per-device arrays (no copies)."""
    shards = sorted(replicated.addressable_shards, key=lambda s: s.device.id)
    return [s.data for s in shards[:n]]


def allreduce(per_device, op: ReduceOp = ReduceOp.SUM):
    """per_device: list of same-shape jax.Arrays, one per local device.
    Returns the reduced array materialized on every participating device.
    One XLA program; the all-reduce rides ICI."""
    n = len(per_device)
    out = _reduce_prog(n, op)(_stack(per_device))
    return _unstack(out, n)


@functools.lru_cache(maxsize=32)
def _gather_prog(n: int):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = _mesh_for(n)
    return jax.jit(
        lambda x: x,
        in_shardings=NamedSharding(mesh, P("d")),
        out_shardings=NamedSharding(mesh, P()),  # resharding = all-gather
    )


def allgather(per_device):
    """Returns on every device the stacked [W, ...] of all inputs."""
    n = len(per_device)
    return _unstack(_gather_prog(n)(_stack(per_device)), n)


@functools.lru_cache(maxsize=32)
def _reducescatter_prog(n: int, op: ReduceOp):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = _mesh_for(n)
    return jax.jit(
        _REDUCERS[op],
        in_shardings=NamedSharding(mesh, P("d")),
        out_shardings=NamedSharding(mesh, P("d")),  # shard rows of the result
    )


def reducescatter(per_device, op: ReduceOp = ReduceOp.SUM):
    """Reduce then scatter row-shards back: device i gets rows i*k:(i+1)*k
    of the reduction (inputs' leading dim must divide by world size)."""
    n = len(per_device)
    out = _reducescatter_prog(n, op)(_stack(per_device))
    shards = sorted(out.addressable_shards, key=lambda s: s.device.id)
    return [s.data for s in shards[:n]]


def broadcast(array, n_devices: int):
    """One array -> materialized on each of the first n local devices."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = _mesh_for(n_devices)
    out = jax.device_put(array, NamedSharding(mesh, P()))
    return _unstack(out, n_devices)
