"""Collective communication API on actor groups.

Reference parity: python/ray/util/collective/collective.py — GroupManager
(:76), init_collective_group, ops allreduce/reduce/broadcast/allgather/
reducescatter/send/recv/barrier (:339-735). The reference's NCCL rendezvous
(rank-0 creating a NCCLUniqueIDStore named actor,
nccl_collective_group.py:29-69) maps here to a named rendezvous actor; the
data plane is the host object store (DCN-equivalent). The ICI fast path is
NOT this API — it is GSPMD collectives inside jitted programs (see
ray_tpu.parallel) — matching the TPU split: control/host tensors over DCN,
device tensors inside XLA programs.
"""

from __future__ import annotations

import threading

import numpy as np

import ray_tpu
from ray_tpu.collective.types import Backend, ReduceOp, apply_reduce


class _GroupInfo:
    def __init__(self, name, world_size, rank, backend, handle):
        self.name = name
        self.world_size = world_size
        self.rank = rank
        self.backend = backend
        self.handle = handle
        self.round = 0
        self.p2p_seq: dict = {}  # (kind, peer, tag) -> count
        self.lock = threading.Lock()

    def next_round(self) -> int:
        with self.lock:
            self.round += 1
            return self.round

    def next_p2p(self, kind: str, peer: int, tag: int) -> int:
        """Per-(direction, peer, tag) sequence number so repeated sends on
        one tag match their recvs in order instead of clobbering a slot."""
        with self.lock:
            key = (kind, peer, tag)
            self.p2p_seq[key] = self.p2p_seq.get(key, 0) + 1
            return self.p2p_seq[key]


_groups: dict[str, _GroupInfo] = {}


@ray_tpu.remote(num_cpus=0)
class CollectiveRendezvous:
    """Named actor every rank rendezvouses on (reference:
    NCCLUniqueIDStore pattern, nccl_collective_group.py:29-69). Async so
    waiting ranks don't block one another."""

    def __init__(self, world_size: int):
        import asyncio

        self.world_size = world_size
        self.rounds: dict = {}
        self._asyncio = asyncio

    def _entry(self, key):
        if key not in self.rounds:
            self.rounds[key] = {"data": {}, "event": self._asyncio.Event(), "result": None, "done": 0}
        return self.rounds[key]

    async def exchange(self, key, rank, payload, op: str, mode: str):
        from ray_tpu._config import get_config

        e = self._entry(key)
        e["data"][rank] = payload
        if len(e["data"]) == self.world_size:
            arrays = [e["data"][r] for r in range(self.world_size)]
            if mode == "allreduce":
                e["result"] = apply_reduce(ReduceOp(op), arrays)
            elif mode == "allgather":
                e["result"] = arrays
            elif mode == "reducescatter":
                red = apply_reduce(ReduceOp(op), arrays)
                e["result"] = np.array_split(red, self.world_size, axis=0)
            elif mode == "barrier":
                e["result"] = True
            elif mode == "broadcast":
                e["result"] = None  # picked below by src rank lookup
                e["bcast"] = e["data"]
            e["event"].set()
        await self._asyncio.wait_for(e["event"].wait(), timeout=get_config().collective_timeout_s)
        try:
            if mode == "reducescatter":
                return e["result"][rank]
            if mode == "broadcast":
                src = int(op)  # op carries src_rank for broadcast
                return e["bcast"][src]
            return e["result"]
        finally:
            # precise GC: the round is dropped once every rank has read it
            e["done"] += 1
            if e["done"] == self.world_size:
                self.rounds.pop(key, None)

    async def p2p_send(self, key, payload):
        e = self._entry(key)
        e["data"][0] = payload
        e["event"].set()

    async def p2p_recv(self, key):
        from ray_tpu._config import get_config

        e = self._entry(key)
        await self._asyncio.wait_for(e["event"].wait(), timeout=get_config().collective_timeout_s)
        val = e["data"][0]
        self.rounds.pop(key, None)
        return val

    def reset(self):
        self.rounds.clear()
        return True


def _rendezvous_name(group_name: str) -> str:
    return f"rt_collective::{group_name}"


def init_collective_group(
    world_size: int,
    rank: int,
    backend: Backend | str = Backend.OBJECT_STORE,
    group_name: str = "default",
):
    """Call on every rank (reference: collective.py:init_collective_group)."""
    backend = Backend.normalize(backend)
    name = _rendezvous_name(group_name)
    if rank == 0:
        handle = CollectiveRendezvous.options(name=name, lifetime="detached").remote(world_size)
        ray_tpu.get(handle.__ray_ready__())
    else:
        import time

        deadline = time.time() + 60
        while True:
            try:
                handle = ray_tpu.get_actor(name)
                break
            except ValueError:
                if time.time() > deadline:
                    raise TimeoutError(f"rendezvous actor for group {group_name!r} never appeared") from None
                time.sleep(0.05)
    _groups[group_name] = _GroupInfo(group_name, world_size, rank, backend, handle)


def create_collective_group(actors, world_size: int, ranks: list[int], backend="object_store", group_name: str = "default"):
    """Declare a group across actor handles (driver-side; reference:
    collective.py:create_collective_group). Each actor must then call
    init_collective_group in its own process."""
    return declare_collective_group(actors, world_size=world_size, ranks=ranks, backend=backend, group_name=group_name)


def declare_collective_group(actors, world_size=None, ranks=None, backend="object_store", group_name="default"):
    world_size = world_size or len(actors)
    ranks = ranks or list(range(len(actors)))
    refs = []
    for actor, rank in zip(actors, ranks):
        refs.append(actor.__rt_init_collective__.remote(world_size, rank, str(backend), group_name))
    return refs


def destroy_collective_group(group_name: str = "default"):
    g = _groups.pop(group_name, None)
    if g is not None and g.rank == 0:
        try:
            ray_tpu.kill(g.handle)
        except Exception:
            pass


def cleanup_group_actor(group_name: str):
    """Driver/controller-side: kill a group's (detached) rendezvous actor by
    name — used to reap groups whose ranks died without destroy."""
    try:
        ray_tpu.kill(ray_tpu.get_actor(_rendezvous_name(group_name)))
    except Exception:
        pass


def get_rank(group_name: str = "default") -> int:
    return _groups[group_name].rank


def get_world_size(group_name: str = "default") -> int:
    return _groups[group_name].world_size


def _g(group_name) -> _GroupInfo:
    if group_name not in _groups:
        raise RuntimeError(f"collective group {group_name!r} not initialized in this process")
    return _groups[group_name]


# p2p payloads above this ride the shm object store (single producer and
# consumer per key, so the sender alone can decide the plane)
_SHM_PLANE_THRESHOLD = 32 * 1024


def _roundtrip(g: _GroupInfo, tensor, op, mode, round_key=None):
    key = round_key or f"{mode}:{g.next_round()}"
    payload = None if tensor is None else np.asarray(tensor)
    op_str = op.value if isinstance(op, ReduceOp) else str(op)
    if mode in ("allreduce", "allgather", "reducescatter", "broadcast"):
        # data modes ALWAYS take the shm plane so every rank of a round
        # agrees on the protocol (a per-rank size threshold would let
        # ranks of one round mix planes and corrupt the exchange)
        return _shm_plane(g, key, payload, op_str, mode)
    return ray_tpu.get(g.handle.exchange.remote(key, g.rank, payload, op_str, mode))


def _shm_plane(g: _GroupInfo, key, payload, op_str, mode):
    """Data plane over the shm object store: ranks exchange ObjectRefs via
    the rendezvous actor (tiny control messages), attach each other's
    segments directly, and reduce locally — the rendezvous heap never holds
    world_size x tensor bytes (the O(world x bytes) funnel the round-1
    review flagged). A closing barrier lets each rank free its payload, so
    rounds leave nothing in the store."""
    if mode == "broadcast" and int(op_str) != g.rank:
        my_ref = (None,)  # only the src rank ships bytes
    else:
        # 1-tuple wrap: a bare ObjectRef arg would be auto-dereferenced by
        # the task runtime; nested refs pass through opaque
        my_ref = (ray_tpu.put(payload),)
    refs = ray_tpu.get(g.handle.exchange.remote(key, g.rank, my_ref, "sum", "allgather"))
    try:
        if mode == "broadcast":
            src = int(op_str)
            return payload if src == g.rank else ray_tpu.get(refs[src][0])
        arrays = [
            payload if r == g.rank else ray_tpu.get(refs[r][0]) for r in range(g.world_size)
        ]
        if mode == "allgather":
            return arrays
        red = apply_reduce(ReduceOp(op_str), arrays)
        if mode == "reducescatter":
            return np.array_split(red, g.world_size, axis=0)[g.rank]
        return red
    finally:
        # every rank has read what it needs once it reaches this barrier;
        # then each rank frees its own payload object
        ray_tpu.get(g.handle.exchange.remote(f"{key}::done", g.rank, None, "sum", "barrier"))
        if my_ref[0] is not None:
            ray_tpu.internal_free([my_ref[0]])


def _like(result, tensor):
    """Return result with the same array flavor as the input."""
    try:
        import jax.numpy as jnp

        if hasattr(tensor, "devices") or type(tensor).__module__.startswith("jax"):
            return jnp.asarray(result)
    except Exception:
        pass
    return result


def allreduce(tensor, group_name: str = "default", op: ReduceOp = ReduceOp.SUM):
    g = _g(group_name)
    return _like(_roundtrip(g, tensor, op, "allreduce"), tensor)


def reduce(tensor, dst_rank: int = 0, group_name: str = "default", op: ReduceOp = ReduceOp.SUM):
    g = _g(group_name)
    out = _roundtrip(g, tensor, op, "allreduce")
    return _like(out, tensor) if g.rank == dst_rank else tensor


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    g = _g(group_name)
    return _like(_roundtrip(g, tensor, src_rank, "broadcast"), tensor)


def allgather(tensor, group_name: str = "default"):
    g = _g(group_name)
    return [_like(r, tensor) for r in _roundtrip(g, tensor, "sum", "allgather")]


def reducescatter(tensor, group_name: str = "default", op: ReduceOp = ReduceOp.SUM):
    g = _g(group_name)
    return _like(_roundtrip(g, tensor, op, "reducescatter"), tensor)


def barrier(group_name: str = "default"):
    g = _g(group_name)
    _roundtrip(g, None, "sum", "barrier")


def send(tensor, dst_rank: int, group_name: str = "default", tag: int = 0):
    g = _g(group_name)
    seq = g.next_p2p("send", dst_rank, tag)
    key = f"p2p:{g.rank}->{dst_rank}:{tag}:{seq}"
    payload = np.asarray(tensor)
    if payload.nbytes >= _SHM_PLANE_THRESHOLD:
        # shm data plane; the actor relays a (wrapped, not auto-deref'd) ref
        payload = (ray_tpu.put(payload),)
    ray_tpu.get(g.handle.p2p_send.remote(key, payload))


def recv(shape_or_tensor, src_rank: int, group_name: str = "default", tag: int = 0):
    from ray_tpu.core.object_ref import ObjectRef

    g = _g(group_name)
    seq = g.next_p2p("recv", src_rank, tag)
    key = f"p2p:{src_rank}->{g.rank}:{tag}:{seq}"
    out = ray_tpu.get(g.handle.p2p_recv.remote(key))
    if isinstance(out, tuple) and len(out) == 1 and isinstance(out[0], ObjectRef):
        ref = out[0]
        out = ray_tpu.get(ref)
        ray_tpu.internal_free([ref])  # single consumer: free after fetch
    return _like(out, shape_or_tensor)


class CollectiveActorMixin:
    """Mixin giving actors the __rt_init_collective__ hook used by
    declare_collective_group."""

    def __rt_init_collective__(self, world_size, rank, backend, group_name):
        init_collective_group(world_size, rank, backend, group_name)
        return rank
