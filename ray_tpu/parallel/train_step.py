"""Sharded training step construction.

Lowers a (model loss_fn, optax optimizer, mesh, sharding rules) tuple to a
single jitted SPMD program: parameters/optimizer state sharded per the
logical rules (FSDP/TP), batch sharded over (dp, fsdp) x sp, gradients
reduced by XLA-inserted collectives over ICI. This is the TPU-native
replacement for the reference's DDP/FSDP wrap + NCCL allreduce
(train/torch/train_loop_utils.py:153,374).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.lint import jaxcheck
from ray_tpu.parallel.mesh import DEFAULT_RULES, ShardingRules, shard_batch_spec


@dataclass
class TrainState:
    step: Any
    params: Any
    opt_state: Any

    def tree_flatten(self):
        return (self.step, self.params, self.opt_state), None


jax.tree_util.register_pytree_node(
    TrainState,
    lambda s: ((s.step, s.params, s.opt_state), None),
    lambda _, c: TrainState(*c),
)


def _bucket_train_step(B=32, D=1024):
    """Linear-regression probe state: the donation/dtype/collective
    contracts under test are model-independent."""
    tx = optax.adam(1e-3)
    w = jax.ShapeDtypeStruct((D, D), jnp.float32)
    params = {"w": w}
    opt_state = jax.eval_shape(tx.init, params)
    state = TrainState(step=jax.ShapeDtypeStruct((), jnp.int32), params=params, opt_state=opt_state)
    batch = {
        "x": jax.ShapeDtypeStruct((B, D), jnp.float32),
        "y": jax.ShapeDtypeStruct((B, D), jnp.float32),
    }

    def loss_fn(p, b):
        return jnp.mean((b["x"] @ p["w"] - b["y"]) ** 2)

    return (state, batch), {"loss_fn": loss_fn, "tx": tx}


@jaxcheck.entry(
    name="parallel.train_step",
    shapes={"b32_d1024": _bucket_train_step},
    donate=("state",),
)
def train_step(state: TrainState, batch, *, loss_fn: Callable, tx: optax.GradientTransformation):
    """One optimizer step — the body every make_train_step program jits
    (state donated; XLA shards it per the caller's in_shardings)."""
    loss, grads = jax.value_and_grad(loss_fn)(state.params, batch)
    updates, new_opt = tx.update(grads, state.opt_state, state.params)
    new_params = optax.apply_updates(state.params, updates)
    gnorm = optax.global_norm(grads)
    return (
        TrainState(step=state.step + 1, params=new_params, opt_state=new_opt),
        {"loss": loss, "grad_norm": gnorm, "step": state.step + 1},
    )


def make_train_step(
    loss_fn: Callable,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    param_axes,
    rules: ShardingRules = DEFAULT_RULES,
    donate: bool = True,
):
    """Returns (init_fn, step_fn, state_shardings).

    - init_fn(rng) -> TrainState, sharded at creation (no host gather)
    - step_fn(state, batch) -> (state, metrics); jitted with donation
    """
    param_shardings = rules.tree_shardings(param_axes, mesh)
    batch_sharding = NamedSharding(mesh, shard_batch_spec(mesh))
    repl = NamedSharding(mesh, P())

    def _opt_shardings(params_shape, p_shardings):
        # optimizer-state subtrees that mirror the param tree structure
        # (adam mu/nu, momentum, ...) get the param shardings; everything
        # else (step counts, scalars) replicates. Structural matching —
        # NOT shape matching — so same-shaped params with different
        # shardings (e.g. wq vs wo) keep their own layout.
        opt_shape = jax.eval_shape(tx.init, params_shape)
        params_treedef = jax.tree.structure(params_shape)

        def is_param_mirror(sub):
            return jax.tree.structure(sub) == params_treedef

        return jax.tree.map(
            lambda sub: p_shardings if is_param_mirror(sub) else jax.tree.map(lambda _: repl, sub),
            opt_shape,
            is_leaf=is_param_mirror,
        )

    def init_fn(rng, init_params_fn):
        params_shape = jax.eval_shape(init_params_fn, rng)
        opt_shard = _opt_shardings(params_shape, param_shardings)
        state_shardings = TrainState(step=repl, params=param_shardings, opt_state=opt_shard)

        def _init(r):
            params = init_params_fn(r)
            return TrainState(step=jnp.zeros((), jnp.int32), params=params, opt_state=tx.init(params))

        init_jit = jax.jit(_init, out_shardings=state_shardings)
        return init_jit(rng), state_shardings

    def _step(state: TrainState, batch):
        return train_step(state, batch, loss_fn=loss_fn, tx=tx)

    def compile_step(state_shardings):
        return jax.jit(
            _step,
            in_shardings=(state_shardings, batch_sharding),
            out_shardings=(state_shardings, repl),
            donate_argnums=(0,) if donate else (),
        )

    return init_fn, compile_step, batch_sharding


def shard_batch(batch, mesh: Mesh):
    """Device-put a host batch with the canonical batch sharding."""
    sharding = NamedSharding(mesh, shard_batch_spec(mesh))
    return jax.tree.map(lambda x: jax.device_put(x, sharding), batch)
