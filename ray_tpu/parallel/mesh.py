"""Device mesh construction + logical sharding rules.

The TPU-native core of the framework: every parallelism strategy the
reference delegates to NCCL/torch (DP via DDP allreduce, FSDP param
sharding, TP via vLLM engine args — SURVEY.md §2.5) is expressed here as
GSPMD sharding over a named `jax.sharding.Mesh`:

  axis   | role
  -------|----------------------------------------------------------
  dp     | data parallel (batch split; gradients psum over dp)
  fsdp   | fully-sharded data parallel (params/opt-state sharded; ZeRO)
  tp     | tensor parallel (matmul column/row sharding over ICI)
  sp     | sequence/context parallel (ring attention over sequence)
  ep     | expert parallel (MoE expert sharding + all-to-all dispatch)
  pp     | pipeline stages (usually across slices / DCN)

XLA inserts the collectives (psum/all-gather/reduce-scatter/ppermute) on
ICI automatically from these shardings — no NCCL anywhere.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS_ORDER = ("pp", "dp", "fsdp", "sp", "tp", "ep")


@dataclass
class MeshConfig:
    """Sizes per logical axis; -1 means 'absorb remaining devices'."""

    dp: int = -1
    fsdp: int = 1
    tp: int = 1
    sp: int = 1
    ep: int = 1
    pp: int = 1

    def resolve(self, n_devices: int) -> dict[str, int]:
        sizes = {"pp": self.pp, "dp": self.dp, "fsdp": self.fsdp, "sp": self.sp, "tp": self.tp, "ep": self.ep}
        fixed = math.prod(v for v in sizes.values() if v > 0)
        wild = [k for k, v in sizes.items() if v == -1]
        if len(wild) > 1:
            raise ValueError(f"at most one axis may be -1, got {wild}")
        if wild:
            if n_devices % fixed != 0:
                raise ValueError(f"{n_devices} devices not divisible by fixed axes product {fixed}")
            sizes[wild[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(f"mesh axes product {fixed} != device count {n_devices}")
        return sizes


def create_mesh(
    config: MeshConfig | dict | None = None,
    devices=None,
    **axis_sizes,
) -> Mesh:
    """Build a named mesh over the given (default: all) devices.

    create_mesh(dp=4)            -> 1D data-parallel mesh
    create_mesh(dp=2, tp=4)      -> 2D mesh, tp innermost (fastest ICI)
    create_mesh(MeshConfig(...)) -> from config

    Axis order puts tp/ep innermost so tensor-parallel collectives ride the
    shortest ICI hops, and pp outermost (cross-slice / DCN), matching the
    scaling-book recipe.
    """
    if devices is None:
        devices = jax.devices()
    if config is None:
        config = MeshConfig(**{**{"dp": -1}, **axis_sizes}) if axis_sizes else MeshConfig()
    elif isinstance(config, dict):
        config = MeshConfig(**config)
    sizes = config.resolve(len(devices))
    axes = [a for a in AXIS_ORDER if sizes[a] > 1] or ["dp"]
    shape = [sizes[a] for a in axes]
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, axis_names=tuple(axes))


def mesh_axes(mesh: Mesh) -> dict[str, int]:
    return dict(zip(mesh.axis_names, mesh.devices.shape))


def axis_or_none(mesh: Mesh, *names: str):
    """The subset of `names` present in the mesh (for PartitionSpecs that
    degrade gracefully when an axis is absent)."""
    present = [n for n in names if n in mesh.axis_names]
    if not present:
        return None
    return tuple(present) if len(present) > 1 else present[0]


def axis_size(mesh: Mesh, name: str) -> int:
    """Size of one named axis (1 when absent — the degenerate no-op)."""
    return mesh_axes(mesh).get(name, 1)


def is_tp_only(mesh: Mesh) -> bool:
    """True when the mesh is a pure tensor-parallel mesh (the serving
    shard_map hot path engages only here: other axes would shard params
    on dims the manual per-shard programs assume replicated)."""
    return set(mesh.axis_names) == {"tp"}


# ----------------------------------------------------------------------
# logical sharding rules
# ----------------------------------------------------------------------
@dataclass
class ShardingRules:
    """Map logical array-dimension names to mesh axes (flax-style
    partitioning rules, applied to pytrees of logical axis annotations)."""

    rules: dict[str, object] = field(
        default_factory=lambda: {
            "batch": ("dp", "fsdp"),  # batch dim split over dp (+fsdp data shards)
            "sequence": "sp",
            "embed": "fsdp",  # param sharding axis (ZeRO-3 over fsdp)
            "heads": "tp",
            "kv_heads": "tp",
            "mlp": "tp",
            "vocab": "tp",
            "expert": "ep",
            "stage": "pp",
            None: None,
        }
    )

    def spec(self, logical_axes: tuple, mesh: Mesh) -> P:
        out = []
        used = set()
        for ax in logical_axes:
            m = self.rules.get(ax)
            if m is None:
                out.append(None)
                continue
            names = (m,) if isinstance(m, str) else tuple(m)
            names = tuple(n for n in names if n in mesh.axis_names and n not in used)
            used.update(names)
            if not names:
                out.append(None)
            elif len(names) == 1:
                out.append(names[0])
            else:
                out.append(names)
        return P(*out)

    def sharding(self, logical_axes: tuple, mesh: Mesh) -> NamedSharding:
        return NamedSharding(mesh, self.spec(logical_axes, mesh))

    def tree_shardings(self, logical_tree, mesh: Mesh):
        """Pytree of logical-axis tuples -> pytree of NamedShardings."""
        return jax.tree.map(
            lambda axes: self.sharding(axes, mesh),
            logical_tree,
            is_leaf=lambda x: isinstance(x, tuple) and all(a is None or isinstance(a, str) for a in x),
        )


DEFAULT_RULES = ShardingRules()


def shard_batch_spec(mesh: Mesh) -> P:
    """PartitionSpec for input batches: batch over dp(+fsdp), sequence over sp."""
    return P(axis_or_none(mesh, "dp", "fsdp"), axis_or_none(mesh, "sp"))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
