"""Pipeline parallelism: GPipe-style microbatch pipelining over the `pp`
mesh axis, inside ONE jitted SPMD program.

TPU-native replacement for the reference's compiled-graph pipelines
(python/ray/dag/compiled_dag_node.py + experimental/channel/
torch_tensor_accelerator_channel.py): where the reference wires actor
stages together with NCCL channels and a compiled schedule, here the
schedule IS the XLA program — stages are devices along the `pp` mesh
axis, activations hop stage-to-stage with `lax.ppermute` (a neighbor
copy on ICI/DCN), and the whole (M + n - 1)-tick loop is a `lax.scan`
that jax.grad differentiates into the reverse pipeline automatically.

Design:
- layer-stacked params [L, ...] are reshaped to [n_stages, L/n, ...] and
  sharded `P('pp')` on the leading dim: each device materializes only its
  own stage's weights (the pp memory win).
- the batch is split into M microbatches. At tick t, stage 0 feeds
  microbatch t (while t < M); every stage applies its L/n layers to its
  current activation; the result hops to the next stage. After n-1 warmup
  ticks the pipe is full; total ticks = M + n - 1, bubble fraction
  (n-1)/(M+n-1).
- shard_map is manual ONLY over `pp` (`axes` arg) — dp/fsdp/tp stay
  auto, so XLA still shards batch/params inside each stage exactly as in
  the non-pp program.
- embedding/unembedding stay OUTSIDE the pipeline region (auto-sharded;
  their FLOPs are marginal), which keeps their gradients trivially
  correct: the transpose of the replicated-in/psum-out shard_map handles
  the stage-gated activations.

Composition notes: pp × {dp, fsdp, tp} is supported. pp × sp is not —
ring attention runs its own shard_map over `sp` and JAX does not nest
manual regions; use Ulysses-style head sharding via tp for long sequences
in pipelined configs.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


def to_stage_stacked(layer_params, n_stages: int):
    """[L, ...]-stacked layer params -> [n_stages, L/n, ...]."""

    def reshape(leaf):
        L = leaf.shape[0]
        if L % n_stages:
            raise ValueError(f"num_layers {L} not divisible by pp={n_stages}")
        return leaf.reshape(n_stages, L // n_stages, *leaf.shape[1:])

    return jax.tree.map(reshape, layer_params)


def from_stage_stacked(layer_params):
    """[n_stages, L/n, ...] -> [L, ...]."""
    return jax.tree.map(lambda leaf: leaf.reshape(leaf.shape[0] * leaf.shape[1], *leaf.shape[2:]), layer_params)


def pipeline_apply(
    stage_params,
    x,
    *,
    mesh: Mesh,
    layer_fn: Callable,
    num_microbatches: int,
    axis_name: str = "pp",
):
    """Run stage-stacked layers over x with GPipe microbatch pipelining.

    stage_params: pytree with leading [n_stages, L/n, ...] dims, sharded
      P('pp') on dim 0. layer_fn(x, layer) applies ONE layer.
    x: [B, ...] activations (NOT sharded over pp).
    Returns [B, ...] outputs (replicated over pp, identical on every
    stage after the closing psum).
    """
    n = mesh.shape[axis_name]
    B = x.shape[0]
    M = num_microbatches
    if B % M:
        raise ValueError(f"batch {B} not divisible by num_microbatches {M}")
    mb = B // M
    x_mb = x.reshape(M, mb, *x.shape[1:])

    def local(stage_p, xs):
        # stage_p: [1, L/n, ...] (this device's stage); xs: [M, mb, ...]
        my = lax.axis_index(axis_name)
        stage_p = jax.tree.map(lambda t: t[0], stage_p)

        def apply_stage(act):
            def body(carry, layer):
                return layer_fn(carry, layer), None

            out, _ = lax.scan(body, act, stage_p)
            return out

        shift_perm = [(i, i + 1) for i in range(n - 1)]  # a shift, not a ring

        def tick(carry, t):
            state, outputs = carry
            # stage 0 ingests microbatch t (clamped once the feed is done);
            # later stages consume what the previous stage sent last tick
            feed = lax.dynamic_index_in_dim(xs, jnp.minimum(t, M - 1), axis=0, keepdims=False)
            inp = jnp.where(my == 0, feed, state)
            out = apply_stage(inp)
            # last stage banks microbatch t-(n-1) once the pipe is primed
            oidx = jnp.clip(t - (n - 1), 0, M - 1)
            bank = jnp.logical_and(my == n - 1, t >= n - 1)
            cur = lax.dynamic_index_in_dim(outputs, oidx, axis=0, keepdims=False)
            outputs = lax.dynamic_update_index_in_dim(
                outputs, jnp.where(bank, out, cur), oidx, axis=0
            )
            state = lax.ppermute(out, axis_name, shift_perm) if n > 1 else out
            return (state, outputs), None

        init = jax.tree.map(
            lambda t: lax.pvary(t, (axis_name,)),
            (jnp.zeros_like(xs[0]), jnp.zeros_like(xs)),
        )
        (_, outputs), _ = lax.scan(tick, init, jnp.arange(M + n - 1))
        # only the last stage holds real outputs; psum broadcasts them so
        # the (auto-sharded) unembed/loss outside sees one consistent value.
        # f32 for the wire: XLA's bf16 all-reduce promotion pass crashes on
        # CPU, and f32 costs nothing extra on TPU (promotion does it anyway)
        gated = jnp.where(my == n - 1, outputs, jnp.zeros_like(outputs)).astype(jnp.float32)
        return lax.psum(gated, axis_name).astype(outputs.dtype)

    fn = jax.shard_map(
        local,
        mesh=mesh,
        in_specs=(P(axis_name), P()),
        out_specs=P(),
        axis_names={axis_name},
    )
    out_mb = fn(stage_params, x_mb)
    return out_mb.reshape(B, *x.shape[1:])


# ----------------------------------------------------------------------
# Llama integration: pipelined forward/loss drop-ins
# ----------------------------------------------------------------------
def pp_param_logical_axes(config, n_stages: int):
    """param_logical_axes for pp: layer leaves are [n_stages, L/n, *dims],
    logical axes ('stage', None, *per-layer axes)."""
    from ray_tpu.models.llama import PARAM_AXES, param_logical_axes

    axes = param_logical_axes(config)
    axes["layers"] = {
        k: ("stage", None) + tuple(v[1:]) for k, v in PARAM_AXES["layers"].items()
    }
    return axes


def pp_init_params(config, key, n_stages: int):
    """init_params with the layer stack reshaped to [n_stages, L/n, ...]."""
    from ray_tpu.models.llama import init_params

    params = init_params(config, key)
    params["layers"] = to_stage_stacked(params["layers"], n_stages)
    return params


def pp_forward(params, tokens, config, mesh: Mesh, num_microbatches: int):
    """Pipelined llama forward: embed -> pp pipeline over layers -> unembed."""
    from ray_tpu.models.llama import _layer_fn
    from ray_tpu.ops.layers import rms_norm, rotary_embedding

    B, T = tokens.shape
    positions = jnp.arange(T, dtype=jnp.int32)
    cos, sin = rotary_embedding(positions, config.hd, config.rope_theta, dtype=jnp.float32)
    x = jnp.take(params["embed"], tokens, axis=0)

    layer_fn = functools.partial(_layer_fn, config=config, cos=cos, sin=sin, positions=positions)
    if config.remat:
        policy = getattr(jax.checkpoint_policies, config.remat_policy)
        layer_fn = jax.checkpoint(layer_fn, policy=policy)

    x = pipeline_apply(
        params["layers"], x, mesh=mesh, layer_fn=layer_fn, num_microbatches=num_microbatches
    )
    x = rms_norm(x, params["final_norm"], config.rms_eps)
    unembed = params["embed"].T if config.tie_embeddings else params["unembed"]
    return jnp.dot(x, unembed, preferred_element_type=jnp.float32)


def pp_loss_fn(params, batch, config, mesh: Mesh, num_microbatches: int):
    from ray_tpu.ops.layers import cross_entropy_loss

    logits = pp_forward(params, batch["tokens"], config, mesh, num_microbatches)
    return cross_entropy_loss(logits, batch["targets"])
