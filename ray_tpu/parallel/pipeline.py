"""Pipeline parallelism: GPipe-style microbatch pipelining over the `pp`
mesh axis, inside ONE jitted SPMD program.

TPU-native replacement for the reference's compiled-graph pipelines
(python/ray/dag/compiled_dag_node.py + experimental/channel/
torch_tensor_accelerator_channel.py): where the reference wires actor
stages together with NCCL channels and a compiled schedule, here the
schedule IS the XLA program — stages are devices along the `pp` mesh
axis, activations hop stage-to-stage with `lax.ppermute` (a neighbor
copy on ICI/DCN), and the whole (M + n - 1)-tick loop is a `lax.scan`
that jax.grad differentiates into the reverse pipeline automatically.

Design:
- layer-stacked params [L, ...] are reshaped to [n_stages, v, L/(n*v), ...]
  (v = virtual_stages, 1 for GPipe) and sharded `P('pp')` on the leading
  dim: each device materializes only its own chunks' weights (the pp
  memory win). With v > 1 the chunks are placed round-robin: device d
  owns model chunks d, d+n, ..., d+(v-1)n.
- the batch is split into M microbatches. GPipe (v=1): at tick t, stage 0
  feeds microbatch t; every stage applies its L/n layers; the result hops
  to the next stage; total ticks = M + n - 1, bubble fraction
  (n-1)/(M+n-1). Interleaved (v>1): the activation stream rides a RING
  (wraparound n-1 -> 0 between chunk rounds); total ticks = M*v + n - 1
  in 1/v-sized chunk-times, so the fill/drain bubble shrinks to
  (n-1)/v stage-times — the Megatron-style virtual-pipeline schedule.
- shard_map is manual ONLY over `pp` (`axes` arg) — dp/fsdp/tp stay
  auto, so XLA still shards batch/params inside each stage exactly as in
  the non-pp program.
- embedding/unembedding stay OUTSIDE the pipeline region (auto-sharded;
  their FLOPs are marginal), which keeps their gradients trivially
  correct: the transpose of the replicated-in/psum-out shard_map handles
  the stage-gated activations.

Composition notes: pp × {dp, fsdp, tp, sp} are all supported. pp × sp
does NOT nest shard_maps (JAX forbids that): pipeline_apply(sp_axis=...)
makes the ONE region manual over {pp, sp} and runs ring attention's
local form (manual ppermute collectives, ring_attention_local) inside
the stage body, with activations sequence-sharded and RoPE tables passed
as sp-sharded seq_inputs. dp/fsdp/tp stay auto inside either way.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ray_tpu.lint import jaxcheck


def _shard_map(f, mesh: Mesh, in_specs, out_specs, axis_names: set[str]):
    """``jax.shard_map(..., axis_names=...)`` where available; on older
    JAX (0.4.x) fall back to jax.experimental.shard_map with the
    complement of ``axis_names`` as ``auto`` axes."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, axis_names=axis_names
        )
    from jax.experimental.shard_map import shard_map as _sm

    auto = frozenset(mesh.axis_names) - set(axis_names)
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=False, auto=auto)


def _pvary(t, axis_names: tuple[str, ...]):
    # lax.pvary is a no-op value-wise; it only exists on newer JAX to mark
    # varying-manual-axes metadata. Identity is correct where it's absent.
    return lax.pvary(t, axis_names) if hasattr(lax, "pvary") else t


def to_stage_stacked(layer_params, n_stages: int, virtual_stages: int = 1):
    """[L, ...]-stacked layer params -> [n_stages, v, L/(n*v), ...].

    With virtual_stages v > 1 (interleaved schedule), device d owns model
    chunks d, d+n, ..., d+(v-1)n — round-robin layer placement, so chunk
    r on device d covers layers [(r*n + d) * L/(nv), ...). Dim 0 shards
    P('pp'); dim 1 indexes the device's local chunk round."""

    def reshape(leaf):
        L = leaf.shape[0]
        if L % (n_stages * virtual_stages):
            raise ValueError(f"num_layers {L} not divisible by pp*virtual = {n_stages}*{virtual_stages}")
        per = L // (n_stages * virtual_stages)
        # chunk k covers layers [k*per, (k+1)*per); chunk k lives on
        # device k % n as local round k // n
        chunked = leaf.reshape(n_stages * virtual_stages, per, *leaf.shape[1:])
        return (
            chunked.reshape(virtual_stages, n_stages, per, *leaf.shape[1:])
            .swapaxes(0, 1)  # [n, v, per, ...]
        )

    return jax.tree.map(reshape, layer_params)


def from_stage_stacked(layer_params):
    """[n_stages, v, L/(n*v), ...] -> [L, ...] (inverse chunk layout)."""

    def restore(leaf):
        n, v, per = leaf.shape[:3]
        return leaf.swapaxes(0, 1).reshape(n * v * per, *leaf.shape[3:])

    return jax.tree.map(restore, layer_params)


def pipeline_apply(
    stage_params,
    x,
    *,
    mesh: Mesh,
    layer_fn: Callable,
    num_microbatches: int,
    virtual_stages: int = 1,
    axis_name: str = "pp",
    sp_axis: str | None = None,
    seq_inputs: tuple = (),
):
    """Run stage-stacked layers over x with microbatch pipelining.

    virtual_stages=1 is the GPipe schedule: M microbatches flow through n
    device-stages; bubble fraction (n-1)/(M+n-1) in stage-time units.

    virtual_stages=v>1 is the INTERLEAVED schedule (Megatron-style virtual
    pipeline, reference capability: compiled multi-stage pipelines in
    dag/compiled_dag_node.py): device d owns model chunks d, d+n, ...,
    d+(v-1)n, each 1/v of a stage. Microbatch m of group g runs chunk
    round r on device d at tick d + g*v*n + r*n + m; the activation ring
    (ppermute with wraparound n-1 -> 0) hands off with zero idle ticks,
    so total ticks = M*v + (n-1) CHUNK-times — the pipeline fill/drain
    costs (n-1)/v stage-times instead of GPipe's (n-1): the bubble
    shrinks by the virtual-stage factor. Requires M % n == 0 (microbatch
    groups of n keep every device on exactly one chunk per tick).

    stage_params: pytree with leading [n_stages, v, L/(n*v), ...] dims,
      sharded P('pp') on dim 0. layer_fn(x, layer, *seq_locals) applies
      ONE layer. x: [B, ...] activations (NOT sharded over pp).
    Returns [B, ...] outputs (replicated over pp after the closing psum).

    pp x sp composition: with ``sp_axis`` set, the ONE shard_map region
    goes manual over BOTH axes — ring attention cannot nest its own
    shard_map inside the pp region, but its local form
    (ring_attention_local, manual ppermute collectives over sp) runs
    directly in the stage body. Activations shard their sequence dim
    (axis 2 of the microbatched [M, mb, T, ...]) over sp; ``seq_inputs``
    are per-position arrays ([T, ...], e.g. RoPE cos/sin) sharded over
    sp on dim 0 and handed to layer_fn as extra args. dp/fsdp/tp stay
    auto inside, exactly as without sp. (The reference cannot compose
    these at all — SURVEY.md §5.7: it has no sequence parallelism.)
    """
    n = mesh.shape[axis_name]
    B = x.shape[0]
    M = num_microbatches
    v = int(virtual_stages)
    if B % M:
        raise ValueError(f"batch {B} not divisible by num_microbatches {M}")
    if v > 1 and M % n:
        raise ValueError(f"interleaved schedule needs num_microbatches ({M}) divisible by pp ({n})")
    mb = B // M
    x_mb = x.reshape(M, mb, *x.shape[1:])

    def local(stage_p, xs, *seq_locals):
        # stage_p: [1, v, L/(n*v), ...] (this device's chunks); xs: [M, mb, ...]
        my = lax.axis_index(axis_name)
        stage_p = jax.tree.map(lambda t: t[0], stage_p)  # [v, per, ...]

        def apply_chunk(act, r):
            chunk = jax.tree.map(lambda t: lax.dynamic_index_in_dim(t, r, axis=0, keepdims=False), stage_p)

            def body(carry, layer):
                return layer_fn(carry, layer, *seq_locals), None

            out, _ = lax.scan(body, act, chunk)
            return out

        # interleaved: a ring — device n-1's output wraps to device 0 as
        # the next chunk round's input. GPipe (v=1) never reads the
        # wrapped value, so drop that edge and save the hop.
        ring_perm = [(i, (i + 1) % n) for i in range(n if v > 1 else n - 1)]
        jobs = M * v  # chunk applications per device

        def tick(carry, t):
            state, outputs = carry
            j = jnp.clip(t - my, 0, jobs - 1)  # this device's job index
            active = jnp.logical_and(t >= my, t - my < jobs)
            g = j // (v * n)  # microbatch group
            jj = j % (v * n)
            r = jj // n  # chunk round
            m = jj % n  # member within the group
            mb_idx = jnp.minimum(g * n + m, M - 1)
            feed = lax.dynamic_index_in_dim(xs, mb_idx, axis=0, keepdims=False)
            inp = jnp.where(jnp.logical_and(my == 0, r == 0), feed, state)
            out = apply_chunk(inp, r)
            # the final logical stage (chunk round v-1 on device n-1)
            # banks its microbatch's output
            bank = jnp.logical_and(jnp.logical_and(my == n - 1, r == v - 1), active)
            cur = lax.dynamic_index_in_dim(outputs, mb_idx, axis=0, keepdims=False)
            outputs = lax.dynamic_update_index_in_dim(
                outputs, jnp.where(bank, out, cur), mb_idx, axis=0
            )
            state = lax.ppermute(out, axis_name, ring_perm) if n > 1 else out
            return (state, outputs), None

        init = jax.tree.map(
            lambda t: _pvary(t, (axis_name,)),
            (jnp.zeros_like(xs[0]), jnp.zeros_like(xs)),
        )
        (_, outputs), _ = lax.scan(tick, init, jnp.arange(M * v + n - 1))
        # only the last stage holds real outputs; psum broadcasts them so
        # the (auto-sharded) unembed/loss outside sees one consistent value.
        # f32 for the wire: XLA's bf16 all-reduce promotion pass crashes on
        # CPU, and f32 costs nothing extra on TPU (promotion does it anyway)
        gated = jnp.where(my == n - 1, outputs, jnp.zeros_like(outputs)).astype(jnp.float32)
        return lax.psum(gated, axis_name).astype(outputs.dtype)

    if sp_axis is None:
        x_spec = P()
        seq_specs = tuple(P() for _ in seq_inputs)
        manual = {axis_name}
    else:
        # [M, mb, T, ...]: sequence dim sharded over sp
        x_spec = P(None, None, sp_axis)
        seq_specs = tuple(P(sp_axis) for _ in seq_inputs)
        manual = {axis_name, sp_axis}
    fn = _shard_map(
        local,
        mesh=mesh,
        in_specs=(P(axis_name), x_spec) + seq_specs,
        out_specs=x_spec,
        axis_names=manual,
    )
    out_mb = fn(stage_params, x_mb, *seq_inputs)
    return out_mb.reshape(B, *x.shape[1:])


# ----------------------------------------------------------------------
# Llama integration: pipelined forward/loss drop-ins
# ----------------------------------------------------------------------
def pp_param_logical_axes(config, n_stages: int, virtual_stages: int = 1):
    """param_logical_axes for pp: layer leaves are [n_stages, v, L/(n*v),
    *dims], logical axes ('stage', None, None, *per-layer axes)."""
    from ray_tpu.models.llama import PARAM_AXES, param_logical_axes

    axes = param_logical_axes(config)
    axes["layers"] = {
        k: ("stage", None, None) + tuple(v[1:]) for k, v in PARAM_AXES["layers"].items()
    }
    return axes


def pp_init_params(config, key, n_stages: int, virtual_stages: int = 1):
    """init_params with the layer stack reshaped to [n_stages, v, L/(n*v), ...]."""
    from ray_tpu.models.llama import init_params

    params = init_params(config, key)
    params["layers"] = to_stage_stacked(params["layers"], n_stages, virtual_stages)
    return params


def _sp_local_layer_fn(x, layer, cos_l, sin_l, *, config):
    """One llama layer on a LOCAL sequence shard, inside a region manual
    over {pp, sp}: per-token ops (norms, projections, MLP) need no
    communication; attention is the manual-collective ring
    (ring_attention_local — ppermute over sp on ICI). cos_l/sin_l are
    this shard's RoPE tables."""
    from ray_tpu.ops.layers import apply_rope, rms_norm
    from ray_tpu.parallel.ring_attention import ring_attention_local

    B, Tl, H = x.shape
    nh, nkv, hd = config.num_heads, config.num_kv_heads, config.hd
    xn = rms_norm(x, layer["attn_norm"], config.rms_eps)
    q = jnp.dot(xn, layer["wq"]).reshape(B, Tl, nh, hd).transpose(0, 2, 1, 3)
    k = jnp.dot(xn, layer["wk"]).reshape(B, Tl, nkv, hd).transpose(0, 2, 1, 3)
    v = jnp.dot(xn, layer["wv"]).reshape(B, Tl, nkv, hd).transpose(0, 2, 1, 3)
    q = apply_rope(q, cos_l, sin_l)
    k = apply_rope(k, cos_l, sin_l)
    rep = nh // nkv
    if rep > 1:
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    o = ring_attention_local(q, k, v, axis_name="sp", causal=True)
    o = o.transpose(0, 2, 1, 3).reshape(B, Tl, nh * hd)
    x = x + jnp.dot(o, layer["wo"])
    xn = rms_norm(x, layer["mlp_norm"], config.rms_eps)
    g = jnp.dot(xn, layer["w_gate"])
    u = jnp.dot(xn, layer["w_up"])
    return x + jnp.dot(jax.nn.silu(g) * u, layer["w_down"])


def _bucket_pp_forward(B=8, T=128, n_stages=2):
    """Tile-true abstract shapes on a pp-only mesh (fully manual shard_map,
    so the trace works on any >=2-device backend)."""
    from ray_tpu.models.llama import LlamaConfig
    from ray_tpu.parallel.mesh import create_mesh

    cfg = LlamaConfig(
        vocab_size=32256, hidden_size=1024, intermediate_size=2816,
        num_layers=4, num_heads=8, num_kv_heads=8, head_dim=128, remat=False,
    )
    mesh = create_mesh(pp=n_stages)
    params = jax.eval_shape(lambda: pp_init_params(cfg, jax.random.PRNGKey(0), n_stages))
    tokens = jax.ShapeDtypeStruct((B, T), jnp.int32)
    return (params, tokens, cfg, mesh, 4), {}


@jaxcheck.entry(
    name="parallel.pipeline_forward",
    shapes={"pp2_b8_t128": _bucket_pp_forward},
    mesh_axes=("pp", "sp"),
)
def pp_forward(params, tokens, config, mesh: Mesh, num_microbatches: int, virtual_stages: int = 1):
    """Pipelined llama forward: embed -> pp pipeline over layers -> unembed.
    When the mesh also has an `sp` axis, the pipeline region goes manual
    over {pp, sp} and runs ring attention per stage (pp x sp — see
    pipeline_apply; the reference has no sequence parallelism at all)."""
    from ray_tpu.models.llama import _layer_fn
    from ray_tpu.ops.layers import rms_norm, rotary_embedding

    B, T = tokens.shape
    positions = jnp.arange(T, dtype=jnp.int32)
    cos, sin = rotary_embedding(positions, config.hd, config.rope_theta, dtype=jnp.float32)
    x = jnp.take(params["embed"], tokens, axis=0)

    sp = "sp" if "sp" in mesh.axis_names and mesh.shape.get("sp", 1) > 1 else None
    if sp is not None:
        layer_fn = functools.partial(_sp_local_layer_fn, config=config)
        seq_inputs = (cos, sin)
    else:
        layer_fn = functools.partial(_layer_fn, config=config, cos=cos, sin=sin, positions=positions)
        seq_inputs = ()
    if config.remat:
        policy = getattr(jax.checkpoint_policies, config.remat_policy)
        layer_fn = jax.checkpoint(layer_fn, policy=policy)

    x = pipeline_apply(
        params["layers"], x, mesh=mesh, layer_fn=layer_fn,
        num_microbatches=num_microbatches, virtual_stages=virtual_stages,
        sp_axis=sp, seq_inputs=seq_inputs,
    )
    x = rms_norm(x, params["final_norm"], config.rms_eps)
    unembed = params["embed"].T if config.tie_embeddings else params["unembed"]
    return jnp.dot(x, unembed, preferred_element_type=jnp.float32)


def pp_loss_fn(params, batch, config, mesh: Mesh, num_microbatches: int, virtual_stages: int = 1):
    from ray_tpu.ops.layers import cross_entropy_loss

    logits = pp_forward(params, batch["tokens"], config, mesh, num_microbatches, virtual_stages)
    return cross_entropy_loss(logits, batch["targets"])
