"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

The reference has NO sequence-parallel implementation (SURVEY.md §5.7:
grep for ulysses/ring_attention/context_parallel over python/ray + rllib is
empty; long sequences are delegated to engines). Here it is first-class and
TPU-native:

- ring_attention: blockwise attention with online-softmax merging while
  K/V shards rotate around the `sp` mesh axis via `lax.ppermute` (ICI
  neighbor exchange — the ring topology IS the TPU interconnect). Memory
  per chip: O(T/sp); compute overlaps with the rotation.
- ulysses_attention: all-to-all head<->sequence reshard over `sp` (each
  chip sees the full sequence for H/sp heads), full local attention, then
  the inverse all-to-all. One collective round instead of sp ring steps —
  better when heads >= sp and ICI all-to-all bandwidth is plentiful.

Both are called INSIDE shard_map over the mesh (see sp_attention entry
point) so XLA lowers the permutes onto ICI.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

_NEG_INF = -1e30


def _block_attn(q, k, v, q_off, k_off, causal, scale):
    """Unnormalized blockwise attention: returns (acc, m, l).

    q: [B,H,Tq,D], k/v: [B,H,Tk,D]; offsets are global position starts used
    for causal masking across ring steps.
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
    if causal:
        Tq, Tk = q.shape[2], k.shape[2]
        qp = q_off + lax.broadcasted_iota(jnp.int32, (Tq, Tk), 0)
        kp = k_off + lax.broadcasted_iota(jnp.int32, (Tq, Tk), 1)
        s = jnp.where((kp <= qp)[None, None], s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    acc = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    return acc, m, l


def ring_attention_local(q, k, v, axis_name: str = "sp", causal: bool = True, scale: float | None = None):
    """Runs inside shard_map: q,k,v are the local sequence shards
    [B, H, T/sp, D]. Returns the local output shard."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    n = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    Tl = q.shape[2]
    q32 = q.astype(jnp.float32)

    def _merge(carry, kv, i):
        m_acc, l_acc, o_acc = carry
        k_i, v_i = kv
        src = (my - i) % n  # whose kv shard we currently hold
        acc, m_b, l_b = _block_attn(q32, k_i.astype(jnp.float32), v_i, my * Tl, src * Tl, causal, scale)
        m_new = jnp.maximum(m_acc, m_b)
        alpha = jnp.exp(m_acc - m_new)
        beta = jnp.exp(m_b - m_new)
        l_new = alpha * l_acc + beta * l_b
        o_new = o_acc * alpha + acc * beta
        return m_new, l_new, o_new

    def step(carry, i):
        softmax_carry, kv = carry
        new_carry = _merge(softmax_carry, kv, i)
        # rotate kv to the next device (ring over ICI)
        perm = [(j, (j + 1) % n) for j in range(n)]
        kv_next = jax.tree.map(lambda t: lax.ppermute(t, axis_name, perm), kv)
        return (new_carry, kv_next), None

    B, H, _, D = q.shape
    init = (
        jnp.full((B, H, Tl, 1), _NEG_INF, jnp.float32),
        jnp.zeros((B, H, Tl, 1), jnp.float32),
        jnp.zeros((B, H, Tl, D), jnp.float32),
    )
    # scan n-1 (attend, rotate) steps, then a final attend with no rotation
    # (the last hop's result would be discarded — skip the wasted ICI round)
    (carry, kv_last), _ = lax.scan(step, (init, (k, v)), jnp.arange(n - 1))
    m_f, l_f, o_f = _merge(carry, kv_last, n - 1)
    out = o_f / jnp.maximum(l_f, 1e-30)
    return out.astype(q.dtype)


def ulysses_attention_local(q, k, v, axis_name: str = "sp", causal: bool = True, scale: float | None = None, attn_fn=None):
    """Runs inside shard_map: all-to-all so each chip gets full sequence for
    H/sp heads, local full attention, inverse all-to-all."""
    n = lax.psum(1, axis_name)
    # [B, H, Tl, D] -> [B, H/n, T, D]
    q2 = lax.all_to_all(q, axis_name, split_axis=1, concat_axis=2, tiled=True)
    k2 = lax.all_to_all(k, axis_name, split_axis=1, concat_axis=2, tiled=True)
    v2 = lax.all_to_all(v, axis_name, split_axis=1, concat_axis=2, tiled=True)
    if attn_fn is None:
        from ray_tpu.ops.flash_attention import attention_xla

        attn_fn = functools.partial(attention_xla, causal=causal, scale=scale)
    o2 = attn_fn(q2, k2, v2)
    # [B, H/n, T, D] -> [B, H, Tl, D]
    return lax.all_to_all(o2, axis_name, split_axis=2, concat_axis=1, tiled=True)


def sp_attention(q, k, v, mesh: Mesh, impl: str = "ring", causal: bool = True):
    """Top-level entry: q,k,v globally [B, H, T, D] sharded over sp on T.
    Wraps the local kernels in shard_map over the full mesh."""
    from jax.experimental.shard_map import shard_map

    if "sp" not in mesh.axis_names:
        from ray_tpu.ops.flash_attention import attention_xla

        return attention_xla(q, k, v, causal=causal)
    batch_ax = tuple(a for a in ("dp", "fsdp") if a in mesh.axis_names) or None
    spec = P(batch_ax, None, "sp", None)
    local = ring_attention_local if impl == "ring" else ulysses_attention_local

    fn = shard_map(
        functools.partial(local, axis_name="sp", causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_rep=False,
    )
    return fn(q, k, v)
