"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

The reference has NO sequence-parallel implementation (SURVEY.md §5.7:
grep for ulysses/ring_attention/context_parallel over python/ray + rllib is
empty; long sequences are delegated to engines). Here it is first-class and
TPU-native:

- ring_attention: blockwise attention with online-softmax merging while
  K/V shards rotate around the `sp` mesh axis via `lax.ppermute` (ICI
  neighbor exchange — the ring topology IS the TPU interconnect). Memory
  per chip: O(T/sp * chunk), never O((T/sp)^2): each ring step runs the
  Pallas flash kernel (TPU) or a chunked-XLA blockwise scan (CPU), both
  returning (o, lse) without materializing local score matrices.
- custom VJP: the backward is a second ring pass in which (k, v, dk, dv)
  rotate together — every device adds its gradient contribution to the
  visiting shard, and after n hops dk/dv arrive back at their owner.
  Residuals are O(T/sp): (q, k, v, o, lse). No [Tl, Tl] buffers anywhere.
- ulysses_attention: all-to-all head<->sequence reshard over `sp` (each
  chip sees the full sequence for H/sp heads), full local attention, then
  the inverse all-to-all. One collective round instead of sp ring steps —
  better when heads >= sp and ICI all-to-all bandwidth is plentiful.

Both are called INSIDE shard_map over the mesh (see sp_attention entry
point) so XLA lowers the permutes onto ICI.

Causal schedule: with K/V rotating ring-wise, device `my` holding shard
`src` needs: full attention if src < my, diagonal-causal if src == my,
nothing if src > my. The diagonal step always runs first (it initializes
the online-softmax carry with a finite lse — every query attends at least
to itself), then n-1 (rotate, switch{skip|full}) steps. Skipped steps cost
one ppermute but no FLOPs (lax.switch executes one branch).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ray_tpu.ops.flash_attention import (
    _bwd_pallas_with_delta,
    _fwd_pallas,
    _use_pallas,
    chunked_attention_bwd,
    chunked_attention_fwd,
)

_NEG_INF = -1e30  # finite sentinel: exp(_NEG_INF - finite) underflows to 0.0


def _local_fwd(q, k, v, causal, scale, impl, chunk):
    """One ring step's local attention -> (o f32, lse f32), no [Tl,Tl]."""
    if _use_pallas(q, impl):
        o, lse = _fwd_pallas(q, k, v, causal=causal, scale=scale)
        return o.astype(jnp.float32), lse
    return chunked_attention_fwd(q, k, v, causal=causal, scale=scale, chunk=chunk)


def _local_bwd(q, k, v, g, lse, delta, causal, scale, impl, chunk):
    """One ring step's local backward -> (dq, dk, dv) f32."""
    if _use_pallas(q, impl):
        dq, dk, dv = _bwd_pallas_with_delta(
            q, k, v, g.astype(q.dtype), lse, delta, causal=causal, scale=scale
        )
        return dq.astype(jnp.float32), dk.astype(jnp.float32), dv.astype(jnp.float32)
    return chunked_attention_bwd(q, k, v, g, lse, delta, causal=causal, scale=scale, chunk=chunk)


def ring_attention_local(q, k, v, axis_name: str = "sp", causal: bool = True, scale: float | None = None, impl: str = "auto", chunk: int = 1024):
    """Runs inside shard_map: q,k,v are the local sequence shards
    [B, H, T/sp, D]. Returns the local output shard [B, H, T/sp, D]."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    return _ring_attn(q, k, v, axis_name, causal, float(scale), impl, chunk)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _ring_attn(q, k, v, axis_name, causal, scale, impl, chunk):
    out, _ = _ring_attn_fwd(q, k, v, axis_name, causal, scale, impl, chunk)
    return out


def _ring_attn_fwd(q, k, v, axis_name, causal, scale, impl, chunk):
    n = lax.psum(1, axis_name)  # static: shard_map axis size
    my = lax.axis_index(axis_name)
    perm = [(j, (j + 1) % n) for j in range(n)]
    rotate = lambda t: lax.ppermute(t, axis_name, perm)

    # step 0: the diagonal shard (src == my) — always computed, so the
    # online-softmax carry starts finite for every query row
    o_acc, lse_acc = _local_fwd(q, k, v, causal, scale, impl, chunk)

    if n > 1:
        def full_step(k_i, v_i):
            return _local_fwd(q, k_i, v_i, False, scale, impl, chunk)

        def skip_step(k_i, v_i):
            # zeros DERIVED from q/k_i so they inherit the region's varying
            # manual axes (vma): fresh jnp.zeros would be unvarying and
            # lax.switch rejects branch-type mismatch when this runs inside
            # a wider manual region (e.g. pp x sp in parallel/pipeline.py)
            zero_o = (q * 0 + k_i[..., :1, :] * 0).astype(jnp.float32)
            return zero_o, jnp.full_like(zero_o[..., 0], _NEG_INF)

        def step(carry, i):
            (o, lse), kv = carry
            kv = jax.tree.map(rotate, kv)  # neighbor exchange on ICI
            k_i, v_i = kv
            src = (my - i) % n
            use = (src < my).astype(jnp.int32) if causal else jnp.int32(1)
            o_i, lse_i = lax.switch(use, [skip_step, full_step], k_i, v_i)
            # merge two normalized partials: weights exp(lse - m) / w, w >= 1
            m = jnp.maximum(lse, lse_i)
            alpha = jnp.exp(lse - m)
            beta = jnp.exp(lse_i - m)
            w = alpha + beta
            o = (o * alpha[..., None] + o_i * beta[..., None]) / w[..., None]
            return ((o, m + jnp.log(w)), kv), None

        ((o_acc, lse_acc), _), _ = lax.scan(step, ((o_acc, lse_acc), (k, v)), jnp.arange(1, n))

    out = o_acc.astype(q.dtype)
    return out, (q, k, v, out, lse_acc)


def _ring_attn_bwd(axis_name, causal, scale, impl, chunk, res, g):
    q, k, v, o, lse = res
    n = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    perm = [(j, (j + 1) % n) for j in range(n)]
    rotate = lambda t: lax.ppermute(t, axis_name, perm)
    g32 = g.astype(jnp.float32)
    delta = jnp.sum(g32 * o.astype(jnp.float32), axis=-1)  # [B,H,Tl] f32

    # step 0: diagonal — gradient contribution to our own kv shard
    dq_acc, dk0, dv0 = _local_bwd(q, k, v, g32, lse, delta, causal, scale, impl, chunk)

    if n == 1:
        return dq_acc.astype(q.dtype), dk0.astype(k.dtype), dv0.astype(v.dtype)

    def full_step(k_i, v_i):
        return _local_bwd(q, k_i, v_i, g32, lse, delta, False, scale, impl, chunk)

    def skip_step(k_i, v_i):
        # vma-inheriting zeros (see forward skip_step)
        z = (q * 0).astype(jnp.float32)
        return z, (k_i * 0).astype(jnp.float32), (v_i * 0).astype(jnp.float32)

    def step(carry, i):
        dq, pkg = carry
        pkg = jax.tree.map(rotate, pkg)  # (k_s, v_s, dk_s, dv_s) travel together
        k_i, v_i, dk_i, dv_i = pkg
        src = (my - i) % n
        use = (src < my).astype(jnp.int32) if causal else jnp.int32(1)
        dq_c, dk_c, dv_c = lax.switch(use, [skip_step, full_step], k_i, v_i)
        return (dq + dq_c, (k_i, v_i, dk_i + dk_c, dv_i + dv_c)), None

    (dq_acc, (_, _, dk_acc, dv_acc)), _ = lax.scan(
        step, (dq_acc, (k, v, dk0, dv0)), jnp.arange(1, n)
    )
    # one final hop brings each shard's accumulated dk/dv home to its owner
    dk_acc = rotate(dk_acc)
    dv_acc = rotate(dv_acc)
    return dq_acc.astype(q.dtype), dk_acc.astype(k.dtype), dv_acc.astype(v.dtype)


_ring_attn.defvjp(_ring_attn_fwd, _ring_attn_bwd)


def ulysses_attention_local(q, k, v, axis_name: str = "sp", causal: bool = True, scale: float | None = None, attn_fn=None):
    """Runs inside shard_map: all-to-all so each chip gets full sequence for
    H/sp heads, local full attention, inverse all-to-all."""
    n = lax.psum(1, axis_name)
    # [B, H, Tl, D] -> [B, H/n, T, D]
    q2 = lax.all_to_all(q, axis_name, split_axis=1, concat_axis=2, tiled=True)
    k2 = lax.all_to_all(k, axis_name, split_axis=1, concat_axis=2, tiled=True)
    v2 = lax.all_to_all(v, axis_name, split_axis=1, concat_axis=2, tiled=True)
    if attn_fn is None:
        from ray_tpu.ops.flash_attention import flash_attention

        attn_fn = lambda a, b, c: flash_attention(a, b, c, causal, scale)
    o2 = attn_fn(q2, k2, v2)
    # [B, H/n, T, D] -> [B, H, Tl, D]
    return lax.all_to_all(o2, axis_name, split_axis=2, concat_axis=1, tiled=True)


def sp_attention(q, k, v, mesh: Mesh, impl: str = "ring", causal: bool = True):
    """Top-level entry: q,k,v globally [B, H, T, D] sharded over sp on T.
    Wraps the local kernels in shard_map over the full mesh."""
    from jax.experimental.shard_map import shard_map

    if "sp" not in mesh.axis_names:
        from ray_tpu.ops.flash_attention import flash_attention

        return flash_attention(q, k, v, causal, None)
    batch_ax = tuple(a for a in ("dp", "fsdp") if a in mesh.axis_names) or None
    spec = P(batch_ax, None, "sp", None)
    local = ring_attention_local if impl == "ring" else ulysses_attention_local

    fn = shard_map(
        functools.partial(local, axis_name="sp", causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_rep=False,
    )
    return fn(q, k, v)
