"""Single-machine multi-node cluster harness for tests.

Reference parity: python/ray/cluster_utils.py — Cluster (:135) with
add_node (:202, spawns real raylet processes). add_node here spawns a real
node-agent daemon process (core/node_agent.py) by default: workers live
under the agent, frames cross a socket, and health checks/chaos apply —
the process boundaries distributed behavior tests need (node death, PG
atomicity, failover, slice gang scheduling).
"""

from __future__ import annotations

from ray_tpu.core import context
from ray_tpu.core.runtime import Runtime


class Cluster:
    def __init__(self, initialize_head: bool = True, head_node_args: dict | None = None):
        self._rt: Runtime | None = None
        self.head_node = None
        if initialize_head:
            args = dict(head_node_args or {})
            resources = args.pop("resources", {})
            if "num_cpus" in args:
                resources["CPU"] = float(args.pop("num_cpus"))
            self._rt = Runtime(resources=resources or None, **args)
            context.set_client(self._rt)
            self.head_node = self._rt.head_node

    def connect(self):
        context.set_client(self._rt)
        return self._rt

    @property
    def address(self) -> str:
        return "local://" + (self._rt.node_id.hex() if self._rt else "none")

    def add_node(self, *, num_cpus: int = 1, num_tpus: int = 0, resources: dict | None = None, labels: dict | None = None, env: dict | None = None, remote: bool = True, shm_isolation: bool | None = None):
        """shm_isolation=True gives the node a private shm namespace: every
        object crossing its boundary rides the TCP transfer service, like a
        real second host (no same-host shm fast path)."""
        res = dict(resources or {})
        res.setdefault("CPU", float(num_cpus))
        if num_tpus:
            res["TPU"] = float(num_tpus)
        return self._rt.add_node(res, labels=labels, env=env, remote=remote, shm_isolation=shm_isolation)

    def remove_node(self, node, allow_graceful: bool = True):
        node_id = node.node_id if hasattr(node, "node_id") else node
        self._rt.remove_node(node_id, graceful=allow_graceful)

    def wait_for_nodes(self, timeout: float = 30.0):
        return True  # membership is synchronous in-process

    def shutdown(self):
        if self._rt is not None:
            self._rt.shutdown()
            self._rt = None
