"""Dashboard: HTTP observability endpoint for a running cluster.

Reference parity: python/ray/dashboard/ (aiohttp app serving cluster
state, jobs, metrics to the UI) — collapsed to a threaded stdlib HTTP
server over the head's live registries:

  GET /                 tiny auto-refreshing HTML overview
  GET /api/cluster      `ray status`-shaped summary
  GET /api/nodes        node table
  GET /api/actors       actor table
  GET /api/tasks        task-state summary
  GET /api/pgs          placement groups
  GET /api/jobs         submitted jobs
  GET /api/objects      object store stats
  GET /metrics          Prometheus text exposition

    from ray_tpu.dashboard import start_dashboard
    dash = start_dashboard(port=8265)   # 0 = ephemeral port
    dash.url
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

_PAGE = """<!doctype html>
<html><head><title>ray_tpu dashboard</title><meta charset="utf-8">
<style>
 :root{--bg:#fafafa;--fg:#222;--mut:#667;--line:#ddd;--card:#fff;--ok:#107a3d;--bad:#b3261e;--bar:#3b6fd4}
 @media (prefers-color-scheme: dark){:root{--bg:#16181d;--fg:#e6e6e6;--mut:#9aa;--line:#333;--card:#1e2128;--bar:#6c9bf2}}
 body{font-family:system-ui,sans-serif;margin:1.5rem;background:var(--bg);color:var(--fg)}
 h1{font-size:1.25rem;margin:0 0 .75rem}
 .cards{display:flex;gap:.75rem;flex-wrap:wrap;margin-bottom:1rem}
 .card{background:var(--card);border:1px solid var(--line);border-radius:8px;padding:.6rem .9rem;min-width:8rem}
 .card b{display:block;font-size:1.25rem} .card span{color:var(--mut);font-size:.75rem}
 nav{display:flex;gap:.25rem;border-bottom:1px solid var(--line);margin-bottom:.75rem}
 nav a{padding:.4rem .8rem;cursor:pointer;color:var(--mut);border-bottom:2px solid transparent;font-size:.9rem}
 nav a.on{color:var(--fg);border-color:var(--bar)}
 section{display:none} section.on{display:block}
 table{border-collapse:collapse;width:100%;background:var(--card)} td,th{border:1px solid var(--line);padding:.3rem .6rem;font-size:.82rem;text-align:left}
 th{color:var(--mut);font-weight:600}
 .bar{background:var(--line);border-radius:4px;height:8px;width:120px;display:inline-block;vertical-align:middle}
 .bar i{display:block;height:8px;border-radius:4px;background:var(--bar)}
 .ok{color:var(--ok)} .bad{color:var(--bad)}
 svg{vertical-align:middle}
 pre{background:var(--card);border:1px solid var(--line);padding:.6rem;font-size:.75rem;overflow:auto;max-height:24rem}
 button{background:var(--card);border:1px solid var(--line);color:var(--fg);border-radius:5px;padding:.2rem .6rem;cursor:pointer;font-size:.8rem}
</style></head>
<body>
<h1>ray_tpu dashboard</h1>
<div class="cards" id="cards"></div>
<nav id="tabs"></nav>
<section id="t-nodes"><table id="nodes"></table></section>
<section id="t-actors"><table id="actors"></table></section>
<section id="t-tasks"><div id="tasks-summary"></div><h3>throughput (finished/s)</h3><svg id="spark" width="560" height="70"></svg></section>
<section id="t-pgs"><table id="pgs"></table></section>
<section id="t-jobs"><table id="jobs"></table></section>
<section id="t-objects"><div id="objects"></div></section>
<section id="t-stacks"><button onclick="loadStacks()">capture live stacks</button><div id="stacks"></div></section>
<script>
const TABS=[["nodes","Nodes"],["actors","Actors"],["tasks","Tasks"],["pgs","Placement groups"],["jobs","Jobs"],["objects","Objects"],["stacks","Stacks"]];
let cur="nodes";
function renderTabs(){document.getElementById("tabs").innerHTML=TABS.map(([id,label])=>
  `<a class="${id===cur?"on":""}" onclick="show('${id}')">${label}</a>`).join("");
  TABS.forEach(([id])=>document.getElementById("t-"+id).className=id===cur?"on":"")}
function show(id){cur=id;renderTabs()}
async function j(p){const r=await fetch(p);return r.json()}
function esc(v){const d=document.createElement("div");d.textContent=String(v);return d.innerHTML}
function row(cells,tag){return "<tr>"+cells.map(c=>`<${tag}>${c}</${tag}>`).join("")+"</tr>"}
function fill(id,header,rows){document.getElementById(id).innerHTML=
  row(header.map(esc),"th")+rows.map(r=>row(r,"td")).join("")}
function bar(used,total){const pct=total>0?Math.min(100,100*used/total):0;
  return `<span class="bar"><i style="width:${pct.toFixed(0)}%"></i></span> ${used.toFixed(1)}/${total.toFixed(1)}`}
const hist=[];let lastFinished=null,lastT=null;
function spark(){const svg=document.getElementById("spark");if(!hist.length){svg.innerHTML="";return}
  const w=560,h=70,max=Math.max(...hist,1);const pts=hist.map((v,i)=>
    `${(i/(Math.max(hist.length-1,1))*w).toFixed(1)},${(h-4-(v/max)*(h-10)).toFixed(1)}`).join(" ");
  svg.innerHTML=`<polyline fill="none" stroke="var(--bar)" stroke-width="2" points="${pts}"/>
    <text x="4" y="12" fill="var(--mut)" font-size="10">peak ${max.toFixed(1)}/s</text>`}
async function refresh(){
  const [c,tl,a,pgs,jobs,o]=await Promise.all([
    j("/api/cluster"),j("/api/tasks"),j("/api/actors"),j("/api/pgs"),j("/api/jobs"),j("/api/objects")]);
  const res=c.cluster_resources||{},avail=c.available_resources||{};
  const cpuT=res.CPU||0,cpuA=avail.CPU||0,tpuT=res.TPU||0,tpuA=avail.TPU||0;
  const t={}; for(const x of (Array.isArray(tl)?tl:[])){t[x.status]=(t[x.status]||0)+1}
  // throughput from LIFETIME totals (the record list is windowed/pruned)
  const finished=(c.task_counts||{}).finished??(t.FINISHED||0);
  const running=t.RUNNING||0,pending=(t.PENDING||0)+(t.QUEUED||0)+(t.WAITING||0);
  const now=Date.now()/1000;
  if(lastFinished!==null&&now>lastT){hist.push(Math.max(0,(finished-lastFinished)/(now-lastT)));if(hist.length>120)hist.shift()}
  lastFinished=finished;lastT=now;
  document.getElementById("cards").innerHTML=
    `<div class="card"><b>${c.nodes.length}</b><span>nodes</span></div>`+
    `<div class="card"><b>${running}</b><span>tasks running</span></div>`+
    `<div class="card"><b>${pending}</b><span>tasks pending</span></div>`+
    `<div class="card"><b>${bar(cpuT-cpuA,cpuT)}</b><span>CPU in use</span></div>`+
    (tpuT?`<div class="card"><b>${bar(tpuT-tpuA,tpuT)}</b><span>TPU chips in use</span></div>`:"")+
    `<div class="card"><b>${c.pending_demand.length}</b><span>pending demand</span></div>`;
  fill("nodes",["node","alive","workers","CPU","TPU","labels"],
    c.nodes.map(n=>[esc(n.node_id.slice(0,12)),
      n.alive?'<span class="ok">alive</span>':'<span class="bad">dead</span>',
      esc(n.num_workers),
      bar((n.resources.CPU||0)-(n.available.CPU||0),n.resources.CPU||0),
      n.resources.TPU?bar((n.resources.TPU||0)-(n.available.TPU||0),n.resources.TPU):"",
      esc(JSON.stringify(n.labels||{}))]));
  fill("actors",["actor","name","state","class","node","restarts"],
    a.map(x=>[esc(x.actor_id.slice(0,12)),esc(x.name||""),
      x.state==="ALIVE"?'<span class="ok">ALIVE</span>':esc(x.state),
      esc(x["class"]),esc((x.node_id||"").slice(0,12)),esc(x.num_restarts)]));
  document.getElementById("tasks-summary").innerHTML=
    Object.entries(t).map(([k,v])=>`<span class="card" style="margin-right:.5rem"><b>${esc(v)}</b> <span>${esc(k)}</span></span>`).join("");
  spark();
  fill("pgs",["pg","name","strategy","state","bundles"],
    pgs.map(x=>[esc((x.pg_id||"").slice(0,12)),esc(x.name||""),esc(x.strategy),esc(x.state),esc(JSON.stringify(x.bundles))]));
  fill("jobs",["job","status","entrypoint","returncode"],
    jobs.map(x=>[esc(x.job_id),esc(x.status),esc(x.entrypoint),esc(x.returncode??"")]));
  document.getElementById("objects").innerHTML="<pre>"+esc(JSON.stringify(o,null,1))+"</pre>";
}
async function loadStacks(){
  const s=await j("/api/stacks");
  document.getElementById("stacks").innerHTML=Object.entries(s).map(([w,d])=>
    `<h3>worker ${esc(w.slice(0,12))} pid=${esc(d.pid??"?")} task=${esc((d.current_task||"idle").slice(0,12))}</h3>`+
    `<pre>${esc(Object.entries(d.stacks||{}).map(([t,st])=>t+"\n"+st).join("\n"))}</pre>`).join("")||"no workers";
}
renderTabs();refresh();setInterval(refresh,2000);
</script></body></html>"""


class Dashboard:
    def __init__(self, client=None, host: str = "127.0.0.1", port: int = 8265):
        from ray_tpu.core import context

        self.client = client or context.get_client()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # silence request logging
                pass

            def _send(self, body: bytes, ctype: str, code: int = 200):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _json(self, obj, code: int = 200):
                self._send(json.dumps(obj, default=str).encode(), "application/json", code)

            def do_GET(self):
                c = outer.client
                try:
                    path = self.path.split("?")[0].rstrip("/") or "/"
                    if path == "/":
                        self._send(_PAGE.encode(), "text/html")
                    elif path == "/api/cluster":
                        from ray_tpu.util.state import cluster_status

                        self._json(cluster_status(c))
                    elif path == "/api/nodes":
                        self._json(c.cluster_info("nodes"))
                    elif path == "/api/actors":
                        self._json(c.cluster_info("actors"))
                    elif path == "/api/tasks":
                        self._json(c.cluster_info("tasks"))
                    elif path == "/api/pgs":
                        self._json(c.cluster_info("placement_groups"))
                    elif path == "/api/objects":
                        self._json(c.cluster_info("objects"))
                    elif path == "/api/jobs":
                        from dataclasses import asdict

                        from ray_tpu.job.job_manager import _default_manager

                        jobs = _default_manager.list_jobs() if _default_manager else []
                        self._json([asdict(j) for j in jobs])
                    elif path.startswith("/api/stacks"):
                        # on-demand live stacks of (all|prefix) workers —
                        # the py-spy-attach capability (reference:
                        # dashboard/modules/reporter/profile_manager.py)
                        prefix = path[len("/api/stacks"):].strip("/")
                        self._json(c.dump_worker_stacks(prefix))
                    elif path == "/metrics":
                        from ray_tpu.util.metrics import export_prometheus

                        self._send(export_prometheus(c).encode(), "text/plain; version=0.0.4")
                    else:
                        self._json({"error": "not found"}, 404)
                except Exception as e:  # noqa: BLE001
                    self._json({"error": str(e)}, 500)

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.host = host
        self.port = self._server.server_address[1]
        self._thread: threading.Thread | None = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self):
        self._thread = threading.Thread(target=self._server.serve_forever, daemon=True, name="rt-dashboard")
        self._thread.start()
        return self

    def stop(self):
        self._server.shutdown()
        self._server.server_close()


def start_dashboard(port: int = 8265, host: str = "127.0.0.1", client=None) -> Dashboard:
    return Dashboard(client=client, host=host, port=port).start()
